"""Registered tensor-parallel collectives (the megatron f/g pair plus
the serving gather) with ALGEBRAIC — not autodiff-default — transposes.

The step body's cotangent convention is replicated-downstream: every tp
rank carries the FULL upstream gradient (the loss and everything after
the parallel region are replicated over 'tp'). Under that convention
the AD transpose of a raw ``lax.psum`` is another psum — inflating the
shard gradients by tp — and the transpose of a tiled ``all_gather`` is
``psum_scatter`` (same inflation). ``jax.custom_vjp`` pins the correct
pairings:

- ``tp_copy``  (megatron *f*): forward identity, backward psum — the
  entry of each parallel region, so replicated/dp-sharded upstream
  parameters see the complete, tp-invariant gradient.
- ``tp_sum``   (megatron *g*): forward psum, backward identity — the
  exit of row-parallel layers in training.
- ``tp_gather``: forward tiled all_gather, backward slice-own-chunk —
  the exit of column-parallel layers into replicated math (the serving
  path; a concatenation, so merged values are BITWISE the unsharded
  model's).

Registered ``jit=False`` so each replay re-evaluates the fn in its own
context: inside ``shard_map`` the axis name is bound and the real
collective lowers; in a plain eager evaluation (the deferred-compute
trace, run with per-rank local values) the eager ``NameError: unbound
axis name`` path substitutes a shape-correct stand-in and records the
payload bytes on the active ``parallel.tp`` context — the build's only
window into the in-program tp traffic (``collective_bytes.tp``).
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def _note(kind, nbytes):
    from ..parallel import tp as _tp

    ctx = _tp.current()
    if ctx is not None:
        if kind == "psum":
            ctx.psum_bytes += int(nbytes)
        else:
            ctx.gather_bytes += int(nbytes)


@functools.lru_cache(maxsize=None)
def _copy_prim(axis):
    @jax.custom_vjp
    def f(x):
        return x

    f.defvjp(lambda x: (x, None), lambda _, g: (lax.psum(g, axis),))
    return f


@functools.lru_cache(maxsize=None)
def _sum_prim(axis):
    @jax.custom_vjp
    def f(x):
        return lax.psum(x, axis)

    f.defvjp(lambda x: (lax.psum(x, axis), None), lambda _, g: (g,))
    return f


@functools.lru_cache(maxsize=None)
def _gather_prim(axis, dim, size):
    @jax.custom_vjp
    def f(x):
        return lax.all_gather(x, axis, axis=dim, tiled=True)

    def fwd(x):
        return lax.all_gather(x, axis, axis=dim, tiled=True), None

    def bwd(_, g):
        local = g.shape[dim] // size
        start = lax.axis_index(axis) * local
        return (lax.dynamic_slice_in_dim(g, start, local, axis=dim),)

    f.defvjp(fwd, bwd)
    return f


@register("tp_copy", jit=False)
def _make_tp_copy(axis="tp"):
    prim = _copy_prim(axis)

    def f(x):
        if isinstance(x, jax.core.Tracer):
            try:
                return prim(x)
            except NameError:   # abstract eval outside shard_map
                return x
        # concrete (the eager trace): identity value, but account the
        # bytes this op's BACKWARD psum moves in the compiled program
        _note("psum", x.nbytes)
        return x

    return f


@register("tp_sum", jit=False)
def _make_tp_sum(axis="tp"):
    prim = _sum_prim(axis)

    def f(x):
        if isinstance(x, jax.core.Tracer):
            try:
                return prim(x)
            except NameError:
                return x
        _note("psum", x.nbytes)
        return x   # rank-local partial: eager trace values are throwaway

    return f


@register("tp_gather", jit=False)
def _make_tp_gather(axis="tp", size=2, dim=0):
    prim = _gather_prim(axis, dim, size)

    def f(x):
        if isinstance(x, jax.core.Tracer):
            try:
                return prim(x)
            except NameError:
                return jnp.concatenate([x] * size, axis=dim)
        _note("gather", x.nbytes * size)
        return jnp.concatenate([x] * size, axis=dim)

    return f
