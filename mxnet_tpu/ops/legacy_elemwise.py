"""Legacy scalar / strict-elemwise / creation / slice op families.

TPU-native registrations for the reference op names that carry a *distinct
signature* from the numpy-surface ops (so a plain alias would be wrong):

- ``_*_scalar`` binary-with-scalar family — the scalar operand is a static
  attr (reference: src/operator/tensor/elemwise_binary_scalar_op_basic.cc).
  Keeping it static is TPU-friendly: under CachedOp tracing the constant is
  baked into the jitted HLO instead of becoming a device operand.
- creation ops (reference: src/operator/tensor/init_op.cc, numpy/np_init_op.cc)
- legacy slice family (reference: src/operator/tensor/matrix_op.cc)
- legacy ``Reshape`` 0/-1/-2/-3/-4 shape codes and ``_npx_reshape``
  (reference: matrix_op-inl.h InferReshapeShape, np_matrix_op.cc NumpyXReshape)
- LARS / multi-tensor helper ops (reference: src/operator/contrib/multi_lars.cc,
  multi_sum_sq.cc, reset_arrays.cc)
- small contrib ops: div_sqrt_dim, index_array, gradientmultiplier, LRN,
  SoftmaxActivation, BatchNormWithReLU, SyncBatchNorm, make_loss.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register, register_alias, get_op

# ---------------------------------------------------------------------------
# binary-with-scalar family — elemwise_binary_scalar_op_basic.cc:*
# ---------------------------------------------------------------------------
_SCALAR_OPS = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_mod_scalar": lambda x, s: jnp.mod(x, s),
    "_rmod_scalar": lambda x, s: jnp.mod(s, x),
    "_power_scalar": lambda x, s: jnp.power(x, s),
    "_rpower_scalar": lambda x, s: jnp.power(s, x),
    "_maximum_scalar": lambda x, s: jnp.maximum(x, s),
    "_minimum_scalar": lambda x, s: jnp.minimum(x, s),
    "_hypot_scalar": lambda x, s: jnp.hypot(x, jnp.asarray(s, x.dtype)),
    "_npi_copysign_scalar": lambda x, s: jnp.copysign(x, s),
    "_npi_rcopysign_scalar": lambda x, s: jnp.copysign(
        jnp.asarray(s, x.dtype), x),
    "_npi_arctan2_scalar": lambda x, s: jnp.arctan2(
        x, jnp.asarray(s, x.dtype)),
    "_npi_rarctan2_scalar": lambda x, s: jnp.arctan2(
        jnp.asarray(s, x.dtype), x),
    "_npi_fmax_scalar": lambda x, s: jnp.fmax(x, s),
    "_npi_fmin_scalar": lambda x, s: jnp.fmin(x, s),
    "_npi_fmod_scalar": lambda x, s: jnp.fmod(x, s),
    "_npi_rfmod_scalar": lambda x, s: jnp.fmod(jnp.asarray(s, x.dtype), x),
    "_npi_ldexp_scalar": lambda x, s: jnp.ldexp(x, jnp.int32(s)),
    "_npi_rldexp_scalar": lambda x, s: jnp.ldexp(
        jnp.asarray(s, jnp.float32), x.astype(jnp.int32)),
}
for _name, _fn2 in _SCALAR_OPS.items():
    register(_name,
             (lambda f: (lambda scalar=0.0, is_int=False, **a:
                         (lambda x: f(x, scalar))))(_fn2))

_SCALAR_INT_OPS = {
    "_npi_gcd_scalar": lambda x, s: jnp.gcd(x, jnp.asarray(s, x.dtype)),
    "_npi_lcm_scalar": lambda x, s: jnp.lcm(x, jnp.asarray(s, x.dtype)),
    "_npi_bitwise_and_scalar": lambda x, s: jnp.bitwise_and(
        x, jnp.asarray(s, x.dtype)),
    "_npi_bitwise_or_scalar": lambda x, s: jnp.bitwise_or(
        x, jnp.asarray(s, x.dtype)),
    "_npi_bitwise_xor_scalar": lambda x, s: jnp.bitwise_xor(
        x, jnp.asarray(s, x.dtype)),
}
for _name, _fn2 in _SCALAR_INT_OPS.items():
    register(_name,
             (lambda f: (lambda scalar=0, is_int=True, **a:
                         (lambda x: f(x, int(scalar)))))(_fn2),
             differentiable=False)


# legacy comparison-with-scalar: reference returns input dtype 0/1, not bool
# (elemwise_binary_scalar_op_logic.cc) and registers zero-gradient.
_SCALAR_CMP = {
    "_equal_scalar": jnp.equal,
    "_not_equal_scalar": jnp.not_equal,
    "_greater_scalar": jnp.greater,
    "_greater_equal_scalar": jnp.greater_equal,
    "_lesser_scalar": jnp.less,
    "_lesser_equal_scalar": jnp.less_equal,
    "_logical_and_scalar": jnp.logical_and,
    "_logical_or_scalar": jnp.logical_or,
    "_logical_xor_scalar": jnp.logical_xor,
}
for _name, _fn2 in _SCALAR_CMP.items():
    register(_name,
             (lambda f: (lambda scalar=0.0, is_int=False, **a:
                         (lambda x: f(x, scalar).astype(x.dtype))))(_fn2),
             differentiable=False)

# numpy-internal dispatch names for the same scalar kernels
for _alias, _tgt in {
    "_npi_add_scalar": "_plus_scalar",
    "_npi_subtract_scalar": "_minus_scalar",
    "_npi_rsubtract_scalar": "_rminus_scalar",
    "_npi_multiply_scalar": "_mul_scalar",
    "_npi_true_divide_scalar": "_div_scalar",
    "_npi_rtrue_divide_scalar": "_rdiv_scalar",
    "_npi_mod_scalar": "_mod_scalar",
    "_npi_rmod_scalar": "_rmod_scalar",
    "_npi_power_scalar": "_power_scalar",
    "_npi_rpower_scalar": "_rpower_scalar",
}.items():
    register_alias(_alias, _tgt)

# where-with-scalar variants (np_where_op.cc)
register("_npi_where_lscalar", lambda scalar=0.0, **a:
         (lambda cond, rhs: jnp.where(cond.astype(bool), scalar, rhs)))
register("_npi_where_rscalar", lambda scalar=0.0, **a:
         (lambda cond, lhs: jnp.where(cond.astype(bool), lhs, scalar)))
register("_npi_where_scalar2", lambda x=0.0, y=0.0, **a:
         (lambda cond: jnp.where(cond.astype(bool),
                                 jnp.float32(x), jnp.float32(y))),
         differentiable=False)

# ---------------------------------------------------------------------------
# missing unary ops — elemwise_unary_op_basic.cc / _pow.cc
# ---------------------------------------------------------------------------
register("reciprocal_sqrt", lambda **a: lax.rsqrt)          # rsqrt
register("rcbrt", lambda **a: (lambda x: 1.0 / jnp.cbrt(x)))
register("digamma", lambda **a: jax.scipy.special.digamma)
register("hard_sigmoid", lambda alpha=0.2, beta=0.5:
         (lambda x: jnp.clip(alpha * x + beta, 0.0, 1.0)))
register("nanprod", lambda axis=None, keepdims=False, **a:
         (lambda x: jnp.nanprod(x, axis=axis, keepdims=keepdims)))
register("ones_like", lambda **a: jnp.ones_like)
register("zeros_like", lambda **a: jnp.zeros_like)
register_alias("_npi_ones_like", "ones_like")
register_alias("_npi_zeros_like", "zeros_like")


def _make_make_loss(grad_scale=1.0, **a):
    """MakeLoss (src/operator/make_loss.cc): identity forward; the backward
    seeds the tape with grad_scale regardless of the incoming gradient."""

    @jax.custom_vjp
    def f(x):
        return x

    f.defvjp(lambda x: (x, None),
             lambda res, g: (jnp.full_like(g, grad_scale),))
    return f


register("make_loss", _make_make_loss)
register_alias("MakeLoss", "make_loss")


def _make_gradmult(scalar=1.0, **a):
    """gradientmultiplier (contrib/gradient_multiplier_op.cc): identity
    forward, gradient scaled by ``scalar`` (gradient-reversal when < 0)."""

    @jax.custom_vjp
    def f(x):
        return x

    f.defvjp(lambda x: (x, None), lambda res, g: (g * scalar,))
    return f


register("gradientmultiplier", _make_gradmult)
register_alias("_contrib_gradientmultiplier", "gradientmultiplier")


def _make_id_kl(sparseness_target=0.1, penalty=0.001, momentum=0.9, **a):
    """IdentityAttachKLSparseReg (src/operator/identity_attach_KL_sparse_reg.cc):
    identity forward; backward adds the KL-divergence sparsity penalty gradient
    penalty * (-t/rho + (1-t)/(1-rho)) where rho is the batch mean activation.
    """
    t = sparseness_target

    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, jnp.clip(jnp.mean(x), 1e-6, 1 - 1e-6)

    def bwd(rho, g):
        return (g + penalty * (-t / rho + (1 - t) / (1 - rho)),)

    f.defvjp(fwd, bwd)
    return f


register("IdentityAttachKLSparseReg", _make_id_kl)

register("_grad_add", lambda **a: jnp.add)
register("add_n", lambda num_args=0, **a:
         (lambda *xs: sum(xs[1:], xs[0])))
register_alias("ElementWiseSum", "add_n")
register("_identity_with_attr_like_rhs", lambda **a:
         (lambda lhs, rhs: lhs), differentiable=False)
register("_npx_constraint_check", lambda msg="constraint violated", **a:
         (lambda x: _constraint_check(x, msg)), differentiable=False)


def _constraint_check(x, msg):
    ok = jnp.all(x)
    # eager path surfaces the failure immediately; under jit the boolean
    # result flows to the caller (reference npx.constraint_check contract)
    try:
        if not bool(ok):
            raise MXNetError(msg)
    except jax.errors.TracerBoolConversionError:
        pass
    return ok


register("div_sqrt_dim", lambda **a:
         (lambda x: x / jnp.sqrt(jnp.asarray(x.shape[-1], x.dtype))))
register_alias("_contrib_div_sqrt_dim", "div_sqrt_dim")

# ---------------------------------------------------------------------------
# creation ops — init_op.cc + numpy/np_init_op.cc (zero-input ops)
# ---------------------------------------------------------------------------
register("zeros", lambda shape=(), dtype="float32", ctx=None, **a:
         (lambda: jnp.zeros(shape, dtype or "float32")),
         differentiable=False)
register("ones", lambda shape=(), dtype="float32", ctx=None, **a:
         (lambda: jnp.ones(shape, dtype or "float32")),
         differentiable=False)
register("full", lambda shape=(), value=0.0, dtype="float32", ctx=None, **a:
         (lambda: jnp.full(shape, value, dtype or "float32")),
         differentiable=False)
register("full_like", lambda fill_value=0.0, dtype=None, **a:
         (lambda x: jnp.full_like(x, fill_value, dtype=dtype)),
         differentiable=False)
register("eye", lambda N=1, M=None, k=0, dtype="float32", ctx=None, **a:
         (lambda: jnp.eye(int(N), M if M is None else int(M), k=int(k),
                          dtype=dtype or "float32")),
         differentiable=False)
# NB: the bare op name `identity` is an alias of `copy` in the reference
# (elemwise_unary_op_basic.cc:245 — elementwise identity over one input);
# only the numpy-namespace `_npi_identity` is the zero-input matrix creator
# (np_init_op.cc). Registering the creator under the bare name would break
# legacy nd.identity(x) callers.
def _make_npi_identity(shape=None, n=None, dtype="float32", ctx=None, **a):
    # reference frontend passes shape=(n, n) (np_init_op.cc IdentityParam);
    # n= kept as a convenience spelling
    if n is None:
        n = shape[0] if shape else 1
    return lambda: jnp.identity(int(n), dtype=dtype or "float32")


register("_npi_identity", _make_npi_identity, differentiable=False)
def _make_arange(start=0, stop=None, step=1.0, repeat=1, dtype="float32",
                 ctx=None, infer_range=False, **a):
    # legacy contract (init_op.cc RangeParam): arange(N) means [0, N)
    lo, hi = (0, start) if stop is None else (start, stop)

    def f():
        out = jnp.arange(lo, hi, step, dtype=dtype)
        return jnp.repeat(out, repeat) if repeat != 1 else out

    return f


register("arange", _make_arange, differentiable=False)
register("linspace", lambda start=0.0, stop=1.0, num=50, endpoint=True,
         dtype="float32", ctx=None, **a:
         (lambda: jnp.linspace(start, stop, int(num), endpoint=endpoint,
                               dtype=dtype)),
         differentiable=False)
register("logspace", lambda start=0.0, stop=1.0, num=50, endpoint=True,
         base=10.0, dtype="float32", ctx=None, **a:
         (lambda: jnp.logspace(start, stop, int(num), endpoint=endpoint,
                               base=base, dtype=dtype)),
         differentiable=False)
register("tri", lambda N=1, M=None, k=0, dtype="float32", ctx=None, **a:
         (lambda: jnp.tri(int(N), M if M is None else int(M), int(k),
                          dtype=dtype)),
         differentiable=False)
register("indices", lambda dimensions=(), dtype="int32", ctx=None, **a:
         (lambda: jnp.indices(tuple(dimensions), dtype=dtype)),
         differentiable=False)
for _alias, _tgt in {
    "_zeros": "zeros", "_zeros_without_dtype": "zeros", "_ones": "ones",
    "_full": "full", "_eye": "eye", "_arange": "arange",
    "_linspace": "linspace",
    "_npi_zeros": "zeros", "_npi_ones": "ones", "_npi_full": "full",
    "_npi_full_like": "full_like", "_npi_eye": "eye",
    "_npi_arange": "arange",
    "_npi_linspace": "linspace", "_npi_logspace": "logspace",
    "_npi_tri": "tri", "_npi_indices": "indices",
}.items():
    register_alias(_alias, _tgt)

# ---------------------------------------------------------------------------
# stack/split variants — np_matrix_op.cc
# ---------------------------------------------------------------------------
register("hstack", lambda **a: (lambda *xs: jnp.hstack(xs)))
register("vstack", lambda **a: (lambda *xs: jnp.vstack(xs)))
register("dstack", lambda **a: (lambda *xs: jnp.dstack(xs)))
register("column_stack", lambda **a: (lambda *xs: jnp.column_stack(xs)))
register("hsplit", lambda indices_or_sections=1, **a:
         (lambda x: tuple(jnp.hsplit(x, indices_or_sections))))
register("dsplit", lambda indices_or_sections=1, **a:
         (lambda x: tuple(jnp.dsplit(x, indices_or_sections))))
for _alias, _tgt in {
    "_npi_hstack": "hstack", "_npi_vstack": "vstack",
    "_npi_dstack": "dstack", "_npi_column_stack": "column_stack",
    "_npi_hsplit": "hsplit", "_npi_dsplit": "dsplit",
}.items():
    register_alias(_alias, _tgt)

# ---------------------------------------------------------------------------
# legacy slice family — matrix_op.cc (slice:700, slice_axis:780, slice_like)
# ---------------------------------------------------------------------------
def _norm_be(b, e, s, dim):
    """Normalize one (begin, end, step) triple to a Python slice."""
    s = 1 if s in (None, 0) else s
    if b is not None and b < 0:
        b += dim
    if e is not None and e < 0:
        e += dim
    return slice(b, e, s)


def _legacy_slice_key(begin, end, step, shape):
    step = tuple(step or ()) + (None,) * (len(begin) - len(step or ()))
    return tuple(_norm_be(b, e, s, d)
                 for b, e, s, d in zip(begin, end, step, shape))


register("slice", lambda begin=(), end=(), step=(), **a:
         (lambda x: x[_legacy_slice_key(begin, end, step, x.shape)]))
register_alias("crop", "slice")
register("slice_axis", lambda axis=0, begin=0, end=None, **a:
         (lambda x: lax.slice_in_dim(
             x, begin if begin >= 0 else x.shape[axis] + begin,
             x.shape[axis] if end is None
             else (end if end >= 0 else x.shape[axis] + end),
             axis=axis)))
register("slice_like", lambda axes=(), **a:
         (lambda x, like: x[tuple(
             slice(0, like.shape[i]) if (not axes or i in tuple(
                 ax + x.ndim if ax < 0 else ax for ax in axes)) else
             slice(None) for i in range(x.ndim))]))
register("broadcast_axis", lambda axis=(), size=(), **a:
         (lambda x: _broadcast_axis(x, axis, size)))
register_alias("broadcast_axes", "broadcast_axis")
register("broadcast_like", lambda lhs_axes=None, rhs_axes=None, **a:
         (lambda x, like: jnp.broadcast_to(x, like.shape)
          if lhs_axes is None else _broadcast_like_axes(
              x, like, lhs_axes, rhs_axes)))
register("reshape_like", lambda lhs_begin=None, lhs_end=None,
         rhs_begin=None, rhs_end=None, **a:
         (lambda x, like: _reshape_like(x, like, lhs_begin, lhs_end,
                                        rhs_begin, rhs_end)))


def _broadcast_axis(x, axis, size):
    axis = (axis,) if isinstance(axis, int) else tuple(axis)
    size = (size,) if isinstance(size, int) else tuple(size)
    shape = list(x.shape)
    for ax, sz in zip(axis, size):
        shape[ax] = sz
    return jnp.broadcast_to(x, tuple(shape))


def _broadcast_like_axes(x, like, lhs_axes, rhs_axes):
    shape = list(x.shape)
    for la, ra in zip(lhs_axes, rhs_axes):
        shape[la] = like.shape[ra]
    return jnp.broadcast_to(x, tuple(shape))


def _reshape_like(x, like, lb, le, rb, re):
    if lb is None and le is None and rb is None and re is None:
        return jnp.reshape(x, like.shape)
    lb = 0 if lb is None else lb
    le = x.ndim if le is None else le
    rb = 0 if rb is None else rb
    re = like.ndim if re is None else re
    new_shape = x.shape[:lb] + like.shape[rb:re] + x.shape[le:]
    return jnp.reshape(x, new_shape)


# legacy Reshape with 0/-1/-2/-3/-4 codes — matrix_op-inl.h InferReshapeShape
def _legacy_reshape_shape(src, spec, reverse=False):
    if reverse:
        src = src[::-1]
        spec = tuple(spec)[::-1]
    out, i = [], 0
    spec = list(spec)
    j = 0
    while j < len(spec):
        c = spec[j]
        if c == 0:
            out.append(src[i]); i += 1
        elif c == -1:
            out.append(-1); i += 1
        elif c == -2:
            out.extend(src[i:]); i = len(src)
        elif c == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif c == -4:
            d1, d2 = spec[j + 1], spec[j + 2]
            if d1 == -1:
                d1 = src[i] // d2
            if d2 == -1:
                d2 = src[i] // d1
            out.extend([d1, d2]); i += 1; j += 2
        else:
            out.append(c); i += 1
        j += 1
    if reverse:
        out = out[::-1]
    return tuple(out)


register("Reshape", lambda shape=(), reverse=False, **a:
         (lambda x: jnp.reshape(
             x, _legacy_reshape_shape(x.shape, shape, reverse))))


def _npx_reshape_shape(src, spec, reverse=False):
    """NumpyXReshape shape codes (np_matrix_op.cc NumpyXReshapeInferShape:202):
    -1 infer one dim, -2 copy the next src dim, -3 skip a size-1 src dim
    (emits nothing), -4 copy ALL remaining src dims, -5 merge two consecutive
    src dims, -6 split one src dim into the two following spec values (one of
    which may be -1). ``reverse=True`` applies the spec right-to-left
    (np_matrix_op.cc:348-354: reverse src and spec, infer, reverse output)."""
    src = list(src)
    spec = list(spec)
    if reverse:
        return tuple(reversed(
            _npx_reshape_shape(src[::-1], spec[::-1], reverse=False)))
    out, i = [], 0
    j = 0
    while j < len(spec):
        c = spec[j]
        if c == -2:
            out.append(src[i]); i += 1
        elif c == -1:
            out.append(-1); i += 1
        elif c == -3:
            if src[i] != 1:
                raise ValueError(
                    "-3 reshape code may only skip a size-1 dimension, "
                    f"got {src[i]} at axis {i}")
            i += 1  # emit nothing
        elif c == -4:
            out.extend(src[i:]); i = len(src)
        elif c == -5:
            out.append(src[i] * src[i + 1]); i += 2
        elif c == -6:
            d0 = src[i]
            d1, d2 = spec[j + 1], spec[j + 2]
            if d1 == -1:
                d1 = d0 // d2
            if d2 == -1:
                d2 = d0 // d1
            out.extend([d1, d2]); i += 1; j += 2
        else:
            out.append(c); i += 1
        j += 1
    return tuple(out)


register("_npx_reshape", lambda newshape=(), reverse=False, **a:
         (lambda x: jnp.reshape(
             x, _npx_reshape_shape(x.shape, newshape, reverse))))

register("SliceChannel", lambda num_outputs=1, axis=1, squeeze_axis=False, **a:
         (lambda x: tuple(
             jnp.squeeze(p, axis) if squeeze_axis else p
             for p in jnp.split(x, num_outputs, axis))),
         nout=2)
register_alias("split_legacy", "SliceChannel")
register("_split_v2", lambda indices=(), axis=0, squeeze_axis=False,
         sections=0, **a:
         (lambda x: tuple(
             jnp.squeeze(p, axis) if squeeze_axis else p
             for p in (jnp.split(x, sections, axis) if sections
                       else jnp.split(x, list(indices), axis)))),
         nout=2)
register("swapaxes_legacy", lambda dim1=0, dim2=0, **a:
         (lambda x: jnp.swapaxes(x, dim1, dim2)))
register("_rnn_param_concat", lambda dim=0, num_args=0, **a:
         (lambda *xs: jnp.concatenate([jnp.ravel(x) for x in xs], 0)))

# ---------------------------------------------------------------------------
# scatter / assignment — indexing_op.cc, matrix_op.cc (_slice_assign:410)
# ---------------------------------------------------------------------------
register("scatter_nd", lambda shape=(), **a:
         (lambda data, ind: jnp.zeros(shape, data.dtype).at[
             tuple(ind[i] for i in range(ind.shape[0]))].add(data)))
register("_scatter_set_nd", lambda shape=(), **a:
         (lambda data, ind: jnp.zeros(shape, data.dtype).at[
             tuple(ind[i] for i in range(ind.shape[0]))].set(data)))
register("_slice_assign", lambda begin=(), end=(), step=(), **a:
         (lambda lhs, rhs: lhs.at[
             _legacy_slice_key(begin, end, step, lhs.shape)].set(rhs)))
register_alias("_crop_assign", "_slice_assign")
register("_slice_assign_scalar", lambda begin=(), end=(), step=(),
         scalar=0.0, **a:
         (lambda lhs: lhs.at[
             _legacy_slice_key(begin, end, step, lhs.shape)].set(scalar)))
register_alias("_crop_assign_scalar", "_slice_assign_scalar")

# ---------------------------------------------------------------------------
# sparse-storage helpers — cast_storage.cc, square_sum.cc, sparse_retain.cc.
# Dense jax arrays are the single storage here (PJRT HBM); RowSparse/CSR
# live in mxnet_tpu.ndarray.sparse as wrappers, so cast_storage on the op
# level is identity over values (the NDArray frontend swaps the wrapper).
# ---------------------------------------------------------------------------
register("cast_storage", lambda stype="default", **a: (lambda x: x))
register("_sparse_retain", lambda **a:
         (lambda data, idx: jnp.zeros_like(data).at[idx].set(data[idx])))
register("square_sum", lambda axis=None, keepdims=False, **a:
         (lambda x: jnp.sum(jnp.square(x), axis=axis, keepdims=keepdims)))
register_alias("_square_sum", "square_sum")

# ---------------------------------------------------------------------------
# multi-tensor helpers — multi_sum_sq.cc, multi_lars.cc, reset_arrays.cc
# ---------------------------------------------------------------------------
register("multi_sum_sq", lambda num_arrays=1, **a:
         (lambda *xs: tuple(jnp.sum(jnp.square(x)) for x in xs)),
         nout=2, differentiable=False)
register("reset_arrays", lambda num_arrays=1, **a:
         (lambda *xs: tuple(jnp.zeros_like(x) for x in xs)),
         nout=2, differentiable=False)


def _multi_lars(eta=0.001, eps=1e-8, rescale_grad=1.0, **a):
    """multi_lars (contrib/multi_lars.cc): layer-wise adaptive LR —
    lr * eta * ||w|| / (||g|| * rescale + wd * ||w|| + eps), with the plain
    lr kept where either norm is zero."""

    def f(lrs, w_sq, g_sq, wds):
        w_n = jnp.sqrt(w_sq)
        g_n = jnp.sqrt(g_sq) * rescale_grad
        adaptive = eta * w_n / (g_n + wds * w_n + eps)
        cond = (w_n > 0) & (g_n > 0)
        return lrs * jnp.where(cond, adaptive, 1.0)

    return f


register("multi_lars", _multi_lars, differentiable=False)

# ---------------------------------------------------------------------------
# histogram — tensor/histogram.cc (static bin_cnt attr, or bin-edges input)
# ---------------------------------------------------------------------------
register("histogram", lambda bin_cnt=None, range=None, **a:
         ((lambda x: tuple(jnp.histogram(x, bins=bin_cnt,
                                         range=tuple(range)
                                         if range else None)))
          if bin_cnt is not None else
          (lambda x, bins: tuple(jnp.histogram(x, bins=bins)))),
         nout=2, differentiable=False)
register_alias("_histogram", "histogram")

# ---------------------------------------------------------------------------
# contrib: index_array (contrib/index_array.cc), share_memory,
# diag_indices_from (np_matrix_op.cc)
# ---------------------------------------------------------------------------
register("index_array", lambda axes=None, **a:
         (lambda x: _index_array(x, axes)), differentiable=False)
register_alias("_contrib_index_array", "index_array")


def _index_array(x, axes):
    grids = jnp.indices(x.shape, dtype=jnp.int32)
    full = jnp.stack([g for g in grids], axis=-1)
    if axes is not None:
        full = full[..., tuple(axes)]
    return full


register("_npi_share_memory", lambda **a:
         (lambda a_, b: jnp.array(False)), differentiable=False)
register("_npi_diag_indices_from", lambda **a:
         (lambda x: tuple(jnp.arange(x.shape[0])
                          for _ in range(x.ndim))),
         nout=2, differentiable=False)

# ---------------------------------------------------------------------------
# legacy NN extras: LRN (nn/lrn.cc), SoftmaxActivation
# (nn/softmax_activation.cc), BatchNormWithReLU / SyncBatchNorm
# (contrib/batch_norm_relu.cc, contrib/sync_batch_norm.cc)
# ---------------------------------------------------------------------------
def _lrn(alpha=1e-4, beta=0.75, knorm=2.0, nsize=5, **a):
    def f(x):
        sq = jnp.square(x)
        half = nsize // 2
        # cross-channel window sum on axis 1 (NCHW): static unrolled sum of
        # shifted slices — fully differentiable and fuses into one HLO
        pads = [(0, 0)] * x.ndim
        pads[1] = (half, half)
        padded = jnp.pad(sq, pads)
        c = x.shape[1]
        ssum = sum(lax.slice_in_dim(padded, k, k + c, axis=1)
                   for k in range(nsize))
        return x / jnp.power(knorm + (alpha / nsize) * ssum, beta)

    return f


register("lrn", _lrn)
register_alias("LRN", "lrn")


def _softmax_activation(mode="instance", **a):
    def f(x):
        if mode == "channel":
            return jax.nn.softmax(x, axis=1)
        flat = x.reshape(x.shape[0], -1)
        return jax.nn.softmax(flat, axis=-1).reshape(x.shape)

    return f


register("softmax_activation", _softmax_activation)
register_alias("SoftmaxActivation", "softmax_activation")


def _bn_with_relu(**attrs):
    bn = get_op("batch_norm")._make_fn(**attrs)

    def f(x, gamma, beta, mmean, mvar):
        out = bn(x, gamma, beta, mmean, mvar)
        y, *rest = out if isinstance(out, tuple) else (out,)
        return (jax.nn.relu(y), *rest)

    return f


register("batch_norm_with_relu", _bn_with_relu, nout=3)
register_alias("_contrib_BatchNormWithReLU", "batch_norm_with_relu")


def _sync_batch_norm(eps=1e-3, momentum=0.9, fix_gamma=True, ndev=1,
                     key="", axis_name=None, **a):
    """SyncBatchNorm: under pjit/shard_map the plain batch_norm already
    computes *global* batch statistics (XLA inserts the all-reduce for the
    mean/var reductions over the sharded batch axis); inside an explicit
    shard_map region pass ``axis_name`` to psum the per-device moments
    (reference semantics: contrib/sync_batch_norm.cc ndev all-reduce)."""

    def f(x, gamma, beta, mmean, mvar):
        g = jnp.ones_like(gamma) if fix_gamma else gamma
        red = tuple(i for i in range(x.ndim) if i != 1)
        mean = jnp.mean(x, axis=red)
        mean_sq = jnp.mean(jnp.square(x), axis=red)
        if axis_name is not None:
            mean = lax.pmean(mean, axis_name)
            mean_sq = lax.pmean(mean_sq, axis_name)
        var = mean_sq - jnp.square(mean)
        shape = [1] * x.ndim
        shape[1] = x.shape[1]
        out = (x - mean.reshape(shape)) * lax.rsqrt(
            var.reshape(shape) + eps) * g.reshape(shape) + beta.reshape(shape)
        new_mean = lax.stop_gradient(momentum * mmean + (1 - momentum) * mean)
        new_var = lax.stop_gradient(momentum * mvar + (1 - momentum) * var)
        return out, new_mean, new_var

    return f


register("sync_batch_norm", _sync_batch_norm, nout=3)
register_alias("_contrib_SyncBatchNorm", "sync_batch_norm")

# dynamic_reshape (contrib/dynamic_shape_ops.cc): shape arrives as a tensor —
# eager-only by design (data-dependent output shape cannot trace under jit;
# same restriction as the reference's dynamic-shape ops under hybridize).
register("_contrib_dynamic_reshape", lambda **a:
         (lambda x, shape: jnp.reshape(x, tuple(int(s) for s in shape))),
         jit=False)
