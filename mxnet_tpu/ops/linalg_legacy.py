"""Legacy `mx.nd.linalg_*` operator family (reference: src/operator/tensor/
la_op.cc — gemm/gemm2/potrf/potri/trmm/trsm/syrk/syevd/gelqf/makediag/
extractdiag/maketrian/extracttrian/sumlogdiag/inverse).

XLA lowerings over jax.lax.linalg / jnp.linalg: batched by construction
(leading dims broadcast), fp32 accumulation on the MXU for the matmul
family. Ops are registered under the reference's exact names so symbolic
scripts using `sym.linalg_gemm2(...)` port unchanged.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = []


def _t(x, transpose):
    return jnp.swapaxes(x, -1, -2) if transpose else x


@register("linalg_gemm")
def _linalg_gemm(transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0,
                 axis=-2):
    def f(a, b, c):
        return alpha * jnp.matmul(_t(a, transpose_a), _t(b, transpose_b)) \
            + beta * c

    return f


@register("linalg_gemm2")
def _linalg_gemm2(transpose_a=False, transpose_b=False, alpha=1.0, axis=-2):
    def f(a, b):
        return alpha * jnp.matmul(_t(a, transpose_a), _t(b, transpose_b))

    return f


@register("linalg_potrf")
def _linalg_potrf(lower=True):
    def f(a):
        ch = jnp.linalg.cholesky(a)
        return ch if lower else jnp.swapaxes(ch, -1, -2)

    return f


@register("linalg_potri")
def _linalg_potri(lower=True):
    """Inverse from a Cholesky factor (reference: potri)."""
    def f(l):  # noqa: E741 — reference operand name
        lt = l if lower else jnp.swapaxes(l, -1, -2)
        eye = jnp.broadcast_to(jnp.eye(lt.shape[-1], dtype=lt.dtype),
                               lt.shape)
        linv = jax.lax.linalg.triangular_solve(
            lt, eye, left_side=True, lower=True)
        return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)

    return f


@register("linalg_trmm")
def _linalg_trmm(transpose=False, rightside=False, lower=True, alpha=1.0):
    def f(a, b):
        tri = jnp.tril(a) if lower else jnp.triu(a)
        tri = _t(tri, transpose)
        return alpha * (jnp.matmul(b, tri) if rightside
                        else jnp.matmul(tri, b))

    return f


@register("linalg_trsm")
def _linalg_trsm(transpose=False, rightside=False, lower=True, alpha=1.0):
    def f(a, b):
        tri = jnp.tril(a) if lower else jnp.triu(a)
        return alpha * jax.lax.linalg.triangular_solve(
            tri, b, left_side=not rightside, lower=lower,
            transpose_a=transpose)

    return f


@register("linalg_syrk")
def _linalg_syrk(transpose=False, alpha=1.0):
    def f(a):
        return alpha * (jnp.matmul(_t(a, True), a) if transpose
                        else jnp.matmul(a, _t(a, True)))

    return f


@register("linalg_syevd", nout=2)
def _linalg_syevd():
    def f(a):
        w, v = jnp.linalg.eigh(a)
        # reference returns (U, lambda) with rows of U the eigenvectors
        return jnp.swapaxes(v, -1, -2), w

    return f


@register("linalg_gelqf", nout=2)
def _linalg_gelqf():
    """LQ factorization A = L Q (reference: gelqf) via QR of Aᵀ."""
    def f(a):
        q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2), mode="reduced")
        return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)

    return f


@register("linalg_makediag")
def _linalg_makediag(offset=0):
    def f(a):
        return jax.vmap(lambda v: jnp.diagflat(v, offset))(
            a.reshape(-1, a.shape[-1])).reshape(
            a.shape[:-1] + (a.shape[-1] + abs(offset),
                            a.shape[-1] + abs(offset))) \
            if a.ndim > 1 else jnp.diagflat(a, offset)

    return f


@register("linalg_extractdiag")
def _linalg_extractdiag(offset=0):
    def f(a):
        return jnp.diagonal(a, offset=offset, axis1=-2, axis2=-1)

    return f


@register("linalg_maketrian")
def _linalg_maketrian(offset=0, lower=True):
    """Pack a vector into a triangular matrix (reference: maketrian).

    Only the main-diagonal packing (offset=0) is implemented; a silent
    wrong-size answer for banded offsets would be worse than an error."""
    from ..base import MXNetError

    if offset != 0:
        raise MXNetError("linalg_maketrian: offset != 0 is not supported")

    def f(a):
        n_elem = a.shape[-1]
        # n*(n+1)/2 = n_elem → n
        n = int((-1 + (1 + 8 * n_elem) ** 0.5) / 2)
        idx = jnp.tril_indices(n) if lower else jnp.triu_indices(n)

        def pack(v):
            m = jnp.zeros((n, n), a.dtype)
            return m.at[idx].set(v)

        flat = a.reshape(-1, n_elem)
        return jax.vmap(pack)(flat).reshape(a.shape[:-1] + (n, n))

    return f


@register("linalg_extracttrian")
def _linalg_extracttrian(offset=0, lower=True):
    def f(a):
        n = a.shape[-1]
        idx = jnp.tril_indices(n, offset) if lower else \
            jnp.triu_indices(n, offset)

        def unpack(m):
            return m[idx]

        flat = a.reshape((-1,) + a.shape[-2:])
        out = jax.vmap(unpack)(flat)
        return out.reshape(a.shape[:-2] + (out.shape[-1],))

    return f


@register("linalg_sumlogdiag")
def _linalg_sumlogdiag():
    def f(a):
        return jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)), -1)

    return f


@register("linalg_inverse")
def _linalg_inverse():
    def f(a):
        return jnp.linalg.inv(a)

    return f


# non-symmetric eigen decompositions (CPU-only in XLA — the reference's
# numpy parity surface; run them on host-backed arrays)
@register("linalg_eig", nout=2, differentiable=False)
def _linalg_eig():
    def f(a):
        return tuple(jnp.linalg.eig(a))

    return f


@register("linalg_eigvals", differentiable=False)
def _linalg_eigvals():
    def f(a):
        return jnp.linalg.eigvals(a)

    return f
