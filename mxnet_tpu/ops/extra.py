"""Op-surface breadth: legacy tensor ops, transformer projection ops,
MultiBox detection trio, window functions, and numpy-parity stragglers.

Reference homes: src/operator/tensor/ (batch_dot dot.cc, reverse, depth/
space ops, khatri_rao la_op.cc), src/operator/contrib/transformer.cc
(interleaved attention matmuls), src/operator/contrib/multibox_*.cc (SSD
anchor machinery), src/operator/nn/im2col, src/operator/numpy/ window fns.
All are pure XLA lowerings — static shapes, MXU-friendly batched matmuls.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import MXNetError
from .registry import register

__all__ = []


# -- batched / structured matmuls -------------------------------------------
@register("batch_dot")
def _batch_dot(transpose_a=False, transpose_b=False):
    def f(a, b):
        x = jnp.swapaxes(a, -1, -2) if transpose_a else a
        y = jnp.swapaxes(b, -1, -2) if transpose_b else b
        return jnp.matmul(x, y)

    return f


@register("khatri_rao")
def _khatri_rao():
    def f(*mats):
        out = mats[0]
        for m in mats[1:]:
            out = (out[:, None, :] * m[None, :, :]).reshape(
                out.shape[0] * m.shape[0], -1)
        return out

    return f


# transformer fused projections (reference: transformer.cc
# _contrib_interleaved_matmul_selfatt_qk/valatt, encdec variants). Layout:
# queries_keys_values (T, B, 3*H*D) interleaved per head.
@register("interleaved_matmul_selfatt_qk")
def _imm_selfatt_qk(heads=1):
    def f(qkv):
        t, b, e3 = qkv.shape
        d = e3 // (3 * heads)
        r = qkv.reshape(t, b, heads, 3, d)
        q = r[..., 0, :].transpose(1, 2, 0, 3)  # (B, H, T, D)
        k = r[..., 1, :].transpose(1, 2, 0, 3)
        scale = 1.0 / (d ** 0.5)
        out = jnp.einsum("bhqd,bhkd->bhqk", q * scale, k)
        return out.reshape(b * heads, t, t)

    return f


@register("interleaved_matmul_selfatt_valatt")
def _imm_selfatt_valatt(heads=1):
    def f(qkv, att):
        t, b, e3 = qkv.shape
        d = e3 // (3 * heads)
        r = qkv.reshape(t, b, heads, 3, d)
        v = r[..., 2, :].transpose(1, 2, 0, 3)          # (B, H, T, D)
        w = att.reshape(b, heads, t, t)
        out = jnp.einsum("bhqk,bhkd->bhqd", w, v)
        return out.transpose(2, 0, 1, 3).reshape(t, b, heads * d)

    return f


@register("interleaved_matmul_encdec_qk")
def _imm_encdec_qk(heads=1):
    def f(q_proj, kv_proj):
        tq, b, e = q_proj.shape
        d = e // heads
        tk = kv_proj.shape[0]
        q = q_proj.reshape(tq, b, heads, d).transpose(1, 2, 0, 3)
        kv = kv_proj.reshape(tk, b, heads, 2, d)
        k = kv[..., 0, :].transpose(1, 2, 0, 3)
        scale = 1.0 / (d ** 0.5)
        out = jnp.einsum("bhqd,bhkd->bhqk", q * scale, k)
        return out.reshape(b * heads, tq, tk)

    return f


@register("interleaved_matmul_encdec_valatt")
def _imm_encdec_valatt(heads=1):
    def f(kv_proj, att):
        tk, b, e2 = kv_proj.shape
        d = e2 // (2 * heads)
        kv = kv_proj.reshape(tk, b, heads, 2, d)
        v = kv[..., 1, :].transpose(1, 2, 0, 3)
        tq = att.shape[1]
        w = att.reshape(b, heads, tq, tk)
        out = jnp.einsum("bhqk,bhkd->bhqd", w, v)
        return out.transpose(2, 0, 1, 3).reshape(tq, b, heads * d)

    return f


# -- layout ops -------------------------------------------------------------
@register("depth_to_space")
def _depth_to_space(block_size=2):
    s = block_size

    def f(x):
        n, c, h, w = x.shape
        r = x.reshape(n, s, s, c // (s * s), h, w)
        return r.transpose(0, 3, 4, 1, 5, 2).reshape(
            n, c // (s * s), h * s, w * s)

    return f


@register("space_to_depth")
def _space_to_depth(block_size=2):
    s = block_size

    def f(x):
        n, c, h, w = x.shape
        r = x.reshape(n, c, h // s, s, w // s, s)
        return r.transpose(0, 3, 5, 1, 2, 4).reshape(
            n, c * s * s, h // s, w // s)

    return f


@register("im2col")
def _im2col(kernel=(3, 3), stride=(1, 1), dilate=(1, 1), pad=(0, 0)):
    """Unfold conv patches to columns (reference: src/operator/nn/im2col).
    (N, C, H, W) → (N, C*kh*kw, L)."""
    kh, kw = kernel
    sh, sw = stride
    dh, dw = dilate
    ph, pw = pad

    def f(x):
        n, c, h, w = x.shape
        xp = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        cols = []
        for i in range(kh):
            for j in range(kw):
                patch = xp[:, :, i * dh:i * dh + oh * sh:sh,
                           j * dw:j * dw + ow * sw:sw]
                cols.append(patch.reshape(n, c, oh * ow))
        col = jnp.stack(cols, axis=2)  # (N, C, kh*kw, L)
        return col.reshape(n, c * kh * kw, oh * ow)

    return f


@register("col2im")
def _col2im(output_size=(4, 4), kernel=(3, 3), stride=(1, 1), dilate=(1, 1),
            pad=(0, 0)):
    """Fold columns back to an image, summing overlaps (im2col's adjoint)."""
    kh, kw = kernel
    sh, sw = stride
    dh, dw = dilate
    ph, pw = pad
    H, W = output_size

    def f(col):
        n = col.shape[0]
        c = col.shape[1] // (kh * kw)
        oh = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        ow = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        colr = col.reshape(n, c, kh * kw, oh, ow)
        out = jnp.zeros((n, c, H + 2 * ph, W + 2 * pw), col.dtype)
        idx = 0
        for i in range(kh):
            for j in range(kw):
                out = out.at[:, :, i * dh:i * dh + oh * sh:sh,
                             j * dw:j * dw + ow * sw:sw].add(
                    colr[:, :, idx])
                idx += 1
        return out[:, :, ph:ph + H, pw:pw + W]

    return f


# -- misc tensor ops --------------------------------------------------------
@register("reverse")
def _reverse(axis=0):
    ax = axis

    def f(x):
        return jnp.flip(x, axis=ax)

    return f


@register("batch_take")
def _batch_take():
    def f(x, idx):
        return jnp.take_along_axis(
            x, idx.astype(jnp.int32)[..., None], axis=-1)[..., 0]

    return f


@register("argmax_channel")
def _argmax_channel():
    def f(x):
        return jnp.argmax(x, axis=1).astype(x.dtype)

    return f


@register("shape_array", differentiable=False)
def _shape_array():
    def f(x):
        return jnp.asarray(x.shape, jnp.int64)

    return f


@register("size_array", differentiable=False)
def _size_array():
    def f(x):
        return jnp.asarray([x.size], jnp.int64)

    return f


@register("arange_like", differentiable=False)
def _arange_like(start=0.0, step=1.0, axis=None):
    def f(x):
        if axis is None:
            n = x.size
            return (start + step * jnp.arange(n)).reshape(x.shape).astype(
                x.dtype)
        n = x.shape[axis]
        return (start + step * jnp.arange(n)).astype(x.dtype)

    return f


@register("allclose", differentiable=False)
def _allclose(rtol=1e-5, atol=1e-8, equal_nan=False):
    def f(a, b):
        return jnp.allclose(a, b, rtol=rtol, atol=atol,
                            equal_nan=equal_nan).reshape(())

    return f


@register("index_copy")
def _index_copy():
    """Copy rows of new_tensor into old_tensor at index (reference:
    _contrib_index_copy)."""
    def f(old, index, new):
        return old.at[index.astype(jnp.int32)].set(new)

    return f


@register("quadratic")
def _quadratic(a=0.0, b=0.0, c=0.0):
    def f(x):
        return a * x * x + b * x + c

    return f


@register("softmin")
def _softmin(axis=-1):
    def f(x):
        return jax.nn.softmax(-x, axis=axis)

    return f


@register("masked_log_softmax")
def _masked_log_softmax(axis=-1):
    def f(x, mask):
        z = jnp.where(mask.astype(bool), x, -jnp.inf)
        out = jax.nn.log_softmax(z, axis=axis)
        return jnp.where(mask.astype(bool), out, -jnp.inf)

    return f


@register("softmax_cross_entropy")
def _softmax_cross_entropy():
    def f(data, label):
        logp = jax.nn.log_softmax(data, axis=-1)
        picked = jnp.take_along_axis(
            logp, label.astype(jnp.int32)[:, None], axis=-1)
        return -picked.sum().reshape((1,))

    return f


@register("amp_cast")
def _amp_cast(dtype="float16"):
    import numpy as onp

    target = jnp.bfloat16 if dtype == "bfloat16" else onp.dtype(dtype)

    def f(x):
        return x.astype(target)

    return f


@register("amp_multicast")
def _amp_multicast(num_outputs=1, cast_narrow=False):
    def f(*xs):
        dts = [x.dtype for x in xs]
        widths = [jnp.dtype(d).itemsize for d in dts]
        pick = min(range(len(xs)), key=lambda i: widths[i]) if cast_narrow \
            else max(range(len(xs)), key=lambda i: widths[i])
        return tuple(x.astype(dts[pick]) for x in xs)

    return f


@register("bipartite_matching", nout=2, differentiable=False)
def _bipartite_matching(threshold=0.5, is_ascend=False, topk=-1):
    """Greedy bipartite matching over a score matrix (reference:
    _contrib_bipartite_matching, bounding_box.cc): rows claim their best
    column, best-scoring rows win conflicts. Static-shape greedy sweep."""
    def match_one(score):
        n_row, n_col = score.shape
        order = jnp.argsort(-score.max(axis=1) if not is_ascend
                            else score.min(axis=1))
        row_match = jnp.full((n_row,), -1, jnp.int32)
        col_used = jnp.zeros((n_col,), bool)

        def body(i, carry):
            rm, cu = carry
            r = order[i]
            s = jnp.where(cu, -jnp.inf if not is_ascend else jnp.inf,
                          score[r])
            c = jnp.argmax(s) if not is_ascend else jnp.argmin(s)
            ok = (score[r, c] >= threshold) if not is_ascend else \
                (score[r, c] <= threshold)
            rm = rm.at[r].set(jnp.where(ok, c.astype(jnp.int32), -1))
            cu = cu.at[c].set(cu[c] | ok)
            return rm, cu

        limit = n_row if topk <= 0 else min(topk, n_row)
        row_match, col_used = jax.lax.fori_loop(0, limit, body,
                                                (row_match, col_used))
        col_match = jnp.full((n_col,), -1, jnp.int32)
        rows = jnp.arange(n_row, dtype=jnp.int32)
        valid = row_match >= 0
        col_match = col_match.at[jnp.where(valid, row_match, n_col)].set(
            jnp.where(valid, rows, -1), mode="drop")
        return row_match.astype(score.dtype), col_match.astype(score.dtype)

    def f(score):
        if score.ndim == 2:
            return match_one(score)
        return jax.vmap(match_one)(score)

    return f


# -- MultiBox (SSD legacy trio — reference: multibox_prior.cc,
#    multibox_target.cc, multibox_detection.cc) -----------------------------
@register("multibox_prior", differentiable=False)
def _multibox_prior(sizes=(1.0,), ratios=(1.0,), clip=False, steps=(-1, -1),
                    offsets=(0.5, 0.5)):
    def f(data):
        h, w = data.shape[-2], data.shape[-1]
        step_y = steps[0] if steps[0] > 0 else 1.0 / h
        step_x = steps[1] if steps[1] > 0 else 1.0 / w
        cy = (jnp.arange(h) + offsets[0]) * step_y
        cx = (jnp.arange(w) + offsets[1]) * step_x
        cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"), -1)  # (h,w,2)
        anchors = []
        # reference enumerates (size[0], ratios...) + (sizes[1:], ratio[0])
        combos = [(sizes[0], r) for r in ratios] + \
                 [(s, ratios[0]) for s in sizes[1:]]
        for s, r in combos:
            aw = s * (r ** 0.5) / 2
            ah = s / (r ** 0.5) / 2
            box = jnp.stack([cyx[..., 1] - aw, cyx[..., 0] - ah,
                             cyx[..., 1] + aw, cyx[..., 0] + ah], -1)
            anchors.append(box)
        out = jnp.stack(anchors, 2).reshape(1, -1, 4)
        if clip:
            out = jnp.clip(out, 0.0, 1.0)
        return out

    return f


@register("multibox_target", nout=3, differentiable=False)
def _multibox_target(overlap_threshold=0.5, negative_mining_ratio=-1.0,
                     variances=(0.1, 0.1, 0.2, 0.2)):
    """Match anchors to labels, emit (loc_target, loc_mask, cls_target).
    label: (B, M, 5) rows [cls, x1, y1, x2, y2], -1 padded."""
    from .vision import _pair_iou

    var = jnp.asarray(variances)

    def one(anchors, cls_pred, label):
        valid = label[:, 0] >= 0
        gt = label[:, 1:5]
        iou = _pair_iou(anchors, gt)               # (N, M)
        iou = jnp.where(valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        pos = best_iou > overlap_threshold
        # reference two-stage matching (multibox_target.cc): every valid
        # ground truth claims its best-IoU anchor unconditionally, THEN
        # the threshold stage adds the rest — without this, a gt whose
        # best anchor is below threshold would go untrained
        m = gt.shape[0]
        best_anchor = jnp.argmax(iou, axis=0)       # (M,)
        best_gt = best_gt.at[best_anchor].set(
            jnp.where(valid, jnp.arange(m), best_gt[best_anchor]))
        pos = pos.at[best_anchor].set(
            jnp.where(valid, True, pos[best_anchor]))
        g = gt[best_gt]
        a_xy = (anchors[:, :2] + anchors[:, 2:]) / 2
        a_wh = jnp.maximum(anchors[:, 2:] - anchors[:, :2], 1e-9)
        g_xy = (g[:, :2] + g[:, 2:]) / 2
        g_wh = jnp.maximum(g[:, 2:] - g[:, :2], 1e-9)
        t = jnp.concatenate([(g_xy - a_xy) / a_wh / var[:2],
                             jnp.log(g_wh / a_wh) / var[2:]], -1)
        loc_t = jnp.where(pos[:, None], t, 0.0).reshape(-1)
        loc_m = jnp.where(pos[:, None],
                          jnp.ones_like(t), 0.0).reshape(-1)
        cls_t = jnp.where(pos, label[best_gt, 0] + 1, 0.0)
        if negative_mining_ratio > 0:
            # hard negative mining (multibox_target.cc MiningBackward):
            # keep only the ratio*num_pos hardest negatives — ranked by
            # max non-background confidence of cls_pred — train the rest
            # as ignore (-1)
            conf = jnp.max(cls_pred[1:, :], axis=0)  # (N,) hardest first
            neg_score = jnp.where(pos, -jnp.inf, conf)
            order = jnp.argsort(-neg_score)          # best negatives first
            rank = jnp.zeros_like(order).at[order].set(
                jnp.arange(order.shape[0]))
            budget = negative_mining_ratio * jnp.sum(pos)
            keep_neg = (~pos) & (rank < budget)
            cls_t = jnp.where(pos | keep_neg, cls_t, -1.0)
        return loc_t, loc_m, cls_t

    def f(anchors, cls_preds, label):
        # cls_preds layout (B, num_classes+1, N) — reference operand order
        anc = anchors.reshape(-1, 4)
        lt, lm, ct = jax.vmap(
            lambda cp, lb: one(anc, cp, lb))(cls_preds, label)
        return lt, lm, ct

    return f


@register("multibox_detection", differentiable=False)
def _multibox_detection(clip=True, threshold=0.01, nms_threshold=0.5,
                        force_suppress=False, nms_topk=-1,
                        variances=(0.1, 0.1, 0.2, 0.2)):
    """Decode predictions + per-class NMS → (B, N, 6) rows
    [cls, score, x1, y1, x2, y2], invalid rows -1."""
    from .registry import get_op

    var = variances

    def f(cls_prob, loc_pred, anchors):
        b, nc, n = cls_prob.shape
        anc = anchors.reshape(-1, 4)
        a_xy = (anc[:, :2] + anc[:, 2:]) / 2
        a_wh = jnp.maximum(anc[:, 2:] - anc[:, :2], 1e-9)
        loc = loc_pred.reshape(b, n, 4)
        v = jnp.asarray(var)
        xy = loc[..., :2] * v[:2] * a_wh + a_xy
        wh = jnp.exp(loc[..., 2:] * v[2:]) * a_wh / 2
        boxes = jnp.concatenate([xy - wh, xy + wh], -1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        score = cls_prob[:, 1:, :]                # drop background row
        cls_id = jnp.argmax(score, axis=1).astype(cls_prob.dtype)
        best = jnp.max(score, axis=1)
        keep = best > threshold
        rows = jnp.concatenate(
            [jnp.where(keep, cls_id, -1.0)[..., None],
             jnp.where(keep, best, -1.0)[..., None], boxes], -1)
        nms = get_op("box_nms").fn(
            overlap_thresh=nms_threshold, valid_thresh=threshold,
            topk=nms_topk, coord_start=2, score_index=1, id_index=0,
            force_suppress=force_suppress)
        return nms(rows)

    return f


# -- window functions + numpy stragglers ------------------------------------
register("blackman", lambda M=10, **a: (lambda: jnp.blackman(M)))
register("hamming", lambda M=10, **a: (lambda: jnp.hamming(M)))
register("hanning", lambda M=10, **a: (lambda: jnp.hanning(M)))


@register("diagflat")
def _diagflat(k=0):
    def f(x):
        return jnp.diagflat(x, k)

    return f


@register("fill_diagonal")
def _fill_diagonal(val=None, wrap=False):
    """numpy.fill_diagonal semantics over flat strides: for 2-D the
    diagonal is ``a.flat[:end:ncols+1]`` with ``end = ncols*ncols`` for
    tall matrices unless ``wrap``; val may be a scalar attr or an array
    operand (tiled like numpy)."""
    def f(x, *val_arr):
        v = val_arr[0] if val_arr else val
        if x.ndim != 2:
            # >2-D requires equal dims (numpy contract)
            n = x.shape[0]
            idx = (jnp.arange(n),) * x.ndim
            return x.at[idx].set(v if not val_arr else
                                 jnp.resize(v, (n,)))
        rows, cols = x.shape
        step = cols + 1
        end = None if (wrap or rows <= cols) else cols * cols
        flat = x.reshape(-1)
        pos = jnp.arange(flat.shape[0])[:end:step]
        vals = jnp.resize(v, pos.shape) if val_arr else \
            jnp.full(pos.shape, v, x.dtype)
        return flat.at[pos].set(vals.astype(x.dtype)).reshape(x.shape)

    return f


@register("rollaxis")
def _rollaxis(axis=0, start=0):
    def f(x):
        return jnp.rollaxis(x, axis, start)

    return f


@register("polyval")
def _polyval():
    def f(p, x):
        return jnp.polyval(p, x)

    return f


@register("tril_indices", differentiable=False)
def _tril_indices(n=1, k=0, m=None):
    def f():
        return tuple(jnp.tril_indices(n, k, m))

    return f


@register("dot_csr")
def _dot_csr(num_rows=0, transpose_a=False):
    """Device CSR × dense product (reference: src/operator/tensor/dot.cc
    CSR forward, python/mxnet/ndarray/sparse.py dot).

    Inputs: values (nnz,), col_ids (nnz,), row_ids (nnz,), dense (K,) or
    (K, N). XLA-native sparse formulation: gather the dense rows each
    stored entry touches, scale by the value, and ``segment_sum`` into the
    output — static shapes throughout, autodiff supplies the dense-side
    (and value-side) gradients.
    """

    def f(values, col_ids, row_ids, dense):
        out_ids = col_ids if transpose_a else row_ids
        gather_ids = row_ids if transpose_a else col_ids
        g = dense[gather_ids]
        contrib = values[:, None] * g if g.ndim > 1 else values * g
        return jax.ops.segment_sum(contrib, out_ids,
                                   num_segments=int(num_rows))

    return f
