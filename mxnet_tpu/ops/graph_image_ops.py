"""Sliding-window attention, DGL graph-sampling, and image/cv operators.

Three reference op families:

- ``_contrib_sldwin_atten_*`` (src/operator/contrib/transformer.cc): banded
  (Longformer-style) attention. TPU-first design: the band is materialized as
  a static-width gather — score/context are dense ``(B, L, H, W)`` einsums
  that XLA tiles straight onto the MXU; per-head dilation arrives as a
  tensor operand exactly like the reference.
- ``_contrib_dgl_*`` + ``_contrib_edge_id``/``_contrib_getnnz``
  (src/operator/contrib/dgl_graph.cc): graph sampling over CSR. The
  reference pins these to CPU (FComputeEx<cpu> only); we keep the same
  contract — eager host-side ops (``jit=False``) over (indptr, indices)
  operands, since data-dependent output shapes cannot trace under jit.
- ``_image_*`` / ``_cv*`` (src/operator/image/*.cc, plugin/opencv): bridges
  onto mxnet_tpu.image's host pipeline (per-sample work stays on host numpy —
  a device round-trip per sample would be a tunnel-latency disaster).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as onp

from .registry import register, register_alias

# ---------------------------------------------------------------------------
# sliding-window attention — contrib/transformer.cc (sldwin_atten_score,
# sldwin_atten_context, sldwin_atten_mask_like)
# ---------------------------------------------------------------------------
def _band_offsets(w, symmetric):
    # symmetric: [-w..w]; causal: [-w..0] (reference band layout)
    return jnp.arange(-w, w + 1) if symmetric else jnp.arange(-w, 1)


def _band_index(L, H, dilation, w, symmetric):
    """idx[h, l, k] = l + offset_k * dilation_h, clipped to [0, L-1];
    also returns the validity mask of the unclipped index."""
    offs = _band_offsets(w, symmetric)              # (W,)
    d = dilation.astype(jnp.int32).reshape(H, 1, 1)  # (H,1,1)
    pos = jnp.arange(L).reshape(1, L, 1)
    raw = pos + offs.reshape(1, 1, -1) * d           # (H, L, W)
    valid = (raw >= 0) & (raw < L)
    return jnp.clip(raw, 0, L - 1), valid


@register("sldwin_atten_score")
def _sldwin_score(w=1, symmetric=True, **a):
    def f(query, key, dilation):
        B, L, H, D = query.shape
        idx, valid = _band_index(L, H, dilation, w, symmetric)
        k_t = key.transpose(0, 2, 1, 3)              # (B,H,L,D)
        kb = k_t[:, jnp.arange(H)[:, None, None], idx, :]  # (B,H,L,W,D)
        q_t = query.transpose(0, 2, 1, 3)            # (B,H,L,D)
        score = jnp.einsum("bhld,bhlwd->bhlw", q_t, kb)
        score = jnp.where(valid[None], score, 0.0)
        return score.transpose(0, 2, 1, 3)           # (B,L,H,W)

    return f


@register("sldwin_atten_context")
def _sldwin_context(w=1, symmetric=True, **a):
    def f(score, value, dilation):
        B, L, H, W = score.shape
        idx, valid = _band_index(L, H, dilation, w, symmetric)
        v_t = value.transpose(0, 2, 1, 3)            # (B,H,L,D)
        vb = v_t[:, jnp.arange(H)[:, None, None], idx, :]  # (B,H,L,W,D)
        s_t = score.transpose(0, 2, 1, 3)            # (B,H,L,W)
        s_t = jnp.where(valid[None], s_t, 0.0)
        ctx = jnp.einsum("bhlw,bhlwd->bhld", s_t, vb)
        return ctx.transpose(0, 2, 1, 3)             # (B,L,H,D)

    return f


@register("sldwin_atten_mask_like")
def _sldwin_mask_like(w=1, symmetric=True, **a):
    def f(score, dilation, val_length):
        B, L, H, W = score.shape
        idx, valid = _band_index(L, H, dilation, w, symmetric)
        vl = val_length.astype(jnp.int32).reshape(B, 1, 1, 1)
        in_len = idx[None] < vl                       # (B,H,L,W)
        pos_ok = (jnp.arange(L).reshape(1, 1, L, 1) < vl)
        mask = valid[None] & in_len & pos_ok
        return mask.transpose(0, 2, 1, 3).astype(score.dtype)

    return f


for _n in ("score", "context", "mask_like"):
    register_alias(f"_contrib_sldwin_atten_{_n}", f"sldwin_atten_{_n}")

# ---------------------------------------------------------------------------
# DGL graph sampling — contrib/dgl_graph.cc. CSR travels as (indptr, indices)
# int operands. Eager/host-only by contract (CPU-pinned in the reference too).
# ---------------------------------------------------------------------------
@register("dgl_adjacency", jit=False, differentiable=False)
def _dgl_adjacency(**a):
    """Adjacency-like CSR with all-ones data (reference _contrib_dgl_adjacency
    returns the graph's adjacency as a CSR of 1s): dense here."""
    def f(indptr, indices):
        ip = onp.asarray(indptr)
        ix = onp.asarray(indices)
        n = ip.shape[0] - 1
        out = onp.zeros((n, n), dtype="float32")
        for u in range(n):
            out[u, ix[ip[u]:ip[u + 1]]] = 1.0
        return jnp.asarray(out)

    return f


@register("dgl_subgraph", nout=2, jit=False, differentiable=False)
def _dgl_subgraph(return_mapping=False, **a):
    """Vertex-induced subgraph: returns (sub_indptr, sub_indices[, eids])."""
    def f(indptr, indices, vids):
        ip, ix = onp.asarray(indptr), onp.asarray(indices)
        vs = onp.asarray(vids).astype("int32")
        relabel = {int(v): i for i, v in enumerate(vs)}
        new_ip = [0]
        new_ix = []
        eids = []
        for v in vs:
            for e in range(int(ip[v]), int(ip[v + 1])):
                u = int(ix[e])
                if u in relabel:
                    new_ix.append(relabel[u])
                    eids.append(e)
            new_ip.append(len(new_ix))
        outs = (jnp.asarray(onp.asarray(new_ip, "int32")),
                jnp.asarray(onp.asarray(new_ix, "int32")))
        if return_mapping:
            outs = outs + (jnp.asarray(onp.asarray(eids, "int32")),)
        return outs

    return f


@register("dgl_csr_neighbor_uniform_sample", nout=2, jit=False,
          differentiable=False, needs_rng=True)
def _dgl_neighbor_uniform(num_hops=1, num_neighbor=2, max_num_vertices=100,
                          **a):
    """Uniform neighbor sampling from seeds (NodeFlow layer 0): returns
    (sampled_vertices padded to max_num_vertices with -1, layer offsets)."""
    def f(key, indptr, indices, seeds):
        ip, ix = onp.asarray(indptr), onp.asarray(indices)
        rng = onp.random.RandomState(
            int(onp.asarray(jax.random.key_data(key)).ravel()[-1] % 2**31))
        frontier = list(dict.fromkeys(int(s) for s in onp.asarray(seeds)))
        seen = list(frontier)
        seen_set = set(seen)
        offsets = [0, len(frontier)]
        for _ in range(num_hops):
            nxt = []
            for v in frontier:
                nbrs = ix[ip[v]:ip[v + 1]]
                if len(nbrs) == 0:
                    continue
                take = rng.choice(nbrs, size=min(num_neighbor, len(nbrs)),
                                  replace=False)
                nxt.extend(int(u) for u in take)
            nxt = [u for u in dict.fromkeys(nxt) if u not in seen_set]
            seen.extend(nxt)
            seen_set.update(nxt)
            frontier = nxt
            offsets.append(len(seen))
        out = onp.full(max_num_vertices, -1, "int32")
        out[:len(seen)] = seen[:max_num_vertices]
        return (jnp.asarray(out),
                jnp.asarray(onp.asarray(offsets, "int32")))

    return f


@register("dgl_csr_neighbor_non_uniform_sample", nout=2, jit=False,
          differentiable=False, needs_rng=True)
def _dgl_neighbor_non_uniform(num_hops=1, num_neighbor=2,
                              max_num_vertices=100, **a):
    """Importance-weighted neighbor sampling: per-vertex probability array
    is the extra operand (reference non-uniform variant)."""
    def f(key, indptr, indices, probability, seeds):
        ip, ix = onp.asarray(indptr), onp.asarray(indices)
        prob = onp.asarray(probability).astype("float64")
        rng = onp.random.RandomState(
            int(onp.asarray(jax.random.key_data(key)).ravel()[-1] % 2**31))
        frontier = list(dict.fromkeys(int(s) for s in onp.asarray(seeds)))
        seen = list(frontier)
        seen_set = set(seen)
        offsets = [0, len(frontier)]
        for _ in range(num_hops):
            nxt = []
            for v in frontier:
                nbrs = ix[ip[v]:ip[v + 1]]
                if len(nbrs) == 0:
                    continue
                p = prob[nbrs]
                total = p.sum()
                if total <= 0:
                    continue  # no reachable neighbor under this measure
                p = p / total
                # without replacement only as many draws as non-zero-prob
                # neighbors exist
                take = rng.choice(
                    nbrs, size=min(num_neighbor, int((p > 0).sum())),
                    replace=False, p=p)
                nxt.extend(int(u) for u in take)
            nxt = [u for u in dict.fromkeys(nxt) if u not in seen_set]
            seen.extend(nxt)
            seen_set.update(nxt)
            frontier = nxt
            offsets.append(len(seen))
        out = onp.full(max_num_vertices, -1, "int32")
        out[:len(seen)] = seen[:max_num_vertices]
        return (jnp.asarray(out),
                jnp.asarray(onp.asarray(offsets, "int32")))

    return f


@register("dgl_graph_compact", nout=2, jit=False, differentiable=False)
def _dgl_graph_compact(return_mapping=False, graph_sizes=(), **a):
    """Relabel a padded vertex-id graph to a compact [0, n) id space."""
    def f(indptr, indices, vids):
        ip, ix = onp.asarray(indptr), onp.asarray(indices)
        vs = [int(v) for v in onp.asarray(vids) if v >= 0]
        relabel = {v: i for i, v in enumerate(vs)}
        new_ip = [0]
        new_ix = []
        for v in vs:
            row = [relabel[int(u)] for u in ix[ip[v]:ip[v + 1]]
                   if int(u) in relabel]
            new_ix.extend(row)
            new_ip.append(len(new_ix))
        return (jnp.asarray(onp.asarray(new_ip, "int32")),
                jnp.asarray(onp.asarray(new_ix, "int32")))

    return f


@register("edge_id", jit=False, differentiable=False)
def _edge_id(**a):
    """edge_id(csr, u, v) -> data index of edge (u,v), -1 if absent
    (contrib/dgl_graph.cc _contrib_edge_id)."""
    def f(indptr, indices, u, v):
        ip, ix = onp.asarray(indptr), onp.asarray(indices)
        us, vs = onp.asarray(u).ravel(), onp.asarray(v).ravel()
        out = onp.full(us.shape, -1, "int32")
        for i, (a_, b_) in enumerate(zip(us, vs)):
            row = ix[ip[int(a_)]:ip[int(a_) + 1]]
            hit = onp.nonzero(row == int(b_))[0]
            if hit.size:
                out[i] = int(ip[int(a_)]) + int(hit[0])
        return jnp.asarray(out)

    return f


register_alias("_contrib_dgl_adjacency", "dgl_adjacency")
register_alias("_contrib_dgl_subgraph", "dgl_subgraph")
register_alias("_contrib_dgl_csr_neighbor_uniform_sample",
               "dgl_csr_neighbor_uniform_sample")
register_alias("_contrib_dgl_csr_neighbor_non_uniform_sample",
               "dgl_csr_neighbor_non_uniform_sample")
register_alias("_contrib_dgl_graph_compact", "dgl_graph_compact")
register_alias("_contrib_edge_id", "edge_id")

register("getnnz", lambda axis=None, **a:
         (lambda x: jnp.count_nonzero(x, axis=axis).astype(jnp.int32)),
         differentiable=False)
register_alias("_contrib_getnnz", "getnnz")

# ---------------------------------------------------------------------------
# image ops — src/operator/image/{resize,crop,normalize}.cc + plugin/opencv
# (_cvimdecode/_cvimread/_cvimresize/_cvcopyMakeBorder). Host-side bridges
# onto mxnet_tpu.image.
# ---------------------------------------------------------------------------
def _img_mod():
    from .. import image as img

    return img


register("image_to_tensor", lambda **a:
         (lambda x: (x.astype(jnp.float32) / 255.0).transpose(
             (2, 0, 1) if x.ndim == 3 else (0, 3, 1, 2))))
register_alias("_image_to_tensor", "image_to_tensor")

register("image_normalize", lambda mean=0.0, std=1.0, **a:
         (lambda x: (x - jnp.asarray(mean, x.dtype).reshape(-1, 1, 1))
          / jnp.asarray(std, x.dtype).reshape(-1, 1, 1)))
register_alias("_image_normalize", "image_normalize")


@register("image_resize", jit=False, differentiable=False)
def _image_resize(size=(), keep_ratio=False, interp=1, **a):
    def f(x):
        img = _img_mod()
        from ..ndarray.ndarray import NDArray

        if keep_ratio and isinstance(size, int):
            # reference image/resize.cc: int size + keep_ratio resizes the
            # shorter edge and preserves aspect
            return img.resize_short(NDArray(x), size, interp=interp)._data
        h, w = (size, size) if isinstance(size, int) else \
            (size[1], size[0])
        out = img.imresize(NDArray(x), w, h, interp=interp)
        return out._data

    return f


register_alias("_image_resize", "image_resize")


@register("image_crop", jit=False, differentiable=False)
def _image_crop(x=0, y=0, width=0, height=0, **a):
    def f(data):
        return data[y:y + height, x:x + width]

    return f


register_alias("_image_crop", "image_crop")


@register("image_random_crop", jit=False, differentiable=False)
def _image_random_crop(size=(), interp=1, **a):
    # randomness comes from the image pipeline's host rng (seeded by
    # mx.random.seed), matching the rest of the host-side augmenters
    def f(data):
        img = _img_mod()
        from ..ndarray.ndarray import NDArray

        out, _ = img.random_crop(NDArray(data),
                                 size if not isinstance(size, int)
                                 else (size, size), interp=interp)
        return out._data

    return f


register_alias("_image_random_crop", "image_random_crop")


@register("image_random_resized_crop", jit=False, differentiable=False)
def _image_random_resized_crop(size=(), scale=(0.08, 1.0),
                               ratio=(0.75, 1.333), interp=1, **a):
    def f(data):
        img = _img_mod()
        from ..ndarray.ndarray import NDArray

        aug = img.RandomSizedCropAug(
            size if not isinstance(size, int) else (size, size),
            scale, ratio, interp)
        return aug(NDArray(data))._data

    return f


register_alias("_image_random_resized_crop", "image_random_resized_crop")


@register("cvimresize", jit=False, differentiable=False)
def _cvimresize(w=0, h=0, interp=1, **a):
    def f(x):
        img = _img_mod()
        from ..ndarray.ndarray import NDArray

        return img.imresize(NDArray(x), w, h, interp=interp)._data

    return f


register_alias("_cvimresize", "cvimresize")


@register("cvcopyMakeBorder", jit=False, differentiable=False)
def _cv_copy_make_border(top=0, bot=0, left=0, right=0, type=0, value=0.0,
                         **a):
    def f(x):
        return jnp.pad(x, ((top, bot), (left, right)) +
                       ((0, 0),) * (x.ndim - 2),
                       constant_values=value)

    return f


register_alias("_cvcopyMakeBorder", "cvcopyMakeBorder")


@register("cvimdecode", jit=False, differentiable=False)
def _cvimdecode(flag=1, to_rgb=True, **a):
    def f(buf):
        img = _img_mod()
        raw = onp.asarray(buf).astype("uint8").tobytes()
        return img.imdecode(raw, flag=flag, to_rgb=to_rgb)._data

    return f


register_alias("_cvimdecode", "cvimdecode")


@register("cvimread", jit=False, differentiable=False)
def _cvimread(filename="", flag=1, to_rgb=True, **a):
    def f():
        img = _img_mod()
        return img.imread(filename, flag=flag, to_rgb=to_rgb)._data

    return f


register_alias("_cvimread", "cvimread")
