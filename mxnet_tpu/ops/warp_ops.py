"""Spatial-warping / deformable operator tier — XLA-native, static shapes.

TPU-native equivalents of the reference's legacy vision families:
- BilinearSampler        (src/operator/bilinear_sampler.cc:235)
- GridGenerator          (src/operator/grid_generator.cc, affine+warp)
- SpatialTransformer     (src/operator/spatial_transformer.cc:224)
- Correlation            (src/operator/correlation.cc)
- DeformableConvolution  (src/operator/deformable_convolution.cc:46)
- ModulatedDeformableConvolution (modulated_deformable_convolution.cc)
- PSROIPooling           (src/operator/contrib/psroi_pooling.cc)
- DeformablePSROIPooling (src/operator/contrib/deformable_psroi_pooling.cc)

Design notes (TPU-first): the reference implements each as a scalar CUDA
kernel over output elements. Here everything is expressed as dense gathers
with bilinear weights plus matmuls so XLA can tile onto the MXU:
- one shared `_sample2d` (zero outside the image, per-corner validity like
  the reference's `between()` checks) serves the sampler, the deformable
  im2col, and the deformable PSROI taps, so all of them get exact autodiff
  gradients through both values and sampling coordinates for free;
- deformable convolution is im2col-with-offsets → ONE grouped matmul per
  batch (the MXU does the work; no per-tap scalar loops);
- correlation/PSROI enumerate their small static tap/bin grids in Python
  (compile-time unrolled), each iteration a vectorized slice-reduce.
"""
from __future__ import annotations

import numpy as onp

import jax
import jax.numpy as jnp

from ..base import MXNetError
from .registry import register

__all__ = []


def _pair(v):
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


def _round_half_away(v):
    """C `round()` semantics (half away from zero) — jnp.round is banker's
    rounding and would shift ROI edges ending in .5 by a pixel."""
    return jnp.sign(v) * jnp.floor(jnp.abs(v) + 0.5)


def _sample2d(feat, y, x):
    """Bilinear-sample ``feat`` (C, H, W) at continuous (y, x) of any shape;
    corners outside the image contribute zero (reference bilinear_sampler.cc
    `between()` semantics). Returns (C,) + y.shape."""
    H, W = feat.shape[-2:]
    y0f = jnp.floor(y)
    x0f = jnp.floor(x)
    wy = (y - y0f).astype(feat.dtype)
    wx = (x - x0f).astype(feat.dtype)
    y0 = y0f.astype(jnp.int32)
    x0 = x0f.astype(jnp.int32)

    def corner(yi, xi, w):
        ok = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        v = feat[:, jnp.clip(yi, 0, H - 1), jnp.clip(xi, 0, W - 1)]
        return v * (w * ok.astype(feat.dtype))

    return (corner(y0, x0, (1 - wy) * (1 - wx)) +
            corner(y0, x0 + 1, (1 - wy) * wx) +
            corner(y0 + 1, x0, wy * (1 - wx)) +
            corner(y0 + 1, x0 + 1, wy * wx))


def _norm_grid_coords(grid, H, W):
    """[-1, 1] normalized grid (B, 2, H', W') → pixel (y, x) coords."""
    x_real = (grid[:, 0] + 1) * (W - 1) / 2
    y_real = (grid[:, 1] + 1) * (H - 1) / 2
    return y_real, x_real


@register("bilinear_sampler")
def _bilinear_sampler(cudnn_off=None):
    """data (B, C, H, W) sampled at grid (B, 2, H', W') in [-1, 1]
    (channel 0 = x, channel 1 = y) → (B, C, H', W')."""

    def f(data, grid):
        H, W = data.shape[-2:]
        y, x = _norm_grid_coords(grid.astype(data.dtype), H, W)
        return jax.vmap(_sample2d)(data, y, x)

    return f


def _affine_grid(theta, target_shape, dtype):
    """theta (B, 6) affine rows [[sx, shx, tx], [shy, sy, ty]] → normalized
    sampling grid (B, 2, H, W) over the [-1, 1]² target raster."""
    th, tw = target_shape
    xs = jnp.linspace(-1.0, 1.0, tw, dtype=dtype)
    ys = jnp.linspace(-1.0, 1.0, th, dtype=dtype)
    yy, xx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(xx)
    base = jnp.stack([xx, yy, ones], 0).reshape(3, th * tw)  # (3, HW)
    out = theta.reshape(-1, 2, 3).astype(dtype) @ base  # (B, 2, HW)
    return out.reshape(-1, 2, th, tw)


@register("grid_generator")
def _grid_generator(transform_type="affine", target_shape=(0, 0)):
    """affine: data (B, 6) → grid (B, 2, H, W) over ``target_shape``.
    warp: data = optical flow (B, 2, H, W) → normalized grid of
    (pixel + flow) positions."""
    tt = transform_type

    def f(data):
        if tt == "affine":
            th, tw = _pair(target_shape)
            if th < 2 or tw < 2:
                raise MXNetError(
                    f"grid_generator(affine) needs target_shape >= (2, 2), "
                    f"got {target_shape}")
            return _affine_grid(data, (th, tw), data.dtype)
        if tt == "warp":
            _, _, H, W = data.shape
            xs = jnp.arange(W, dtype=data.dtype)
            ys = jnp.arange(H, dtype=data.dtype)
            gx = (data[:, 0] + xs[None, None, :]) * (2.0 / (W - 1)) - 1.0
            gy = (data[:, 1] + ys[None, :, None]) * (2.0 / (H - 1)) - 1.0
            return jnp.stack([gx, gy], 1)
        raise MXNetError(f"grid_generator: unknown transform_type {tt!r}")

    return f


@register("spatial_transformer")
def _spatial_transformer(target_shape=(0, 0), transform_type="affine",
                         sampler_type="bilinear", cudnn_off=None):
    """Affine grid from loc (B, 6) + bilinear sampling of data — the STN
    module as one fused op."""
    if transform_type != "affine":
        raise MXNetError("spatial_transformer supports transform_type="
                         f"'affine' only, got {transform_type!r}")
    if sampler_type != "bilinear":
        raise MXNetError("spatial_transformer supports sampler_type="
                         f"'bilinear' only, got {sampler_type!r}")
    th, tw = _pair(target_shape)
    if th < 2 or tw < 2:
        raise MXNetError("spatial_transformer needs target_shape >= (2, 2), "
                         f"got {target_shape}")

    def f(data, loc):
        grid = _affine_grid(loc, (th, tw), data.dtype)
        H, W = data.shape[-2:]
        y, x = _norm_grid_coords(grid, H, W)
        return jax.vmap(_sample2d)(data, y, x)

    return f


@register("correlation")
def _correlation(kernel_size=1, max_displacement=1, stride1=1, stride2=1,
                 pad_size=0, is_multiply=True):
    """FlowNet correlation of two feature maps (B, C, H, W) →
    (B, D², H', W') where D = 2·(max_displacement//stride2) + 1. Each of
    the D² static displacements is one vectorized channel-contraction."""
    k = int(kernel_size)
    md, st1, st2 = int(max_displacement), int(stride1), int(stride2)
    pad = int(pad_size)
    if k % 2 == 0:
        raise MXNetError(f"correlation kernel_size must be odd, got {k}")
    radius = md // st2
    D = 2 * radius + 1

    def f(data1, data2):
        B, C, H, W = data1.shape
        kr = (k - 1) // 2
        border = md + kr
        Hp, Wp = H + 2 * pad, W + 2 * pad
        th = -(-(Hp - 2 * border) // st1)
        tw = -(-(Wp - 2 * border) // st1)
        if th <= 0 or tw <= 0:
            raise MXNetError(
                "correlation: output would be empty — increase pad_size or "
                "input size")
        p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        ys = md + jnp.arange(th) * st1  # kernel-window top-left in p1
        xs = md + jnp.arange(tw) * st1
        sumelems = float(k * k * C)
        chans = []
        for dy in range(-radius, radius + 1):
            for dx in range(-radius, radius + 1):
                acc = jnp.zeros((B, th, tw), p1.dtype)
                for h in range(k):
                    for w in range(k):
                        a = p1[:, :, ys + h, :][:, :, :, xs + w]
                        b = p2[:, :, ys + h + dy * st2, :][
                            :, :, :, xs + w + dx * st2]
                        if is_multiply:
                            acc = acc + jnp.sum(a * b, axis=1)
                        else:
                            acc = acc + jnp.sum(jnp.abs(a - b), axis=1)
                chans.append(acc / sumelems)
        return jnp.stack(chans, 1)

    return f


def _deform_conv_impl(kernel, stride, dilate, pad, num_filter, num_group,
                      num_deformable_group, no_bias, modulated):
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride) if stride else (1, 1)
    dh, dw = _pair(dilate) if dilate else (1, 1)
    ph, pw = _pair(pad) if pad else (0, 0)
    ng, dg = int(num_group), int(num_deformable_group)
    K = kh * kw

    def f(data, offset, *rest):
        # reference input order: data, offset[, mask], weight[, bias]
        # (modulated_deformable_convolution-inl.h:54)
        rest = list(rest)
        mask = rest.pop(0) if modulated else None
        weight = rest.pop(0)
        bias = rest.pop(0) if not no_bias else None
        B, C, H, W = data.shape
        if C % dg or C % ng or num_filter % ng:
            raise MXNetError(
                f"deformable conv: channels {C} / filters {num_filter} not "
                f"divisible by num_deformable_group {dg} / num_group {ng}")
        Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        # base sampling positions per tap t = i*kw + j (on UNPADDED input)
        oy = jnp.arange(Ho) * sh - ph
        ox = jnp.arange(Wo) * sw - pw
        Y = oy[None, :] + (jnp.arange(kh) * dh)[:, None]  # (kh, Ho)
        X = ox[None, :] + (jnp.arange(kw) * dw)[:, None]  # (kw, Wo)
        base_y = jnp.broadcast_to(
            Y[:, None, :, None], (kh, kw, Ho, Wo)).reshape(K, Ho, Wo)
        base_x = jnp.broadcast_to(
            X[None, :, None, :], (kh, kw, Ho, Wo)).reshape(K, Ho, Wo)

        # offsets: (B, dg*2K, Ho, Wo) — per group, channel 2t is Δy of tap
        # t, 2t+1 is Δx (layout of deformable_im2col.h)
        offs = offset.reshape(B, dg, 2 * K, Ho, Wo)
        y = base_y[None, None].astype(data.dtype) + offs[:, :, 0::2]
        x = base_x[None, None].astype(data.dtype) + offs[:, :, 1::2]
        # im2col: sample each deformable group's channel block at that
        # group's (K, Ho, Wo) coordinates via the shared _sample2d — the
        # coords stay per-group (dg blocks), never repeated per channel
        datag = data.reshape(B, dg, C // dg, H, W)
        col = jax.vmap(jax.vmap(_sample2d))(datag, y, x)
        # (B, dg, C/dg, K, Ho, Wo)
        if modulated:
            col = col * mask.reshape(B, dg, 1, K, Ho, Wo)
        col = col.reshape(B, C, K, Ho, Wo)

        # grouped matmul: (F/ng, C/ng·K) @ (C/ng·K, Ho·Wo) per conv group
        wg = weight.reshape(ng, num_filter // ng, (C // ng) * K)

        def project(colb):
            colg = colb.reshape(ng, (C // ng) * K, Ho * Wo)
            o = jnp.einsum("gfk,gkp->gfp", wg.astype(colb.dtype), colg)
            return o.reshape(num_filter, Ho, Wo)

        out = jax.vmap(project)(col)
        if bias is not None:
            out = out + bias[None, :, None, None].astype(out.dtype)
        return out

    return f


@register("deformable_convolution")
def _deformable_convolution(kernel=(3, 3), stride=(1, 1), dilate=(1, 1),
                            pad=(0, 0), num_filter=1, num_group=1,
                            num_deformable_group=1, workspace=1024,
                            no_bias=False, layout=None):
    """DCNv1: inputs (data, offset, weight[, bias]); offset has
    2·K·num_deformable_group channels at the output resolution."""
    return _deform_conv_impl(kernel, stride, dilate, pad, int(num_filter),
                             num_group, num_deformable_group, no_bias,
                             modulated=False)


@register("modulated_deformable_convolution")
def _modulated_deformable_convolution(kernel=(3, 3), stride=(1, 1),
                                      dilate=(1, 1), pad=(0, 0),
                                      num_filter=1, num_group=1,
                                      num_deformable_group=1,
                                      workspace=1024, no_bias=False,
                                      im2col_step=64, layout=None):
    """DCNv2: inputs (data, offset, mask, weight[, bias]); sampled taps are
    scaled by the sigmoid-activated mask (K·dg channels)."""
    return _deform_conv_impl(kernel, stride, dilate, pad, int(num_filter),
                             num_group, num_deformable_group, no_bias,
                             modulated=True)


@register("psroi_pooling")
def _psroi_pooling(spatial_scale=1.0, output_dim=1, pooled_size=7,
                   group_size=0):
    """Position-sensitive ROI pooling (R-FCN): data
    (B, output_dim·gs², H, W), rois (N, 5) → (N, output_dim, P, P). Each
    static (ph, pw) bin averages its own channel slice over the bin's
    integer pixel rectangle (masked mean — XLA-friendly fixed shapes)."""
    P = int(pooled_size)
    gs = int(group_size) or P
    od = int(output_dim)

    def f(data, rois):
        B, C, H, W = data.shape
        if C != od * gs * gs:
            raise MXNetError(
                f"psroi_pooling: data has {C} channels, needs "
                f"output_dim*group_size² = {od * gs * gs}")
        ys = jnp.arange(H, dtype=data.dtype)
        xs = jnp.arange(W, dtype=data.dtype)

        def one(roi):
            feat = data[roi[0].astype(jnp.int32)]
            x1 = _round_half_away(roi[1]) * spatial_scale
            y1 = _round_half_away(roi[2]) * spatial_scale
            x2 = (_round_half_away(roi[3]) + 1.0) * spatial_scale
            y2 = (_round_half_away(roi[4]) + 1.0) * spatial_scale
            rw = jnp.maximum(x2 - x1, 0.1)
            rh = jnp.maximum(y2 - y1, 0.1)
            bh, bw = rh / P, rw / P
            bins = []
            for ph in range(P):
                for pw_ in range(P):
                    hs = jnp.clip(jnp.floor(ph * bh + y1), 0, H)
                    he = jnp.clip(jnp.ceil((ph + 1) * bh + y1), 0, H)
                    ws = jnp.clip(jnp.floor(pw_ * bw + x1), 0, W)
                    we = jnp.clip(jnp.ceil((pw_ + 1) * bw + x1), 0, W)
                    m = (((ys >= hs) & (ys < he))[:, None] &
                         ((xs >= ws) & (xs < we))[None, :]).astype(data.dtype)
                    gh = min(max(ph * gs // P, 0), gs - 1)
                    gw = min(max(pw_ * gs // P, 0), gs - 1)
                    chans = onp.arange(od) * gs * gs + gh * gs + gw
                    sel = feat[chans]  # (od, H, W)
                    area = jnp.sum(m)
                    val = jnp.sum(sel * m[None], axis=(1, 2)) / \
                        jnp.maximum(area, 1.0)
                    bins.append(jnp.where(area > 0, val, 0.0))
            return jnp.stack(bins, -1).reshape(od, P, P)

        return jax.vmap(one)(rois)

    return f


@register("deformable_psroi_pooling")
def _deformable_psroi_pooling(spatial_scale=1.0, output_dim=1, group_size=1,
                              pooled_size=7, part_size=0, sample_per_part=1,
                              trans_std=0.0, no_trans=False):
    """Deformable PSROI pooling (Deformable R-FCN head): bins shift by
    learned normalized offsets from ``trans`` (N, 2·num_classes, ps, ps)
    and average ``sample_per_part²`` bilinear taps per bin."""
    P = int(pooled_size)
    gs = int(group_size)
    od = int(output_dim)
    ps = int(part_size) or P
    spp = int(sample_per_part)

    def f(data, rois, trans=None):
        B, C, H, W = data.shape
        use_trans = not no_trans and trans is not None
        n_cls = int(trans.shape[1]) // 2 if use_trans else 1
        ch_per_cls = od // max(n_cls, 1)

        def one(roi, tr):
            feat = data[roi[0].astype(jnp.int32)]
            x1 = _round_half_away(roi[1]) * spatial_scale - 0.5
            y1 = _round_half_away(roi[2]) * spatial_scale - 0.5
            x2 = (_round_half_away(roi[3]) + 1.0) * spatial_scale - 0.5
            y2 = (_round_half_away(roi[4]) + 1.0) * spatial_scale - 0.5
            rw = jnp.maximum(x2 - x1, 0.1)
            rh = jnp.maximum(y2 - y1, 0.1)
            bh, bw = rh / P, rw / P
            sbh, sbw = bh / spp, bw / spp
            bins = []
            for ph in range(P):
                for pw_ in range(P):
                    part_h = min(ph * ps // P, ps - 1)
                    part_w = min(pw_ * ps // P, ps - 1)
                    gh = min(max(ph * gs // P, 0), gs - 1)
                    gw = min(max(pw_ * gs // P, 0), gs - 1)
                    chans = onp.arange(od) * gs * gs + gh * gs + gw
                    if use_trans:
                        cls = onp.arange(od) // max(ch_per_cls, 1)
                        tx = tr[2 * cls, part_h, part_w] * trans_std
                        ty = tr[2 * cls + 1, part_h, part_w] * trans_std
                    else:
                        tx = ty = jnp.zeros((od,), data.dtype)
                    hs = ph * bh + y1 + ty * rh  # (od,)
                    ws = pw_ * bw + x1 + tx * rw
                    acc = jnp.zeros((od,), data.dtype)
                    cnt = jnp.zeros((od,), data.dtype)
                    sel = feat[chans]  # (od, H, W)
                    idx = jnp.arange(od)
                    for ih in range(spp):
                        for iw in range(spp):
                            hh = hs + ih * sbh
                            ww = ws + iw * sbw
                            ok = ((ww >= -0.5) & (ww <= W - 0.5) &
                                  (hh >= -0.5) & (hh <= H - 0.5))
                            hcl = jnp.clip(hh, 0.0, H - 1.0)
                            wcl = jnp.clip(ww, 0.0, W - 1.0)
                            h0 = jnp.floor(hcl).astype(jnp.int32)
                            w0 = jnp.floor(wcl).astype(jnp.int32)
                            h1 = jnp.minimum(h0 + 1, H - 1)
                            w1 = jnp.minimum(w0 + 1, W - 1)
                            ay = (hcl - h0).astype(data.dtype)
                            ax = (wcl - w0).astype(data.dtype)
                            v = (sel[idx, h0, w0] * (1 - ay) * (1 - ax) +
                                 sel[idx, h0, w1] * (1 - ay) * ax +
                                 sel[idx, h1, w0] * ay * (1 - ax) +
                                 sel[idx, h1, w1] * ay * ax)
                            okf = ok.astype(data.dtype)
                            acc = acc + v * okf
                            cnt = cnt + okf
                    bins.append(jnp.where(cnt > 0, acc / jnp.maximum(cnt, 1),
                                          0.0))
            return jnp.stack(bins, -1).reshape(od, P, P)

        if use_trans:
            return jax.vmap(one)(rois, trans)
        dummy = jnp.zeros((rois.shape[0], 2, ps, ps), data.dtype)
        return jax.vmap(one)(rois, dummy)

    return f
