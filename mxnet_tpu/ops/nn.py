"""Neural-network operators (the npx.* surface backing Gluon layers).

TPU-native equivalent of src/operator/nn/* (conv, FC, BN, LN, GN, pooling,
softmax, dropout, activation) and src/operator/contrib/transformer.cc
(attention projections). Design notes:

- Convs/matmuls lower to lax.conv_general_dilated / jnp.matmul → MXU. The
  reference's cuDNN algo autotuning (src/operator/nn/cudnn/) has no analog:
  XLA picks the conv emitter.
- BatchNorm is functional: in training mode it RETURNS updated running stats
  (out, new_mean, new_var) and the Gluon layer writes them back; the moving
  stats are stop_gradient'ed (the reference mutates aux states in-kernel).
- Dropout is an rng op (needs_rng): the PRNG key is threaded in by the
  registry; under CachedOp the key becomes an explicit input so every compiled
  call gets fresh randomness (the reference used per-op random resources,
  include/mxnet/resource.h:39).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register


# ---------------------------------------------------------------------------
# fully connected — reference: src/operator/nn/fully_connected.cc
# ---------------------------------------------------------------------------
@register("fully_connected")
def _fc(no_bias=False, flatten=True, num_hidden=0):
    def f(x, w, *b):
        if flatten and x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        y = jnp.matmul(x, w.T)
        if not no_bias:
            y = y + b[0]
        return y

    return f


# ---------------------------------------------------------------------------
# convolution — reference: src/operator/nn/convolution.cc
# ---------------------------------------------------------------------------
def _conv_dnums(ndim, layout):
    if layout is None:
        layout = {3: "NCW", 4: "NCHW", 5: "NCDHW"}[ndim]
    spatial = layout[2:] if layout[1] == "C" else layout[1:-1]
    rhs = "OI" + spatial
    return layout, rhs, layout


@register("convolution")
def _convolution(kernel=(), stride=(), dilate=(), pad=(), num_filter=0,
                 num_group=1, no_bias=False, layout=None):
    def f(x, w, *b):
        nd = x.ndim
        lhs_l, rhs_l, out_l = _conv_dnums(nd, layout)
        nsp = nd - 2
        strides = tuple(stride) if stride else (1,) * nsp
        dil = tuple(dilate) if dilate else (1,) * nsp
        pads = tuple(pad) if pad else (0,) * nsp
        # no preferred_element_type: the MXU accumulates bf16 convs in f32
        # internally, and a widened output dtype breaks the conv transpose
        # rule under grad
        y = lax.conv_general_dilated(
            x, w,
            window_strides=strides,
            padding=[(p, p) for p in pads],
            rhs_dilation=dil,
            dimension_numbers=(lhs_l, rhs_l, out_l),
            feature_group_count=num_group,
        )
        if not no_bias:
            c_axis = out_l.index("C")
            bshape = [1] * nd
            bshape[c_axis] = b[0].shape[0]
            y = y + b[0].reshape(bshape)
        return y

    return f


@register("deconvolution")
def _deconvolution(kernel=(), stride=(), dilate=(), pad=(), adj=(),
                   num_filter=0, num_group=1, no_bias=False, layout=None):
    def f(x, w, *b):
        if num_group != 1:
            # grouped transpose conv: split channels, run per group, concat
            # (lax.conv_transpose has no feature_group_count)
            lhs_l, _, out_l = _conv_dnums(x.ndim, layout)
            c_axis = lhs_l.index("C")
            xs = jnp.split(x, num_group, axis=c_axis)
            ws = jnp.split(w, num_group, axis=0)
            parts = [_deconv_one(xi, wi, (), kernel, stride, dilate, pad,
                                 adj, True, layout)
                     for xi, wi in zip(xs, ws)]
            y = jnp.concatenate(parts, axis=out_l.index("C"))
            if not no_bias:
                bshape = [1] * x.ndim
                bshape[out_l.index("C")] = b[0].shape[0]
                y = y + b[0].reshape(bshape)
            return y
        return _deconv_one(x, w, b, kernel, stride, dilate, pad, adj,
                           no_bias, layout)

    return f


def _deconv_one(x, w, b, kernel, stride, dilate, pad, adj, no_bias, layout):
    nd = x.ndim
    lhs_l, rhs_l, out_l = _conv_dnums(nd, layout)
    nsp = nd - 2
    strides = tuple(stride) if stride else (1,) * nsp
    pads = tuple(pad) if pad else (0,) * nsp
    adjs = tuple(adj) if adj else (0,) * nsp
    dil = tuple(dilate) if dilate else (1,) * nsp
    k = tuple(kernel)
    # MXNet semantics: out = (in-1)*s + d*(k-1) + 1 - 2p + adj
    # lax explicit padding pads the stride-dilated input directly:
    # out = (in-1)*s + 1 + pl + ph - k_eff + 1 with k_eff = d*(k-1)+1
    # => pl = k_eff - 1 - p, ph = pl + adj
    keff = [dil[i] * (k[i] - 1) + 1 for i in range(nsp)]
    padding = [(keff[i] - 1 - pads[i], keff[i] - 1 - pads[i] + adjs[i])
               for i in range(nsp)]
    y = lax.conv_transpose(
        x, w,
        strides=strides,
        padding=padding,
        rhs_dilation=dil,
        dimension_numbers=(lhs_l, rhs_l, out_l),
        transpose_kernel=True,
    )
    if not no_bias:
        c_axis = out_l.index("C")
        bshape = [1] * nd
        bshape[c_axis] = b[0].shape[0]
        y = y + b[0].reshape(bshape)
    return y


# ---------------------------------------------------------------------------
# pooling — reference: src/operator/nn/pooling.cc
# ---------------------------------------------------------------------------
@register("pooling")
def _pooling(kernel=(), pool_type="max", stride=(), pad=(), global_pool=False,
             count_include_pad=True, layout=None, ceil_mode=False):
    def f(x):
        nd = x.ndim
        lay = layout or {3: "NCW", 4: "NCHW", 5: "NCDHW"}[nd]
        sp_axes = tuple(i for i, c in enumerate(lay) if c not in "NC")
        if global_pool:
            if pool_type == "max":
                return jnp.max(x, axis=sp_axes, keepdims=True)
            return jnp.mean(x, axis=sp_axes, keepdims=True)
        nsp = len(sp_axes)
        k = tuple(kernel)
        strides = tuple(stride) if stride else (1,) * nsp
        pads = tuple(pad) if pad else (0,) * nsp
        wdims = [1] * nd
        wstr = [1] * nd
        wpad = [(0, 0)] * nd
        for i, ax in enumerate(sp_axes):
            wdims[ax] = k[i]
            wstr[ax] = strides[i]
            extra = 0
            if ceil_mode:
                # include the last partial window (reference pooling.cc
                # ceil rounding): pad right so the window grid covers it
                span = x.shape[ax] + 2 * pads[i] - k[i]
                rem = span % strides[i]
                if rem:
                    extra = strides[i] - rem
            wpad[ax] = (pads[i], pads[i] + extra)
        if pool_type == "max":
            init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
                jnp.iinfo(x.dtype).min
            return lax.reduce_window(x, init, lax.max, wdims, wstr, wpad)
        s = lax.reduce_window(x, 0.0, lax.add, wdims, wstr, wpad)
        has_extra = any(hi != lo for lo, hi in wpad)
        if count_include_pad and not has_extra:
            # constant divisor fast path (the default config)
            denom = 1
            for i in range(nsp):
                denom *= k[i]
            return s / denom
        # divisor (reference pool.h:468-479): symmetric padding counts when
        # count_include_pad, but the ceil-mode extra region NEVER does — so
        # count window positions over a mask that is 1 on data (+sym pad if
        # include_pad) and 0 on the ceil extra
        ones = jnp.ones(x.shape, jnp.float32)
        if count_include_pad:
            mask_pad = [(lo, lo) for lo, _ in wpad]  # symmetric part = 1s
            ones = jnp.pad(ones, mask_pad, constant_values=1.0)
            extra_pad = [(0, hi - lo) for lo, hi in wpad]
            cnt = lax.reduce_window(ones, 0.0, lax.add, wdims, wstr,
                                    extra_pad)
        else:
            cnt = lax.reduce_window(ones, 0.0, lax.add, wdims, wstr, wpad)
        return s / cnt.astype(s.dtype)

    return f


@register("adaptive_avg_pool2d")
def _adaptive_avg_pool2d(output_size=1):
    osz = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)

    def f(x):  # NCHW
        n, c, h, w = x.shape
        if osz == (1, 1):
            return jnp.mean(x, axis=(2, 3), keepdims=True)
        if h % osz[0] == 0 and w % osz[1] == 0:
            x = x.reshape(n, c, osz[0], h // osz[0], osz[1], w // osz[1])
            return jnp.mean(x, axis=(3, 5))
        raise MXNetError("adaptive_avg_pool2d requires divisible sizes on TPU")

    return f


# ---------------------------------------------------------------------------
# normalization — reference: nn/batch_norm.cc, nn/layer_norm.cc, nn/group_norm.cc
# ---------------------------------------------------------------------------
@register("batch_norm")
def _batch_norm(eps=1e-5, momentum=0.9, fix_gamma=True, use_batch_stats=True,
                axis=1):
    def f(x, gamma, beta, moving_mean, moving_var):
        g = jnp.ones_like(gamma) if fix_gamma else gamma
        ax = axis if axis >= 0 else x.ndim + axis  # normalize negative axis
        red = tuple(i for i in range(x.ndim) if i != ax)
        shape = [1] * x.ndim
        shape[ax] = x.shape[ax]
        if use_batch_stats:
            mean = jnp.mean(x, axis=red)
            var = jnp.var(x, axis=red)
            new_mean = lax.stop_gradient(
                momentum * moving_mean + (1 - momentum) * mean)
            new_var = lax.stop_gradient(
                momentum * moving_var + (1 - momentum) * var)
        else:
            mean, var = moving_mean, moving_var
            new_mean, new_var = moving_mean, moving_var
        inv = lax.rsqrt(var.astype(jnp.float32) + eps).astype(x.dtype)
        out = (x - mean.reshape(shape).astype(x.dtype)) * \
            (g * inv).reshape(shape).astype(x.dtype) + \
            beta.reshape(shape).astype(x.dtype)
        return out, new_mean, new_var

    return f


@register("layer_norm")
def _layer_norm(axis=-1, eps=1e-5):
    def f(x, gamma, beta):
        ax = axis if axis >= 0 else x.ndim + axis
        if ax == x.ndim - 1:
            # fused row-norm kernel on TPU (Pallas), XLA formula elsewhere
            from .pallas_kernels import fused_layer_norm

            return fused_layer_norm(x, gamma, beta, eps)
        mean = jnp.mean(x, axis=axis, keepdims=True)
        var = jnp.var(x, axis=axis, keepdims=True)
        inv = lax.rsqrt(var.astype(jnp.float32) + eps).astype(x.dtype)
        shape = [1] * x.ndim
        shape[ax] = x.shape[ax]
        return (x - mean) * inv * gamma.reshape(shape) + beta.reshape(shape)

    return f


@register("group_norm")
def _group_norm(num_groups=1, eps=1e-5):
    def f(x, gamma, beta):  # NC...
        n, c = x.shape[0], x.shape[1]
        rest = x.shape[2:]
        xg = x.reshape(n, num_groups, c // num_groups, *rest)
        red = tuple(range(2, xg.ndim))
        mean = jnp.mean(xg, axis=red, keepdims=True)
        var = jnp.var(xg, axis=red, keepdims=True)
        xg = (xg - mean) * lax.rsqrt(var + eps)
        out = xg.reshape(x.shape)
        shape = [1] * x.ndim
        shape[1] = c
        return out * gamma.reshape(shape) + beta.reshape(shape)

    return f


@register("instance_norm")
def _instance_norm(eps=1e-5):
    def f(x, gamma, beta):  # NC...
        red = tuple(range(2, x.ndim))
        mean = jnp.mean(x, axis=red, keepdims=True)
        var = jnp.var(x, axis=red, keepdims=True)
        shape = [1] * x.ndim
        shape[1] = x.shape[1]
        return (x - mean) * lax.rsqrt(var + eps) * gamma.reshape(shape) + \
            beta.reshape(shape)

    return f


@register("rms_norm")
def _rms_norm(axis=-1, eps=1e-6):
    def f(x, gamma):
        ms = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axis,
                      keepdims=True)
        return (x * lax.rsqrt(ms + eps).astype(x.dtype)) * gamma

    return f


# ---------------------------------------------------------------------------
# activations — reference: nn/activation.cc, leaky_relu.cc
# ---------------------------------------------------------------------------
@register("activation")
def _activation(act_type="relu"):
    table = {
        "relu": jax.nn.relu,
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "softrelu": jax.nn.softplus,
        "softsign": jax.nn.soft_sign,
        "log_sigmoid": jax.nn.log_sigmoid,
        "mish": jax.nn.mish,
        # reference HardSigmoid (leaky_relu.cc): clip(0.2*x + 0.5, 0, 1) —
        # NOT jax.nn.hard_sigmoid, whose slope is 1/6
        "hard_sigmoid": lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0),
        "hard_swish": jax.nn.hard_swish,
        "silu": jax.nn.silu,
    }
    if act_type not in table:
        raise MXNetError(f"unknown activation {act_type!r}")
    return table[act_type]


@register("leaky_relu")
def _leaky_relu(act_type="leaky", slope=0.25):
    if act_type == "leaky":
        return lambda x: jax.nn.leaky_relu(x, slope)
    if act_type == "elu":
        return lambda x: jax.nn.elu(x, slope)
    if act_type == "selu":
        return jax.nn.selu
    if act_type == "gelu":
        return lambda x: jax.nn.gelu(x, approximate=False)
    if act_type == "gelu_tanh":
        return lambda x: jax.nn.gelu(x, approximate=True)
    if act_type == "prelu":
        return lambda x, alpha: jnp.where(x >= 0, x, alpha * x)
    raise MXNetError(f"unknown leaky_relu variant {act_type!r}")


@register("softmax")
def _softmax(axis=-1, temperature=None, use_length=False):
    def f(x, *length):
        z = x / temperature if temperature not in (None, 1.0) else x
        if use_length:
            mask = _length_mask(x, length[0], axis)
            z = jnp.where(mask, z, -jnp.inf)
        return jax.nn.softmax(z, axis=axis)

    return f


@register("log_softmax")
def _log_softmax(axis=-1, temperature=None):
    def f(x):
        z = x / temperature if temperature not in (None, 1.0) else x
        return jax.nn.log_softmax(z, axis=axis)

    return f


@register("masked_softmax")
def _masked_softmax(axis=-1, temperature=1.0):
    def f(x, mask):
        z = x / temperature if temperature != 1.0 else x
        z = jnp.where(mask.astype(bool), z, -jnp.inf)
        out = jax.nn.softmax(z, axis=axis)
        return jnp.where(mask.astype(bool), out, 0.0)

    return f


def _length_mask(x, length, axis):
    ax = axis if axis >= 0 else x.ndim + axis
    idx = jnp.arange(x.shape[ax])
    shape = [1] * x.ndim
    shape[ax] = x.shape[ax]
    idx = idx.reshape(shape)
    lshape = [1] * x.ndim
    lshape[0] = x.shape[0]
    return idx < length.reshape(lshape)


# ---------------------------------------------------------------------------
# dropout — reference: nn/dropout.cc (rng resource -> explicit key input)
# ---------------------------------------------------------------------------
@register("dropout", needs_rng=True)
def _dropout(p=0.5, mode="training", training=True):
    def f(key, x):
        if not training or p <= 0.0:
            return x
        keep = 1.0 - p
        mask = jax.random.bernoulli(key, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)

    return f


# ---------------------------------------------------------------------------
# embedding / sequence — reference: indexing_op.cc (Embedding), sequence_*.cc
# ---------------------------------------------------------------------------
@register("embedding")
def _embedding(input_dim=0, output_dim=0, sparse_grad=False):
    def f(idx, weight):
        return jnp.take(weight, idx.astype(jnp.int32), axis=0)

    return f


@register("sequence_mask")
def _sequence_mask(use_sequence_length=False, value=0.0, axis=0):
    def f(x, *length):
        if not use_sequence_length:
            return x
        seq_ax = axis
        idx = jnp.arange(x.shape[seq_ax])
        shape = [1] * x.ndim
        shape[seq_ax] = x.shape[seq_ax]
        idx = idx.reshape(shape)
        batch_ax = 1 - seq_ax
        lshape = [1] * x.ndim
        lshape[batch_ax] = x.shape[batch_ax]
        mask = idx < length[0].reshape(lshape)
        return jnp.where(mask, x, value)

    return f


@register("sequence_reverse")
def _sequence_reverse(use_sequence_length=False, axis=0):
    def f(x, *length):
        if not use_sequence_length:
            return jnp.flip(x, axis=axis)
        # per-example reverse of the first `length` steps (seq axis 0)
        T = x.shape[0]
        t = jnp.arange(T)[:, None]
        ln = length[0][None, :].astype(jnp.int32)
        src = jnp.where(t < ln, ln - 1 - t, t)  # (T, B)
        b = jnp.arange(x.shape[1])[None, :]
        return x[src, b]

    return f


@register("sequence_last")
def _sequence_last(use_sequence_length=False, axis=0):
    def f(x, *length):
        if not use_sequence_length:
            return x[-1] if axis == 0 else jnp.take(x, x.shape[axis] - 1, axis)
        idx = (length[0].astype(jnp.int32) - 1)  # (B,)
        b = jnp.arange(x.shape[1])
        return x[idx, b]

    return f


# ---------------------------------------------------------------------------
# losses / misc — reference: smooth_l1, pick (indexing_op.cc)
# ---------------------------------------------------------------------------
@register("pick")
def _pick(axis=-1, keepdims=False, mode="clip"):
    def f(x, idx):
        i = jnp.expand_dims(idx.astype(jnp.int32), axis)
        out = jnp.take_along_axis(x, i, axis=axis)
        return out if keepdims else jnp.squeeze(out, axis)

    return f


@register("smooth_l1")
def _smooth_l1(scalar=1.0):
    def f(x):
        s2 = scalar * scalar
        return jnp.where(jnp.abs(x) < 1.0 / s2, 0.5 * s2 * x * x,
                         jnp.abs(x) - 0.5 / s2)

    return f


@register("ctc_loss")
def _ctc_loss(use_data_lengths=False, use_label_lengths=False, blank_label="first"):
    import optax

    def f(data, label, *lens):
        # data: (T, B, V) logits; label: (B, L)
        logits = jnp.transpose(data, (1, 0, 2))  # (B, T, V)
        B, T, V = logits.shape
        i = 0
        if use_data_lengths:
            dl = lens[i].astype(jnp.int32)
            i += 1
        else:
            dl = jnp.full((B,), T, jnp.int32)
        if use_label_lengths:
            ll = lens[i].astype(jnp.int32)
        else:
            ll = jnp.sum((label >= 0) & (label != 0), axis=-1).astype(jnp.int32) \
                if blank_label == "first" else \
                jnp.sum(label >= 0, axis=-1).astype(jnp.int32)
        t = jnp.arange(T)[None, :]
        logit_pad = (t >= dl[:, None]).astype(jnp.float32)
        L = label.shape[1]
        lt = jnp.arange(L)[None, :]
        label_pad = (lt >= ll[:, None]).astype(jnp.float32)
        lab = label.astype(jnp.int32)
        if blank_label == "first":
            blank_id = 0
        else:
            blank_id = V - 1
        return optax.ctc_loss(logits, logit_pad, lab, label_pad,
                              blank_id=blank_id)

    return f


# attention — reference: src/operator/contrib/transformer.cc. The unmasked
# path routes through the Pallas flash-attention kernel (online softmax,
# no O(T^2) materialization); arbitrary masks use the XLA path.
@register("multihead_attention")
def _multihead_attention(num_heads=1, dropout=0.0, causal=False, scale=None,
                         num_kv_heads=None):
    """``num_kv_heads`` (beyond the reference): grouped-query / multi-query
    attention — k/v carry ``num_kv_heads`` heads, each shared by
    ``num_heads // num_kv_heads`` query heads (the modern LLM KV-cache
    shrink). Default None = classic MHA."""
    n_kv = num_heads if num_kv_heads is None else int(num_kv_heads)
    if n_kv < 1 or num_heads % n_kv:
        raise MXNetError(
            f"num_kv_heads must be a positive divisor of num_heads "
            f"{num_heads}, got {num_kv_heads}")

    def f(q, k, v, *mask):
        # q: (B, T, num_heads*D); k/v: (B, T, n_kv*D)
        B, Tq, E = q.shape
        Tk = k.shape[1]
        D = E // num_heads
        qh = q.reshape(B, Tq, num_heads, D).transpose(0, 2, 1, 3)
        kh = k.reshape(B, Tk, n_kv, D).transpose(0, 2, 1, 3)
        vh = v.reshape(B, Tk, n_kv, D).transpose(0, 2, 1, 3)
        if n_kv != num_heads:
            # materializing stopgap: the repeat restores (B, H, T, D) for
            # the shared kernels; the GQA input/KV-cache stays n_kv-sized,
            # but attention-time KV traffic matches MHA until the Pallas
            # kernel grows a native grouped-heads mode (XLA typically folds
            # the broadcast into the batched matmul on the dense path)
            reps = num_heads // n_kv
            kh = jnp.repeat(kh, reps, axis=1)
            vh = jnp.repeat(vh, reps, axis=1)
        s = scale if scale is not None else 1.0 / (D ** 0.5)
        if not mask:
            from .pallas_kernels import flash_attention

            out = flash_attention(qh, kh, vh, s, causal)
        elif mask[0].ndim == 4 and mask[0].shape[1] == 1 and \
                mask[0].shape[2] == 1 and mask[0].shape[0] == B and \
                mask[0].shape[3] == Tk and Tq == Tk:
            # key-padding mask (B, 1, 1, Tk), constant over heads and
            # queries: express as segment ids (valid=its mask value,
            # padding=0) and stay on the fused flash path. Matches the
            # dense-mask branch for every row with >=1 valid key; a fully
            # masked row emits zeros here vs ~uniform softmax there
            # (documented in npx.multihead_attention)
            from .pallas_kernels import flash_attention

            seg = (mask[0].reshape(B, Tk) != 0).astype(jnp.int32)
            out = flash_attention(qh, kh, vh, s, causal,
                                  q_segment_ids=jnp.ones_like(seg),
                                  kv_segment_ids=seg)
        else:
            logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * s
            if causal:
                # bottom-right aligned (decode with cached KV: Tk >= Tq)
                cm = jnp.tril(jnp.ones((Tq, Tk), bool), Tk - Tq)
                logits = jnp.where(cm, logits, -1e30)
            logits = jnp.where(mask[0].astype(bool), logits, -1e30)
            w = jax.nn.softmax(logits, axis=-1)
            out = jnp.einsum("bhqk,bhkd->bhqd", w, vh)
        return out.transpose(0, 2, 1, 3).reshape(B, Tq, E)

    return f


@register("flash_attention")
def _flash_attention_op(num_heads=1, causal=False, scale=None):
    def f(q, k, v, *segments):
        # canonical layout (B, H, T, D); rank-2/3 operands (headless
        # attention, e.g. the optimize_for rewrite of a 3-D matmul chain)
        # are lifted to 4-D and the output restored — the kernel itself is
        # rank-4 only. Optional 4th/5th inputs: (B, Tq)/(B, Tk) segment
        # ids (one id given → used for both sides), keeping padded/packed
        # batches on the fused path
        from .pallas_kernels import flash_attention

        ndim = q.ndim
        if ndim == 2:
            qq, kk, vv = (a[None, None] for a in (q, k, v))
        elif ndim == 3:
            qq, kk, vv = (a[:, None] for a in (q, k, v))
        elif ndim == 4:
            qq, kk, vv = q, k, v
        else:
            raise MXNetError(
                f"flash_attention expects rank 2-4 operands, got {ndim}")
        q_seg = k_seg = None
        if segments:
            q_seg = segments[0]
            k_seg = segments[1] if len(segments) > 1 else segments[0]
            if ndim == 2:
                q_seg, k_seg = q_seg[None], k_seg[None]
        out = flash_attention(qq, kk, vv, scale, causal,
                              q_segment_ids=q_seg, kv_segment_ids=k_seg)
        if ndim == 2:
            return out[0, 0]
        if ndim == 3:
            return out[:, 0]
        return out

    return f
