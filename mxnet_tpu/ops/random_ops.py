"""Random-sampling operators (registry ops with explicit PRNG-key operand).

TPU-native equivalent of the reference's random op families:

- ``_random_*``  — shape+attr samplers (src/operator/random/sample_op.cc)
- ``_sample_*``  — per-row parameter tensors: params of shape ``(B,)`` with
  ``shape=(S,)`` produce ``(B, S)`` draws (src/operator/random/
  multisample_op.cc)
- ``_npi_*``     — numpy.random internals (src/operator/numpy/random/*.cc)

Design: every sampler is a registered op with ``needs_rng=True`` — invoke()
prepends a fresh PRNG key operand, so the op stays a *pure* function. Under
CachedOp tracing the key becomes a fresh-per-call input, which is what makes
replayed graphs produce fresh randomness (the reference reaches the same goal
with the kRandom resource, resource_manager; here it is explicit dataflow,
the jax idiom — and it shards trivially under pjit).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, register_alias

_F = {"float32": jnp.float32, "float64": jnp.float64,
      "float16": jnp.float16, "bfloat16": jnp.bfloat16,
      None: jnp.float32, "None": jnp.float32}


def _dt(dtype):
    return _F.get(dtype, dtype)


def _shp(shape):
    if shape is None:
        return ()
    return (shape,) if isinstance(shape, int) else tuple(shape)


# ---------------------------------------------------------------------------
# shape+attr samplers — sample_op.cc (params are static attrs)
# ---------------------------------------------------------------------------
register("_random_uniform", lambda low=0.0, high=1.0, shape=(),
         dtype="float32", ctx=None, **a:
         (lambda key: jax.random.uniform(key, _shp(shape), _dt(dtype),
                                         low, high)),
         needs_rng=True, differentiable=False)
register("_random_normal", lambda loc=0.0, scale=1.0, shape=(),
         dtype="float32", ctx=None, **a:
         (lambda key: loc + scale * jax.random.normal(key, _shp(shape),
                                                      _dt(dtype))),
         needs_rng=True, differentiable=False)
register("_random_gamma", lambda alpha=1.0, beta=1.0, shape=(),
         dtype="float32", ctx=None, **a:
         (lambda key: beta * jax.random.gamma(key, alpha, _shp(shape),
                                              _dt(dtype))),
         needs_rng=True, differentiable=False)
register("_random_exponential", lambda lam=1.0, shape=(), dtype="float32",
         ctx=None, **a:
         (lambda key: jax.random.exponential(key, _shp(shape),
                                             _dt(dtype)) / lam),
         needs_rng=True, differentiable=False)
register("_random_poisson", lambda lam=1.0, shape=(), dtype="float32",
         ctx=None, **a:
         (lambda key: jax.random.poisson(key, lam, _shp(shape)).astype(
             _dt(dtype))),
         needs_rng=True, differentiable=False)
register("_random_negative_binomial", lambda k=1, p=1.0, shape=(),
         dtype="float32", ctx=None, **a:
         (lambda key: _neg_binomial(key, k, p, _shp(shape), _dt(dtype))),
         needs_rng=True, differentiable=False)
register("_random_generalized_negative_binomial",
         lambda mu=1.0, alpha=1.0, shape=(), dtype="float32", ctx=None, **a:
         (lambda key: _gen_neg_binomial(key, mu, alpha, _shp(shape),
                                        _dt(dtype))),
         needs_rng=True, differentiable=False)
register("_random_randint", lambda low=0, high=1, shape=(), dtype="int32",
         ctx=None, **a:
         (lambda key: jax.random.randint(key, _shp(shape), low, high,
                                         dtype)),
         needs_rng=True, differentiable=False)


def _neg_binomial(key, k, p, shape, dtype):
    """NB(k, p) as Gamma–Poisson mixture (the reference samples the same
    way: sampler.h NegativeBinomialSampler)."""
    kg, kp = jax.random.split(key)
    lam = jax.random.gamma(kg, k, shape) * (1.0 - p) / p
    return jax.random.poisson(kp, lam, shape).astype(dtype)


def _gen_neg_binomial(key, mu, alpha, shape, dtype):
    kg, kp = jax.random.split(key)
    r = 1.0 / alpha
    beta = mu * alpha
    lam = jax.random.gamma(kg, r, shape) * beta
    return jax.random.poisson(kp, lam, shape).astype(dtype)


# ---------------------------------------------------------------------------
# per-row parameter samplers — multisample_op.cc: params are tensor inputs,
# draw `shape` samples per parameter row
# ---------------------------------------------------------------------------
def _rowwise(sampler, nparam):
    def make(shape=(), dtype="float32", **a):
        s = _shp(shape)

        def f(key, *params):
            if len(params) != nparam:
                raise ValueError(
                    f"sampler expects {nparam} parameter tensor(s), "
                    f"got {len(params)}")
            out_shape = params[0].shape + s
            broad = [jnp.reshape(p, p.shape + (1,) * len(s))
                     for p in params]
            return sampler(key, broad, out_shape).astype(_dt(dtype))

        return f

    return make


register("_sample_uniform",
         _rowwise(lambda key, p, sh: jax.random.uniform(
             key, sh, minval=0.0, maxval=1.0) * (p[1] - p[0]) + p[0], 2),
         needs_rng=True, differentiable=False)
register("_sample_normal",
         _rowwise(lambda key, p, sh: p[0] + p[1] * jax.random.normal(
             key, sh), 2),
         needs_rng=True, differentiable=False)
register("_sample_gamma",
         _rowwise(lambda key, p, sh: p[1] * jax.random.gamma(
             key, jnp.broadcast_to(p[0], sh), sh), 2),
         needs_rng=True, differentiable=False)
register("_sample_exponential",
         _rowwise(lambda key, p, sh: jax.random.exponential(
             key, sh) / p[0], 1),
         needs_rng=True, differentiable=False)
register("_sample_poisson",
         _rowwise(lambda key, p, sh: jax.random.poisson(
             key, jnp.broadcast_to(p[0], sh), sh).astype(jnp.float32), 1),
         needs_rng=True, differentiable=False)
register("_sample_negative_binomial",
         _rowwise(lambda key, p, sh: _nb_rows(key, p[0], p[1], sh), 2),
         needs_rng=True, differentiable=False)
register("_sample_generalized_negative_binomial",
         _rowwise(lambda key, p, sh: _gnb_rows(key, p[0], p[1], sh), 2),
         needs_rng=True, differentiable=False)


def _nb_rows(key, k, p, shape):
    kg, kp = jax.random.split(key)
    lam = jax.random.gamma(kg, jnp.broadcast_to(k, shape), shape) \
        * (1.0 - p) / p
    return jax.random.poisson(kp, lam, shape).astype(jnp.float32)


def _gnb_rows(key, mu, alpha, shape):
    kg, kp = jax.random.split(key)
    r = 1.0 / alpha
    lam = jax.random.gamma(kg, jnp.broadcast_to(r, shape), shape) \
        * (mu * alpha)
    return jax.random.poisson(kp, lam, shape).astype(jnp.float32)


def _make_sample_multinomial(shape=(), get_prob=False, dtype="int32", **a):
    """_sample_multinomial (multisample_op.cc): data rows are probability
    vectors; draw `shape` categorical indices per row."""
    s = _shp(shape)

    def f(key, probs):
        logits = jnp.log(jnp.clip(probs, 1e-30, None))
        batch, ncat = probs.shape[:-1], probs.shape[-1]
        expanded = jnp.broadcast_to(
            logits.reshape(batch + (1,) * len(s) + (ncat,)),
            batch + s + (ncat,))
        out = jax.random.categorical(key, expanded).astype(dtype)
        if get_prob:
            lp = jnp.take_along_axis(
                expanded, out.astype(jnp.int32)[..., None], axis=-1)[..., 0]
            return out, lp
        return out

    return f


register("_sample_multinomial", _make_sample_multinomial,
         needs_rng=True, differentiable=False, nout=1)


def categorical_counts(key, pv, n, shape):
    """Draw ``n`` categorical samples from 1-D probabilities ``pv`` and
    return per-category counts, shape ``shape + (len(pv),)``. Shared by the
    ``_npi_multinomial`` op and ``mx.random.multinomial``. Counts are int32
    (int64 would be silently truncated under JAX's default x64-off config).
    Uses bincount per draw row, so peak memory is O(size*n + size*ncat) —
    no one-hot (size, n, ncat) intermediate."""
    ncat = pv.shape[-1]
    draws = jax.random.categorical(
        key, jnp.log(jnp.clip(pv, 1e-30, None)), shape=tuple(shape) + (n,))
    flat = draws.reshape(-1, n)
    cnt = jax.vmap(lambda d: jnp.bincount(d, length=ncat))(flat)
    return cnt.reshape(tuple(shape) + (ncat,)).astype(jnp.int32)


def _make_npi_multinomial(n=1, pvals=None, size=None, **a):
    """numpy.random.multinomial (np_multinomial_op.cc): draw ``n`` samples
    from one categorical distribution and return per-category counts with
    shape ``size + (num_categories,)``. Distinct from the legacy
    ``_sample_multinomial`` index sampler (multisample_op.cc), which draws
    categorical *indices* per probability row."""
    s = _shp(size)
    n = int(n)
    if pvals is not None:
        attr_pvals = jnp.asarray(pvals, jnp.float32)
        return lambda key: categorical_counts(key, attr_pvals, n, s)
    return lambda key, pv: categorical_counts(key, pv, n, s)


register("_npi_multinomial", _make_npi_multinomial,
         needs_rng=True, differentiable=False, nout=1)

register("_shuffle", lambda **a:
         (lambda key, x: jax.random.permutation(key, x)),
         needs_rng=True, differentiable=False)
register_alias("shuffle", "_shuffle")

# ---------------------------------------------------------------------------
# numpy.random internals — np_random_op.cc family
# ---------------------------------------------------------------------------
register("_npi_uniform", lambda low=0.0, high=1.0, size=None,
         dtype="float32", ctx=None, **a:
         (lambda key: jax.random.uniform(key, _shp(size), _dt(dtype),
                                         low, high)),
         needs_rng=True, differentiable=False)
register("_npi_normal", lambda loc=0.0, scale=1.0, size=None,
         dtype="float32", ctx=None, **a:
         (lambda key: loc + scale * jax.random.normal(key, _shp(size),
                                                      _dt(dtype))),
         needs_rng=True, differentiable=False)
register("_npi_bernoulli", lambda prob=0.5, logit=None, size=None,
         dtype="float32", is_logit=False, ctx=None, **a:
         (lambda key: jax.random.bernoulli(
             key, jax.nn.sigmoid(logit) if is_logit else prob,
             _shp(size)).astype(_dt(dtype))),
         needs_rng=True, differentiable=False)
register("_npi_exponential", lambda scale=1.0, size=None, ctx=None,
         dtype="float32", **a:
         (lambda key: scale * jax.random.exponential(key, _shp(size),
                                                     _dt(dtype))),
         needs_rng=True, differentiable=False)
register("_npi_gumbel", lambda loc=0.0, scale=1.0, size=None, ctx=None,
         dtype="float32", **a:
         (lambda key: loc + scale * jax.random.gumbel(key, _shp(size),
                                                      _dt(dtype))),
         needs_rng=True, differentiable=False)
register("_npi_laplace", lambda loc=0.0, scale=1.0, size=None, ctx=None,
         dtype="float32", **a:
         (lambda key: loc + scale * jax.random.laplace(key, _shp(size),
                                                       _dt(dtype))),
         needs_rng=True, differentiable=False)
register("_npi_logistic", lambda loc=0.0, scale=1.0, size=None, ctx=None,
         dtype="float32", **a:
         (lambda key: loc + scale * jax.random.logistic(key, _shp(size),
                                                        _dt(dtype))),
         needs_rng=True, differentiable=False)
register("_npi_pareto", lambda a=1.0, size=None, ctx=None,
         dtype="float32", **kw:
         (lambda key: jax.random.pareto(key, a, _shp(size),
                                        _dt(dtype)) - 1.0),
         needs_rng=True, differentiable=False)
register("_npi_rayleigh", lambda scale=1.0, size=None, ctx=None,
         dtype="float32", **a:
         (lambda key: scale * jnp.sqrt(
             -2.0 * jnp.log(jax.random.uniform(
                 key, _shp(size), _dt(dtype), 1e-7, 1.0)))),
         needs_rng=True, differentiable=False)
register("_npi_weibull", lambda a=1.0, size=None, ctx=None,
         dtype="float32", **kw:
         (lambda key: jnp.power(
             -jnp.log(jax.random.uniform(key, _shp(size), _dt(dtype),
                                         1e-7, 1.0)), 1.0 / a)),
         needs_rng=True, differentiable=False)
register("_npi_gamma", lambda shape=1.0, scale=1.0, size=None, ctx=None,
         dtype="float32", **a:
         (lambda key: scale * jax.random.gamma(key, shape, _shp(size),
                                               _dt(dtype))),
         needs_rng=True, differentiable=False)
register("_npi_choice", lambda a=1, size=None, replace=True, weights=None,
         ctx=None, **kw:
         (lambda key, *p: jax.random.choice(
             key, int(a), _shp(size), replace=replace,
             p=p[0] if p else None)),
         needs_rng=True, differentiable=False)
register("_npi_normal_n", lambda loc=0.0, scale=1.0, size=None,
         dtype="float32", ctx=None, **a:
         (lambda key, *p: _param_n(
             key, p, (loc, scale), _shp(size), _dt(dtype),
             lambda k, l_, s_, sh: l_ + s_ * jax.random.normal(
                 k, sh))),
         needs_rng=True, differentiable=False)
register("_npi_uniform_n", lambda low=0.0, high=1.0, size=None,
         dtype="float32", ctx=None, **a:
         (lambda key, *p: _param_n(
             key, p, (low, high), _shp(size), _dt(dtype),
             lambda k, lo, hi, sh: jax.random.uniform(
                 k, sh, minval=0.0, maxval=1.0) * (hi - lo) + lo)),
         needs_rng=True, differentiable=False)


def _param_n(key, tensor_params, attr_params, size, dtype, draw):
    """``*_n`` variants (np_random_op.cc): params may arrive as tensors;
    the output shape is size + broadcast(param shapes)."""
    p = list(tensor_params) + list(attr_params[len(tensor_params):])
    a0 = jnp.asarray(p[0], dtype)
    a1 = jnp.asarray(p[1], dtype)
    bshape = jnp.broadcast_shapes(a0.shape, a1.shape)
    return draw(key, a0, a1, size + bshape).astype(dtype)
