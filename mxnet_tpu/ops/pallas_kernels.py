"""Pallas TPU kernels: flash attention, fused layer norm, fused softmax.

TPU-native replacement for the reference's hand-fused CUDA ops
(src/operator/contrib/transformer.cc fused attention projections,
nn/layer_norm.* CUDA kernels, softmax-inl.h) and the NVRTC pointwise fusion
engine (src/operator/fusion/fused_op.*). XLA already fuses elementwise chains;
these kernels cover what XLA won't fuse on its own — the attention
softmax(QK^T)V chain is materialization-bound at O(T^2) without an online-
softmax kernel.

Design:
- flash attention fwd is a Pallas kernel (online softmax, tiled over KV
  blocks, accumulation in fp32 VMEM scratch); backward is a blockwise
  recompute (two lax.scans over KV blocks, standard flash-bwd identities) so
  training memory stays O(T * block) — a hand-written Pallas bwd kernel is a
  possible further optimization.
- kernels engage only on the TPU backend with aligned shapes; everywhere else
  the mathematically identical XLA reference path runs, so the CPU test mesh
  exercises the same API.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

import os as _os

_NEG_INF = -1e30

# flash-attention tile-size floors (Mosaic minimum tiles: 8 sublanes on
# the Q axis, 128 lanes on the K axis)
_MIN_BLOCK_Q = 8
_MIN_BLOCK_K = 128


def _validated_block_env(name, default, min_tile) -> int:
    """Block size from env var ``name`` — read PER CALL, not at import,
    so tests and the tuner can vary it without reloading the module.
    Must be a power of two >= the Mosaic minimum tile for its axis."""
    from ..base import MXNetError

    raw = _os.environ.get(name, "")
    if not raw:
        return default
    try:
        v = int(raw)
    except ValueError:
        raise MXNetError(
            f"{name}={raw!r} is not an integer; expected a power of two "
            f">= {min_tile}") from None
    if v < min_tile or (v & (v - 1)) != 0:
        raise MXNetError(
            f"{name}={v} is invalid: flash-attention block sizes must be "
            f"powers of two >= {min_tile} (the Mosaic minimum tile for "
            "this axis)")
    return v


def flash_block_q() -> int:
    """Default Q-axis tile (``MXTPU_FLASH_BLOCK_Q``, default 256) — the
    starting point the tuner measures against, not a frozen constant."""
    return _validated_block_env("MXTPU_FLASH_BLOCK_Q", 256, _MIN_BLOCK_Q)


def flash_block_k() -> int:
    """Default K-axis tile (``MXTPU_FLASH_BLOCK_K``, default 512)."""
    return _validated_block_env("MXTPU_FLASH_BLOCK_K", 512, _MIN_BLOCK_K)


def _on_tpu() -> bool:
    from ..context import _is_tpu_platform, default_backend

    try:
        return _is_tpu_platform(default_backend())
    except RuntimeError:
        return False


def _interpret() -> bool:
    """Run Pallas kernels in interpreter mode (works on the CPU test mesh) —
    lets the kernel code paths be exercised without TPU hardware."""
    import os

    return os.environ.get("MXTPU_PALLAS_INTERPRET", "") == "1"


def _use_pallas() -> bool:
    return _HAVE_PALLAS and (_on_tpu() or _interpret())


# ---------------------------------------------------------------------------
# tuned-config resolution (trace-time only — block sizes are static args
# of the compiled programs, so steady state never pays a lookup)
# ---------------------------------------------------------------------------
def _tune_cache():
    from ..tune import cache

    return cache


def _resolve_attention_blocks(kernel, q, k, causal, seg):
    """(block_q, block_k) for this trace, or None for the XLA lowering.

    ``kernel`` is ``"flash_fwd"`` or ``"flash_bwd"`` — the two are tuned
    independently (their grids iterate opposite axes). With tuning off
    this returns the env-default blocks, byte-identical to the pre-tuner
    behavior; with tuning on, a miss or a tuned Pallas loss returns None
    so the caller takes the XLA path (never silently slower)."""
    tc = _tune_cache()
    cfg = tc.resolve(kernel, tc.key_attention(
        kernel, q.shape, k.shape, q.dtype, causal, seg))
    if cfg == "default":
        return flash_block_q(), flash_block_k()
    if cfg == "xla":
        return None
    return (int(cfg.get("block_q", flash_block_q())),
            int(cfg.get("block_k", flash_block_k())))


def _resolve_block_rows(kernel, rows, d, dtype):
    """block_rows for a row-wise kernel (``"layer_norm"``/``"softmax"``),
    or 0 for the XLA lowering."""
    tc = _tune_cache()
    cfg = tc.resolve(kernel, tc.key_rows(kernel, rows, d, dtype))
    if cfg == "default":
        return 128
    if cfg == "xla":
        return 0
    return int(cfg.get("block_rows", 128))


# ---------------------------------------------------------------------------
# reference (XLA) attention — also the vjp recompute path
# ---------------------------------------------------------------------------
def _attention_reference(q, k, v, scale, causal, q_seg=None, k_seg=None):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    masked = None
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        masked = jnp.tril(jnp.ones((tq, tk), bool), tk - tq)[None, None]
    if q_seg is not None:
        seg = q_seg[:, None, :, None] == k_seg[:, None, None, :]
        masked = seg if masked is None else (masked & seg)
    if masked is None:
        p = jax.nn.softmax(s, axis=-1)
    else:
        s = jnp.where(masked, s, _NEG_INF)
        # where-masked softmax: a fully masked query row yields zeros, not
        # a uniform distribution (matters for padded batches)
        m = jnp.max(s, axis=-1, keepdims=True)
        e = jnp.where(masked, jnp.exp(s - m), 0.0)
        p = e / jnp.maximum(jnp.sum(e, axis=-1, keepdims=True), 1e-30)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# flash attention forward kernel
# ---------------------------------------------------------------------------
def _flash_fwd_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, block_q,
                      block_k, seq_k, causal_offset=0, use_seg=False):
    if use_seg:
        qs_ref, ks_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    qb = pl.program_id(1)
    q = q_ref[0]  # (BQ, D) — stays in input dtype so the MXU runs bf16
    num_kb = seq_k // block_k

    m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

    def body(kb, _):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        # bf16 (or f32) operands, fp32 accumulation on the MXU
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (BQ, BK) f32
        ok = None
        if causal:
            # bottom-right alignment (matches _attention_reference and the
            # custom_vjp backward): query i attends keys <= i + (Tk - Tq)
            qi = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            ki = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            ok = qi + causal_offset >= ki
        if use_seg:
            # tokens attend within their segment only (padding tokens get a
            # segment id of their own, so padded keys never contribute)
            qs = qs_ref[:].reshape(block_q, 1)
            ks = ks_ref[0, pl.ds(kb * block_k, block_k)].reshape(1, block_k)
            seg_ok = qs == ks
            ok = seg_ok if ok is None else (ok & seg_ok)
        if ok is not None:
            s = jnp.where(ok, s, _NEG_INF)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if ok is not None:
            # zero p under the COMBINED mask: _NEG_INF is finite, so a row
            # with no visible keys in this block has s == m_new and p would
            # otherwise be 1 everywhere (fully masked rows must emit zeros,
            # matching the XLA reference and the bwd kernels)
            p = jnp.where(ok, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new
        return 0

    jax.lax.fori_loop(0, num_kb, body, 0)
    # fully masked rows (l == 0) output zeros, not NaN
    o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)
    # log-sum-exp per query row: saved for the backward kernels, which
    # reconstruct p = exp(s - lse) without a second online-softmax pass
    lse_ref[0] = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))


try:  # pallas imports are deferred-safe: CPU-only installs still work
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except Exception:  # noqa: BLE001
    _HAVE_PALLAS = False


def _flash_attention_tpu(q, k, v, scale, causal, block_q, block_k,
                         return_lse=False, q_seg=None, k_seg=None):
    """q,k,v: (B, H, T, D) with T % block == 0, D % 128 == 0 (pre-padded).
    q_seg/k_seg: optional (B, T) int32 segment ids."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    qr = q.reshape(b * h, tq, d)
    kr = k.reshape(b * h, tk, d)
    vr = v.reshape(b * h, tk, d)
    use_seg = q_seg is not None
    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_k=tk,
        causal_offset=tk - tq, use_seg=use_seg)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qb: (bh, qb, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, tk, d), lambda bh, qb: (bh, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, tk, d), lambda bh, qb: (bh, 0, 0),
                     memory_space=pltpu.VMEM),
    ]
    operands = [qr, kr, vr]
    if use_seg:
        # segment ids are per-batch; grid dim 0 runs over b*h fused heads
        in_specs += [
            pl.BlockSpec((1, block_q), lambda bh, qb: (bh // h, qb),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tk), lambda bh, qb: (bh // h, 0),
                         memory_space=pltpu.VMEM),
        ]
        operands += [q_seg.astype(jnp.int32), k_seg.astype(jnp.int32)]
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, tq // block_q),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qb: (bh, qb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda bh, qb: (bh, qb, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * tq * tk * d,
            bytes_accessed=(qr.size + kr.size + vr.size) * qr.dtype.itemsize,
            transcendentals=b * h * tq * tk,
        ),
        interpret=_interpret(),
    )(*operands)
    out = out.reshape(b, h, tq, d)
    if return_lse:
        return out, lse.reshape(b, h, tq, 1)
    return out


def _pad_to(x, axis, multiple):
    size = x.shape[axis]
    rem = size % multiple
    if rem == 0:
        return x, size
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, multiple - rem)
    return jnp.pad(x, pad), size


def flash_attention(q, k, v, scale=None, causal=False, q_segment_ids=None,
                    kv_segment_ids=None):
    """Fused attention over (B, H, T, D) operands.

    Pallas online-softmax kernel on TPU; identical XLA math elsewhere.
    ``q_segment_ids``/``kv_segment_ids`` are optional (B, T) int arrays:
    tokens attend only within matching segment ids, which
    covers BERT key-padding masks (valid tokens id 1, padding id 0) and
    packed sequences — without materializing an O(T²) mask.

    Ragged sequence lengths (not block-divisible, e.g. BERT T=384) stay on
    the fused path: operands are padded to block shape and the padding is
    hidden behind sentinel segment ids, then the output is sliced back.

    Block sizes resolve once per TRACE through the tuning tier
    (``tune.cache``): env defaults when tuning is off, the persisted
    per-bucket winner when on, block 0 (= the XLA lowering) on a miss or
    a tuned Pallas loss. They ride the custom_vjp as nondiff args so the
    backward sees the same forward decision.
    """
    if kv_segment_ids is None:
        kv_segment_ids = q_segment_ids
    if q_segment_ids is None:
        q_segment_ids = kv_segment_ids
    if _use_pallas():
        blocks = _resolve_attention_blocks("flash_fwd", q, k, causal,
                                           q_segment_ids is not None)
    else:
        # no Pallas here: blocks are inert (the reference path runs), so
        # skip the tuning tier — a CPU process logs no spurious misses
        blocks = (flash_block_q(), flash_block_k())
    if blocks is None:
        bq = bk = 0  # sentinel: XLA lowering
    else:
        bq, bk = blocks
        if _use_pallas():
            tq, tk = q.shape[2], k.shape[2]
            ok = _axis_tiles(tq, bq) and _axis_tiles(tk, bk)
            if not ok and (not causal or tq == tk):
                # under causal, padding both seqs by the SAME amount
                # preserves the bottom-right alignment offset (tk - tq);
                # with tq != tk that cannot be guaranteed, so those rare
                # shapes fall back
                return _flash_attention_padded(q, k, v, scale, causal,
                                               q_segment_ids,
                                               kv_segment_ids, bq, bk)
    if q_segment_ids is None:
        return _flash_attention_plain(q, k, v, scale, causal, bq, bk)
    return _flash_attention_seg(q, k, v,
                                q_segment_ids.astype(jnp.int32),
                                kv_segment_ids.astype(jnp.int32),
                                scale, causal, bq, bk)


def _block_padded_len(t, block):
    """Next multiple of ``block`` >= t. Reached only when some axis fails
    to tile; any t <= its own block size tiles trivially because the
    block clamps to min(block, t)."""
    return -(-t // block) * block


def _axis_tiles(t, block):
    return t % min(block, t) == 0


def _flash_attention_padded(q, k, v, scale, causal, q_seg, k_seg,
                            block_q, block_k):
    b, _, tq, d = q.shape
    tk = k.shape[2]
    if causal:  # tq == tk here: one common padded length keeps the offset
        lq = lk = max(_block_padded_len(tq, block_q),
                      _block_padded_len(tk, block_k))
    else:
        # pad only the axes that don't already tile (e.g. non-causal
        # T=384: q needs 512 but k tiles at bk=384 — leave k alone)
        lq = tq if _axis_tiles(tq, block_q) else \
            _block_padded_len(tq, block_q)
        lk = tk if _axis_tiles(tk, block_k) else \
            _block_padded_len(tk, block_k)

    def padt(x, length):
        return jnp.pad(x, ((0, 0), (0, 0), (0, length - x.shape[2]),
                           (0, 0)))

    if q_seg is None and (lk == tk or causal):
        # no masking needed: padded KEYS are either absent (k unpadded) or
        # causally invisible (common-length padding puts them at indices
        # >= tk > any real query's reach); padded QUERY rows are sliced
        # off and their zero output-cotangents keep the backward exact —
        # so the cheaper plain kernel runs, with no seg operands
        out = _flash_attention_plain(padt(q, lq), padt(k, lk),
                                     padt(v, lk), scale, causal,
                                     block_q, block_k)
        return out[:, :, :tq]
    if q_seg is None:
        q_seg = jnp.ones((b, tq), jnp.int32)
        k_seg = jnp.ones((b, tk), jnp.int32)
    # ids are doubled (even) so the ODD sentinels can never collide with
    # any user id — including negative ones; equality between real pairs
    # is preserved. (|id| must fit int32 after doubling.)
    q_seg = jnp.pad(q_seg.astype(jnp.int32) * 2, ((0, 0), (0, lq - tq)),
                    constant_values=-1)
    k_seg = jnp.pad(k_seg.astype(jnp.int32) * 2, ((0, 0), (0, lk - tk)),
                    constant_values=-3)
    out = _flash_attention_seg(padt(q, lq), padt(k, lk), padt(v, lk),
                               q_seg, k_seg, scale, causal,
                               block_q, block_k)
    return out[:, :, :tq]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_plain(q, k, v, scale, causal, block_q, block_k):
    return _flash_attention_impl(q, k, v, scale, causal, block_q, block_k)


def _clamped_blocks(q, k, block_q, block_k):
    """Clamp raw (possibly bucket-sized) blocks to the actual seq axes and
    check tiling. block 0 is the XLA sentinel — never ok."""
    if block_q <= 0 or block_k <= 0:
        return 0, 0, False
    bq = min(block_q, q.shape[2])
    bk = min(block_k, k.shape[2])
    ok = q.shape[2] % bq == 0 and k.shape[2] % bk == 0
    return bq, bk, ok


def _flash_attention_impl(q, k, v, scale, causal, block_q, block_k):
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    if not _use_pallas():
        return _attention_reference(q, k, v, s, causal)
    # head_dim needs no padding (Mosaic handles sub-lane widths); the seq
    # axes must tile evenly by the block sizes
    bq, bk, ok = _clamped_blocks(q, k, block_q, block_k)
    if not ok:
        # XLA sentinel, or ragged shapes where padded KV rows would need
        # an extra mask: the reference path is simplest-correct
        return _attention_reference(q, k, v, s, causal)
    return _flash_attention_tpu(q, k, v, s, causal, bq, bk)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    bq, bk, ok = _clamped_blocks(q, k, block_q, block_k)
    if _use_pallas() and ok:
        out, lse = _flash_attention_tpu(q, k, v, s, causal, bq, bk,
                                        return_lse=True)
        return out, (q, k, v, out, lse)
    return _attention_reference(q, k, v, s, causal), (q, k, v, None, None)


# -- segment-ids (key padding / packed sequences) variant -------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash_attention_seg(q, k, v, q_seg, k_seg, scale, causal,
                         block_q, block_k):
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    if not _use_pallas():
        return _attention_reference(q, k, v, s, causal, q_seg, k_seg)
    bq, bk, ok = _clamped_blocks(q, k, block_q, block_k)
    if not ok:
        return _attention_reference(q, k, v, s, causal, q_seg, k_seg)
    return _flash_attention_tpu(q, k, v, s, causal, bq, bk,
                                q_seg=q_seg, k_seg=k_seg)


def _flash_seg_fwd(q, k, v, q_seg, k_seg, scale, causal, block_q, block_k):
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    bq, bk, ok = _clamped_blocks(q, k, block_q, block_k)
    if _use_pallas() and ok:
        out, lse = _flash_attention_tpu(q, k, v, s, causal, bq, bk,
                                        return_lse=True,
                                        q_seg=q_seg, k_seg=k_seg)
        return out, (q, k, v, q_seg, k_seg, out, lse)
    out = _attention_reference(q, k, v, s, causal, q_seg, k_seg)
    return out, (q, k, v, q_seg, k_seg, None, None)


def _flash_seg_bwd(scale, causal, block_q, block_k, res, g):
    import numpy as onp

    q, k, v, q_seg, k_seg, out, lse = res
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    if lse is not None and _use_pallas():
        bwd = _resolve_attention_blocks("flash_bwd", q, k, causal, True)
        if bwd is not None:
            bq, bk, ok = _clamped_blocks(q, k, *bwd)
            if ok:
                dq, dk, dv = _flash_bwd_tpu(q, k, v, out, lse, g, s,
                                            causal, bq, bk,
                                            q_seg=q_seg, k_seg=k_seg)
                return (dq, dk, dv,
                        onp.zeros(q_seg.shape, jax.dtypes.float0),
                        onp.zeros(k_seg.shape, jax.dtypes.float0))
    dq, dk, dv = _attention_bwd_blockwise(q, k, v, g, s, causal,
                                          q_seg=q_seg, k_seg=k_seg)
    return (dq, dk, dv,
            onp.zeros(q_seg.shape, jax.dtypes.float0),
            onp.zeros(k_seg.shape, jax.dtypes.float0))


_flash_attention_seg.defvjp(_flash_seg_fwd, _flash_seg_bwd)


# ---------------------------------------------------------------------------
# flash attention backward kernels
#
# Standard flash-bwd identities with the forward's saved LSE:
#   p_ij  = exp(s_ij - lse_i)
#   dv_j  = Σ_i p_ij g_i
#   dp_ij = g_i · v_j
#   ds_ij = p_ij (dp_ij - Δ_i) * scale,   Δ_i = Σ_d g_id o_id
#   dq_i  = Σ_j ds_ij k_j ;  dk_j = Σ_i ds_ij q_i
# Two kernels: one gridded over KV blocks (dk, dv), one over Q blocks (dq).
# No O(T²) materialization; accumulation in fp32 VMEM scratch.
# ---------------------------------------------------------------------------
def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                          *rest, scale, causal, block_q, block_k, seq_q,
                          causal_offset, use_seg=False):
    if use_seg:
        qs_ref, ks_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        dk_ref, dv_ref, dk_acc, dv_acc = rest
    kb = pl.program_id(1)
    k = k_ref[0]  # (BK, D)
    v = v_ref[0]
    num_qb = seq_q // block_q

    dk_acc[:] = jnp.zeros_like(dk_acc)
    dv_acc[:] = jnp.zeros_like(dv_acc)

    def body(qb, _):
        q = q_ref[0, pl.ds(qb * block_q, block_q), :]
        g = g_ref[0, pl.ds(qb * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(qb * block_q, block_q), :]   # (BQ, 1)
        delta = delta_ref[0, pl.ds(qb * block_q, block_q), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale      # (BQ, BK)
        ok = None
        if causal:
            qi = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            ki = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            ok = qi + causal_offset >= ki
            s = jnp.where(ok, s, _NEG_INF)
        p = jnp.exp(s - lse)                                  # normalized
        if use_seg:
            qs = qs_ref[0, pl.ds(qb * block_q, block_q)].reshape(block_q, 1)
            ks = ks_ref[:].reshape(1, block_k)
            ok = (qs == ks) if ok is None else (ok & (qs == ks))
        if ok is not None:
            # mask p under the COMBINED mask: for a fully masked row lse
            # was clamped, so exp(s - lse) is not reliably ~0 there
            p = jnp.where(ok, p, 0.0)
        gf = g.astype(jnp.float32)
        dv_acc[:] += jax.lax.dot_general(
            p, gf, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (BK, D)
        dp = jax.lax.dot_general(
            gf, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # (BQ, BK)
        ds = p * (dp - delta) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (BK, D)
        return 0

    jax.lax.fori_loop(0, num_qb, body, 0)
    dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
    dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                         *rest, scale, causal, block_q,
                         block_k, seq_k, causal_offset, use_seg=False):
    if use_seg:
        qs_ref, ks_ref, dq_ref, dq_acc = rest
    else:
        dq_ref, dq_acc = rest
    qb = pl.program_id(1)
    q = q_ref[0]   # (BQ, D)
    g = g_ref[0]
    lse = lse_ref[0]    # (BQ, 1)
    delta = delta_ref[0]
    num_kb = seq_k // block_k

    dq_acc[:] = jnp.zeros_like(dq_acc)
    gf = g.astype(jnp.float32)

    def body(kb, _):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        ok = None
        if causal:
            qi = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            ki = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            ok = qi + causal_offset >= ki
            s = jnp.where(ok, s, _NEG_INF)
        p = jnp.exp(s - lse)
        if use_seg:
            qs = qs_ref[:].reshape(block_q, 1)
            ks = ks_ref[0, pl.ds(kb * block_k, block_k)].reshape(1, block_k)
            ok = (qs == ks) if ok is None else (ok & (qs == ks))
        if ok is not None:
            p = jnp.where(ok, p, 0.0)
        dp = jax.lax.dot_general(
            gf, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return 0

    jax.lax.fori_loop(0, num_kb, body, 0)
    dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_tpu(q, k, v, out, lse, g, scale, causal, block_q, block_k,
                   q_seg=None, k_seg=None):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    qr = q.reshape(b * h, tq, d)
    kr = k.reshape(b * h, tk, d)
    vr = v.reshape(b * h, tk, d)
    gr = g.reshape(b * h, tq, d)
    lser = lse.reshape(b * h, tq, 1)
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True).reshape(b * h, tq, 1)
    off = tk - tq
    use_seg = q_seg is not None
    if use_seg:
        q_seg = q_seg.astype(jnp.int32)
        k_seg = k_seg.astype(jnp.int32)

    full_q = pl.BlockSpec((1, tq, d), lambda bh, blk: (bh, 0, 0),
                          memory_space=pltpu.VMEM)
    full_k = pl.BlockSpec((1, tk, d), lambda bh, blk: (bh, 0, 0),
                          memory_space=pltpu.VMEM)
    full_stat = pl.BlockSpec((1, tq, 1), lambda bh, blk: (bh, 0, 0),
                             memory_space=pltpu.VMEM)
    kv_blk = pl.BlockSpec((1, block_k, d), lambda bh, kb: (bh, kb, 0),
                          memory_space=pltpu.VMEM)
    dkv_in_specs = [full_q, kv_blk, kv_blk, full_q, full_stat, full_stat]
    dkv_operands = [qr, kr, vr, gr, lser, delta]
    if use_seg:
        dkv_in_specs += [
            pl.BlockSpec((1, tq), lambda bh, kb: (bh // h, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k), lambda bh, kb: (bh // h, kb),
                         memory_space=pltpu.VMEM),
        ]
        dkv_operands += [q_seg, k_seg]
    dkv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_q=tq,
                          causal_offset=off, use_seg=use_seg),
        grid=(b * h, tk // block_k),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, kb: (bh, kb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda bh, kb: (bh, kb, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=5 * b * h * tq * tk * d,
            bytes_accessed=(qr.size * 2 + kr.size * 3) * qr.dtype.itemsize,
            transcendentals=b * h * tq * tk,
        ),
        interpret=_interpret(),
    )(*dkv_operands)
    dk, dv = dkv

    dq_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qb: (bh, qb, 0),
                     memory_space=pltpu.VMEM),
        full_k, full_k,
        pl.BlockSpec((1, block_q, d), lambda bh, qb: (bh, qb, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, 1), lambda bh, qb: (bh, qb, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, 1), lambda bh, qb: (bh, qb, 0),
                     memory_space=pltpu.VMEM),
    ]
    dq_operands = [qr, kr, vr, gr, lser, delta]
    if use_seg:
        dq_in_specs += [
            pl.BlockSpec((1, block_q), lambda bh, qb: (bh // h, qb),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tk), lambda bh, qb: (bh // h, 0),
                         memory_space=pltpu.VMEM),
        ]
        dq_operands += [q_seg, k_seg]
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_k=tk,
                          causal_offset=off, use_seg=use_seg),
        grid=(b * h, tq // block_q),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qb: (bh, qb, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=3 * b * h * tq * tk * d,
            bytes_accessed=(qr.size * 2 + kr.size * 2) * qr.dtype.itemsize,
            transcendentals=b * h * tq * tk,
        ),
        interpret=_interpret(),
    )(*dq_operands)
    return (dq.reshape(b, h, tq, d), dk.reshape(b, h, tk, d),
            dv.reshape(b, h, tk, d))


_BWD_BLOCK = 512


def _attention_bwd_blockwise(q, k, v, g, scale, causal, q_seg=None,
                             k_seg=None):
    """Memory-capped attention backward: recompute scores blockwise over KV.

    Standard flash-attention backward structure without a hand-written
    kernel: two passes of lax.scan over KV blocks keep peak memory at
    O(T * block) instead of O(T^2), so long-context training fits in HBM.
    XLA fuses each block's matmul chain onto the MXU.
    """
    b, h, tq, d = q.shape
    tk = k.shape[2]
    # largest divisor of tk up to the cap keeps the memory bound for ANY
    # block-unfriendly length; only tiny/pathological divisors (where the
    # scan would degenerate) fall back to the dense vjp — and those lengths
    # are small enough that O(T^2) is not a memory problem
    blk = max((d_ for d_ in range(1, min(_BWD_BLOCK, tk) + 1)
               if tk % d_ == 0), default=tk)
    if blk < 16 and tk > 4096:
        blk = 1  # prime-ish huge tk: still capped, just slower
    elif blk < 16:
        _, vjp = jax.vjp(lambda q_, k_, v_:
                         _attention_reference(q_, k_, v_, scale, causal,
                                              q_seg, k_seg),
                         q, k, v)
        return vjp(g)
    nblk = tk // blk
    qf = q.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    kb = k.reshape(b, h, nblk, blk, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, h, nblk, blk, d).transpose(2, 0, 1, 3, 4)

    def mask_for(idx):
        m = None
        if causal:
            qi = jnp.arange(tq)[:, None] + (tk - tq)
            ki = idx * blk + jnp.arange(blk)[None, :]
            m = (qi >= ki)[None, None]
        if q_seg is not None:
            ks_i = lax.dynamic_slice_in_dim(k_seg, idx * blk, blk, axis=1)
            seg = q_seg[:, None, :, None] == ks_i[:, None, None, :]
            m = seg if m is None else (m & seg)
        return m

    # pass 1: softmax stats (row max m, denominator l) + output recompute
    def stats_step(carry, inputs):
        m_prev, l_prev, acc = carry
        kb_i, vb_i, idx = inputs
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb_i.astype(jnp.float32),
                       preferred_element_type=jnp.float32) * scale
        msk = mask_for(idx)
        if msk is not None:
            s = jnp.where(msk, s, _NEG_INF)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if msk is not None:
            p = jnp.where(msk, p, 0.0)  # fully masked rows: l stays 0
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vb_i.astype(jnp.float32))
        return (m_new, l_new, acc), None

    m0 = jnp.full((b, h, tq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, tq, 1), jnp.float32)
    a0 = jnp.zeros((b, h, tq, d), jnp.float32)
    (m, l, acc), _ = lax.scan(stats_step, (m0, l0, a0),
                              (kb, vb, jnp.arange(nblk)))
    out = acc / jnp.maximum(l, 1e-30)
    # delta_i = sum_d g_i * o_i (standard flash bwd identity)
    delta = jnp.sum(gf * out, axis=-1, keepdims=True)

    # pass 2: gradients per KV block
    def grad_step(dq_acc, inputs):
        kb_i, vb_i, idx = inputs
        kf = kb_i.astype(jnp.float32)
        vf = vb_i.astype(jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf,
                       preferred_element_type=jnp.float32) * scale
        msk = mask_for(idx)
        if msk is not None:
            s = jnp.where(msk, s, _NEG_INF)
        p = jnp.exp(s - m) / jnp.maximum(l, 1e-30)  # (b,h,q,blk)
        if msk is not None:
            p = jnp.where(msk, p, 0.0)
        dv_i = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf, vf)
        ds = p * (dp - delta) * scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds, kf)
        dk_i = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        return dq_acc, (dk_i, dv_i)

    dq, (dk_b, dv_b) = lax.scan(grad_step, jnp.zeros_like(qf),
                                (kb, vb, jnp.arange(nblk)))
    dk = dk_b.transpose(1, 2, 0, 3, 4).reshape(b, h, tk, d)
    dv = dv_b.transpose(1, 2, 0, 3, 4).reshape(b, h, tk, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _flash_bwd(scale, causal, block_q, block_k, res, g):
    q, k, v, out, lse = res
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    if lse is not None and _use_pallas():
        # the backward resolves its own tuned config: its grids iterate
        # the opposite axes from the forward, so the winners differ
        bwd = _resolve_attention_blocks("flash_bwd", q, k, causal, False)
        if bwd is not None:
            bq, bk, ok = _clamped_blocks(q, k, *bwd)
            if ok:
                return _flash_bwd_tpu(q, k, v, out, lse, g, s, causal,
                                      bq, bk)
    return _attention_bwd_blockwise(q, k, v, g, s, causal)


_flash_attention_plain.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# fused layer norm
# ---------------------------------------------------------------------------
def _ln_kernel(x_ref, g_ref, b_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    o_ref[:] = (xc * inv * g_ref[:].astype(jnp.float32) +
                b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _rows_of(shape):
    rows = 1
    for sdim in shape[:-1]:
        rows *= sdim
    return rows


def fused_layer_norm(x, gamma, beta, eps=1e-5, block_rows=None):
    """Row-wise LayerNorm over the last axis (Pallas on TPU, XLA elsewhere).

    Differentiable: forward runs the kernel, backward flows through the
    identical XLA formula via jax.custom_vjp below.

    ``block_rows=None`` resolves through the tuning tier per trace
    (env default 128 when tuning is off; 0 = the XLA lowering on a miss
    or a tuned Pallas loss); pass an explicit value to pin it.
    """
    if block_rows is None:
        if _use_pallas() and x.shape[-1] % 128 == 0:
            block_rows = _resolve_block_rows("layer_norm",
                                             _rows_of(x.shape),
                                             x.shape[-1], x.dtype)
        else:
            # kernel can't run here anyway — don't log a tuning miss
            block_rows = 128
    return _fused_ln(x, gamma, beta, eps, int(block_rows))


def _ln_reference(x, gamma, beta, eps):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    inv = lax.rsqrt(var.astype(jnp.float32) + eps).astype(x.dtype)
    # keep x's dtype even with f32 gamma/beta so the Pallas-kernel primal
    # and this reference (used for the VJP) agree on output type
    return ((x - mean) * inv * gamma + beta).astype(x.dtype)


def _pad_rows(xr, br):
    """Pad the row axis up to a multiple of ``br`` (zero rows — sliced
    off after the kernel, so their values never escape)."""
    rows = xr.shape[0]
    target = -(-rows // br) * br
    if target == rows:
        return xr, rows
    return jnp.pad(xr, ((0, target - rows), (0, 0))), rows


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_ln(x, gamma, beta, eps, block_rows):
    if not _use_pallas() or block_rows <= 0:
        return _ln_reference(x, gamma, beta, eps)
    d = x.shape[-1]
    if d % 128 != 0:
        # the feature axis cannot be padded (it changes the row mean);
        # non-lane-aligned widths stay on the reference path
        return _ln_reference(x, gamma, beta, eps)
    orig_shape = x.shape
    rows = _rows_of(orig_shape)
    br = min(block_rows, rows)
    # ragged row counts stay fused: pad tail rows, slice them back off
    xr, rows = _pad_rows(x.reshape(rows, d), br)
    out = pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=(xr.shape[0] // br,),
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((d,), lambda i: (0,), memory_space=pltpu.VMEM),
            pl.BlockSpec((d,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((br, d), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((xr.shape[0], d), x.dtype),
        interpret=_interpret(),
    )(xr, gamma, beta)
    return out[:rows].reshape(orig_shape)


def _fused_ln_fwd(x, gamma, beta, eps, block_rows):
    return _fused_ln(x, gamma, beta, eps, block_rows), (x, gamma, beta)


def _fused_ln_bwd(eps, block_rows, res, g):
    x, gamma, beta = res
    _, vjp = jax.vjp(lambda x_, g_, b_: _ln_reference(x_, g_, b_, eps),
                     x, gamma, beta)
    return vjp(g)


_fused_ln.defvjp(_fused_ln_fwd, _fused_ln_bwd)


# ---------------------------------------------------------------------------
# fused softmax (last axis)
# ---------------------------------------------------------------------------
def _softmax_kernel(x_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    o_ref[:] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(o_ref.dtype)


def fused_softmax(x, block_rows=None):
    """Last-axis softmax (Pallas on TPU, XLA elsewhere) — same gate audit
    as attention/LayerNorm: ``_use_pallas()`` + lane-aligned width, with
    ragged row counts padded to the block and sliced back. ``block_rows``
    resolves through the tuning tier when None.
    """
    if block_rows is None:
        if _use_pallas() and x.shape[-1] % 128 == 0:
            block_rows = _resolve_block_rows("softmax", _rows_of(x.shape),
                                             x.shape[-1], x.dtype)
        else:
            block_rows = 128
    return _fused_softmax(x, int(block_rows))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _fused_softmax(x, block_rows):
    return _fused_softmax_impl(x, block_rows)


def _fused_softmax_impl(x, block_rows):
    d = x.shape[-1]
    if not _use_pallas() or block_rows <= 0 or d % 128 != 0:
        return jax.nn.softmax(x, axis=-1)
    rows = _rows_of(x.shape)
    br = min(block_rows, rows)
    xr, rows = _pad_rows(x.reshape(rows, d), br)
    out = pl.pallas_call(
        _softmax_kernel,
        grid=(xr.shape[0] // br,),
        in_specs=[pl.BlockSpec((br, d), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((br, d), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((xr.shape[0], d), x.dtype),
        interpret=_interpret(),
    )(xr)
    return out[:rows].reshape(x.shape)


def _fused_softmax_fwd(x, block_rows):
    y = _fused_softmax_impl(x, block_rows)
    return y, y


def _fused_softmax_bwd(block_rows, y, g):
    gy = (g - jnp.sum(g * y, axis=-1, keepdims=True)) * y
    return (gy,)


_fused_softmax.defvjp(_fused_softmax_fwd, _fused_softmax_bwd)
