"""Quantized (int8) operator family + intgemm bridge.

TPU-native equivalent of src/operator/quantization/*.cc and
src/operator/contrib/intgemm/*.cc. Conventions kept from the reference:

- a quantized tensor travels as ``(int8 data, min_range, max_range)`` — every
  quantized op consumes the ranges as trailing float operands and emits its
  own output ranges, exactly the dataflow quantize_graph_pass.cc wires up;
- symmetric int8: scale = max(|min|, |max|) / 127;
- int8 × int8 contractions accumulate in int32 via XLA's
  ``preferred_element_type`` — on TPU this is the MXU's native int8 path
  (the analog of the reference's cuDNN int8 / intgemm AVX kernels);
- ``*_ste`` straight-through estimators for quantization-aware training
  (reference: contrib/stes_op.cc).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, register_alias

_I8MAX = 127.0


def _scale(mn, mx):
    return jnp.maximum(jnp.maximum(jnp.abs(mn), jnp.abs(mx)), 1e-12) / _I8MAX


# ---------------------------------------------------------------------------
# quantize / requantize — quantize_v2.cc, requantize.cc
# ---------------------------------------------------------------------------
@register("quantize_v2", nout=3)
def _quantize_v2(out_type="int8", min_calib_range=None,
                 max_calib_range=None, **a):
    def f(x):
        if min_calib_range is not None:
            mn = jnp.float32(min_calib_range)
            mx = jnp.float32(max_calib_range)
        else:
            mn = jnp.min(x).astype(jnp.float32)
            mx = jnp.max(x).astype(jnp.float32)
        s = _scale(mn, mx)
        q = jnp.clip(jnp.round(x / s), -_I8MAX, _I8MAX).astype(jnp.int8)
        return q, mn, mx

    return f


register_alias("_contrib_quantize_v2", "quantize_v2")


@register("requantize", nout=3)
def _requantize(min_calib_range=None, max_calib_range=None, **a):
    """int32 accumulator -> int8 with recalibrated range
    (requantize.cc): input carries ranges of the int32 data."""
    def f(q32, mn_in, mx_in):
        s_in = jnp.maximum(jnp.maximum(jnp.abs(mn_in), jnp.abs(mx_in)),
                           1e-12) / (2.0 ** 31 - 1)
        real = q32.astype(jnp.float32) * s_in
        if min_calib_range is not None:
            mn = jnp.float32(min_calib_range)
            mx = jnp.float32(max_calib_range)
        else:
            mn = jnp.min(real)
            mx = jnp.max(real)
        s_out = _scale(mn, mx)
        q = jnp.clip(jnp.round(real / s_out), -_I8MAX, _I8MAX).astype(
            jnp.int8)
        return q, mn, mx

    return f


register_alias("_contrib_requantize", "requantize")


# ---------------------------------------------------------------------------
# quantized compute ops — quantized_*.cc
# ---------------------------------------------------------------------------
@register("quantized_act", nout=3)
def _quantized_act(act_type="relu", **a):
    """quantized_activation (quantized_activation.cc): only relu — it is
    monotonic and zero-preserving, so it acts directly on int8 codes."""
    def f(q, mn, mx):
        if act_type != "relu":
            raise ValueError("quantized_act supports act_type='relu' only")
        return jnp.maximum(q, 0), jnp.maximum(mn, 0.0), jnp.maximum(mx, 0.0)

    return f


register_alias("_contrib_quantized_act", "quantized_act")


@register("quantized_flatten", nout=3)
def _quantized_flatten(**a):
    def f(q, mn, mx):
        return q.reshape(q.shape[0], -1), mn, mx

    return f


register_alias("_contrib_quantized_flatten", "quantized_flatten")


@register("quantized_concat", nout=3)
def _quantized_concat(dim=1, num_args=1, **a):
    """quantized_concat.cc: rescale every input onto the widest range, then
    concatenate in int8."""
    def f(*args):
        n = len(args) // 3
        qs, mns, mxs = args[:n], args[n:2 * n], args[2 * n:]
        mn = mns[0]
        mx = mxs[0]
        for m in mns[1:]:
            mn = jnp.minimum(mn, m)
        for m in mxs[1:]:
            mx = jnp.maximum(mx, m)
        s_out = _scale(mn, mx)
        parts = []
        for q, m0, m1 in zip(qs, mns, mxs):
            s_in = _scale(m0, m1)
            parts.append(jnp.clip(
                jnp.round(q.astype(jnp.float32) * (s_in / s_out)),
                -_I8MAX, _I8MAX).astype(jnp.int8))
        return jnp.concatenate(parts, axis=dim), mn, mx

    return f


register_alias("_contrib_quantized_concat", "quantized_concat")


@register("quantized_elemwise_add", nout=3)
def _quantized_elemwise_add(**a):
    def f(qa, qb, mna, mxa, mnb, mxb):
        sa, sb = _scale(mna, mxa), _scale(mnb, mxb)
        # sum in float32 (exact for int8-scaled values), then emit the
        # int32 code against a shared output scale. The previous
        # fixed-point route round(s*2^16) underflowed to 0 for ranges
        # below ~1e-3, silently dropping that operand from the sum.
        fsum = qa.astype(jnp.float32) * sa + qb.astype(jnp.float32) * sb
        s_out = jnp.maximum(sa, sb) * 2.0 / (2.0 ** 23)
        acc = jnp.clip(jnp.round(fsum / s_out),
                       -(2 ** 31 - 1), 2 ** 31 - 1).astype(jnp.int32)
        mx = jnp.float32(2 ** 31 - 1) * s_out
        return acc, -mx, mx

    return f


register_alias("_contrib_quantized_elemwise_add", "quantized_elemwise_add")


@register("quantized_elemwise_mul", nout=3)
def _quantized_elemwise_mul(**a):
    def f(qa, qb, mna, mxa, mnb, mxb):
        sa, sb = _scale(mna, mxa), _scale(mnb, mxb)
        acc = qa.astype(jnp.int32) * qb.astype(jnp.int32)
        # int32-code convention shared by every quantized producer: code
        # 2^31-1 maps to the range max, so requantize decodes uniformly
        s_out = sa * sb
        mx = jnp.float32(2 ** 31 - 1) * s_out
        return acc, -mx, mx

    return f


register_alias("_contrib_quantized_elemwise_mul", "quantized_elemwise_mul")


@register("quantized_embedding", nout=3)
def _quantized_embedding(input_dim=0, output_dim=0, **a):
    def f(idx, qweight, mn, mx):
        return (jnp.take(qweight, idx.astype(jnp.int32), axis=0), mn, mx)

    return f


register_alias("_contrib_quantized_embedding", "quantized_embedding")


@register("quantized_fully_connected_v2", nout=3)
def _quantized_fc(num_hidden=0, no_bias=False, flatten=True, **a):
    """quantized_fully_connected.cc on the MXU: int8×int8→int32 GEMM via
    preferred_element_type (XLA emits the native int8 systolic matmul)."""
    def f(*args):
        if no_bias:
            qx, qw, mnx, mxx, mnw, mxw = args
            qb = None
        else:
            qx, qw, qb, mnx, mxx, mnw, mxw, mnb, mxb = args
        x = qx.reshape(qx.shape[0], -1) if flatten else qx
        acc = lax.dot_general(
            x.astype(jnp.int8), qw.astype(jnp.int8),
            (((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)
        sx, sw = _scale(mnx, mxx), _scale(mnw, mxw)
        s_out = sx * sw
        if qb is not None:
            sb = _scale(mnb, mxb)
            acc = acc + jnp.round(
                qb.astype(jnp.float32) * (sb / s_out)).astype(jnp.int32)
        mx = jnp.float32(2 ** 31 - 1) * s_out
        return acc, -mx, mx

    return f


@register("quantized_conv", nout=3)
def _quantized_conv(kernel=(), stride=(), pad=(), dilate=(), num_filter=0,
                    no_bias=True, layout="NCHW", **a):
    def f(*args):
        if no_bias:
            qx, qw, mnx, mxx, mnw, mxw = args
            qb = None
        else:
            qx, qw, qb, mnx, mxx, mnw, mxw, mnb, mxb = args
        nd = len(kernel) if kernel else qw.ndim - 2
        strides = tuple(stride) if stride else (1,) * nd
        pads = tuple((p, p) for p in pad) if pad else ((0, 0),) * nd
        dil = tuple(dilate) if dilate else (1,) * nd
        acc = lax.conv_general_dilated(
            qx.astype(jnp.int8), qw.astype(jnp.int8), strides, pads,
            rhs_dilation=dil, preferred_element_type=jnp.int32)
        sx, sw = _scale(mnx, mxx), _scale(mnw, mxw)
        s_out = sx * sw
        if qb is not None:
            sb = _scale(mnb, mxb)
            acc = acc + jnp.round(qb.astype(jnp.float32) * (sb / s_out)
                                  ).astype(jnp.int32).reshape(
                                      1, -1, *([1] * (acc.ndim - 2)))
        mx = jnp.float32(2 ** 31 - 1) * s_out
        return acc, -mx, mx

    return f


register_alias("_contrib_quantized_conv", "quantized_conv")


@register("quantized_pooling", nout=3)
def _quantized_pooling(kernel=(), pool_type="max", stride=(), pad=(),
                       global_pool=False, **a):
    def f(q, mn, mx):
        nd = len(kernel) if kernel else q.ndim - 2
        if global_pool:
            window = (1, 1) + q.shape[2:]
            strides = (1,) * q.ndim
            pads = ((0, 0),) * q.ndim
        else:
            window = (1, 1) + tuple(kernel)
            strides = (1, 1) + (tuple(stride) if stride else (1,) * nd)
            pads = ((0, 0), (0, 0)) + tuple((p, p) for p in (
                pad if pad else (0,) * nd))
        if pool_type == "max":
            out = lax.reduce_window(q, jnp.array(-128, q.dtype), lax.max,
                                    window, strides, pads)
            return out, mn, mx
        acc = lax.reduce_window(q.astype(jnp.int32), jnp.array(0, jnp.int32),
                                lax.add, window, strides, pads)
        denom = 1
        for w in window:
            denom *= w
        out = jnp.round(acc.astype(jnp.float32) / denom).astype(jnp.int8)
        return out, mn, mx

    return f


register_alias("_contrib_quantized_pooling", "quantized_pooling")


@register("quantized_batch_norm", nout=3)
def _quantized_batch_norm(eps=1e-3, min_calib_range=None,
                          max_calib_range=None, **a):
    """quantized_batch_norm.cc: BN folded onto the int8 codes — an affine
    per-channel rescale computed from the float BN parameters."""
    def f(q, gamma, beta, mean, var, mn, mx):
        s_in = _scale(mn, mx)
        inv = gamma / jnp.sqrt(var + eps)
        shape = (1, -1) + (1,) * (q.ndim - 2)
        real = (q.astype(jnp.float32) * s_in - mean.reshape(shape)) \
            * inv.reshape(shape) + beta.reshape(shape)
        if min_calib_range is not None:
            mn_o = jnp.float32(min_calib_range)
            mx_o = jnp.float32(max_calib_range)
        else:
            mn_o, mx_o = jnp.min(real), jnp.max(real)
        s_out = _scale(mn_o, mx_o)
        qo = jnp.clip(jnp.round(real / s_out), -_I8MAX, _I8MAX).astype(
            jnp.int8)
        return qo, mn_o, mx_o

    return f


register_alias("_contrib_quantized_batch_norm", "quantized_batch_norm")


# ---------------------------------------------------------------------------
# straight-through estimators — contrib/stes_op.cc
# ---------------------------------------------------------------------------
def _make_round_ste(**a):
    @jax.custom_vjp
    def f(x):
        return jnp.round(x)

    f.defvjp(lambda x: (jnp.round(x), None), lambda _, g: (g,))
    return f


def _make_sign_ste(**a):
    @jax.custom_vjp
    def f(x):
        return jnp.sign(x)

    f.defvjp(lambda x: (jnp.sign(x), None), lambda _, g: (g,))
    return f


register("round_ste", _make_round_ste)
register("sign_ste", _make_sign_ste)
register_alias("_contrib_round_ste", "round_ste")
register_alias("_contrib_sign_ste", "sign_ste")


# ---------------------------------------------------------------------------
# intgemm bridge — contrib/intgemm/*.cc. The reference wraps the AVX2/AVX512
# intgemm library; the TPU analog is the same 4-op protocol (maxabsolute →
# prepare → gemm) lowered onto the MXU int8 path.
# ---------------------------------------------------------------------------
register("intgemm_maxabsolute", lambda **a:
         (lambda x: jnp.max(jnp.abs(x))))
register_alias("_contrib_intgemm_maxabsolute", "intgemm_maxabsolute")

register("intgemm_prepare_data", lambda **a:
         (lambda x, maxabs: jnp.clip(
             jnp.round(x * (_I8MAX / jnp.maximum(maxabs, 1e-12))),
             -_I8MAX, _I8MAX).astype(jnp.int8)),
         differentiable=False)
register_alias("_contrib_intgemm_prepare_data", "intgemm_prepare_data")

# On CPU, prepare_weight lays the matrix out in a CPU-register tiling; the
# TPU layout is XLA's concern, so preparation = quantization only.
register("intgemm_prepare_weight", lambda already_quantized=False, **a:
         (lambda w, *maxabs: w.astype(jnp.int8) if already_quantized
          else jnp.clip(jnp.round(w * (_I8MAX / jnp.maximum(
              maxabs[0], 1e-12))), -_I8MAX, _I8MAX).astype(jnp.int8)),
         differentiable=False)
register_alias("_contrib_intgemm_prepare_weight", "intgemm_prepare_weight")

register("intgemm_take_weight", lambda **a:
         (lambda w, idx: jnp.take(w, idx.astype(jnp.int32), axis=0)),
         differentiable=False)
register_alias("_contrib_intgemm_take_weight", "intgemm_take_weight")


@register("intgemm_fully_connected")
def _intgemm_fully_connected(out_type="float32", num_hidden=0,
                             no_bias=True, flatten=True, **a):
    """C = A_int8 · B_int8^T · scale (+ bias) — intgemm_fully_connected.cc.
    ``scale`` arrives as the product of the two dequantization scales."""
    def f(*args):
        if no_bias:
            qa, qb_w, scale = args
            bias = None
        else:
            qa, qb_w, scale, bias = args
        x = qa.reshape(qa.shape[0], -1) if flatten else qa
        acc = lax.dot_general(
            x.astype(jnp.int8), qb_w.astype(jnp.int8),
            (((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * scale
        if bias is not None:
            out = out + bias
        if out_type == "int32":
            return acc
        return out

    return f


register_alias("_contrib_intgemm_fully_connected", "intgemm_fully_connected")
