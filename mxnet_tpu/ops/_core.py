"""Core operator definitions (elementwise, reductions, shape, linalg).

TPU-native equivalent of the reference op library's tensor/ + numpy/ subtrees
(src/operator/tensor/*, src/operator/numpy/* — 562 NNVM ops). Each op lowers to
jax.numpy / lax, i.e. straight to XLA HLO; XLA's fusion replaces the reference's
mshadow kernels, pointwise-fusion pass and cuDNN/oneDNN fast paths. Ops are
registered through ops.registry so every invocation is recordable (autograd)
and traceable (deferred compute -> CachedOp jit).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

# ---------------------------------------------------------------------------
# elementwise unary — reference: src/operator/tensor/elemwise_unary_op_basic.cc
# ---------------------------------------------------------------------------
_UNARY = {
    "abs": jnp.abs,
    "negative": jnp.negative,
    "sign": jnp.sign,
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": jnp.log,
    "log2": jnp.log2,
    "log10": jnp.log10,
    "log1p": jnp.log1p,
    "sqrt": jnp.sqrt,
    "cbrt": jnp.cbrt,
    "square": jnp.square,
    "reciprocal": jnp.reciprocal,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "floor": jnp.floor,
    "ceil": jnp.ceil,
    "trunc": jnp.trunc,
    "rint": jnp.rint,
    "fix": jnp.trunc,
    "invert": jnp.invert,
    "logical_not": jnp.logical_not,
    "isnan": jnp.isnan,
    "isinf": jnp.isinf,
    "isfinite": jnp.isfinite,
    "isposinf": jnp.isposinf,
    "isneginf": jnp.isneginf,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "sigmoid": jax.nn.sigmoid,
    "relu": jax.nn.relu,
    "softsign": jax.nn.soft_sign,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "conj": jnp.conj,
    "real": jnp.real,
    "imag": jnp.imag,
    "angle": jnp.angle,
    "copy": lambda x: x,  # buffers are immutable; identity is a true copy
    "stop_gradient": jax.lax.stop_gradient,
}
for _name, _fn in _UNARY.items():
    register(_name, (lambda f: (lambda **a: f))(_fn))

# ---------------------------------------------------------------------------
# elementwise binary — reference: elemwise_binary_broadcast_op*.cc
# ---------------------------------------------------------------------------
_BINARY = {
    "add": jnp.add,
    "subtract": jnp.subtract,
    "multiply": jnp.multiply,
    "true_divide": jnp.true_divide,
    "floor_divide": jnp.floor_divide,
    "mod": jnp.mod,
    "fmod": jnp.fmod,
    "power": jnp.power,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "fmax": jnp.fmax,
    "fmin": jnp.fmin,
    "hypot": jnp.hypot,
    "arctan2": jnp.arctan2,
    "logaddexp": jnp.logaddexp,
    "equal": jnp.equal,
    "not_equal": jnp.not_equal,
    "less": jnp.less,
    "less_equal": jnp.less_equal,
    "greater": jnp.greater,
    "greater_equal": jnp.greater_equal,
    "logical_and": jnp.logical_and,
    "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
    "bitwise_and": jnp.bitwise_and,
    "bitwise_or": jnp.bitwise_or,
    "bitwise_xor": jnp.bitwise_xor,
    "left_shift": jnp.left_shift,
    "right_shift": jnp.right_shift,
    "matmul": jnp.matmul,
    "dot": jnp.dot,
    "copysign": jnp.copysign,
    "gcd": jnp.gcd,
    "lcm": jnp.lcm,
    "ldexp": jnp.ldexp,
    "nextafter": jnp.nextafter,
}
for _name, _fn in _BINARY.items():
    register(_name, (lambda f: (lambda **a: f))(_fn))

register("inner", lambda **a: jnp.inner)
register("outer", lambda **a: jnp.outer)
register("vdot", lambda **a: jnp.vdot)
register("kron", lambda **a: jnp.kron)
register("cross", lambda axis=-1, **a: (lambda x, y: jnp.cross(x, y, axis=axis)))
register("tensordot",
         lambda axes=2: (lambda a, b: jnp.tensordot(a, b, axes=axes)))

# ---------------------------------------------------------------------------
# reductions — reference: src/operator/tensor/broadcast_reduce_op_value.cc
# ---------------------------------------------------------------------------
def _red(fn, **extra):
    def make(axis=None, keepdims=False, dtype=None, ddof=None, **kw):
        def f(x):
            kwargs = dict(axis=axis, keepdims=keepdims)
            if dtype is not None:
                kwargs["dtype"] = dtype
            if ddof is not None:
                kwargs["ddof"] = ddof
            return fn(x, **kwargs)

        return f

    return make


register("sum", _red(jnp.sum))
register("mean", _red(jnp.mean))
register("prod", _red(jnp.prod))
register("std", _red(jnp.std))
register("var", _red(jnp.var))
register("max", lambda axis=None, keepdims=False, **a:
         (lambda x: jnp.max(x, axis=axis, keepdims=keepdims)))
register("min", lambda axis=None, keepdims=False, **a:
         (lambda x: jnp.min(x, axis=axis, keepdims=keepdims)))
register("argmax", lambda axis=None, keepdims=False, **a:
         (lambda x: jnp.argmax(x, axis=axis, keepdims=keepdims)))
register("argmin", lambda axis=None, keepdims=False, **a:
         (lambda x: jnp.argmin(x, axis=axis, keepdims=keepdims)))
register("all", lambda axis=None, keepdims=False, **a:
         (lambda x: jnp.all(x, axis=axis, keepdims=keepdims)))
register("any", lambda axis=None, keepdims=False, **a:
         (lambda x: jnp.any(x, axis=axis, keepdims=keepdims)))
register("cumsum", lambda axis=None, dtype=None:
         (lambda x: jnp.cumsum(x, axis=axis, dtype=dtype)))
register("cumprod", lambda axis=None, dtype=None:
         (lambda x: jnp.cumprod(x, axis=axis, dtype=dtype)))
register("logsumexp", lambda axis=None, keepdims=False:
         (lambda x: jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdims)))
register("nansum", _red(jnp.nansum))
register("nanmean", _red(jnp.nanmean))
register("nanmax", lambda axis=None, keepdims=False, **a:
         (lambda x: jnp.nanmax(x, axis=axis, keepdims=keepdims)))
register("nanmin", lambda axis=None, keepdims=False, **a:
         (lambda x: jnp.nanmin(x, axis=axis, keepdims=keepdims)))
register("median", lambda axis=None, keepdims=False, **a:
         (lambda x: jnp.median(x, axis=axis, keepdims=keepdims)))
register("average", lambda axis=None: (lambda x, w: jnp.average(x, axis, w)))
register("norm", lambda ord=None, axis=None, keepdims=False:
         (lambda x: jnp.linalg.norm(x, ord=ord, axis=axis, keepdims=keepdims)))
register("trace", lambda offset=0, axis1=0, axis2=1:
         (lambda x: jnp.trace(x, offset, axis1, axis2)))

# ---------------------------------------------------------------------------
# shape manipulation — reference: matrix_op.cc
# ---------------------------------------------------------------------------
register("reshape", lambda newshape=None, **a: (lambda x: jnp.reshape(x, newshape)))
register("transpose", lambda axes=None: (lambda x: jnp.transpose(x, axes)))
register("swapaxes", lambda axis1=0, axis2=1:
         (lambda x: jnp.swapaxes(x, axis1, axis2)))
register("moveaxis", lambda source=0, destination=0:
         (lambda x: jnp.moveaxis(x, source, destination)))
register("squeeze", lambda axis=None: (lambda x: jnp.squeeze(x, axis)))
register("expand_dims", lambda axis=0: (lambda x: jnp.expand_dims(x, axis)))
register("broadcast_to", lambda shape=None: (lambda x: jnp.broadcast_to(x, shape)))
register("tile", lambda reps=1: (lambda x: jnp.tile(x, reps)))
register("repeat", lambda repeats=1, axis=None:
         (lambda x: jnp.repeat(x, repeats, axis)))
register("flip", lambda axis=None: (lambda x: jnp.flip(x, axis)))
register("roll", lambda shift=0, axis=None: (lambda x: jnp.roll(x, shift, axis)))
register("rot90", lambda k=1, axes=(0, 1): (lambda x: jnp.rot90(x, k, axes)))
register("astype", lambda dtype="float32": (lambda x: x.astype(dtype)))
register("flatten", lambda **a: (lambda x: jnp.reshape(x, (x.shape[0], -1))))
register("clip", lambda a_min=None, a_max=None:
         (lambda x: jnp.clip(x, a_min, a_max)))
register("round", lambda decimals=0: (lambda x: jnp.round(x, decimals)))
register("diag", lambda k=0: (lambda x: jnp.diag(x, k)))
register("diagonal", lambda offset=0, axis1=0, axis2=1:
         (lambda x: jnp.diagonal(x, offset, axis1, axis2)))
register("tril", lambda k=0: (lambda x: jnp.tril(x, k)))
register("triu", lambda k=0: (lambda x: jnp.triu(x, k)))
register("pad", lambda pad_width=0, mode="constant", constant_values=0:
         (lambda x: jnp.pad(x, pad_width, mode=mode,
                            **({"constant_values": constant_values}
                               if mode == "constant" else {}))))
register("concatenate", lambda axis=0: (lambda *xs: jnp.concatenate(xs, axis)))
register("stack", lambda axis=0: (lambda *xs: jnp.stack(xs, axis)))
register("split", lambda indices_or_sections=1, axis=0:
         (lambda x: tuple(jnp.split(x, indices_or_sections, axis))))
register("array_split", lambda indices_or_sections=1, axis=0:
         (lambda x: tuple(jnp.array_split(x, indices_or_sections, axis))))
register("atleast_1d", lambda **a: jnp.atleast_1d)
register("atleast_2d", lambda **a: jnp.atleast_2d)
register("atleast_3d", lambda **a: jnp.atleast_3d)
register("where", lambda **a: (lambda c, x, y: jnp.where(c, x, y)))
register("searchsorted", lambda side="left":
         (lambda a, v: jnp.searchsorted(a, v, side=side)))
register("sort", lambda axis=-1: (lambda x: jnp.sort(x, axis=axis)))
register("argsort", lambda axis=-1: (lambda x: jnp.argsort(x, axis=axis)))
register("topk", lambda k=1, axis=-1, ret_typ="indices", is_ascend=False:
         (lambda x: _topk(x, k, axis, ret_typ, is_ascend)))
register("take", lambda axis=None, mode="clip":
         (lambda x, idx: jnp.take(x, idx, axis=axis,
                                  mode="clip" if mode == "raise" else mode)))
register("take_along_axis", lambda axis=0:
         (lambda x, idx: jnp.take_along_axis(x, idx, axis=axis)))
register("gather_nd", lambda **a: _gather_nd)
register("one_hot", lambda depth=1, on_value=1.0, off_value=0.0, dtype="float32":
         (lambda idx: jax.nn.one_hot(idx, depth, dtype=dtype) * (on_value - off_value)
          + off_value))
register("interp", lambda **a: (lambda x, xp, fp: jnp.interp(x, xp, fp)))
register("unravel_index", lambda shape=None:
         (lambda idx: jnp.stack(jnp.unravel_index(idx, shape))))
register("ravel_multi_index", lambda shape=None:
         (lambda multi: jnp.ravel_multi_index(tuple(multi), shape, mode="clip")))
register("meshgrid", lambda indexing="xy":
         (lambda *xs: tuple(jnp.meshgrid(*xs, indexing=indexing))))
register("bincount", lambda minlength=0, length=None:
         (lambda x: jnp.bincount(x, minlength=minlength, length=length)))
register("diff", lambda n=1, axis=-1: (lambda x: jnp.diff(x, n=n, axis=axis)))
register("ediff1d", lambda **a: jnp.ediff1d)
register("flatnonzero_bounded", lambda size=None:
         (lambda x: jnp.flatnonzero(x, size=size, fill_value=-1)))
register("tril_indices_from", lambda k=0:
         (lambda x: jnp.stack(jnp.tril_indices_from(x, k))))


def _topk(x, k, axis, ret_typ, is_ascend):
    y = jnp.moveaxis(x, axis, -1)
    vals, idx = jax.lax.top_k(-y if is_ascend else y, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis)
    if ret_typ == "indices":
        return idx
    if ret_typ == "value":
        return vals
    return (vals, idx)


def _gather_nd(data, indices):
    idx = tuple(indices[i] for i in range(indices.shape[0]))
    return data[idx]


# ---------------------------------------------------------------------------
# linalg — reference: src/operator/numpy/linalg/*
# ---------------------------------------------------------------------------
_LINALG = {
    "linalg_inv": jnp.linalg.inv,
    "linalg_pinv": jnp.linalg.pinv,
    "linalg_det": jnp.linalg.det,
    "linalg_cholesky": jnp.linalg.cholesky,
    "linalg_eigh": lambda x: tuple(jnp.linalg.eigh(x)),
    "linalg_eigvalsh": jnp.linalg.eigvalsh,
    "linalg_matrix_rank": jnp.linalg.matrix_rank,
}
for _name, _fn in _LINALG.items():
    register(_name, (lambda f: (lambda **a: f))(_fn))

register("linalg_svd", lambda full_matrices=True, compute_uv=True:
         (lambda x: tuple(jnp.linalg.svd(x, full_matrices=full_matrices))
          if compute_uv else jnp.linalg.svd(x, compute_uv=False)))
register("linalg_qr", lambda mode="reduced":
         (lambda x: tuple(jnp.linalg.qr(x, mode=mode))))
register("linalg_slogdet", lambda **a: (lambda x: tuple(jnp.linalg.slogdet(x))))
register("linalg_solve", lambda **a: (lambda a_, b: jnp.linalg.solve(a_, b)))
register("linalg_lstsq", lambda rcond=None:
         (lambda a_, b: tuple(jnp.linalg.lstsq(a_, b, rcond=rcond))))
register("linalg_matrix_power", lambda n=1:
         (lambda x: jnp.linalg.matrix_power(x, n)))
register("linalg_multi_dot", lambda **a:
         (lambda *xs: jnp.linalg.multi_dot(list(xs))))
register("linalg_tensorsolve", lambda axes=None:
         (lambda a_, b: jnp.linalg.tensorsolve(a_, b, axes=axes)))
register("linalg_tensorinv", lambda ind=2:
         (lambda x: jnp.linalg.tensorinv(x, ind=ind)))
register("einsum", lambda subscripts="", optimize="optimal":
         (lambda *xs: jnp.einsum(subscripts, *xs,
                                 optimize=optimize or "optimal")))

# fft — reference: src/operator/contrib/fft
register("fft", lambda n=None, axis=-1: (lambda x: jnp.fft.fft(x, n, axis)))
register("ifft", lambda n=None, axis=-1: (lambda x: jnp.fft.ifft(x, n, axis)))
register("rfft", lambda n=None, axis=-1: (lambda x: jnp.fft.rfft(x, n, axis)))
register("irfft", lambda n=None, axis=-1: (lambda x: jnp.fft.irfft(x, n, axis)))

# extra numpy-parity elementwise ops
_EXTRA_UNARY = {
    "signbit": jnp.signbit,
    "positive": jnp.positive,
    "deg2rad": jnp.deg2rad,
    "rad2deg": jnp.rad2deg,
    "exp2": jnp.exp2,
    "i0": jnp.i0,
    "sinc": jnp.sinc,
}
for _name, _fn in _EXTRA_UNARY.items():
    register(_name, (lambda f: (lambda **a: f))(_fn))
register("nan_to_num", lambda nan=0.0, posinf=None, neginf=None:
         (lambda x: jnp.nan_to_num(x, nan=nan, posinf=posinf,
                                   neginf=neginf)))
register("heaviside", lambda **a: jnp.heaviside)
register("float_power", lambda **a: jnp.float_power)
register("true_divmod", lambda **a: (lambda a_, b: tuple(jnp.divmod(a_, b))))
register("digitize", lambda right=False:
         (lambda x, bins: jnp.digitize(x, bins, right=right)))
register("histogram_bounded", lambda bins=10, range=None:
         (lambda x: tuple(jnp.histogram(x, bins=bins, range=range))))
register("corrcoef", lambda **a: jnp.corrcoef)
register("cov", lambda **a: jnp.cov)

register("quantile", lambda q=0.5, axis=None, keepdims=False,
         method="linear":
         (lambda x: jnp.quantile(x, jnp.asarray(q), axis=axis,
                                 method=method, keepdims=keepdims)))
register("percentile", lambda q=50.0, axis=None, keepdims=False,
         method="linear":
         (lambda x: jnp.percentile(x, jnp.asarray(q), axis=axis,
                                   method=method, keepdims=keepdims)))
