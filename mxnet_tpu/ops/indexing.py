"""Indexing: __getitem__ / __setitem__ lowering.

Reference: src/operator/tensor/indexing_op.* and the python indexing logic in
python/mxnet/numpy/multiarray.py. Static keys (ints/slices/ellipsis/None)
become a cached XLA slice program; integer-array advanced indexing becomes a
gather with the index arrays as real op inputs (so it works under autograd and
deferred-compute tracing). Boolean-mask indexing produces a data-dependent
shape, which XLA cannot compile — it is executed eagerly on host (documented
dynamic-shape fallback, mirroring the reference's SetShapeFromChunk escape
hatch, src/imperative/imperative.cc:123).
"""
from __future__ import annotations

import numpy as onp

from ..base import MXNetError
from .registry import register, apply_op, get_op, invoke

_SLICE = "s"
_INT = "i"
_ELL = "e"
_NONE = "n"
_ARR = "a"


def _freeze_key(key):
    """Encode an index key into a hashable spec; returns (spec, array_items)."""
    if not isinstance(key, tuple):
        key = (key,)
    spec, arrays = [], []
    from ..ndarray.ndarray import NDArray

    for item in key:
        if isinstance(item, slice):
            spec.append((_SLICE, item.start, item.stop, item.step))
        elif isinstance(item, (int, onp.integer)):
            spec.append((_INT, int(item)))
        elif item is Ellipsis:
            spec.append((_ELL,))
        elif item is None:
            spec.append((_NONE,))
        elif isinstance(item, NDArray):
            spec.append((_ARR,))
            arrays.append(item)
        elif isinstance(item, (list, onp.ndarray)):
            arr = NDArray(onp.asarray(item))
            spec.append((_ARR,))
            arrays.append(arr)
        else:
            raise MXNetError(f"unsupported index item {item!r}")
    return tuple(spec), arrays


def _thaw_key(spec, arrays):
    out, it = [], iter(arrays)
    for s in spec:
        if s[0] == _SLICE:
            out.append(slice(s[1], s[2], s[3]))
        elif s[0] == _INT:
            out.append(s[1])
        elif s[0] == _ELL:
            out.append(Ellipsis)
        elif s[0] == _NONE:
            out.append(None)
        else:
            out.append(next(it))
    return tuple(out)


@register("slice_key")
def _slice_key(spec=()):
    def f(x, *idx_arrays):
        return x[_thaw_key(spec, idx_arrays)]

    return f


def _is_bool_arr(a):
    return str(a.dtype) == "bool"


def getitem(self, key):
    from ..ndarray.ndarray import NDArray

    spec, arrays = _freeze_key(key)
    if any(_is_bool_arr(a) for a in arrays):
        # dynamic output shape — host fallback, not differentiable/traceable
        from .. import _deferred_compute as dc
        from .. import autograd as ag

        if dc.is_tracing():
            raise MXNetError(
                "boolean-mask indexing has a data-dependent shape and cannot "
                "be traced into a compiled graph; use np.where or masked ops"
            )
        np_key = _thaw_key(spec, [a.asnumpy() for a in arrays])
        return NDArray(self.asnumpy()[np_key])
    return invoke(get_op("slice_key"), [self] + arrays, {"spec": spec})


def setitem(self, key, value):
    from ..ndarray.ndarray import NDArray
    from .. import autograd as ag
    from .. import _deferred_compute as dc
    import jax.numpy as jnp

    if dc.is_tracing():
        raise MXNetError("in-place indexed assignment is not supported inside "
                         "a hybridized forward; return new arrays instead")
    if ag.is_recording() and self._ag_info is not None:
        raise MXNetError("in-place indexed assignment on an array recorded by "
                         "autograd is not allowed")
    spec, arrays = _freeze_key(key)
    if isinstance(value, NDArray):
        value = value._data
    if any(_is_bool_arr(a) for a in arrays):
        np_key = _thaw_key(spec, [a.asnumpy() for a in arrays])
        host = self.asnumpy()
        host[np_key] = onp.asarray(value)
        self._set_data(jnp.asarray(host))
        return
    jkey = _thaw_key(spec, [a._data for a in arrays])
    self._set_data(self._data.at[jkey].set(value))


# scatter/index update ops usable under autograd & tracing ------------------
@register("index_update")
def _index_update(spec=()):
    def f(x, v, *idx_arrays):
        return x.at[_thaw_key(spec, idx_arrays)].set(v)

    return f


@register("index_add")
def _index_add(spec=()):
    def f(x, v, *idx_arrays):
        return x.at[_thaw_key(spec, idx_arrays)].add(v)

    return f


def index_update(data, key, value):
    """Functional indexed update: returns a new array (TPU-native scatter)."""
    spec, arrays = _freeze_key(key)
    return invoke(get_op("index_update"), [data, value] + arrays, {"spec": spec})


def index_add(data, key, value):
    spec, arrays = _freeze_key(key)
    return invoke(get_op("index_add"), [data, value] + arrays, {"spec": spec})
