"""Reference-name aliases for registered ops.

The reference exposes most kernels under several NNVM names at once via
``.add_alias`` — a CamelCase legacy name, a ``_npi_``/``_npx_`` numpy-internal
name, and/or a ``_contrib_`` name all resolving to one FCompute (e.g.
src/operator/tensor/elemwise_unary_op_basic.cc, src/operator/numpy/*_op.cc).
This module is the TPU framework's equivalent: one curated table, each entry a
true rename whose attr signature matches the target op. Ops whose legacy
signature *differs* (e.g. ``Reshape``'s 0/-2/-3/-4 shape codes, mp_* optimizer
updates with an extra fp32 master-weight input) are NOT aliased here — they get
real registrations in legacy_elemwise.py / optimizer_ops.py.
"""
from .registry import register_alias

# -- legacy CamelCase layer names (reference: src/operator/nn/*.cc) ---------
_LEGACY_CAMEL = {
    "Activation": "activation",
    "BatchNorm": "batch_norm",
    "CuDNNBatchNorm": "batch_norm",   # reference alias: cudnn_batch_norm.cc
    "Convolution": "convolution",
    "Deconvolution": "deconvolution",
    "Dropout": "dropout",
    "Embedding": "embedding",
    "Flatten": "flatten",
    "FullyConnected": "fully_connected",
    "GroupNorm": "group_norm",
    "InstanceNorm": "instance_norm",
    "LayerNorm": "layer_norm",
    "LeakyReLU": "leaky_relu",
    "Pad": "pad",
    "Pooling": "pooling",
    "SequenceMask": "sequence_mask",
    "SequenceLast": "sequence_last",
    "SequenceReverse": "sequence_reverse",
    "CTCLoss": "ctc_loss",
    "RNN": "rnn",
    "ROIPooling": "roi_pooling",
    "UpSampling": "upsampling",
    "SwapAxis": "swapaxes_legacy",     # registered in legacy_elemwise.py
    "Cast": "astype",
    "BlockGrad": "stop_gradient",
    # spatial-warping / deformable tier (warp_ops.py)
    "BilinearSampler": "bilinear_sampler",
    "GridGenerator": "grid_generator",
    "SpatialTransformer": "spatial_transformer",
    "Correlation": "correlation",
    "_contrib_DeformableConvolution": "deformable_convolution",
    "_contrib_ModulatedDeformableConvolution":
        "modulated_deformable_convolution",
    "_contrib_PSROIPooling": "psroi_pooling",
    "_contrib_DeformablePSROIPooling": "deformable_psroi_pooling",
}

# -- legacy underscore elemwise names (elemwise_binary_op_basic.cc etc.) ----
_LEGACY_UNDER = {
    "_copy": "copy",
    "_copyto": "copy",
    # elemwise_unary_op_basic.cc:245 — bare `identity` is an alias of _copy
    # in the reference; the matrix creator lives at _npi_identity only
    "identity": "copy",
    "_equal": "equal",
    "_not_equal": "not_equal",
    "_greater": "greater",
    "_greater_equal": "greater_equal",
    "_lesser": "less",
    "_lesser_equal": "less_equal",
    "_logical_and": "logical_and",
    "_logical_or": "logical_or",
    "_logical_xor": "logical_xor",
    "_maximum": "maximum",
    "_minimum": "minimum",
    "_hypot": "hypot",
    "_mod": "mod",
    "_power": "power",
    # broadcast_* — in this framework every binary op broadcasts (XLA),
    # so the broadcast_ names are true aliases (reference:
    # elemwise_binary_broadcast_op_basic.cc)
    "broadcast_add": "add",
    "broadcast_plus": "add",
    "broadcast_sub": "subtract",
    "broadcast_minus": "subtract",
    "broadcast_mul": "multiply",
    "broadcast_div": "true_divide",
    "broadcast_mod": "mod",
    "broadcast_power": "power",
    "broadcast_maximum": "maximum",
    "broadcast_minimum": "minimum",
    "broadcast_hypot": "hypot",
    "broadcast_equal": "equal",
    "broadcast_not_equal": "not_equal",
    "broadcast_greater": "greater",
    "broadcast_greater_equal": "greater_equal",
    "broadcast_lesser": "less",
    "broadcast_lesser_equal": "less_equal",
    "broadcast_logical_and": "logical_and",
    "broadcast_logical_or": "logical_or",
    "broadcast_logical_xor": "logical_xor",
    # elemwise_* strict (same-shape) variants — broadcasting superset
    "elemwise_add": "add",
    "elemwise_sub": "subtract",
    "elemwise_mul": "multiply",
    "elemwise_div": "true_divide",
    "rsqrt": "reciprocal_sqrt",        # registered in legacy_elemwise.py
    "_adabelief_update": "adabelief_update",
    "_adamw_update": "adamw_update",
    "_sparse_adagrad_update": "sparse_adagrad_update",
    "_unravel_index": "unravel_index",
    "_ravel_multi_index": "ravel_multi_index",
}

# -- _contrib_* names (src/operator/contrib/*.cc) ---------------------------
_CONTRIB = {
    "_contrib_allclose": "allclose",
    "_contrib_arange_like": "arange_like",
    "_contrib_bipartite_matching": "bipartite_matching",
    "_contrib_box_decode": "box_decode",
    "_contrib_box_encode": "box_encode",
    "_contrib_box_iou": "box_iou",
    "_contrib_box_nms": "box_nms",
    "_contrib_box_non_maximum_suppression": "box_nms",
    "_contrib_group_adagrad_update": "group_adagrad_update",
    "_contrib_index_copy": "index_copy",
    "_contrib_quadratic": "quadratic",
    "_contrib_AdaptiveAvgPooling2D": "adaptive_avg_pool2d",
    "_contrib_BilinearResize2D": "bilinear_resize_2d",
    "_contrib_MultiBoxPrior": "multibox_prior",
    "_contrib_MultiBoxTarget": "multibox_target",
    "_contrib_MultiBoxDetection": "multibox_detection",
    "_contrib_ROIAlign": "roi_align",
    "_contrib_interleaved_matmul_selfatt_qk": "interleaved_matmul_selfatt_qk",
    "_contrib_interleaved_matmul_selfatt_valatt":
        "interleaved_matmul_selfatt_valatt",
    "_contrib_interleaved_matmul_encdec_qk": "interleaved_matmul_encdec_qk",
    "_contrib_interleaved_matmul_encdec_valatt":
        "interleaved_matmul_encdec_valatt",
    "_contrib_quantize": "contrib_quantize",
    "_contrib_dequantize": "contrib_dequantize",
    # the reference operand layout (qdata, qweight[, qbias], min/max ranges)
    # is the _v2 op's contract; the plain contrib op has a scale-based API
    "_contrib_quantized_fully_connected": "quantized_fully_connected_v2",
}

# -- _npi_* numpy-internal names (src/operator/numpy/*.cc) ------------------
_NPI = {
    "_np_reshape": "reshape",
    "_npi_add": "add",
    "_npi_subtract": "subtract",
    "_npi_multiply": "multiply",
    "_npi_true_divide": "true_divide",
    "_npi_mod": "mod",
    "_npi_fmod": "fmod",
    "_npi_power": "power",
    "_npi_powerd": "power",
    "_npi_copysign": "copysign",
    "_npi_arctan2": "arctan2",
    "_npi_hypot": "hypot",
    "_npi_fmax": "fmax",
    "_npi_fmin": "fmin",
    "_npi_gcd": "gcd",
    "_npi_lcm": "lcm",
    "_npi_ldexp": "ldexp",
    "_npi_bitwise_and": "bitwise_and",
    "_npi_bitwise_or": "bitwise_or",
    "_npi_bitwise_xor": "bitwise_xor",
    "_npi_bitwise_not": "invert",
    "_npi_log": "log",
    "_npi_matmul": "matmul",
    "_npi_dot": "dot",
    "_npi_tensordot": "tensordot",
    "_npi_tensordot_int_axes": "tensordot",
    "_npi_kron": "kron",
    "_npi_cross": "cross",
    "_npi_einsum": "einsum",
    "_npi_sum": "sum",
    "_npi_mean": "mean",
    "_npi_prod": "prod",
    "_npi_std": "std",
    "_npi_var": "var",
    "_npi_max": "max",
    "_npi_min": "min",
    "_npi_all": "all",
    "_npi_any": "any",
    "_npi_argmax": "argmax",
    "_npi_argmin": "argmin",
    "_npi_average": "average",
    "_npi_norm": "norm",
    "_npi_trace": "trace",
    "_npi_cumsum": "cumsum",
    "_npi_diff": "diff",
    "_npi_ediff1d": "ediff1d",
    "_npi_percentile": "percentile",
    "_npi_bincount": "bincount",
    "_npi_interp": "interp",
    "_npi_polyval": "polyval",
    "_npi_nan_to_num": "nan_to_num",
    "_npi_around": "round",
    "_npi_deg2rad": "deg2rad",
    "_npi_rad2deg": "rad2deg",
    "_npi_atleast_1d": "atleast_1d",
    "_npi_atleast_2d": "atleast_2d",
    "_npi_atleast_3d": "atleast_3d",
    "_npi_broadcast_to": "broadcast_to",
    "_npi_concatenate": "concatenate",
    "_npi_stack": "stack",
    "_npi_copy": "copy",
    "_npi_flip": "flip",
    "_npi_roll": "roll",
    "_npi_rot90": "rot90",
    "_npi_rollaxis": "rollaxis",
    "_npi_moveaxis": "moveaxis",
    "_npi_squeeze": "squeeze",
    "_npi_transpose": "transpose",
    "_npi_diag": "diag",
    "_npi_diagflat": "diagflat",
    "_npi_diagonal": "diagonal",
    "_npi_fill_diagonal": "fill_diagonal",
    "_npi_tril": "tril",
    "_npi_triu": "triu",
    "_npi_tril_indices": "tril_indices",
    "_npi_pad": "pad",
    "_npi_where": "where",
    "_npi_blackman": "blackman",
    "_npi_hamming": "hamming",
    "_npi_hanning": "hanning",
    "_npi_repeats": "repeat",
    # linalg (src/operator/numpy/linalg/*.cc) — one jnp.linalg lowering,
    # several dispatch names
    "_npi_cholesky": "linalg_cholesky",
    "_npi_eigh": "linalg_eigh",
    "_npi_eigvalsh": "linalg_eigvalsh",
    "_npi_svd": "linalg_svd",
    "_npi_qr": "linalg_qr",
    "_npi_solve": "linalg_solve",
    "_npi_lstsq": "linalg_lstsq",
    "_npi_matrix_rank": "linalg_matrix_rank",
    "_npi_matrix_rank_none_tol": "linalg_matrix_rank",
    "_npi_pinv": "linalg_pinv",
    "_npi_pinv_scalar_rcond": "linalg_pinv",
    "_npi_tensorinv": "linalg_tensorinv",
    "_npi_tensorsolve": "linalg_tensorsolve",
    "_npx_index_add": "index_add",
    "_npx_index_update": "index_update",
}

# legacy _linalg_* names (src/operator/tensor/la_op.cc) → linalg_legacy ops
_LINALG_LEGACY = {
    "_linalg_gemm": "linalg_gemm",
    "_linalg_gemm2": "linalg_gemm2",
    "_linalg_potrf": "linalg_potrf",
    "_linalg_potri": "linalg_potri",
    "_linalg_trmm": "linalg_trmm",
    "_linalg_trsm": "linalg_trsm",
    "_linalg_syrk": "linalg_syrk",
    "_linalg_syevd": "linalg_syevd",
    "_linalg_gelqf": "linalg_gelqf",
    "_linalg_makediag": "linalg_makediag",
    "_linalg_maketrian": "linalg_maketrian",
    "_linalg_extractdiag": "linalg_extractdiag",
    "_linalg_extracttrian": "linalg_extracttrian",
    "_linalg_sumlogdiag": "linalg_sumlogdiag",
    "_linalg_det": "linalg_det",
    "_linalg_inverse": "linalg_inverse",
    "_linalg_slogdet": "linalg_slogdet",
}

ALIASES = {}
for _tbl in (_LEGACY_CAMEL, _LEGACY_UNDER, _CONTRIB, _NPI, _LINALG_LEGACY):
    ALIASES.update(_tbl)


def _register_all():
    """Register every alias whose target exists; callable more than once.

    Some targets live in subpackages imported after ops/ (e.g. the quantize
    ops in mxnet_tpu.contrib.quantization), so mxnet_tpu/__init__ calls this
    again at the end of package import to pick up the stragglers.
    """
    from .registry import _OPS

    for alias, target in ALIASES.items():
        if alias not in _OPS and target in _OPS:
            register_alias(alias, target)
