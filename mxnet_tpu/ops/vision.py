"""Vision / detection operator tier — XLA-native, static-shape throughout.

TPU-native equivalents of the reference's detection ops:
- box_iou / box_nms / box_encode / box_decode
  (src/operator/contrib/bounding_box.cc)
- roi_pooling (src/operator/roi_pooling.cc), roi_align
  (src/operator/contrib/roi_align.cc)
- upsampling (src/operator/nn/upsampling.cc), bilinear_resize_2d
  (src/operator/contrib/bilinear_resize.cc)
- moments (src/operator/nn/moments.cc)

Design notes (TPU-first): every op keeps static shapes. box_nms follows the
reference contract — output has the SAME shape as the input with suppressed
entries overwritten by -1 — which is exactly what a fixed-shape XLA program
wants; the suppression sweep is a `lax.fori_loop` carrying a keep-mask (one
vectorized O(N) step per kept candidate) rather than a data-dependent loop.
ROI ops sample with gather + bilinear weights (MXU-friendly, no host sync).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register

__all__ = []


# ---------------------------------------------------------------------------
# box geometry helpers
# ---------------------------------------------------------------------------
def _to_corner(b, fmt):
    """(..., 4) boxes → corner (x1, y1, x2, y2)."""
    if fmt == "corner":
        return b
    if fmt == "center":
        x, y, w, h = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
        return jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2], -1)
    raise MXNetError(f"unknown box format {fmt!r}")


def _to_center(b):
    """Corner (x1, y1, x2, y2) boxes → center (x, y, w, h)."""
    xy = (b[..., :2] + b[..., 2:]) / 2
    wh = b[..., 2:] - b[..., :2]
    return jnp.concatenate([xy, wh], -1)


def _area(b):
    return jnp.maximum(b[..., 2] - b[..., 0], 0) * \
        jnp.maximum(b[..., 3] - b[..., 1], 0)


def _pair_iou(a, b):
    """IoU of a (..., M, 4) vs b (..., N, 4) → (..., M, N). Corner format."""
    tl = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    br = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = jnp.maximum(br - tl, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = _area(a)[..., :, None] + _area(b)[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register("box_iou")
def _box_iou(format="corner"):  # noqa: A002 — reference attr name
    def f(lhs, rhs):
        return _pair_iou(_to_corner(lhs, format), _to_corner(rhs, format))

    return f


@register("box_nms", differentiable=False)
def _box_nms(overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
             coord_start=2, score_index=1, id_index=-1,
             background_id=-1, force_suppress=False, in_format="corner",
             out_format="corner"):
    """Non-maximum suppression, reference-contract output.

    Input (B, N, K) or (N, K): per-row [.. id .. score .. x1 y1 x2 y2 ..].
    Output has identical shape; suppressed / invalid rows are all -1.
    """
    cs, si, ii = coord_start, score_index, id_index

    def nms_one(rows):
        n = rows.shape[0]
        score = rows[:, si]
        boxes = _to_corner(lax.dynamic_slice_in_dim(rows, cs, 4, axis=1),
                           in_format)
        cls = rows[:, ii] if ii >= 0 else jnp.zeros((n,))
        valid = score > valid_thresh
        if ii >= 0 and background_id >= 0:
            valid &= cls != background_id
        # order by score descending, invalid rows last
        order = jnp.argsort(jnp.where(valid, -score, jnp.inf))
        boxes_s, cls_s, valid_s = boxes[order], cls[order], valid[order]
        if topk > 0:
            # reference contract: NMS runs over only the top-k scored
            # candidates; the rest are discarded outright
            valid_s &= jnp.arange(n) < topk
        iou = _pair_iou(boxes_s, boxes_s)
        same = jnp.ones((n, n), bool) if force_suppress else \
            cls_s[:, None] == cls_s[None, :]
        sup = (iou > overlap_thresh) & same  # candidate suppression matrix

        def body(i, keep):
            # row i survives iff no higher-scored KEPT row suppresses it
            k = valid_s[i] & ~jnp.any(keep & sup[:, i] &
                                      (jnp.arange(n) < i))
            return keep.at[i].set(k)

        keep = lax.fori_loop(0, n, body, jnp.zeros((n,), bool))
        rows_out = rows[order]
        if out_format != in_format:
            conv = _to_corner(boxes_s, "corner") if out_format == "corner" \
                else _to_center(boxes_s)
            rows_out = lax.dynamic_update_slice_in_dim(
                rows_out, conv.astype(rows_out.dtype), cs, axis=1)
        out = jnp.where(keep[:, None], rows_out, -1.0)
        # reference compacts kept rows to the front (score-sorted already)
        front = jnp.argsort(~keep, stable=True)
        return out[front]

    def f(data):
        if data.ndim == 2:
            return nms_one(data)
        if data.ndim == 3:
            return jax.vmap(nms_one)(data)
        raise MXNetError("box_nms expects (N, K) or (B, N, K)")

    return f


@register("box_encode")
def _box_encode(means=(0.0, 0.0, 0.0, 0.0), stds=(0.1, 0.1, 0.2, 0.2)):
    """SSD-style anchor→target encoding (bounding_box.cc BoxEncode).

    samples (B, N): 1 = positive match, 0 ignore, -1 negative;
    matches (B, N): matched ground-truth index per anchor;
    anchors (B, N, 4), refs (B, M, 4) corner format.
    Returns (targets (B, N, 4), masks (B, N, 4)).
    """
    mean = jnp.asarray(means)
    std = jnp.asarray(stds)

    def f(samples, matches, anchors, refs):
        gt = jnp.take_along_axis(
            refs, matches[..., None].astype(jnp.int32), axis=1)
        a_xy = (anchors[..., :2] + anchors[..., 2:]) / 2
        a_wh = jnp.maximum(anchors[..., 2:] - anchors[..., :2], 1e-9)
        g_xy = (gt[..., :2] + gt[..., 2:]) / 2
        g_wh = jnp.maximum(gt[..., 2:] - gt[..., :2], 1e-9)
        t = jnp.concatenate([(g_xy - a_xy) / a_wh, jnp.log(g_wh / a_wh)], -1)
        t = (t - mean) / std
        mask = (samples > 0.5)[..., None].astype(t.dtype)
        return jnp.where(mask > 0, t, 0.0), jnp.broadcast_to(mask, t.shape)

    return f


@register("box_decode")
def _box_decode(std0=0.1, std1=0.1, std2=0.2, std3=0.2, clip=-1.0,
                format="center"):  # noqa: A002
    """Inverse of box_encode (bounding_box.cc BoxDecode): deltas + anchors →
    corner boxes. ``format`` is the ANCHOR storage format."""
    std = jnp.asarray([std0, std1, std2, std3])

    def f(data, anchors):
        a = anchors
        if format == "corner":
            a_xy = (a[..., :2] + a[..., 2:]) / 2
            a_wh = a[..., 2:] - a[..., :2]
        else:
            a_xy, a_wh = a[..., :2], a[..., 2:]
        d = data * std
        xy = d[..., :2] * a_wh + a_xy
        dwh = d[..., 2:]
        if clip > 0:
            dwh = jnp.minimum(dwh, clip)
        wh = jnp.exp(dwh) * a_wh / 2
        return jnp.concatenate([xy - wh, xy + wh], -1)

    return f


# ---------------------------------------------------------------------------
# ROI ops
# ---------------------------------------------------------------------------
@register("roi_pooling")
def _roi_pooling(pooled_size=(7, 7), spatial_scale=1.0):
    """Max-pool each ROI onto a fixed grid (src/operator/roi_pooling.cc).

    data (B, C, H, W); rois (R, 5) rows [batch_idx, x1, y1, x2, y2] in image
    coords. Static shapes: the (ph, pw) bin sweep is a compile-time loop of
    vectorized masked maxes.
    """
    ph, pw = pooled_size

    def f(data, rois):
        _, _, H, W = data.shape
        ys = jnp.arange(H, dtype=data.dtype)
        xs = jnp.arange(W, dtype=data.dtype)

        def one(roi):
            feat = data[roi[0].astype(jnp.int32)]  # (C, H, W)
            x1, y1, x2, y2 = [jnp.round(roi[i + 1] * spatial_scale)
                              for i in range(4)]
            rw = jnp.maximum(x2 - x1 + 1, 1.0)
            rh = jnp.maximum(y2 - y1 + 1, 1.0)
            bh, bw = rh / ph, rw / pw
            outs = []
            for py in range(ph):
                for px in range(pw):
                    ys0 = jnp.floor(y1 + py * bh)
                    ys1 = jnp.ceil(y1 + (py + 1) * bh)
                    xs0 = jnp.floor(x1 + px * bw)
                    xs1 = jnp.ceil(x1 + (px + 1) * bw)
                    m = ((ys >= ys0) & (ys < ys1))[:, None] & \
                        ((xs >= xs0) & (xs < xs1))[None, :]
                    v = jnp.max(jnp.where(m, feat, -jnp.inf), axis=(1, 2))
                    outs.append(jnp.where(jnp.isfinite(v), v, 0.0))
            return jnp.stack(outs, -1).reshape(feat.shape[0], ph, pw)

        return jax.vmap(one)(rois)

    return f


@register("roi_align")
def _roi_align(pooled_size=(7, 7), spatial_scale=1.0, sample_ratio=2,
               position_sensitive=False, aligned=False):
    """Bilinear ROI align (src/operator/contrib/roi_align.cc).

    Average of ``sample_ratio²`` bilinear taps per output bin, matching the
    reference's two-direction averaging. Taps are gathers + 4-point lerp.
    """
    if position_sensitive:
        raise MXNetError("roi_align: position_sensitive=True (PSRoIAlign) "
                         "is not implemented")
    ph, pw = pooled_size
    sr = max(int(sample_ratio), 1)

    def f(data, rois):
        _, _, H, W = data.shape
        off = 0.5 if aligned else 0.0

        def bilinear(feat, y, x):
            # feat (C, H, W); y/x (...,) continuous coords
            y = jnp.clip(y, 0.0, H - 1.0)
            x = jnp.clip(x, 0.0, W - 1.0)
            y0 = jnp.floor(y).astype(jnp.int32)
            x0 = jnp.floor(x).astype(jnp.int32)
            y1 = jnp.minimum(y0 + 1, H - 1)
            x1 = jnp.minimum(x0 + 1, W - 1)
            wy = (y - y0).astype(feat.dtype)
            wx = (x - x0).astype(feat.dtype)
            v00 = feat[:, y0, x0]
            v01 = feat[:, y0, x1]
            v10 = feat[:, y1, x0]
            v11 = feat[:, y1, x1]
            return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                    v10 * wy * (1 - wx) + v11 * wy * wx)

        def one(roi):
            feat = data[roi[0].astype(jnp.int32)]
            x1 = roi[1] * spatial_scale - off
            y1 = roi[2] * spatial_scale - off
            x2 = roi[3] * spatial_scale - off
            y2 = roi[4] * spatial_scale - off
            rw = x2 - x1 if aligned else jnp.maximum(x2 - x1, 1.0)
            rh = y2 - y1 if aligned else jnp.maximum(y2 - y1, 1.0)
            bh, bw = rh / ph, rw / pw
            # sample grid: (ph*sr, pw*sr) tap coordinates
            gy = y1 + (jnp.arange(ph * sr) + 0.5) * bh / sr
            gx = x1 + (jnp.arange(pw * sr) + 0.5) * bw / sr
            yy, xx = jnp.meshgrid(gy, gx, indexing="ij")
            taps = bilinear(feat, yy.ravel(), xx.ravel())  # (C, ph*sr*pw*sr)
            taps = taps.reshape(-1, ph, sr, pw, sr)
            return taps.mean(axis=(2, 4))

        return jax.vmap(one)(rois)

    return f


# ---------------------------------------------------------------------------
# resize / upsample / moments
# ---------------------------------------------------------------------------
def _bilinear_grid(feat, out_h, out_w, align_corners=True):
    """Resize (..., H, W) → (..., out_h, out_w) with true align-corners
    bilinear (the reference's BilinearResize2D semantics, which
    jax.image.resize does not offer)."""
    H, W = feat.shape[-2], feat.shape[-1]

    def coords(n_in, n_out):
        if n_out == 1:
            return jnp.zeros((1,))
        if align_corners:
            return jnp.linspace(0.0, n_in - 1.0, n_out)
        step = n_in / n_out
        return jnp.clip((jnp.arange(n_out) + 0.5) * step - 0.5, 0, n_in - 1)

    y, x = coords(H, out_h), coords(W, out_w)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    wy = (y - y0).astype(feat.dtype)[:, None]
    wx = (x - x0).astype(feat.dtype)[None, :]
    r0 = feat[..., y0, :]
    r1 = feat[..., y1, :]
    row = lambda r: r[..., x0] * (1 - wx) + r[..., x1] * wx  # noqa: E731
    return row(r0) * (1 - wy) + row(r1) * wy


@register("bilinear_resize_2d")
def _bilinear_resize(height=0, width=0, scale_height=None, scale_width=None,
                     align_corners=True):
    def f(data):
        H, W = data.shape[-2], data.shape[-1]
        oh = height if height > 0 else int(round(H * (scale_height or 1.0)))
        ow = width if width > 0 else int(round(W * (scale_width or 1.0)))
        return _bilinear_grid(data, oh, ow, align_corners)

    return f


@register("upsampling")
def _upsampling(scale=2, sample_type="nearest", num_args=1):
    """UpSampling (src/operator/nn/upsampling.cc): nearest repeats; bilinear
    routes through the same gather-lerp as bilinear_resize_2d."""
    s = int(scale)

    def f(data):
        if sample_type == "nearest":
            return jnp.repeat(jnp.repeat(data, s, axis=-2), s, axis=-1)
        if sample_type == "bilinear":
            H, W = data.shape[-2], data.shape[-1]
            return _bilinear_grid(data, H * s, W * s, align_corners=True)
        raise MXNetError(f"unknown sample_type {sample_type!r}")

    return f


@register("moments", nout=2)
def _moments(axes=None, keepdims=False):
    ax = tuple(axes) if axes is not None else None

    def f(data):
        mean = jnp.mean(data, axis=ax, keepdims=keepdims)
        var = jnp.var(data, axis=ax, keepdims=keepdims)
        return mean, var

    return f
