"""Operator library: importing this package registers all built-in ops."""
from .registry import Op, register, get_op, list_ops, invoke, apply_op
from . import _core  # noqa: F401 — registers elemwise/reduce/shape/linalg ops
from . import nn  # noqa: F401 — registers NN ops
from . import indexing  # noqa: F401 — registers slice/scatter ops
from . import rnn  # noqa: F401 — registers the fused scan RNN op
from . import vision  # noqa: F401 — registers detection/resize/ROI ops
from . import extra  # noqa: F401 — legacy tensor/transformer/multibox ops
from . import linalg_legacy  # noqa: F401 — mx.nd.linalg_* family
from . import optimizer_ops  # noqa: F401 — fused update ops incl. sparse

__all__ = ["Op", "register", "get_op", "list_ops", "invoke", "apply_op"]
