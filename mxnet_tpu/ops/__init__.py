"""Operator library: importing this package registers all built-in ops."""
from .registry import Op, register, get_op, list_ops, invoke, apply_op
from . import _core  # noqa: F401 — registers elemwise/reduce/shape/linalg ops
from . import nn  # noqa: F401 — registers NN ops
from . import indexing  # noqa: F401 — registers slice/scatter ops
from . import rnn  # noqa: F401 — registers the fused scan RNN op
from . import vision  # noqa: F401 — registers detection/resize/ROI ops

__all__ = ["Op", "register", "get_op", "list_ops", "invoke", "apply_op"]
