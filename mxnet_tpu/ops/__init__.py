"""Operator library: importing this package registers all built-in ops."""
from .registry import Op, register, get_op, list_ops, invoke, apply_op
from . import _core  # noqa: F401 — registers elemwise/reduce/shape/linalg ops
from . import nn  # noqa: F401 — registers NN ops
from . import indexing  # noqa: F401 — registers slice/scatter ops
from . import rnn  # noqa: F401 — registers the fused scan RNN op
from . import vision  # noqa: F401 — registers detection/resize/ROI ops
from . import extra  # noqa: F401 — legacy tensor/transformer/multibox ops
from . import linalg_legacy  # noqa: F401 — mx.nd.linalg_* family
from . import optimizer_ops  # noqa: F401 — fused update ops incl. sparse
from . import legacy_elemwise  # noqa: F401 — scalar/creation/slice legacy tiers
from . import random_ops  # noqa: F401 — _random_/_sample_/_npi_ sampler ops
from . import quantized_ops  # noqa: F401 — int8 quantized family + intgemm
from . import graph_image_ops  # noqa: F401 — sldwin attention, dgl, image/cv
from . import npi_manip  # noqa: F401 — dynamic-shape manip, control flow, contrib
from . import warp_ops  # noqa: F401 — STN/deformable/correlation tier
from . import tp_collectives  # noqa: F401 — megatron tp collectives
from . import aliases as _aliases  # reference-name aliases (NNVM add_alias analog)

_aliases._register_all()

__all__ = ["Op", "register", "get_op", "list_ops", "invoke", "apply_op"]
