"""Dynamic-shape numpy manipulation ops, control-flow ops, and the last
contrib stragglers.

- ``_npi_unique``/``_npx_nonzero``/``_npi_delete``/``_npi_insert_*``/
  ``_contrib_boolean_mask``/``_npi_advanced_indexing*``
  (src/operator/numpy/np_unique_op.cc, np_nonzero_op.cc, np_delete_op.cc,
  np_insert_op*.cc, contrib/boolean_mask.cc): data-dependent output shapes.
  The reference pins them to CPU FComputeEx; here they are eager host ops
  (``jit=False``) — under CachedOp tracing they raise, same restriction the
  reference has under hybridize.
- ``_foreach``/``_while_loop``/``_cond`` (src/operator/control_flow.cc:1096,
  1157,1218): higher-order ops. The TPU-native lowering is lax.scan /
  lax.while_loop / lax.cond via numpy_extension.control_flow — registered
  here as ops whose subgraph attr is the Python callable (the reference
  stores the subgraph as a node attr the same way).
- hawkesll, mrcnn_mask_target, RROIAlign, calibrate_entropy
  (contrib/hawkes_ll.cc, mrcnn_mask_target.cu, deformable ROI family,
  quantization/calibrate.cc).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as onp

from .registry import register, register_alias

# ---------------------------------------------------------------------------
# dynamic-shape manip (eager host ops)
# ---------------------------------------------------------------------------
@register("unique", nout=2, jit=False, differentiable=False)
def _unique(return_index=False, return_inverse=False, return_counts=False,
            axis=None, **a):
    def f(x):
        res = onp.unique(onp.asarray(x), return_index=return_index,
                         return_inverse=return_inverse,
                         return_counts=return_counts, axis=axis)
        if isinstance(res, tuple):
            return tuple(jnp.asarray(r) for r in res)
        return jnp.asarray(res)

    return f


register_alias("_npi_unique", "unique")


@register("nonzero", jit=False, differentiable=False)
def _nonzero(**a):
    """npx.nonzero (np_nonzero_op.cc): returns an (N, ndim) int array of
    indices — transposed relative to numpy's tuple convention."""
    def f(x):
        nz = onp.nonzero(onp.asarray(x))
        return jnp.asarray(onp.stack(nz, axis=-1).astype("int32"))

    return f


register_alias("_npx_nonzero", "nonzero")


@register("boolean_mask", jit=False, differentiable=False)
def _boolean_mask(axis=0, **a):
    """contrib/boolean_mask.cc: rows of ``data`` where ``mask`` is true.
    Dynamic output shape -> eager only; the bounded-shape variant
    (flatnonzero_bounded + take) is the jit-friendly alternative."""
    def f(data, mask):
        d = onp.asarray(data)
        m = onp.asarray(mask).astype(bool)
        return jnp.asarray(onp.compress(m, d, axis=axis))

    return f


register_alias("_contrib_boolean_mask", "boolean_mask")

register("_npi_boolean_mask_assign_scalar", lambda value=0.0, **a:
         (lambda data, mask: jnp.where(
             mask.astype(bool).reshape(
                 mask.shape + (1,) * (data.ndim - mask.ndim)),
             jnp.asarray(value, data.dtype), data)))
register("_npi_boolean_mask_assign_tensor", lambda **a:
         (lambda data, mask, value: _mask_assign_tensor(data, mask, value)),
         jit=False, differentiable=False)


def _mask_assign_tensor(data, mask, value):
    d = onp.asarray(data).copy()
    m = onp.asarray(mask).astype(bool)
    d[m] = onp.asarray(value)
    return jnp.asarray(d)


@register("delete", jit=False, differentiable=False)
def _delete(start=None, stop=None, step=None, int_ind=None, axis=None, **a):
    def f(x, *obj):
        arr = onp.asarray(x)
        if obj:
            sel = onp.asarray(obj[0]).astype("int64")
        elif int_ind is not None:
            sel = int_ind
        else:
            sel = slice(start, stop, step)
        return jnp.asarray(onp.delete(arr, sel, axis=axis))

    return f


register_alias("_npi_delete", "delete")


def _insert_impl(arr, index, values, axis):
    return jnp.asarray(onp.insert(onp.asarray(arr), index,
                                  onp.asarray(values), axis=axis))


@register("_npi_insert_scalar", jit=False, differentiable=False)
def _insert_scalar(int_ind=0, val=None, axis=None, **a):
    def f(x, *values):
        vals = values[0] if values else val
        return _insert_impl(x, int_ind, vals, axis)

    return f


@register("_npi_insert_slice", jit=False, differentiable=False)
def _insert_slice(start=None, stop=None, step=None, val=None, axis=None,
                  **a):
    def f(x, *values):
        vals = values[0] if values else val
        return _insert_impl(x, slice(start, stop, step), vals, axis)

    return f


@register("_npi_insert_tensor", jit=False, differentiable=False)
def _insert_tensor(axis=None, **a):
    def f(x, values, index):
        return _insert_impl(x, onp.asarray(index).astype("int64"),
                            values, axis)

    return f


@register("advanced_indexing", jit=False, differentiable=False)
def _advanced_indexing(**a):
    """_npi_advanced_indexing (np_indexing_op.cc): x[idx] with an integer
    or boolean index array."""
    def f(x, idx):
        i = onp.asarray(idx)
        if i.dtype == bool:
            return jnp.asarray(onp.asarray(x)[i])
        return jnp.asarray(onp.asarray(x)[i.astype("int64")])

    return f


register_alias("_npi_advanced_indexing", "advanced_indexing")


@register("advanced_indexing_multiple", jit=False, differentiable=False)
def _advanced_indexing_multiple(**a):
    """x[idx0, idx1, ...] with broadcast integer index arrays."""
    def f(x, *idxs):
        key = tuple(onp.asarray(i).astype("int64") for i in idxs)
        return jnp.asarray(onp.asarray(x)[key])

    return f


register_alias("_npi_advanced_indexing_multiple",
               "advanced_indexing_multiple")

# eig/eigvals dispatch names (linalg_legacy implements the kernels)
register_alias("_npi_eig", "linalg_eig")
register_alias("_npi_eigvals", "linalg_eigvals")

# ---------------------------------------------------------------------------
# legacy Concat (dim attr + variadic args) — src/operator/nn/concat.cc
# ---------------------------------------------------------------------------
register("Concat", lambda dim=1, num_args=0, **a:
         (lambda *xs: jnp.concatenate(xs, axis=dim)))
register_alias("concat", "Concat")

# ---------------------------------------------------------------------------
# control flow — control_flow.cc (_foreach:1096, _while_loop:1157, _cond:1218)
# ---------------------------------------------------------------------------
@register("_foreach", nout=2, jit=False)
def _foreach_op(body=None, num_states=0, **a):
    """Runs ``body(slice, states)`` over axis 0 — lowered to lax.scan by
    npx.foreach (the TPU-correct loop: one trace, no per-step dispatch)."""
    def f(data, *states):
        from ..numpy_extension import control_flow as cf
        from ..ndarray.ndarray import NDArray

        outs, st = cf.foreach(body, NDArray(data),
                              [NDArray(s) for s in states])
        out_list = outs if isinstance(outs, (list, tuple)) else [outs]
        st_list = st if isinstance(st, (list, tuple)) else [st]
        return tuple(o._data for o in out_list) + \
            tuple(s._data for s in st_list)

    return f


@register("_while_loop", nout=2, jit=False)
def _while_loop_op(cond=None, func=None, max_iterations=None, **a):
    def f(*loop_vars):
        from ..numpy_extension import control_flow as cf
        from ..ndarray.ndarray import NDArray

        outs, final = cf.while_loop(cond, func,
                                    [NDArray(v) for v in loop_vars],
                                    max_iterations=max_iterations)
        out_list = outs if isinstance(outs, (list, tuple)) else [outs]
        fin_list = final if isinstance(final, (list, tuple)) else [final]
        return tuple(o._data for o in out_list) + \
            tuple(s._data for s in fin_list)

    return f


@register("_cond", jit=False)
def _cond_op(then_func=None, else_func=None, **a):
    def f(pred, *inputs):
        from ..numpy_extension import control_flow as cf
        from ..ndarray.ndarray import NDArray

        out = cf.cond(NDArray(pred), then_func, else_func,
                      [NDArray(v) for v in inputs])
        if isinstance(out, (list, tuple)):
            return tuple(o._data for o in out)
        return out._data

    return f


# ---------------------------------------------------------------------------
# contrib stragglers
# ---------------------------------------------------------------------------
@register("hawkesll", nout=2)
def _hawkesll(**a):
    """Log-likelihood of a marked self-exciting Hawkes process
    (contrib/hawkes_ll.cc). Inputs follow the reference:
    mu (K,), alpha (K,), beta (K,), state (N,K), lags (N,T), marks (N,T),
    valid_length (N,), max_time (N,). Returns (loglik (N,), new_state)."""
    def f(mu, alpha, beta, state, lags, marks, valid_length, max_time):
        N, T = lags.shape
        K = mu.shape[0]
        marks_i = marks.astype(jnp.int32)
        t_idx = jnp.arange(T)
        valid = t_idx[None, :] < valid_length[:, None].astype(jnp.int32)

        def step(carry, xs):
            rmem, t_elapsed, comp = carry
            lag, mark, ok = xs
            decay = jnp.exp(-beta[None, :] * lag[:, None])
            rmem_d = rmem * decay
            lam = mu[mark] + alpha[mark] * jnp.take_along_axis(
                rmem_d, mark[:, None], axis=1)[:, 0]
            ll = jnp.where(ok, jnp.log(jnp.maximum(lam, 1e-30)), 0.0)
            one_hot = jax.nn.one_hot(mark, K, dtype=rmem.dtype)
            rmem_new = jnp.where(ok[:, None], rmem_d + one_hot, rmem)
            t_new = jnp.where(ok, t_elapsed + lag, t_elapsed)
            # this event's excitation integral over (t_event, max_time]:
            # alpha_m/beta_m * (1 - e^{-beta_m (T - t_event)})
            contrib = (alpha[mark] / beta[mark]) * \
                (1.0 - jnp.exp(-beta[mark] *
                               jnp.maximum(max_time - t_new, 0.0)))
            comp_new = comp + jnp.where(ok, contrib, 0.0)
            return (rmem_new, t_new, comp_new), ll

        (rmem_f, t_f, comp_events), lls = jax.lax.scan(
            step, (state, jnp.zeros(N, lags.dtype),
                   jnp.zeros(N, lags.dtype)),
            (lags.T, marks_i.T, valid.T))
        # compensator = baseline integral + per-event excitation integrals
        # + the decaying contribution of the incoming pre-window state
        comp_base = jnp.sum(mu) * max_time
        comp_state = jnp.sum(
            (alpha / beta)[None, :] * state *
            (1.0 - jnp.exp(-beta[None, :] * max_time[:, None])), axis=1)
        loglik = jnp.sum(lls, axis=0) - comp_base - comp_events \
            - comp_state
        return loglik, rmem_f

    return f


register_alias("_contrib_hawkesll", "hawkesll")


@register("mrcnn_mask_target", nout=2, differentiable=False)
def _mrcnn_mask_target(num_rois=1, mask_size=(28, 28), num_classes=1,
                       sample_ratio=2, **a):
    """Mask R-CNN training-target generator
    (contrib/mrcnn_mask_target.cu): crop each gt mask under its ROI and
    resize to mask_size; emit per-class one-hot mask weights."""
    def f(rois, gt_masks, matches, cls_targets):
        B = rois.shape[0]
        Hm, Wm = mask_size
        Hg, Wg = gt_masks.shape[-2:]

        def one_roi(roi, mask):
            x0, y0, x1, y1 = roi[0], roi[1], roi[2], roi[3]
            ys = y0 + (jnp.arange(Hm) + 0.5) / Hm * (y1 - y0)
            xs = x0 + (jnp.arange(Wm) + 0.5) / Wm * (x1 - x0)
            yi = jnp.clip(ys.astype(jnp.int32), 0, Hg - 1)
            xi = jnp.clip(xs.astype(jnp.int32), 0, Wg - 1)
            return mask[yi[:, None], xi[None, :]]

        def one_image(roi_b, masks_b, match_b):
            sel = masks_b[match_b.astype(jnp.int32)]
            return jax.vmap(one_roi)(roi_b, sel)

        m_targets = jax.vmap(one_image)(rois, gt_masks, matches)
        cls = cls_targets.astype(jnp.int32)
        weights = jax.nn.one_hot(cls, num_classes,
                                 dtype=m_targets.dtype)
        m_out = m_targets[:, :, None, :, :] * \
            weights[..., None, None]
        w_out = jnp.broadcast_to(weights[..., None, None],
                                 m_out.shape)
        return m_out, w_out

    return f


register_alias("_contrib_mrcnn_mask_target", "mrcnn_mask_target")


@register("rroi_align", differentiable=False)
def _rroi_align(pooled_size=(7, 7), spatial_scale=1.0, sampling_ratio=-1,
                **a):
    """Rotated ROI align (contrib RROIAlign): rois are
    (batch_idx, cx, cy, w, h, angle_degrees); bilinear sampling on a
    rotated grid."""
    def f(data, rois):
        Ph, Pw = pooled_size
        _, C, H, W = data.shape

        def one(roi):
            b = roi[0].astype(jnp.int32)
            cx, cy, w, h = (roi[1] * spatial_scale,
                            roi[2] * spatial_scale,
                            roi[3] * spatial_scale,
                            roi[4] * spatial_scale)
            ang = roi[5] * jnp.pi / 180.0
            ys = (jnp.arange(Ph) + 0.5) / Ph - 0.5
            xs = (jnp.arange(Pw) + 0.5) / Pw - 0.5
            gy, gx = jnp.meshgrid(ys * h, xs * w, indexing="ij")
            cos, sin = jnp.cos(ang), jnp.sin(ang)
            sx = cx + gx * cos - gy * sin
            sy = cy + gx * sin + gy * cos
            x0 = jnp.clip(jnp.floor(sx).astype(jnp.int32), 0, W - 1)
            y0 = jnp.clip(jnp.floor(sy).astype(jnp.int32), 0, H - 1)
            x1 = jnp.clip(x0 + 1, 0, W - 1)
            y1 = jnp.clip(y0 + 1, 0, H - 1)
            wx = jnp.clip(sx - x0, 0.0, 1.0)
            wy = jnp.clip(sy - y0, 0.0, 1.0)
            img = data[b]
            v = (img[:, y0, x0] * (1 - wy) * (1 - wx)
                 + img[:, y0, x1] * (1 - wy) * wx
                 + img[:, y1, x0] * wy * (1 - wx)
                 + img[:, y1, x1] * wy * wx)
            return v

        return jax.vmap(one)(rois)

    return f


register_alias("_contrib_RROIAlign", "rroi_align")


@register("calibrate_entropy", nout=2, jit=False, differentiable=False)
def _calibrate_entropy(num_quantized_bins=255, **a):
    """KL-divergence-optimal threshold from a histogram
    (quantization/calibrate.cc): returns (min_range, max_range)."""
    def f(hist, hist_edges):
        from ..contrib.quantization import _kl_threshold

        h = onp.asarray(hist)
        edges = onp.asarray(hist_edges)
        t = _kl_threshold(h, float(edges[-1]),
                          num_quant=max(1, num_quantized_bins // 2))
        return (jnp.asarray(onp.float32(-t)), jnp.asarray(onp.float32(t)))

    return f


register_alias("_contrib_calibrate_entropy", "calibrate_entropy")


@register("Custom", jit=False)
def _custom(op_type="", **a):
    """Custom-op dispatch (src/operator/custom/custom.cc): routes to the
    Python CustomOp registry in mxnet_tpu.operator."""
    def f(*inputs):
        from .. import operator as op_mod
        from ..ndarray.ndarray import NDArray

        out = op_mod.custom(*[NDArray(x) for x in inputs],
                            op_type=op_type)
        if isinstance(out, (list, tuple)):
            return tuple(o._data for o in out)
        return out._data

    return f
