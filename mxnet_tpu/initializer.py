"""Weight initializers (reference: python/mxnet/initializer.py:56-694).

Samplers draw from the framework's global PRNG (mx.random), so
``mx.random.seed`` makes initialization reproducible.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as onp

from .base import MXNetError, Registry
from .ndarray.ndarray import NDArray
from . import random as _random

__all__ = ["Initializer", "InitDesc", "Zero", "One", "Constant", "Uniform",
           "Normal", "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear",
           "LSTMBias", "Load", "Mixed", "register", "create"]

_registry = Registry("initializer")
register = _registry.register


def create(init, **kwargs):
    if init is None:
        return Uniform(0.07)
    if isinstance(init, Initializer):
        return init
    if isinstance(init, str):
        return _registry.get(init)(**kwargs)
    raise MXNetError(f"cannot create initializer from {init!r}")


class Initializer:
    """Base initializer; subclasses implement _init_weight(name, arr)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, name, arr=None):
        if arr is None:  # called as init(array) in some legacy code
            arr, name = name, ""
        if isinstance(name, InitDesc):
            # per-variable override wins (reference Initializer.__call__:
            # the symbol's __init__ attr, then the desc's global_init)
            spec = name.attrs.get("__init__")
            if spec:
                create(spec).init_array(str(name), arr)
                return
            if name.global_init is not None and name.global_init is not self:
                name.global_init.init_array(str(name), arr)
                return
        self.init_array(str(name or ""), arr)

    def init_array(self, name: str, arr: NDArray):
        name = name.lower()
        if name.endswith("bias") or name.endswith("beta") or \
                name.endswith("running_mean") or name.endswith("moving_mean"):
            arr._set_data(jnp.zeros(arr.shape, arr.dtype))
        elif name.endswith("gamma") or name.endswith("running_var") or \
                name.endswith("moving_var"):
            arr._set_data(jnp.ones(arr.shape, arr.dtype))
        else:
            self._init_weight(name, arr)

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


@register
class Zero(Initializer):
    def _init_weight(self, name, arr):
        arr._set_data(jnp.zeros(arr.shape, arr.dtype))


_registry.alias("zeros", "zero")


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        arr._set_data(jnp.ones(arr.shape, arr.dtype))


_registry.alias("ones", "one")


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        v = self.value
        if isinstance(v, NDArray):
            arr._set_data(v._data.astype(arr.dtype))
        else:
            arr._set_data(jnp.full(arr.shape, v, arr.dtype))


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        data = jax.random.uniform(_random._next_key(), arr.shape,
                                  minval=-self.scale, maxval=self.scale)
        arr._set_data(data.astype(arr.dtype))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        data = jax.random.normal(_random._next_key(), arr.shape) * self.sigma
        arr._set_data(data.astype(arr.dtype))


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(onp.prod(arr.shape[1:]))
        data = jax.random.orthogonal(_random._next_key(), max(nout, nin))
        data = data[:nout, :nin] * self.scale
        arr._set_data(data.reshape(arr.shape).astype(arr.dtype))


@register
class Xavier(Initializer):
    """Glorot init (reference: initializer.py Xavier; gluon default for convs)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise MXNetError(f"Xavier requires ndim>=2, got shape {shape} "
                             f"for {name}")
        if len(shape) > 2:
            hw_scale = float(onp.prod(shape[2:]))
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0,
                  "in": fan_in, "out": fan_out}[self.factor_type]
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            data = jax.random.uniform(_random._next_key(), shape,
                                      minval=-scale, maxval=scale)
        else:
            data = jax.random.normal(_random._next_key(), shape) * scale
        arr._set_data(data.astype(arr.dtype))


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        weight = onp.zeros(arr.shape, dtype="float32")
        shape = arr.shape
        f = shape[3] / 2.0
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(onp.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr._set_data(jnp.asarray(weight).astype(arr.dtype))


@register
class LSTMBias(Initializer):
    """Forget-gate bias = 1 (reference: initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = onp.zeros(arr.shape, dtype="float32")
        n = arr.shape[0] // 4
        b[n:2 * n] = self.forget_bias  # gate order: i, f, g, o
        arr._set_data(jnp.asarray(b).astype(arr.dtype))


class InitDesc(str):
    """String subclass carrying attrs + a fallback initializer (reference:
    initializer.py:36) — lets name-pattern-driven initializers read the
    variable's ``__init__`` attr recorded on the symbol."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


@register
class Load(Initializer):
    """Initialize from a saved parameter file or name→NDArray dict
    (reference: initializer.py:316); ``arg:``/``aux:`` prefixes dropped.
    Names absent from the dict fall back to ``default_init``."""

    def __init__(self, param, default_init=None, verbose=False):
        super().__init__()
        if isinstance(param, str):
            from .nd import load as _load

            param = _load(param)
        if not isinstance(param, dict):
            raise MXNetError("Load needs a file path or a name->NDArray "
                             f"dict, got {type(param).__name__}")
        self.param = {}
        for name, arr in param.items():
            key = name[4:] if name.startswith(("arg:", "aux:")) else name
            self.param[key] = arr
        self.default_init = default_init
        self.verbose = verbose

    def init_array(self, name, arr):
        import logging

        if name in self.param:
            src = self.param[name]
            if tuple(arr.shape) != tuple(src.shape):
                raise MXNetError(
                    f"parameter {name!r} cannot be initialized by loading: "
                    f"target shape {tuple(arr.shape)} vs loaded "
                    f"{tuple(src.shape)}")
            arr._set_data(jnp.asarray(
                src.asnumpy() if isinstance(src, NDArray) else src,
                dtype=arr.dtype))
            if self.verbose:
                logging.getLogger("mxnet_tpu").info(
                    "Initialized %s by loading", name)
        elif self.default_init is not None:
            self.default_init(name, arr)
            if self.verbose:
                logging.getLogger("mxnet_tpu").info(
                    "Initialized %s by default", name)
        else:
            raise MXNetError(
                f"cannot initialize {name!r}: not in the loaded params and "
                "no default initializer provided")


@register
class Mixed(Initializer):
    """Name-pattern-dispatched initialization (reference:
    initializer.py:363): the first regex in ``patterns`` matching the
    variable name picks the corresponding initializer; ``.*`` as the last
    pattern provides the fallback."""

    def __init__(self, patterns, initializers):
        super().__init__()
        import re

        if len(patterns) != len(initializers):
            raise MXNetError("Mixed needs equally many patterns and "
                             "initializers")
        self.map = [(re.compile(p), init)
                    for p, init in zip(patterns, initializers)]

    def init_array(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise MXNetError(
            f"parameter {name!r} did not match any Mixed pattern — add a "
            "'.*' fallback pattern")
