"""Fault-injection harness (`MXTPU_FAULT_*`): deterministic failures at
named points in production code paths.

The robustness subsystems (crash-consistent checkpointing, serving
self-healing) are only trustworthy if their failure paths are *driven* in
tests, not reasoned about. Production code declares an injection point
with one call::

    from ..testing import chaos
    chaos.fault_point("ckpt.write.manifest")

and a test (or operator reproducing an incident) arms it either through
the environment —

    MXTPU_FAULT_CKPT_WRITE_MANIFEST=die          # SIGKILL self at the point
    MXTPU_FAULT_DECODE_TICK=raise                # raise FaultError, every hit
    MXTPU_FAULT_DECODE_TICK=raise:2              # skip 2 hits, then raise
    MXTPU_FAULT_DECODE_TICK=raise:0:3            # raise on the first 3 hits
    MXTPU_FAULT_CKPT_MANIFEST_CORRUPT=corrupt    # site applies corruption

— or programmatically with :func:`inject` (same spec, no subprocess
needed). Spec grammar: ``action[:countdown[:times]]`` where ``action`` is
``die`` (SIGKILL the process — indistinguishable from ``kill -9`` mid-
write), ``raise`` (raise :class:`FaultError`), ``corrupt`` (the point
returns True and the call site applies the corruption it knows how to
perform), or ``flag`` (returns True — corruption-free observation, e.g.
the simulated preemption signal); ``countdown`` hits pass through before
the fault fires
(default 0) and the fault fires ``times`` times before disarming
(default: forever). Transient-failure tests use ``raise:0:2``-style
specs so a retry layer can be seen to recover.

Cost when nothing is armed: one dict lookup per point (the env is parsed
once and cached; tests that set env vars at runtime call
:func:`refresh`). Every firing bumps the ``fault.injected`` counter so
chaos runs are visible in telemetry.
"""
from __future__ import annotations

import os
import signal
import sys
import threading

from ..base import MXNetError

__all__ = ["FaultError", "fault_point", "inject", "clear", "refresh",
           "armed", "env_name"]

_PREFIX = "MXTPU_FAULT_"
_ACTIONS = ("die", "raise", "corrupt", "flag")


class FaultError(MXNetError):
    """An injected failure (never raised outside chaos testing)."""


class _Fault:
    __slots__ = ("action", "countdown", "times")

    def __init__(self, action, countdown=0, times=None):
        if action not in _ACTIONS:
            raise MXNetError(
                f"unknown fault action {action!r}: expected one of "
                f"{_ACTIONS} (spec grammar: action[:countdown[:times]])")
        self.action = action
        self.countdown = int(countdown)
        self.times = None if times is None else int(times)


_lock = threading.Lock()
_faults: dict[str, _Fault] = {}   # point name -> armed fault
_env_signature = None             # the MXTPU_FAULT_* env snapshot parsed


def env_name(point):
    """`ckpt.write.manifest` -> `MXTPU_FAULT_CKPT_WRITE_MANIFEST`."""
    return _PREFIX + point.upper().replace(".", "_").replace("-", "_")


def _parse_spec(spec):
    parts = str(spec).split(":")
    action = parts[0].strip().lower()
    countdown = int(parts[1]) if len(parts) > 1 and parts[1] else 0
    times = int(parts[2]) if len(parts) > 2 and parts[2] else None
    return _Fault(action, countdown, times)


def _env_faults():
    sig, faults = [], {}
    for key in sorted(os.environ):
        if not key.startswith(_PREFIX):
            continue
        val = os.environ[key]
        if not val:
            continue
        sig.append((key, val))
        name = key[len(_PREFIX):].lower().replace("_", ".")
        faults[name] = _parse_spec(val)
    return tuple(sig), faults


def refresh():
    """Re-read MXTPU_FAULT_* from the environment (tests that set env vars
    after import call this; :func:`fault_point` also detects changes)."""
    global _env_signature
    with _lock:
        sig, faults = _env_faults()
        # keep programmatic injections; env (re)defines only its own points
        _faults.update(faults)
        _env_signature = sig


def inject(point, action="raise", countdown=0, times=1):
    """Arm ``point`` programmatically (in-process tests). Unlike env specs
    the default is to fire ONCE (``times=1``)."""
    with _lock:
        _faults[point] = _Fault(action, countdown, times)


def clear(point=None):
    """Disarm one point (or all), including env-armed ones."""
    global _env_signature
    with _lock:
        if point is None:
            _faults.clear()
            # pin the signature to the current env so fault_point does not
            # immediately re-parse the same vars back in
            _env_signature = tuple(
                (k, os.environ[k]) for k in sorted(os.environ)
                if k.startswith(_PREFIX) and os.environ[k])
        else:
            _faults.pop(point, None)


def armed(point):
    """The armed fault spec for ``point`` (or None) — introspection."""
    f = _faults.get(point)
    return None if f is None else (f.action, f.countdown, f.times)


def _record_fire(point, action):
    # lazy import: chaos must stay importable before telemetry and costs
    # nothing at module load
    try:
        from .. import telemetry as tm

        tm.REGISTRY.counter("fault.injected").inc()
        if tm.ON:
            tm.event("fault.injected", point=point, action=action)
    except Exception:  # noqa: BLE001 — accounting never masks the fault
        pass


def fault_point(point):
    """Declare an injection point. Returns False when unarmed (the cheap,
    overwhelmingly common path), True when an armed ``corrupt`` fault
    fires (the call site applies its corruption), raises
    :class:`FaultError` for ``raise``, and SIGKILLs the process for
    ``die`` — an honest stand-in for ``kill -9`` / OOM-kill mid-write:
    no atexit hooks, no flushing, no finally blocks run."""
    global _env_signature
    if _env_signature is None or not _faults:
        # first call, or a test may have (un)set env vars since last parse
        sig = tuple((k, os.environ[k]) for k in sorted(os.environ)
                    if k.startswith(_PREFIX) and os.environ[k])
        if sig != _env_signature:
            refresh()
    fault = _faults.get(point)
    if fault is None:
        return False
    with _lock:
        fault = _faults.get(point)
        if fault is None:
            return False
        if fault.countdown > 0:
            fault.countdown -= 1
            return False
        if fault.times is not None:
            fault.times -= 1
            if fault.times <= 0:
                _faults.pop(point, None)
    _record_fire(point, fault.action)
    if fault.action == "die":
        sys.stderr.write(f"[chaos] SIGKILL at fault point {point!r}\n")
        sys.stderr.flush()
        os.kill(os.getpid(), signal.SIGKILL)
        # unreachable on POSIX; belt-and-braces for exotic platforms
        os._exit(137)
    if fault.action == "raise":
        raise FaultError(f"injected fault at {point!r}")
    return True  # corrupt/flag: the site applies/observes it
