"""mxnet_tpu.testing — fault injection and robustness test harnesses.

Production code imports only :mod:`chaos` (stdlib-only, near-zero cost
when no fault is armed); everything else here is test-side tooling.
"""
from . import chaos
from .chaos import FaultError, fault_point

__all__ = ["chaos", "FaultError", "fault_point"]
