"""mx.image — image IO + augmentation (reference: python/mxnet/image/image.py
1.6k LoC of OpenCV-backed augmenters + ImageIter).

Host-side numpy/PIL implementations (the OpenCV role); batches transfer to
TPU once per batch. Augmenter objects mirror the reference API
(CreateAugmenter, ImageIter) so legacy scripts run.
"""
from __future__ import annotations

import numpy as onp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .. import io as _io
from .. import recordio as _recordio
from ..gluon.data.vision.transforms import _resize_np
from .. import random as _random

__all__ = ["imread", "imdecode", "imresize", "resize_short", "fixed_crop",
           "random_crop", "center_crop", "color_normalize", "HorizontalFlipAug",
           "ResizeAug", "ForceResizeAug", "RandomCropAug", "CenterCropAug",
           "CastAug", "ColorNormalizeAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "CreateAugmenter",
           "ImageIter",
           "HueJitterAug",
           "RandomOrderAug",
           "ColorJitterAug",
           "LightingAug",
           "RandomGrayAug",
           "RandomSizedCropAug",
           "DetAugmenter",
           "DetBorrowAug",
           "DetHorizontalFlipAug",
           "DetRandomCropAug",
           "DetRandomPadAug",
           "DetRandomSelectAug",
           "CreateDetAugmenter", "scale_down", "copyMakeBorder", "random_size_crop", "imrotate", "random_rotate", "SequentialAug"]


def _np(img):
    return img.asnumpy() if isinstance(img, NDArray) else onp.asarray(img)


def imread(filename, flag=1, to_rgb=True):
    from PIL import Image

    img = Image.open(filename)
    if flag:
        img = img.convert("RGB")
    return NDArray(onp.asarray(img))


def imdecode(buf, flag=1, to_rgb=True):
    import io as _pyio

    from PIL import Image

    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    img = Image.open(_pyio.BytesIO(bytes(buf)))
    if flag:
        img = img.convert("RGB")
    return NDArray(onp.asarray(img))


def imresize(src, w, h, interp=1):
    return NDArray(_resize_np(_np(src), (w, h)))


def resize_short(src, size, interp=1):
    img = _np(src)
    h, w = img.shape[:2]
    if h > w:
        nw, nh = size, int(h * size / w)
    else:
        nw, nh = int(w * size / h), size
    return NDArray(_resize_np(img, (nw, nh)))


def fixed_crop(src, x0, y0, w, h, size=None, interp=1):
    img = _np(src)[y0:y0 + h, x0:x0 + w]
    if size is not None and (size[0] != w or size[1] != h):
        img = _resize_np(img, size)
    return NDArray(img)


def random_crop(src, size, interp=1):
    img = _np(src)
    h, w = img.shape[:2]
    cw, ch = size
    x0 = _random.host_rng.randint(0, max(1, w - cw + 1))
    y0 = _random.host_rng.randint(0, max(1, h - ch + 1))
    return fixed_crop(src, x0, y0, cw, ch), (x0, y0, cw, ch)


def center_crop(src, size, interp=1):
    img = _np(src)
    h, w = img.shape[:2]
    cw, ch = size
    x0 = max(0, (w - cw) // 2)
    y0 = max(0, (h - ch) // 2)
    return fixed_crop(src, x0, y0, cw, ch), (x0, y0, cw, ch)


def color_normalize(src, mean, std=None):
    img = _np(src).astype("float32")
    img = img - _np(mean)
    if std is not None:
        img = img / _np(std)
    return NDArray(img)


class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return resize_short(src, self.size)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1])


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return random_crop(src, self.size)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return center_crop(src, self.size)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _random.host_rng.rand() < self.p:
            return NDArray(_np(src)[:, ::-1].copy())
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(typ=typ)
        self.typ = typ

    def __call__(self, src):
        return NDArray(_np(src).astype(self.typ))


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = mean
        self.std = std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class _JitterAug(Augmenter):
    def __init__(self, jitter):
        super().__init__(jitter=jitter)
        self.jitter = jitter

    def _alpha(self):
        return 1.0 + _random.host_rng.uniform(-self.jitter, self.jitter)


class BrightnessJitterAug(_JitterAug):
    def __call__(self, src):
        return NDArray(_np(src).astype("float32") * self._alpha())


class ContrastJitterAug(_JitterAug):
    def __call__(self, src):
        img = _np(src).astype("float32")
        gray = img.mean()
        a = self._alpha()
        return NDArray(img * a + gray * (1 - a))


class SaturationJitterAug(_JitterAug):
    def __call__(self, src):
        img = _np(src).astype("float32")
        gray = img.mean(axis=-1, keepdims=True)
        a = self._alpha()
        return NDArray(img * a + gray * (1 - a))


class HueJitterAug(_JitterAug):
    """Hue rotation in YIQ space (reference: image.py HueJitterAug)."""

    def __call__(self, src):
        img = _np(src).astype("float32")
        alpha = _random.host_rng.uniform(-self.jitter, self.jitter)
        u, w = onp.cos(alpha * onp.pi), onp.sin(alpha * onp.pi)
        t_yiq = onp.array([[0.299, 0.587, 0.114],
                           [0.596, -0.274, -0.321],
                           [0.211, -0.523, 0.311]])
        t_rgb = onp.array([[1.0, 0.956, 0.621],
                           [1.0, -0.272, -0.647],
                           [1.0, -1.107, 1.705]])
        rot = onp.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]])
        t = t_rgb @ rot @ t_yiq
        return NDArray(img @ t.T.astype("float32"))


class RandomOrderAug(Augmenter):
    """Apply child augmenters in random order (reference: RandomOrderAug —
    the color-jitter pipeline shuffles per sample)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src):
        order = _random.host_rng.permutation(len(self.ts))
        for i in order:
            src = self.ts[i](src)
        return src


def ColorJitterAug(brightness, contrast, saturation):
    """Random-order brightness/contrast/saturation jitter (reference:
    image.py ColorJitterAug over RandomOrderAug)."""
    ts = []
    if brightness > 0:
        ts.append(BrightnessJitterAug(brightness))
    if contrast > 0:
        ts.append(ContrastJitterAug(contrast))
    if saturation > 0:
        ts.append(SaturationJitterAug(saturation))
    return RandomOrderAug(ts)


class LightingAug(Augmenter):
    """PCA-based RGB lighting noise (reference: LightingAug; AlexNet-style)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__()
        self.alphastd = alphastd
        self.eigval = onp.asarray(eigval, dtype="float32")
        self.eigvec = onp.asarray(eigvec, dtype="float32")

    def __call__(self, src):
        alpha = _random.host_rng.normal(0, self.alphastd, size=(3,))
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return NDArray(_np(src).astype("float32") + rgb.astype("float32"))


class RandomGrayAug(Augmenter):
    """Randomly convert to 3-channel grayscale (reference: RandomGrayAug)."""

    _W = onp.array([0.299, 0.587, 0.114], dtype="float32")

    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _random.host_rng.rand() < self.p:
            img = _np(src).astype("float32")
            gray = (img * self._W).sum(axis=-1, keepdims=True)
            return NDArray(onp.broadcast_to(gray, img.shape).copy())
        return src


class RandomSizedCropAug(Augmenter):
    """Random area+aspect crop then resize (reference: RandomSizedCropAug /
    inception-style)."""

    def __init__(self, size, area=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interp=2):
        super().__init__()
        self.size = size
        self.area = area if isinstance(area, tuple) else (area, 1.0)
        self.ratio = ratio

    def __call__(self, src):
        # one sampling implementation for both spellings (reference:
        # RandomSizedCropAug calls random_size_crop)
        return random_size_crop(src, self.size, self.area, self.ratio)[0]


# -- detection augmenters (reference: image/detection.py det_aug family) ----
class DetAugmenter:
    """Augmenter over (image, label) pairs; label rows [cls, x1, y1, x2, y2]
    in RELATIVE coords (reference: image/detection.py DetAugmenter)."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift an image-only augmenter into the detection pipeline."""

    def __init__(self, augmenter):
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetHorizontalFlipAug(DetAugmenter):
    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if _random.host_rng.rand() < self.p:
            img = NDArray(_np(src)[:, ::-1].copy())
            lab = onp.array(label, dtype="float32", copy=True)
            x1 = lab[:, 1].copy()
            lab[:, 1] = 1.0 - lab[:, 3]
            lab[:, 3] = 1.0 - x1
            return img, lab
        return src, onp.asarray(label, dtype="float32")


class DetRandomCropAug(DetAugmenter):
    """IoU-constrained random crop (SSD-style; reference:
    DetRandomCropAug). Boxes are clipped to the crop; boxes whose center
    falls outside are dropped (marked -1)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75,
                 1.33), area_range=(0.3, 1.0), max_attempts=20):
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        img = _np(src)
        h, w = img.shape[:2]
        lab = onp.array(label, dtype="float32", copy=True)
        for _ in range(self.max_attempts):
            area = _random.host_rng.uniform(*self.area_range)
            ar = _random.host_rng.uniform(*self.aspect_ratio_range)
            cw = min(1.0, (area * ar) ** 0.5)
            ch = min(1.0, (area / ar) ** 0.5)
            cx = _random.host_rng.uniform(0, 1 - cw)
            cy = _random.host_rng.uniform(0, 1 - ch)
            valid = lab[:, 0] >= 0
            if valid.any():
                centers_x = (lab[valid, 1] + lab[valid, 3]) / 2
                centers_y = (lab[valid, 2] + lab[valid, 4]) / 2
                inside = ((centers_x >= cx) & (centers_x <= cx + cw) &
                          (centers_y >= cy) & (centers_y <= cy + ch))
                if not inside.any():
                    continue
                # coverage constraint (reference: min_object_covered):
                # every kept (center-inside) box must have enough of its
                # area inside the crop
                ix1 = onp.maximum(lab[valid, 1], cx)
                iy1 = onp.maximum(lab[valid, 2], cy)
                ix2 = onp.minimum(lab[valid, 3], cx + cw)
                iy2 = onp.minimum(lab[valid, 4], cy + ch)
                inter = onp.clip(ix2 - ix1, 0, None) * \
                    onp.clip(iy2 - iy1, 0, None)
                area = (lab[valid, 3] - lab[valid, 1]) * \
                    (lab[valid, 4] - lab[valid, 2])
                cov = onp.where(area > 0, inter / onp.maximum(area, 1e-12),
                                0.0)
                if (cov[inside] < self.min_object_covered).any():
                    continue
            x0, y0 = int(cx * w), int(cy * h)
            x1, y1 = int((cx + cw) * w), int((cy + ch) * h)
            crop = img[y0:y1, x0:x1]
            new = lab.copy()
            for i in range(new.shape[0]):
                if new[i, 0] < 0:
                    continue
                bcx = (new[i, 1] + new[i, 3]) / 2
                bcy = (new[i, 2] + new[i, 4]) / 2
                if not (cx <= bcx <= cx + cw and cy <= bcy <= cy + ch):
                    new[i] = -1.0
                    continue
                new[i, 1] = onp.clip((new[i, 1] - cx) / cw, 0, 1)
                new[i, 3] = onp.clip((new[i, 3] - cx) / cw, 0, 1)
                new[i, 2] = onp.clip((new[i, 2] - cy) / ch, 0, 1)
                new[i, 4] = onp.clip((new[i, 4] - cy) / ch, 0, 1)
            return NDArray(crop.copy()), new
        return src, lab


class DetRandomPadAug(DetAugmenter):
    """Random expand-and-pad (zoom out; reference: DetRandomPadAug)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=20,
                 pad_val=(127, 127, 127)):
        self.area_range = area_range
        self.aspect_ratio_range = aspect_ratio_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        img = _np(src)
        h, w = img.shape[:2]
        lab = onp.array(label, dtype="float32", copy=True)
        nw = nh = 0
        for _ in range(self.max_attempts):
            scale = _random.host_rng.uniform(*self.area_range)
            ar = _random.host_rng.uniform(*self.aspect_ratio_range)
            nw = int(w * (scale * ar) ** 0.5)
            nh = int(h * (scale / ar) ** 0.5)
            if nw >= w and nh >= h:
                break
        if nw < w or nh < h:
            return src, lab
        x0 = _random.host_rng.randint(0, nw - w + 1)
        y0 = _random.host_rng.randint(0, nh - h + 1)
        canvas = onp.empty((nh, nw) + img.shape[2:], img.dtype)
        canvas[...] = onp.asarray(self.pad_val, dtype=img.dtype)
        canvas[y0:y0 + h, x0:x0 + w] = img
        valid = lab[:, 0] >= 0
        lab[valid, 1] = (lab[valid, 1] * w + x0) / nw
        lab[valid, 3] = (lab[valid, 3] * w + x0) / nw
        lab[valid, 2] = (lab[valid, 2] * h + y0) / nh
        lab[valid, 4] = (lab[valid, 4] * h + y0) / nh
        return NDArray(canvas), lab


class DetRandomSelectAug(DetAugmenter):
    """Randomly pick one of several det augmenters (or skip)."""

    def __init__(self, aug_list, skip_prob=0.0):
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if _random.host_rng.rand() < self.skip_prob or not self.aug_list:
            return src, onp.asarray(label, dtype="float32")
        pick = _random.host_rng.randint(len(self.aug_list))
        return self.aug_list[pick](src, label)


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_mirror=False, mean=None, std=None, brightness=0,
                       contrast=0, saturation=0, pca_noise=0, hue=0,
                       inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.3, 3.0), pad_val=(127, 127, 127)):
    """Build the detection augmenter list (reference: image/detection.py
    CreateDetAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(1.0, area_range[1])))
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (max(1.0, area_range[0]), area_range[1]),
                              pad_val=pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    if pca_noise > 0:
        eigval = onp.array([55.46, 4.794, 1.148])
        eigvec = onp.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.814],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(DetBorrowAug(LightingAug(pca_noise, eigval, eigvec)))
    auglist.append(DetBorrowAug(ForceResizeAug((data_shape[2],
                                                data_shape[1]))))
    auglist.append(DetBorrowAug(CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(ColorJitterAug(brightness, contrast,
                                                   saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53])
    if std is True:
        std = onp.array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(
            mean, std if std is not None else onp.ones(3))))
    return auglist


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, rand_gray=0,
                    inter_method=2):
    """Build the standard augmenter list (reference: image.py CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness:
        auglist.append(BrightnessJitterAug(brightness))
    if contrast:
        auglist.append(ContrastJitterAug(contrast))
    if saturation:
        auglist.append(SaturationJitterAug(saturation))
    if pca_noise > 0:
        eigval = onp.array([55.46, 4.794, 1.148])
        eigvec = onp.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.814],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53])
    if std is True:
        std = onp.array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(ColorNormalizeAug(mean, std if std is not None
                                         else onp.ones(3)))
    return auglist


class ImageIter(_io.DataIter):
    """Image iterator over .rec or .lst+images (reference: image.py ImageIter)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, aug_list=None, **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **{k: v for k, v in kwargs.items()
                                           if k in ("resize", "rand_crop",
                                                    "rand_mirror", "mean",
                                                    "std")})
        self._records = []
        self._rec = None
        if path_imgrec:
            # lazy indexed reads: records stay on disk until a batch needs
            # them (the native reader builds the in-file index on open)
            self._rec = _recordio.MXRecordIO(path_imgrec, "r")
            if self._rec._native:
                n = self._rec._native.rio_reader_count(self._rec._handle)
                self._records = list(range(n))
            else:  # fallback engine: buffer (no random access)
                while True:
                    item = self._rec.read()
                    if item is None:
                        break
                    self._records.append(item)
        elif path_imglist:
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    label = float(parts[1])
                    self._records.append((label, path_root + parts[-1]))
        else:
            raise MXNetError("need path_imgrec or path_imglist")
        self._from_rec = path_imgrec is not None
        self._shuffle = shuffle
        self._order = onp.arange(len(self._records))
        self.reset()

    def reset(self):
        self.cur = 0
        if self._shuffle:
            onp.random.shuffle(self._order)

    def _load(self, idx):
        if self._from_rec:
            item = self._records[idx]
            if isinstance(item, int):  # lazy native path
                item = self._rec._read_at(item)
            header, img = _recordio.unpack_img(item)
            label = header.label
        else:
            label, path = self._records[idx]
            img = _np(imread(path))
        for aug in self.auglist:
            img = aug(img)
        arr = _np(img).astype("float32")
        if arr.ndim == 3 and arr.shape[2] in (1, 3):
            arr = arr.transpose(2, 0, 1)
        return arr, label

    def __next__(self):
        if self.cur >= len(self._records):
            raise StopIteration
        n = min(self.batch_size, len(self._records) - self.cur)
        imgs = onp.zeros((self.batch_size,) + self.data_shape, "float32")
        labels = onp.zeros((self.batch_size,), "float32")
        for i in range(n):
            arr, label = self._load(self._order[self.cur + i])
            imgs[i] = arr
            labels[i] = label if onp.isscalar(label) else label[0] \
                if hasattr(label, "__len__") else float(label)
        self.cur += n
        return _io.DataBatch([NDArray(imgs)], [NDArray(labels)],
                             pad=self.batch_size - n)


def scale_down(src_size, size):
    """Shrink a crop size to fit inside the image, keeping aspect
    (reference: image.py scale_down:214)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def copyMakeBorder(src, top, bot, left, right, border_type=0, value=0):
    """Pad an (H, W, C) image border (reference: image.py
    copyMakeBorder:249 over cv2). ``border_type`` 0 = constant fill,
    1 = replicate edge."""
    img = _np(src)
    pad_width = ((top, bot), (left, right)) + ((0, 0),) * (img.ndim - 2)
    if border_type == 1:
        out = onp.pad(img, pad_width, "edge")
    else:
        out = onp.pad(img, pad_width, "constant", constant_values=value)
    return NDArray(out)


def random_size_crop(src, size, area, ratio, interp=1, **kwargs):
    """Random crop with randomized area and aspect ratio, resized to
    ``size`` (reference: image.py random_size_crop:563). Returns
    (image, (x0, y0, w, h))."""
    if "min_area" in kwargs:  # legacy spelling (reference keeps it too)
        area = kwargs.pop("min_area")
    if kwargs:
        raise MXNetError(
            f"random_size_crop: unexpected arguments {sorted(kwargs)}")
    img = _np(src)
    h, w = img.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = _random.host_rng.uniform(*area) * src_area
        log_ratio = (onp.log(ratio[0]), onp.log(ratio[1]))
        ar = onp.exp(_random.host_rng.uniform(*log_ratio))
        cw = int(round(onp.sqrt(target_area * ar)))
        ch = int(round(onp.sqrt(target_area / ar)))
        if cw <= w and ch <= h:
            x0 = _random.host_rng.randint(0, w - cw + 1)
            y0 = _random.host_rng.randint(0, h - ch + 1)
            out = fixed_crop(NDArray(img), x0, y0, cw, ch, size, interp)
            return out, (x0, y0, cw, ch)
    # fallback: center crop at the (scaled-down) requested size
    cw, ch = scale_down((w, h), size)
    x0, y0 = (w - cw) // 2, (h - ch) // 2
    return fixed_crop(NDArray(img), x0, y0, cw, ch, size, interp), \
        (x0, y0, cw, ch)


def imrotate(src, rotation_degrees, zoom_in=False, zoom_out=False):
    """Rotate CHW (or NCHW batch) float32 images by degrees (reference:
    image.py imrotate:618) via inverse affine + bilinear sampling; area
    outside the source fills with zeros. ``zoom_in`` scales so no padding
    shows; ``zoom_out`` so the full rotated frame fits."""
    if zoom_in and zoom_out:
        raise MXNetError("only one of zoom_in and zoom_out may be set")
    img = _np(src).astype("float32")
    batched = img.ndim == 4
    imgs = img if batched else img[None]
    n, c, h, w = imgs.shape
    degs = onp.broadcast_to(onp.asarray(_np(rotation_degrees),
                                        "float32").reshape(-1), (n,)) \
        if not onp.isscalar(rotation_degrees) else \
        onp.full((n,), float(rotation_degrees), "float32")
    out = onp.zeros_like(imgs)
    yy, xx = onp.mgrid[0:h, 0:w].astype("float32")
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    for i in range(n):
        rad = onp.deg2rad(float(degs[i]))
        cos, sin = onp.cos(rad), onp.sin(rad)
        # rotated-frame extents of the ACTUAL h x w rectangle: correct
        # for non-square images (a 90-deg zoom_in of a wide image must
        # zoom until the short side covers the long axis)
        ext = max((w * abs(cos) + h * abs(sin)) / w,
                  (w * abs(sin) + h * abs(cos)) / h)
        if zoom_in:
            s = 1.0 / ext
        elif zoom_out:
            s = ext
        else:
            s = 1.0
        # inverse map: output pixel -> source coords
        dx, dy = (xx - cx) * s, (yy - cy) * s
        sx = cos * dx + sin * dy + cx
        sy = -sin * dx + cos * dy + cy
        x0 = onp.floor(sx).astype(int)
        y0 = onp.floor(sy).astype(int)
        fx, fy = sx - x0, sy - y0
        for dyy in (0, 1):
            for dxx in (0, 1):
                wgt = (fy if dyy else 1 - fy) * (fx if dxx else 1 - fx)
                ys_, xs_ = y0 + dyy, x0 + dxx
                ok = (ys_ >= 0) & (ys_ < h) & (xs_ >= 0) & (xs_ < w)
                ysc = onp.clip(ys_, 0, h - 1)
                xsc = onp.clip(xs_, 0, w - 1)
                out[i] += imgs[i][:, ysc, xsc] * (wgt * ok)[None]
    res = out if batched else out[0]
    return NDArray(res)


def random_rotate(src, angle_limits, zoom_in=False, zoom_out=False):
    """Rotate by an angle drawn uniformly from ``angle_limits``
    (reference: image.py random_rotate)."""
    lo, hi = angle_limits
    img = _np(src)
    if img.ndim == 4:
        angles = _random.host_rng.uniform(lo, hi, size=(img.shape[0],))
        return imrotate(src, angles, zoom_in, zoom_out)
    return imrotate(src, float(_random.host_rng.uniform(lo, hi)),
                    zoom_in, zoom_out)


class SequentialAug(Augmenter):
    """Apply a list of augmenters in order (reference: image.py
    SequentialAug:787)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src):
        for aug in self.ts:
            src = aug(src)
        return src
