"""mx.image — image IO + augmentation (reference: python/mxnet/image/image.py
1.6k LoC of OpenCV-backed augmenters + ImageIter).

Host-side numpy/PIL implementations (the OpenCV role); batches transfer to
TPU once per batch. Augmenter objects mirror the reference API
(CreateAugmenter, ImageIter) so legacy scripts run.
"""
from __future__ import annotations

import numpy as onp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .. import io as _io
from .. import recordio as _recordio
from ..gluon.data.vision.transforms import _resize_np
from .. import random as _random

__all__ = ["imread", "imdecode", "imresize", "resize_short", "fixed_crop",
           "random_crop", "center_crop", "color_normalize", "HorizontalFlipAug",
           "ResizeAug", "ForceResizeAug", "RandomCropAug", "CenterCropAug",
           "CastAug", "ColorNormalizeAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "CreateAugmenter",
           "ImageIter"]


def _np(img):
    return img.asnumpy() if isinstance(img, NDArray) else onp.asarray(img)


def imread(filename, flag=1, to_rgb=True):
    from PIL import Image

    img = Image.open(filename)
    if flag:
        img = img.convert("RGB")
    return NDArray(onp.asarray(img))


def imdecode(buf, flag=1, to_rgb=True):
    import io as _pyio

    from PIL import Image

    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    img = Image.open(_pyio.BytesIO(bytes(buf)))
    if flag:
        img = img.convert("RGB")
    return NDArray(onp.asarray(img))


def imresize(src, w, h, interp=1):
    return NDArray(_resize_np(_np(src), (w, h)))


def resize_short(src, size, interp=1):
    img = _np(src)
    h, w = img.shape[:2]
    if h > w:
        nw, nh = size, int(h * size / w)
    else:
        nw, nh = int(w * size / h), size
    return NDArray(_resize_np(img, (nw, nh)))


def fixed_crop(src, x0, y0, w, h, size=None, interp=1):
    img = _np(src)[y0:y0 + h, x0:x0 + w]
    if size is not None and (size[0] != w or size[1] != h):
        img = _resize_np(img, size)
    return NDArray(img)


def random_crop(src, size, interp=1):
    img = _np(src)
    h, w = img.shape[:2]
    cw, ch = size
    x0 = _random.host_rng.randint(0, max(1, w - cw + 1))
    y0 = _random.host_rng.randint(0, max(1, h - ch + 1))
    return fixed_crop(src, x0, y0, cw, ch), (x0, y0, cw, ch)


def center_crop(src, size, interp=1):
    img = _np(src)
    h, w = img.shape[:2]
    cw, ch = size
    x0 = max(0, (w - cw) // 2)
    y0 = max(0, (h - ch) // 2)
    return fixed_crop(src, x0, y0, cw, ch), (x0, y0, cw, ch)


def color_normalize(src, mean, std=None):
    img = _np(src).astype("float32")
    img = img - _np(mean)
    if std is not None:
        img = img / _np(std)
    return NDArray(img)


class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return resize_short(src, self.size)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1])


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return random_crop(src, self.size)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return center_crop(src, self.size)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _random.host_rng.rand() < self.p:
            return NDArray(_np(src)[:, ::-1].copy())
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(typ=typ)
        self.typ = typ

    def __call__(self, src):
        return NDArray(_np(src).astype(self.typ))


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = mean
        self.std = std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class _JitterAug(Augmenter):
    def __init__(self, jitter):
        super().__init__(jitter=jitter)
        self.jitter = jitter

    def _alpha(self):
        return 1.0 + _random.host_rng.uniform(-self.jitter, self.jitter)


class BrightnessJitterAug(_JitterAug):
    def __call__(self, src):
        return NDArray(_np(src).astype("float32") * self._alpha())


class ContrastJitterAug(_JitterAug):
    def __call__(self, src):
        img = _np(src).astype("float32")
        gray = img.mean()
        a = self._alpha()
        return NDArray(img * a + gray * (1 - a))


class SaturationJitterAug(_JitterAug):
    def __call__(self, src):
        img = _np(src).astype("float32")
        gray = img.mean(axis=-1, keepdims=True)
        a = self._alpha()
        return NDArray(img * a + gray * (1 - a))


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, rand_gray=0,
                    inter_method=2):
    """Build the standard augmenter list (reference: image.py CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness:
        auglist.append(BrightnessJitterAug(brightness))
    if contrast:
        auglist.append(ContrastJitterAug(contrast))
    if saturation:
        auglist.append(SaturationJitterAug(saturation))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53])
    if std is True:
        std = onp.array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(ColorNormalizeAug(mean, std if std is not None
                                         else onp.ones(3)))
    return auglist


class ImageIter(_io.DataIter):
    """Image iterator over .rec or .lst+images (reference: image.py ImageIter)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, aug_list=None, **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **{k: v for k, v in kwargs.items()
                                           if k in ("resize", "rand_crop",
                                                    "rand_mirror", "mean",
                                                    "std")})
        self._records = []
        self._rec = None
        if path_imgrec:
            # lazy indexed reads: records stay on disk until a batch needs
            # them (the native reader builds the in-file index on open)
            self._rec = _recordio.MXRecordIO(path_imgrec, "r")
            if self._rec._native:
                n = self._rec._native.rio_reader_count(self._rec._handle)
                self._records = list(range(n))
            else:  # fallback engine: buffer (no random access)
                while True:
                    item = self._rec.read()
                    if item is None:
                        break
                    self._records.append(item)
        elif path_imglist:
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    label = float(parts[1])
                    self._records.append((label, path_root + parts[-1]))
        else:
            raise MXNetError("need path_imgrec or path_imglist")
        self._from_rec = path_imgrec is not None
        self._shuffle = shuffle
        self._order = onp.arange(len(self._records))
        self.reset()

    def reset(self):
        self.cur = 0
        if self._shuffle:
            onp.random.shuffle(self._order)

    def _load(self, idx):
        if self._from_rec:
            item = self._records[idx]
            if isinstance(item, int):  # lazy native path
                item = self._rec._read_at(item)
            header, img = _recordio.unpack_img(item)
            label = header.label
        else:
            label, path = self._records[idx]
            img = _np(imread(path))
        for aug in self.auglist:
            img = aug(img)
        arr = _np(img).astype("float32")
        if arr.ndim == 3 and arr.shape[2] in (1, 3):
            arr = arr.transpose(2, 0, 1)
        return arr, label

    def __next__(self):
        if self.cur >= len(self._records):
            raise StopIteration
        n = min(self.batch_size, len(self._records) - self.cur)
        imgs = onp.zeros((self.batch_size,) + self.data_shape, "float32")
        labels = onp.zeros((self.batch_size,), "float32")
        for i in range(n):
            arr, label = self._load(self._order[self.cur + i])
            imgs[i] = arr
            labels[i] = label if onp.isscalar(label) else label[0] \
                if hasattr(label, "__len__") else float(label)
        self.cur += n
        return _io.DataBatch([NDArray(imgs)], [NDArray(labels)],
                             pad=self.batch_size - n)
