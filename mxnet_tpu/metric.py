"""Evaluation metrics (reference: python/mxnet/gluon/metric.py — EvalMetric:68,
Accuracy:370, F1:727, Perplexity:1433, registry create:195)."""
from __future__ import annotations

import numpy as onp

from .base import MXNetError, Registry
from .ndarray.ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "MAE", "MSE", "RMSE", "CrossEntropy", "Perplexity",
           "PearsonCorrelation", "Loss", "create", "BinaryAccuracy", "Fbeta", "MeanCosineSimilarity", "MeanPairwiseDistance", "PCC"]

_registry = Registry("metric")
register = _registry.register


def create(metric, *args, **kwargs):
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        m = CompositeEvalMetric()
        for child in metric:
            m.add(create(child, *args, **kwargs))
        return m
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    return _registry.get(metric)(*args, **kwargs)


def _np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, self.sum_metric / self.num_inst

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def update_dict(self, label, pred):
        self.update(list(label.values()), list(pred.values()))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return names, values


def _as_lists(labels, preds):
    if isinstance(labels, (NDArray, onp.ndarray)):
        labels = [labels]
    if isinstance(preds, (NDArray, onp.ndarray)):
        preds = [preds]
    if len(labels) != len(preds):
        raise MXNetError(f"labels/preds length mismatch "
                         f"{len(labels)} vs {len(preds)}")
    return labels, preds


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _np(label), _np(pred)
            if pred.ndim > label.ndim:
                pred = pred.argmax(self.axis)
            pred = pred.astype("int32").ravel()
            label = label.astype("int32").ravel()
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__(f"{name}_{top_k}", **kwargs)
        self.top_k = top_k

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _np(label), _np(pred)
            idx = onp.argsort(-pred, axis=-1)[..., : self.top_k]
            hit = (idx == label[..., None].astype("int64")).any(-1)
            self.sum_metric += float(hit.sum())
            self.num_inst += label.size


@register
class F1(EvalMetric):
    def __init__(self, name="f1", average="macro", threshold=0.5, **kwargs):
        self.average = average
        self.threshold = threshold
        self.beta = 1.0  # F1 is F-beta at beta=1 (Fbeta overrides)
        super().__init__(name, **kwargs)

    def reset(self):
        super().reset()
        self.tp = self.fp = self.fn = 0.0

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _np(label).ravel(), _np(pred)
            if pred.ndim > 1 and pred.shape[-1] == 2:
                pred = pred[..., 1].ravel() > self.threshold
            else:
                pred = pred.ravel() > self.threshold
            label = label.astype(bool)
            self.tp += float((pred & label).sum())
            self.fp += float((pred & ~label).sum())
            self.fn += float((~pred & label).sum())
            self.num_inst += 1

    def get(self):
        prec = self.tp / (self.tp + self.fp) if self.tp + self.fp else 0.0
        rec = self.tp / (self.tp + self.fn) if self.tp + self.fn else 0.0
        b2 = self.beta * self.beta
        denom = b2 * prec + rec
        score = (1 + b2) * prec * rec / denom if denom else 0.0
        return self.name, score


@register
class MCC(EvalMetric):
    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)

    def reset(self):
        super().reset()
        self.tp = self.fp = self.fn = self.tn = 0.0

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            label = _np(label).ravel().astype(bool)
            pred = _np(pred)
            if pred.ndim > 1 and pred.shape[-1] == 2:
                pred = pred[..., 1].ravel() > 0.5
            else:
                pred = pred.ravel() > 0.5
            self.tp += float((pred & label).sum())
            self.fp += float((pred & ~label).sum())
            self.fn += float((~pred & label).sum())
            self.tn += float((~pred & ~label).sum())
            self.num_inst += 1

    def get(self):
        import math

        denom = math.sqrt((self.tp + self.fp) * (self.tp + self.fn) *
                          (self.tn + self.fp) * (self.tn + self.fn))
        mcc = ((self.tp * self.tn - self.fp * self.fn) / denom) if denom \
            else 0.0
        return self.name, mcc


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _np(label), _np(pred)
            self.sum_metric += float(onp.abs(label - pred.reshape(
                label.shape)).mean())
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _np(label), _np(pred)
            self.sum_metric += float(((label - pred.reshape(
                label.shape)) ** 2).mean())
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name, **kwargs)

    def get(self):
        name, value = super().get()
        return name, float(onp.sqrt(value))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            label = _np(label).ravel().astype("int64")
            pred = _np(pred)
            prob = pred[onp.arange(label.shape[0]), label]
            self.sum_metric += float((-onp.log(prob + self.eps)).sum())
            self.num_inst += label.shape[0]


@register
class Perplexity(CrossEntropy):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", **kwargs):
        super().__init__(name=name, **kwargs)
        self.ignore_label = ignore_label

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            label = _np(label).ravel().astype("int64")
            pred = _np(pred).reshape(-1, _np(pred).shape[-1])
            prob = pred[onp.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = label == self.ignore_label
                prob = prob[~ignore]
            self.sum_metric += float(-onp.log(prob + self.eps).sum())
            self.num_inst += prob.shape[0]

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, float(onp.exp(self.sum_metric / self.num_inst))


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)

    def reset(self):
        super().reset()
        self._labels, self._preds = [], []

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            self._labels.append(_np(label).ravel())
            self._preds.append(_np(pred).ravel())
            self.num_inst += 1

    def get(self):
        if not self._labels:
            return self.name, float("nan")
        x = onp.concatenate(self._labels)
        y = onp.concatenate(self._preds)
        return self.name, float(onp.corrcoef(x, y)[0, 1])


@register
class Loss(EvalMetric):
    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        if isinstance(preds, (NDArray, onp.ndarray)):
            preds = [preds]
        for pred in preds:
            loss = _np(pred)
            self.sum_metric += float(loss.sum())
            self.num_inst += loss.size


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False,
                 **kwargs):
        super().__init__(f"custom({name})", **kwargs)
        self._feval = feval

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            val = self._feval(_np(label), _np(pred))
            if isinstance(val, tuple):
                s, n = val
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += val
                self.num_inst += 1


def np_metric(name=None, **kwargs):
    def decorator(f):
        return CustomMetric(f, name or f.__name__, **kwargs)

    return decorator


@register
class BinaryAccuracy(EvalMetric):
    """Accuracy of thresholded scores against 0/1 labels (reference:
    gluon/metric.py BinaryAccuracy)."""

    def __init__(self, name="binary_accuracy", threshold=0.5, **kwargs):
        self.threshold = threshold
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            label = _np(label).ravel().astype(bool)
            pred = _np(pred).ravel() > self.threshold
            self.sum_metric += float((pred == label).sum())
            self.num_inst += label.size


@register
class Fbeta(F1):
    """F-beta score: recall weighted ``beta``× against precision
    (reference: gluon/metric.py Fbeta); beta=1 reduces to F1."""

    def __init__(self, name="fbeta", beta=1.0, average="macro",
                 threshold=0.5, **kwargs):
        super().__init__(name=name, average=average, threshold=threshold,
                         **kwargs)
        self.beta = beta  # the shared F-beta formula lives on F1.get


@register
class MeanCosineSimilarity(EvalMetric):
    """Mean cosine similarity along the last axis (reference:
    gluon/metric.py MeanCosineSimilarity)."""

    def __init__(self, name="cos_sim", eps=1e-12, **kwargs):
        self.eps = eps
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            a, b = _np(label), _np(pred)
            num = (a * b).sum(axis=-1)
            den = onp.sqrt((a * a).sum(axis=-1)) * \
                onp.sqrt((b * b).sum(axis=-1))
            sim = num / onp.maximum(den, self.eps)
            self.sum_metric += float(sim.sum())
            self.num_inst += sim.size


@register
class MeanPairwiseDistance(EvalMetric):
    """Mean p-norm distance between label/pred vectors (reference:
    gluon/metric.py MeanPairwiseDistance)."""

    def __init__(self, name="mpd", p=2, **kwargs):
        self.p = p
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            diff = onp.abs(_np(label) - _np(pred)) ** self.p
            dist = diff.sum(axis=-1) ** (1.0 / self.p)
            self.sum_metric += float(dist.sum())
            self.num_inst += dist.size


@register
class PCC(EvalMetric):
    """Multiclass Matthews/Pearson correlation from a running K×K
    confusion matrix (reference: gluon/metric.py PCC:1597)."""

    def __init__(self, name="pcc", **kwargs):
        self.conf = onp.zeros((0, 0), dtype=onp.float64)
        super().__init__(name, **kwargs)

    def reset(self):
        super().reset()
        self.conf = onp.zeros((0, 0), dtype=onp.float64)

    def _grow(self, k):
        if k > self.conf.shape[0]:
            new = onp.zeros((k, k), dtype=onp.float64)
            old = self.conf.shape[0]
            new[:old, :old] = self.conf
            self.conf = new

    def update(self, labels, preds):
        labels, preds = _as_lists(labels, preds)
        for label, pred in zip(labels, preds):
            lab = _np(label).ravel().astype(int)
            pr = _np(pred)
            if pr.ndim > 1:
                pr = pr.argmax(-1).ravel()
            elif onp.issubdtype(pr.dtype, onp.floating):
                pr = (pr.ravel() > 0.5).astype(int)  # scores, like MCC
            else:
                pr = pr.ravel().astype(int)
            k = int(max(lab.max(initial=0), pr.max(initial=0))) + 1
            self._grow(k)
            onp.add.at(self.conf, (lab, pr), 1)
            self.num_inst += lab.size

    def get(self):
        c = self.conf
        if not c.size or c.sum() == 0:
            return self.name, 0.0
        n = c.sum()
        t = c.sum(axis=1)  # true counts per class
        p = c.sum(axis=0)  # predicted counts per class
        cov_tp = onp.trace(c) * n - (t * p).sum()
        cov_tt = n * n - (t * t).sum()
        cov_pp = n * n - (p * p).sum()
        denom = onp.sqrt(cov_tt * cov_pp)
        return self.name, float(cov_tp / denom) if denom else 0.0
