"""Device / Context model.

TPU-native equivalent of the reference's ``python/mxnet/context.py`` and the C++
``Context`` (include/mxnet/base.h:94-118, device types kCPU=1 kGPU=2 kCPUPinned=3
kCPUShared=5). Here the first-class accelerator is TPU: ``mx.tpu()`` resolves to a
PJRT TPU device through JAX; ``mx.cpu()`` resolves to the host platform. ``gpu`` is
accepted as an alias for the local accelerator so unmodified reference scripts run.

A Context is a lightweight (device_type, device_id) value object; the actual JAX
``Device`` is resolved lazily (so importing the package never forces a TPU runtime
handshake — important for fork-based DataLoader workers, see reference
src/initialize.cc:71-97 for the class of bug this avoids).
"""
from __future__ import annotations

import threading

from .base import MXNetError

__all__ = [
    "Context",
    "cpu",
    "cpu_pinned",
    "gpu",
    "tpu",
    "device",
    "default_backend",
    "current_context",
    "current_device",
    "num_gpus",
    "num_tpus",
    "tpu_memory_info",
    "gpu_memory_info",
    "compilation_cache_dir",
    "enable_compilation_cache",
    "disable_compilation_cache",
]

_DEVTYPES = ("cpu", "tpu", "cpu_pinned", "cpu_shared", "gpu")


class Context:
    """Execution device handle.

    Reference parity: ``mx.Context`` — usable as a context manager
    (``with mx.tpu(0): ...``) and as the ``ctx``/``device`` argument everywhere.
    """

    _local = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if device_type not in _DEVTYPES:
            raise MXNetError(f"unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = int(device_id)

    # -- resolution to a JAX / PJRT device ---------------------------------
    @property
    def _platform(self) -> str:
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            return "cpu"
        if self.device_type == "tpu":
            return "tpu"
        # 'gpu' alias: whatever the default accelerator platform is
        plat = default_backend()
        return plat if plat != "cpu" else "cpu"

    def jax_device(self):
        """Resolve to the concrete ``jax.Device`` (PJRT device).

        Uses local_devices: under jax.distributed, jax.devices() spans all
        processes and placing onto another process's device is an error.
        """
        import jax

        plat = self._platform
        try:
            devs = jax.local_devices(backend=plat)
        except RuntimeError as e:  # platform absent
            if plat != "cpu":
                raise MXNetError(
                    f"no {plat} devices available (requested {self})"
                ) from e
            raise
        if self.device_id >= len(devs):
            raise MXNetError(
                f"{self} out of range: only {len(devs)} {plat} device(s) present"
            )
        return devs[self.device_id]

    # -- context-manager protocol (thread-local stack, like reference) ------
    def __enter__(self):
        stack = getattr(Context._local, "stack", None)
        if stack is None:
            stack = Context._local.stack = []
        stack.append(self)
        return self

    def __exit__(self, *exc):
        Context._local.stack.pop()

    # -- value semantics ----------------------------------------------------
    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    def __str__(self):
        return repr(self)


Device = Context  # mxnet 2.x renamed Context -> Device; keep both names


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    # On TPU hosts all host memory goes through the same PJRT transfer path;
    # pinned is an alias of cpu kept for API parity.
    return Context("cpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Alias for the local accelerator so reference GPU scripts run unmodified."""
    return Context("gpu", device_id)


def device(dev: str | Context | None = None, device_id: int = 0) -> Context:
    if dev is None:
        return current_context()
    if isinstance(dev, Context):
        return dev
    if isinstance(dev, str):
        if ":" in dev:
            kind, idx = dev.split(":")
            return Context(kind, int(idx))
        return Context(dev, device_id)
    raise MXNetError(f"cannot interpret {dev!r} as a device")


_probe_cache = {"backend": None, "error": None, "from_cache": False}


def backend_probe_was_cached() -> bool:
    """True when this process's backend verdict came from the on-disk
    probe cache (no subprocess probe was paid). The bench reports it so
    a fast-failed run is distinguishable from a freshly probed one."""
    return bool(_probe_cache.get("from_cache"))


def last_backend_probe_error() -> str | None:
    """The verbatim plugin error / hang stack from the most recent failed
    backend probe (None after a successful probe). The bench embeds this in
    its JSON artifact so an unreachable TPU is a diagnosable failure, not a
    silent CPU fallback."""
    return _probe_cache.get("error")


def _subprocess_backend_probe(timeout_s: float) -> tuple[str | None, bool]:
    """Ask a child interpreter which backend jax resolves to.

    TPU runtime setup can hang or die inside ``jax.default_backend()``
    (PJRT plugin dial-out); probing in a subprocess keeps the parent's
    backend state untouched so we can still fall back to a working CPU
    runtime — once ``xla_bridge.backends()`` has started in-process there
    is no clean way to abort it.

    The child runs under a faulthandler deadline: on a hang it dumps the
    stack of the blocked init (typically ``make_c_api_client`` — the PJRT
    plugin dial-out) and exits, so the parent learns WHERE it hung, not
    just that it hung. The last plugin error / hang stack is kept in
    ``_probe_cache["error"]`` for diagnostics (the bench embeds it in its
    JSON artifact rather than silently publishing a CPU number).

    Returns ``(backend_name_or_None, timed_out)``.
    """
    import subprocess
    import sys

    # deadline inside the child (exit=True force-exits after the dump) so
    # the stderr tail always contains the hang site; parent timeout is a
    # backstop slightly above it
    child_deadline = max(timeout_s - 2.0, 1.0)
    code = (
        "import faulthandler, sys\n"
        f"faulthandler.dump_traceback_later({child_deadline!r}, exit=True,"
        " file=sys.stderr)\n"
        "import jax\n"
        "try:\n"
        "    b = jax.default_backend()\n"
        "except BaseException as e:\n"
        "    print('PROBE_ERROR=' + repr(e), flush=True)\n"
        "    raise\n"
        "print('BACKEND=' + b, flush=True)\n"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s + 15.0)
    except subprocess.TimeoutExpired as e:
        tail = (e.stderr or b"")
        if isinstance(tail, bytes):
            tail = tail.decode("utf-8", "replace")
        _probe_cache["error"] = ("backend probe timed out after "
                                 f"{timeout_s:.0f}s; stderr tail:\n"
                                 + tail[-2000:])
        return None, True
    except OSError as e:
        _probe_cache["error"] = f"backend probe could not launch: {e!r}"
        return None, False
    for line in reversed(out.stdout.strip().splitlines()):
        if line.startswith("BACKEND="):
            if out.returncode == 0:
                _probe_cache["error"] = None
                return line[len("BACKEND="):], False
        if line.startswith("PROBE_ERROR="):
            _probe_cache["error"] = (line[len("PROBE_ERROR="):]
                                     + "\nstderr tail:\n"
                                     + (out.stderr or "")[-2000:])
            return None, False
    timed_out = "dump_traceback_later" in (out.stderr or "") or \
        "Timeout" in (out.stderr or "")
    _probe_cache["error"] = (
        f"backend probe exited rc={out.returncode}"
        + (" after in-child deadline (hung init; stack below)"
           if timed_out else "")
        + "; stderr tail:\n" + (out.stderr or "")[-2000:])
    return None, timed_out


def _probe_cache_path():
    import os
    import tempfile

    return os.path.join(tempfile.gettempdir(),
                        f".mxtpu_backend_probe_{os.getuid()}.json")


def _probe_env_signature() -> str:
    """Hash of everything that can change the probe's verdict — a cached
    verdict only applies to an identical (interpreter, jax, platform-env)
    configuration; change any of these and the next run re-probes."""
    import hashlib
    import os
    import sys

    import jax

    parts = [sys.executable, getattr(jax, "__version__", "?")]
    for k in ("JAX_PLATFORMS", "TPU_NAME", "TPU_LIBRARY_PATH",
              "PJRT_DEVICE", "MXTPU_BACKEND_PROBE_TIMEOUT_S"):
        parts.append(f"{k}={os.environ.get(k, '')}")
    return hashlib.sha256("\0".join(parts).encode()).hexdigest()[:16]


def _load_cached_probe(sig):
    """The fresh on-disk verdict for this env signature, or None.

    Both successes AND failures are cached, with ASYMMETRIC TTLs:

    - success (``MXTPU_PROBE_CACHE_TTL_S``, default 600 s): a trusted
      verdict leads straight to an in-process accelerator init, and a
      runtime that died inside the window can still hang it — keep the
      window short;
    - failure (``MXTPU_PROBE_FAIL_TTL_S``, default 86400 s): the verdict
      only pins the process to CPU, which is always safe — and it is the
      valuable one: before this split, every bench run against the same
      dead tunnel re-paid the full probe timeout because the 600 s window
      had always lapsed by the next run (BENCH_r05 re-probed ~10 min).
      A day-long failure window means one paid probe per environment per
      day; delete the cache file or set the TTL to 0 to re-probe sooner.

    Setting either TTL to 0 disables that class of cached verdict."""
    import json
    import os
    import time

    ttl = float(os.environ.get("MXTPU_PROBE_CACHE_TTL_S", "600"))
    fail_ttl = float(os.environ.get("MXTPU_PROBE_FAIL_TTL_S", "86400"))
    try:
        with open(_probe_cache_path()) as fh:
            entry = json.load(fh).get(sig)
    except (OSError, ValueError):
        return None
    if not entry:
        return None
    limit = fail_ttl if entry.get("error") else ttl
    if limit > 0 and (time.time() - float(entry.get("ts", 0))) < limit:
        return entry
    return None


def _store_cached_probe(sig, backend, error=None):
    import json
    import os
    import time

    path = _probe_cache_path()
    try:
        try:
            with open(path) as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            data = {}
        data[sig] = {"backend": backend, "error": error, "ts": time.time()}
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(data, fh)
        os.replace(tmp, path)
    except OSError:
        pass


def default_backend() -> str:
    """``jax.default_backend()`` hardened against accelerator-runtime
    init failure (reference analog: MXNet degrades to CPU context when
    CUDA init fails rather than aborting the process).

    Strategy: if a platform is already forced (``jax_platforms``) or the
    backends are already live, call through directly. Otherwise probe in
    a subprocess under ``MXTPU_BACKEND_PROBE_TIMEOUT_S`` (default 300 s,
    generous for tunneled-TPU first contact), retry once, and on failure
    pin this process to CPU *before* any in-process backend init so the
    framework keeps working, loudly.
    """
    if _probe_cache["backend"] is not None:
        return _probe_cache["backend"]
    import os
    import warnings

    import jax
    from jax._src import xla_bridge as _xb

    if os.environ.get("MXTPU_FORCE_CPU") == "1":
        # out-of-band CPU pin that survives site hooks rewriting
        # JAX_PLATFORMS/jax.config in every child interpreter: the test
        # conftest, DataLoader worker spawner and launchers set this so
        # spawned processes skip probing entirely
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # noqa: BLE001 — backends may already be live
            pass
        _probe_cache["backend"] = "cpu"
        return "cpu"

    forced = getattr(jax.config, "jax_platforms", None) or \
        os.environ.get("JAX_PLATFORMS") or ""
    # direct call is safe only when backends are already live or the forced
    # platform list is pure-CPU. A plugin-register site hook may itself set
    # jax_platforms to "<accel>,cpu" — that still hangs if the accelerator
    # runtime is dead, so it does NOT qualify for the fast path.
    cpu_only = bool(forced) and \
        all(p.strip() == "cpu" for p in forced.split(",") if p.strip())
    if cpu_only and getattr(jax.config, "jax_platforms", None) != forced:
        try:  # make an env-only restriction stick in the live config
            jax.config.update("jax_platforms", forced)
        except Exception:
            pass
    live = bool(getattr(_xb, "_backends", None))
    if cpu_only or live:
        # direct in-process call: backends already live or the platform
        # list is pure CPU — an explicitly-set JAX_PLATFORMS=cpu therefore
        # skips the subprocess probe entirely (the common bench/test case).
        # An explicit ACCELERATOR platform list does NOT qualify for an
        # unguarded in-process init: deployment site hooks export
        # JAX_PLATFORMS=<accel> into every process, and when the runtime
        # is dead that init blocks >10 min inside make_c_api_client. Those
        # environments skip the probe through the disk cache below — one
        # probed verdict per env signature per TTL, every later run is
        # probe-free.
        try:
            b = jax.default_backend()
        except RuntimeError as e:
            warnings.warn(
                f"accelerator backend init failed ({e}); falling back to "
                "CPU. Set JAX_PLATFORMS explicitly to silence.",
                RuntimeWarning, stacklevel=2)
            b = "cpu"
        _probe_cache["backend"] = b
        return b

    sig = _probe_env_signature()
    if os.environ.get("MXTPU_SKIP_BACKEND_PROBE", "") == "1":
        # operator asserts the runtime is healthy: skip the child-process
        # round trip (~20-40s of TPU first contact) and init directly
        try:
            b = jax.default_backend()
        except RuntimeError:
            b = "cpu"
        _store_cached_probe(sig, b)
        _probe_cache["backend"] = b
        return b
    cached = _load_cached_probe(sig)
    if cached is not None:
        _probe_cache["from_cache"] = True
        if cached.get("error"):
            # a recent probe in this SAME environment already failed —
            # pin to CPU right away instead of re-paying the timeout
            _probe_cache["error"] = cached["error"]
            warnings.warn(
                "accelerator backend probe failed recently in this "
                "environment; pinning to CPU from the cached verdict. "
                f"Delete {_probe_cache_path()} or set "
                "MXTPU_PROBE_CACHE_TTL_S=0 to re-probe.",
                RuntimeWarning, stacklevel=2)
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
            _probe_cache["backend"] = "cpu"
            return "cpu"
        # a recent probe in this environment succeeded: trust it and init
        # in-process without the duplicate child init. A cached CPU verdict
        # still pins first — an unpinned init would dial the (absent)
        # accelerator plugin the probe never vouched for.
        if cached.get("backend") == "cpu":
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
        try:
            b = jax.default_backend()
        except RuntimeError:
            b = "cpu"
        _probe_cache["backend"] = b
        return b
    timeout_s = float(os.environ.get("MXTPU_BACKEND_PROBE_TIMEOUT_S", "300"))
    probed, timed_out = _subprocess_backend_probe(timeout_s)
    if probed is None and not timed_out:
        # fast nonzero-exit failures can be transient tunnel hiccups —
        # retry once; a TIMEOUT is a deterministic hang, don't double it
        probed, timed_out = _subprocess_backend_probe(timeout_s)
    failed = probed is None
    if probed is None or probed == "cpu":
        if probed is None:
            warnings.warn(
                "accelerator backend probe "
                + ("timed out" if timed_out else "failed twice")
                + f" (budget {timeout_s:.0f}s); pinning this process to "
                "CPU. Set MXTPU_BACKEND_PROBE_TIMEOUT_S or JAX_PLATFORMS "
                "to override. The verdict is cached on disk so the next "
                "run in this environment skips the wait.",
                RuntimeWarning, stacklevel=2)
            _store_cached_probe(sig, "cpu",
                                error=_probe_cache.get("error")
                                or "backend probe failed")
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        probed = "cpu"
    # the child proved this platform initializes; resolve it in-process
    try:
        b = jax.default_backend()
    except RuntimeError as e:
        warnings.warn(
            f"accelerator backend init failed in-process ({e}) after a "
            "successful probe; falling back to CPU.",
            RuntimeWarning, stacklevel=2)
        b = "cpu"
    if not failed:  # never overwrite the cached FAILURE verdict above
        _store_cached_probe(sig, b)
    _probe_cache["backend"] = b
    return b


def spawn_cpu_pinned_env():
    """Context manager setting ``JAX_PLATFORMS=cpu`` + ``MXTPU_FORCE_CPU=1``
    around ``Process.start()`` so spawned children pin to CPU at import —
    the second var survives site hooks that rewrite JAX env/config in every
    child interpreter (the consumer is :func:`default_backend`). One
    definition next to that consumer; DataLoader and the benches use it."""
    import contextlib
    import os

    @contextlib.contextmanager
    def _cm():
        saved = {k: os.environ.get(k)
                 for k in ("JAX_PLATFORMS", "MXTPU_FORCE_CPU")}
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["MXTPU_FORCE_CPU"] = "1"
        try:
            yield
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    return _cm()


def pin_process_to_cpu() -> None:
    """Child-side belt-and-braces: pin THIS process to the CPU backend
    before any jax work (spawned workers call this first thing)."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["MXTPU_FORCE_CPU"] = "1"
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — jax optional in pure-numpy workers
        pass


def ensure_backend() -> None:
    """Resolve the backend through the hardened probe BEFORE the first
    in-process jax touch. A bare ``jnp.ones`` as a process's first device
    call initializes the accelerator runtime directly — with a dead
    tunneled-TPU plugin that blocks ~25 min inside ``make_c_api_client``
    (round-4 diagnosis) and bypasses every safeguard in
    :func:`default_backend`. The NDArray constructor and the op
    dispatcher call this once per process; after the first call it is a
    dict hit."""
    if _probe_cache["backend"] is None:
        default_backend()


def _is_tpu_platform(name: str) -> bool:
    """True for TPU-family platforms. PJRT TPU plugins may register under a
    vendor name (e.g. a tunneled plugin) while canonicalizing to TPU, so
    anything that is not a known host/GPU platform counts as TPU."""
    return name not in ("cpu", "gpu", "cuda", "rocm", "METAL")


def default_context() -> Context:
    """The default device: TPU if the runtime has one, else CPU."""
    return tpu(0) if _is_tpu_platform(default_backend()) else cpu(0)


def current_context() -> Context:
    stack = getattr(Context._local, "stack", None)
    if stack:
        return stack[-1]
    return default_context()


current_device = current_context


def num_gpus() -> int:
    """Reference-parity probe; counts local accelerators."""
    return num_tpus()


def _memory_info(ctx):
    dev = ctx.jax_device()
    stats = dev.memory_stats()
    if not stats:
        raise MXNetError(
            f"device {dev} reports no memory statistics (backend without "
            "memory_stats support)")
    total = stats.get("bytes_limit", 0)
    used = stats.get("bytes_in_use", 0)
    return total - used, total


def tpu_memory_info(device_id: int = 0):
    """(free, total) HBM bytes for a local chip (reference:
    mx.context.gpu_memory_info over cudaMemGetInfo)."""
    return _memory_info(tpu(device_id))


def gpu_memory_info(device_id: int = 0):
    """Legacy alias resolving through the gpu() platform alias (so plugin
    accelerator platforms behave the same as mx.gpu() placements)."""
    return _memory_info(gpu(device_id))


def num_tpus() -> int:
    import jax

    try:
        # local (addressable) chips: under jax.distributed, global devices
        # span other hosts and cannot be targeted by this process
        return len(jax.local_devices(backend="tpu"))
    except RuntimeError:
        return 0


# -- persistent compilation cache -------------------------------------------
_compile_cache_state = {"dir": None, "enabled": False}


def compilation_cache_dir() -> str | None:
    """Resolved on-disk XLA compilation-cache directory for THIS
    environment, or None when disabled.

    Layout: ``<root>/<env signature>`` where root is
    ``MXTPU_COMPILE_CACHE_DIR`` (default ``$TMPDIR/mxtpu_xla_cache_<uid>``)
    and the leaf is the backend-probe environment signature
    (:func:`_probe_env_signature`) — the same key that scopes probe
    verdicts. Compiled XLA programs are only valid for an identical
    (interpreter, jax, platform-env) configuration; keying the directory
    by that signature means a cache populated under one configuration is
    never replayed into another, and switching configurations simply
    selects a sibling directory instead of invalidating anything.
    Set ``MXTPU_COMPILE_CACHE_DIR=off`` to disable.
    """
    import os
    import tempfile

    root = os.environ.get("MXTPU_COMPILE_CACHE_DIR", "")
    if root.lower() in ("0", "off", "none", "disabled"):
        return None
    if not root:
        root = os.path.join(tempfile.gettempdir(),
                            f"mxtpu_xla_cache_{os.getuid()}")
    return os.path.join(root, _probe_env_signature())


def tuning_cache_path() -> str | None:
    """On-disk kernel tuning cache (``tune/``) for THIS environment, or
    None when persistence is disabled.

    Default: ``tuning_cache.json`` inside :func:`compilation_cache_dir` —
    tuned block winners are only as valid as the compiled programs they
    were measured in, so they live and die with the same
    environment-signature directory. ``MXTPU_TUNE_CACHE`` overrides the
    full path (the tune layer still refuses a file whose recorded env
    signature differs); ``MXTPU_TUNE_CACHE=off`` disables persistence
    while leaving the in-process tier working.
    """
    import os

    override = os.environ.get("MXTPU_TUNE_CACHE", "")
    if override.lower() in ("0", "off", "none", "disabled"):
        return None
    if override:
        return override
    d = compilation_cache_dir()
    if not d:
        return None
    return os.path.join(d, "tuning_cache.json")


def enable_compilation_cache(path=None):
    """Point jax's persistent compilation cache at ``path`` (default:
    :func:`compilation_cache_dir`) so compiled XLA programs survive the
    process — a fresh serving process re-traces its programs but restores
    the expensive XLA compiles from disk (``serve.Predictor.warmup``
    rides this to reach steady-state latency before the first request).

    Thresholds are dropped to zero (min compile time / entry size) so
    every program is cached, including the small per-bucket serving
    programs the defaults would skip. Idempotent; returns the directory
    in use, or None when disabled or when jax refuses the config (never
    raises — serving works without persistence, just recompiles).
    """
    import os
    import warnings

    if path is None:
        path = compilation_cache_dir()
    if not path:
        return None
    if _compile_cache_state["enabled"] and \
            _compile_cache_state["dir"] == path:
        return path
    import jax

    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_enable_compilation_cache", True)
        # jax latches the cache decision at the FIRST compile of the
        # process: a compile before the dir was configured pins "no
        # cache" for good unless the latch is reset. Framework import /
        # model init always compiles something, so reset unconditionally.
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception as e:  # noqa: BLE001 — persistence is best-effort
        warnings.warn(
            f"could not enable the persistent compilation cache at "
            f"{path}: {e!r}; compiles will not survive this process",
            RuntimeWarning, stacklevel=2)
        return None
    _compile_cache_state.update(dir=path, enabled=True)
    return path


def disable_compilation_cache():
    """Turn persistence back off (idempotent). The test suite calls this
    after serve tests so later compile-heavy tests don't pay a disk write
    per XLA compile."""
    if not _compile_cache_state["enabled"]:
        return
    try:
        import jax
        from jax._src import compilation_cache as _cc

        jax.config.update("jax_enable_compilation_cache", False)
        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — best-effort teardown
        pass
    _compile_cache_state.update(dir=None, enabled=False)
