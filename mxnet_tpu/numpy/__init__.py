"""mx.np — NumPy-compatible array API executing on TPU via XLA.

TPU-native equivalent of the reference's numpy surface
(python/mxnet/numpy/multiarray.py + python/mxnet/ndarray/numpy/_op.py, backed
by src/operator/numpy/* — 128 files of C++/CUDA kernels). Every function here
funnels through ops.registry.invoke (autograd- and trace-aware); kernels are
XLA lowerings registered in mxnet_tpu.ops.

Functions with data-dependent output shapes (unique, nonzero, boolean-mask
compress) cannot compile to static XLA programs; they execute eagerly with a
host round-trip, mirroring the reference's dynamic-shape escape hatch
(SetShapeFromChunk, src/imperative/imperative.cc:123). Bounded variants
(flatnonzero with ``size=``) are provided for compiled code.
"""
from __future__ import annotations

import numpy as _onp

from ..base import canonical_dtype as _canon
from ..base import check_int32_bound as _check_bound
from ..context import current_context
from ..ndarray.ndarray import NDArray, array
from ..ops.registry import apply_op as _op
from ..ops import indexing as _indexing
from .. import random  # noqa: F401 — mx.np.random
from . import linalg  # noqa: F401
from ._serialization import (save, savez, savez_compressed,  # noqa: F401
                             load)

ndarray = NDArray

pi = _onp.pi
e = _onp.e
euler_gamma = _onp.euler_gamma
inf = _onp.inf
nan = _onp.nan
newaxis = None

float32 = _onp.float32
float64 = _onp.float64
float16 = _onp.float16
int8 = _onp.int8
int16 = _onp.int16
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
uint16 = _onp.uint16
uint32 = _onp.uint32
uint64 = _onp.uint64
bool_ = _onp.bool_


def _as_nd(x):
    if isinstance(x, NDArray):
        return x
    return NDArray(x) if not _onp.isscalar(x) else x


def _both_nd(x1, x2):
    # at least one operand must become an NDArray for dispatch
    if not isinstance(x1, NDArray) and not isinstance(x2, NDArray):
        x1 = array(x1)
    return _as_nd(x1), _as_nd(x2)


# -- generated wrappers ------------------------------------------------------
_UNARY_FUNCS = [
    "abs", "absolute", "negative", "sign", "exp", "expm1", "log", "log2",
    "log10", "log1p", "sqrt", "cbrt", "square", "reciprocal", "sin", "cos",
    "tan", "arcsin", "arccos", "arctan", "sinh", "cosh", "tanh", "arcsinh",
    "arccosh", "arctanh", "floor", "ceil", "trunc", "rint", "fix", "invert",
    "logical_not", "isnan", "isinf", "isfinite", "isposinf", "isneginf",
    "degrees", "radians", "conj", "real", "imag", "angle", "atleast_1d",
    "atleast_2d", "atleast_3d",
]
_ALIAS = {"absolute": "abs"}

_BINARY_FUNCS = [
    "add", "subtract", "multiply", "true_divide", "divide", "floor_divide",
    "mod", "fmod", "remainder", "power", "maximum", "minimum", "fmax", "fmin",
    "hypot", "arctan2", "logaddexp", "equal", "not_equal", "less",
    "less_equal", "greater", "greater_equal", "logical_and", "logical_or",
    "logical_xor", "bitwise_and", "bitwise_or", "bitwise_xor", "left_shift",
    "right_shift", "matmul", "dot", "inner", "outer", "vdot", "kron",
    "copysign", "gcd", "lcm", "ldexp", "nextafter",
]
_BALIAS = {"divide": "true_divide", "remainder": "mod"}


def _def_unary(name):
    opname = _ALIAS.get(name, name)

    def f(x, out=None, **kw):
        return _op(opname, _as_nd(x), out=out)

    f.__name__ = name
    return f


def _def_binary(name):
    opname = _BALIAS.get(name, name)

    def f(x1, x2, out=None, **kw):
        a, b = _both_nd(x1, x2)
        return _op(opname, a, b, out=out)

    f.__name__ = name
    return f


for _n in _UNARY_FUNCS:
    globals()[_n] = _def_unary(_n)
for _n in _BINARY_FUNCS:
    globals()[_n] = _def_binary(_n)

erf = _def_unary("erf")
erfinv = _def_unary("erfinv")
gamma = _def_unary("gamma")
gammaln = _def_unary("gammaln")


# -- reductions --------------------------------------------------------------
def _red(name, has_dtype=True, has_ddof=False):
    def f(a, axis=None, dtype=None, out=None, keepdims=False, ddof=0, **kw):
        attrs = {"axis": _ax(axis), "keepdims": keepdims}
        if has_dtype and dtype is not None:
            attrs["dtype"] = str(_canon(dtype))
        if has_ddof:
            attrs["ddof"] = ddof
        return _op(name, _as_nd(a), out=out, **attrs)

    f.__name__ = name
    return f


def _ax(axis):
    return tuple(axis) if isinstance(axis, list) else axis


sum = _red("sum")
mean = _red("mean")
prod = _red("prod")
std = _red("std", has_ddof=True)
var = _red("var", has_ddof=True)
nansum = _red("nansum")
nanmean = _red("nanmean")


def _red_nodtype(name):
    def f(a, axis=None, out=None, keepdims=False, **kw):
        return _op(name, _as_nd(a), axis=_ax(axis), keepdims=keepdims, out=out)

    f.__name__ = name
    return f


max = _red_nodtype("max")
min = _red_nodtype("min")
amax = max
amin = min
nanmax = _red_nodtype("nanmax")
nanmin = _red_nodtype("nanmin")
all = _red_nodtype("all")
any = _red_nodtype("any")
median = _red_nodtype("median")
logsumexp = _red_nodtype("logsumexp")


def argmax(a, axis=None, out=None, keepdims=False):
    return _op("argmax", _as_nd(a), axis=axis, keepdims=keepdims, out=out)


def argmin(a, axis=None, out=None, keepdims=False):
    return _op("argmin", _as_nd(a), axis=axis, keepdims=keepdims, out=out)


def cumsum(a, axis=None, dtype=None, out=None):
    return _op("cumsum", _as_nd(a), axis=axis,
               dtype=None if dtype is None else str(_canon(dtype)), out=out)


def cumprod(a, axis=None, dtype=None, out=None):
    return _op("cumprod", _as_nd(a), axis=axis,
               dtype=None if dtype is None else str(_canon(dtype)), out=out)


def average(a, axis=None, weights=None, returned=False):
    if weights is None:
        return mean(a, axis=axis)
    return _op("average", _as_nd(a), _as_nd(weights), axis=_ax(axis))


def trace(a, offset=0, axis1=0, axis2=1):
    return _op("trace", _as_nd(a), offset=offset, axis1=axis1, axis2=axis2)


# -- shape manipulation ------------------------------------------------------
def reshape(a, newshape, order="C"):
    return _op("reshape", _as_nd(a), newshape=tuple(newshape)
               if isinstance(newshape, (list, tuple)) else newshape)


def transpose(a, axes=None):
    return _op("transpose", _as_nd(a), axes=tuple(axes) if axes else None)


def swapaxes(a, axis1, axis2):
    return _op("swapaxes", _as_nd(a), axis1=axis1, axis2=axis2)


def moveaxis(a, source, destination):
    return _op("moveaxis", _as_nd(a),
               source=tuple(source) if isinstance(source, (list, tuple))
               else source,
               destination=tuple(destination)
               if isinstance(destination, (list, tuple)) else destination)


def squeeze(a, axis=None):
    return _op("squeeze", _as_nd(a), axis=axis)


def expand_dims(a, axis):
    return _op("expand_dims", _as_nd(a), axis=axis)


def broadcast_to(a, shape):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    _check_bound(shape, "broadcast_to")
    return _op("broadcast_to", _as_nd(a), shape=shape)


def broadcast_arrays(*args):
    shape = _onp.broadcast_shapes(*[a.shape for a in args])
    return [broadcast_to(a, shape) for a in args]


def tile(a, reps):
    return _op("tile", _as_nd(a), reps=tuple(reps)
               if isinstance(reps, (list, tuple)) else reps)


def repeat(a, repeats, axis=None):
    return _op("repeat", _as_nd(a), repeats=repeats, axis=axis)


def flip(a, axis=None):
    return _op("flip", _as_nd(a), axis=axis)


def flipud(a):
    return flip(a, 0)


def fliplr(a):
    return flip(a, 1)


def roll(a, shift, axis=None):
    return _op("roll", _as_nd(a), shift=tuple(shift)
               if isinstance(shift, (list, tuple)) else shift,
               axis=tuple(axis) if isinstance(axis, (list, tuple)) else axis)


def rot90(a, k=1, axes=(0, 1)):
    return _op("rot90", _as_nd(a), k=k, axes=tuple(axes))


def ravel(a, order="C"):
    return reshape(a, (-1,))


def concatenate(seq, axis=0, out=None):
    return _op("concatenate", *[_as_nd(s) for s in seq], axis=axis, out=out)


concat = concatenate


def stack(seq, axis=0, out=None):
    return _op("stack", *[_as_nd(s) for s in seq], axis=axis, out=out)


def vstack(seq):
    return concatenate([atleast_2d(s) for s in seq], axis=0)


def hstack(seq):
    seq = [_as_nd(s) for s in seq]
    if seq[0].ndim == 1:
        return concatenate(seq, axis=0)
    return concatenate(seq, axis=1)


def dstack(seq):
    return concatenate([atleast_3d(s) for s in seq], axis=2)


def column_stack(seq):
    seq = [_as_nd(s) for s in seq]
    seq = [s if s.ndim > 1 else s.reshape((-1, 1)) for s in seq]
    return concatenate(seq, axis=1)


def split(ary, indices_or_sections, axis=0):
    ios = indices_or_sections
    ios = tuple(ios) if isinstance(ios, (list, tuple)) else ios
    out = _op("split", _as_nd(ary), indices_or_sections=ios, axis=axis)
    return list(out) if isinstance(out, tuple) else [out]


def array_split(ary, indices_or_sections, axis=0):
    ios = indices_or_sections
    ios = tuple(ios) if isinstance(ios, (list, tuple)) else ios
    out = _op("array_split", _as_nd(ary), indices_or_sections=ios, axis=axis)
    return list(out) if isinstance(out, tuple) else [out]


def vsplit(ary, ios):
    return split(ary, ios, 0)


def hsplit(ary, ios):
    return split(ary, ios, 1)


def dsplit(ary, ios):
    return split(ary, ios, 2)


def pad(array_, pad_width, mode="constant", constant_values=0, **kw):
    pw = pad_width
    if isinstance(pw, (list, tuple)):
        pw = tuple(tuple(p) if isinstance(p, (list, tuple)) else p for p in pw)
    return _op("pad", _as_nd(array_), pad_width=pw, mode=mode,
               constant_values=constant_values)


def clip(a, a_min=None, a_max=None, out=None):
    return _op("clip", _as_nd(a), a_min=a_min, a_max=a_max, out=out)


def round(a, decimals=0, out=None):
    return _op("round", _as_nd(a), decimals=decimals, out=out)


around = round
round_ = round


def diag(v, k=0):
    return _op("diag", _as_nd(v), k=k)


def diagonal(a, offset=0, axis1=0, axis2=1):
    return _op("diagonal", _as_nd(a), offset=offset, axis1=axis1, axis2=axis2)


def tril(m, k=0):
    return _op("tril", _as_nd(m), k=k)


def triu(m, k=0):
    return _op("triu", _as_nd(m), k=k)


def where(condition, x=None, y=None):
    if x is None and y is None:
        return nonzero(condition)
    a, b = _both_nd(x, y)
    return _op("where", _as_nd(condition), a, b)


def sort(a, axis=-1):
    return _op("sort", _as_nd(a), axis=axis)


def argsort(a, axis=-1):
    return _op("argsort", _as_nd(a), axis=axis)


def searchsorted(a, v, side="left"):
    return _op("searchsorted", _as_nd(a), _as_nd(v), side=side)


def take(a, indices, axis=None, mode="clip", out=None):
    return _op("take", _as_nd(a), _as_nd(indices), axis=axis, mode=mode,
               out=out)


def take_along_axis(a, indices, axis=0):
    return _op("take_along_axis", _as_nd(a), _as_nd(indices), axis=axis)


def gather_nd(data, indices):
    return _op("gather_nd", _as_nd(data), _as_nd(indices))


def pick(data, index, axis=-1, mode="clip", keepdims=False):
    return _op("pick", _as_nd(data), _as_nd(index), axis=axis, mode=mode,
               keepdims=keepdims)


def one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    return _op("one_hot", _as_nd(indices), depth=depth, on_value=on_value,
               off_value=off_value, dtype=str(_canon(dtype)))


def meshgrid(*xi, indexing="xy"):
    out = _op("meshgrid", *[_as_nd(x) for x in xi], indexing=indexing)
    return list(out) if isinstance(out, tuple) else [out]


def bincount(x, weights=None, minlength=0):
    if weights is not None:
        raise NotImplementedError("bincount weights not supported yet")
    return _op("bincount", _as_nd(x), minlength=minlength)


def diff(a, n=1, axis=-1):
    return _op("diff", _as_nd(a), n=n, axis=axis)


def ediff1d(a):
    return _op("ediff1d", _as_nd(a))


def interp(x, xp, fp):
    return _op("interp", _as_nd(x), _as_nd(xp), _as_nd(fp))


def tensordot(a, b, axes=2):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(x) if isinstance(x, (list, tuple)) else x
                     for x in axes)
    return _op("tensordot", _as_nd(a), _as_nd(b), axes=axes)


def einsum(subscripts, *operands, optimize="optimal"):
    return _op("einsum", *[_as_nd(o) for o in operands],
               subscripts=subscripts, optimize=optimize)


def cross(a, b, axis=-1):
    return _op("cross", _as_nd(a), _as_nd(b), axis=axis)


def topk(data, k=1, axis=-1, ret_typ="indices", is_ascend=False):
    return _op("topk", _as_nd(data), k=k, axis=axis, ret_typ=ret_typ,
               is_ascend=is_ascend)


# -- dynamic-shape host fallbacks (documented) ------------------------------
def unique(ar, return_index=False, return_inverse=False, return_counts=False,
           axis=None):
    res = _onp.unique(_as_nd(ar).asnumpy(), return_index=return_index,
                      return_inverse=return_inverse,
                      return_counts=return_counts, axis=axis)
    if isinstance(res, tuple):
        return tuple(NDArray(r) for r in res)
    return NDArray(res)


def nonzero(a):
    res = _onp.nonzero(_as_nd(a).asnumpy())
    return tuple(NDArray(r) for r in res)


def flatnonzero(a, size=None):
    if size is not None:
        return _op("flatnonzero_bounded", _as_nd(a), size=size)
    return NDArray(_onp.flatnonzero(_as_nd(a).asnumpy()))


# -- creation ----------------------------------------------------------------
array = array


def _place(data, ctx=None, device=None):
    # backstop for the int32 single-chip bound: the shape-taking creation
    # ops check BEFORE allocating; anything that slipped through (new
    # creation ops, computed shapes) still surfaces a typed MXNetError
    # here instead of undefined 32-bit-offset behavior downstream
    _check_bound(data.shape)
    arr = NDArray(data)
    tgt = device or ctx
    if tgt is not None and tgt != arr.ctx:
        arr = arr.as_in_ctx(tgt)
    return arr


def zeros(shape, dtype="float32", order="C", ctx=None, device=None):
    import jax.numpy as jnp

    shape = _check_bound((shape,) if isinstance(shape, int)
                         else tuple(shape))
    return _place(jnp.zeros(shape, _canon(dtype) or _onp.float32), ctx, device)


def ones(shape, dtype="float32", order="C", ctx=None, device=None):
    import jax.numpy as jnp

    shape = _check_bound((shape,) if isinstance(shape, int)
                         else tuple(shape))
    return _place(jnp.ones(shape, _canon(dtype) or _onp.float32), ctx, device)


def full(shape, fill_value, dtype=None, ctx=None, device=None, out=None):
    import jax.numpy as jnp

    shape = _check_bound((shape,) if isinstance(shape, int)
                         else tuple(shape))
    if isinstance(fill_value, NDArray):
        fill_value = fill_value._data
    data = jnp.full(shape, fill_value,
                    _canon(dtype) if dtype is not None else None)
    if out is not None:
        out._set_data(data)
        return out
    return _place(data, ctx, device)


def empty(shape, dtype="float32", order="C", ctx=None, device=None):
    return zeros(shape, dtype, order, ctx, device)


def zeros_like(a, dtype=None, ctx=None):
    import jax.numpy as jnp

    return _place(jnp.zeros(_as_nd(a).shape,
                            _canon(dtype) or _as_nd(a).dtype), ctx)


def ones_like(a, dtype=None, ctx=None):
    import jax.numpy as jnp

    return _place(jnp.ones(_as_nd(a).shape,
                           _canon(dtype) or _as_nd(a).dtype), ctx)


def full_like(a, fill_value, dtype=None, ctx=None):
    import jax.numpy as jnp

    return _place(jnp.full(_as_nd(a).shape, fill_value,
                           _canon(dtype) or _as_nd(a).dtype), ctx)


def empty_like(a, dtype=None, ctx=None):
    return zeros_like(a, dtype, ctx)


def arange(start, stop=None, step=1, dtype=None, ctx=None, device=None):
    import jax.numpy as jnp

    lo, hi = (0, start) if stop is None else (start, stop)
    if step:
        n = int(-(-(hi - lo) // step))  # ceil; module shadows builtin max
        _check_bound((n if n > 0 else 0,), "arange")
    return _place(jnp.arange(start, stop, step,
                             _canon(dtype) if dtype else None), ctx, device)


def linspace(start, stop, num=50, endpoint=True, retstep=False, dtype=None,
             axis=0, ctx=None, device=None):
    import jax.numpy as jnp

    _check_bound((int(num),), "linspace")
    out = jnp.linspace(start, stop, num, endpoint=endpoint, retstep=retstep,
                       dtype=_canon(dtype) if dtype else None, axis=axis)
    if retstep:
        return _place(out[0], ctx, device), float(out[1])
    return _place(out, ctx, device)


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             ctx=None):
    import jax.numpy as jnp

    _check_bound((int(num),), "logspace")
    return _place(jnp.logspace(start, stop, num, endpoint=endpoint, base=base,
                               dtype=_canon(dtype) if dtype else None), ctx)


def eye(N, M=None, k=0, dtype="float32", ctx=None, device=None):
    import jax.numpy as jnp

    _check_bound((int(N), int(M if M is not None else N)), "eye")
    return _place(jnp.eye(N, M, k, _canon(dtype)), ctx, device)


def identity(n, dtype="float32", ctx=None):
    return eye(n, dtype=dtype, ctx=ctx)


def tri(N, M=None, k=0, dtype="float32", ctx=None):
    import jax.numpy as jnp

    _check_bound((int(N), int(M if M is not None else N)), "tri")
    return _place(jnp.tri(N, M, k, _canon(dtype)), ctx)


def indices(dimensions, dtype="int32", ctx=None):
    import jax.numpy as jnp

    dims = tuple(dimensions)
    _check_bound((len(dims),) + dims, "indices")
    return _place(jnp.indices(dims, dtype=_canon(dtype)), ctx)


def asarray(a, dtype=None):
    if isinstance(a, NDArray) and dtype is None:
        return a
    return array(a, dtype=dtype)


def ascontiguousarray(a, dtype=None):
    return asarray(a, dtype)


def copy(a):
    return _op("copy", _as_nd(a))


def astype(a, dtype):
    return _as_nd(a).astype(dtype)


def may_share_memory(a, b):
    return a is b


def shares_memory(a, b):
    return a is b


def isscalar(x):
    return _onp.isscalar(x)


def ndim(a):
    return _as_nd(a).ndim if isinstance(a, NDArray) else _onp.ndim(a)


def shape(a):
    return _as_nd(a).shape


def size(a, axis=None):
    if axis is None:
        return _as_nd(a).size
    return _as_nd(a).shape[axis]


def result_type(*args):
    import jax.numpy as jnp

    return jnp.result_type(*[
        a._data if isinstance(a, NDArray) else a for a in args])


def isclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    a, b = _both_nd(a, b)
    diff_ok = less_equal(abs(subtract(a, b)),
                         add(array(atol, dtype="float32"),
                             multiply(array(rtol, dtype="float32"), abs(b))))
    return diff_ok


def allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    return bool(all(isclose(a, b, rtol, atol, equal_nan)).item())


def array_equal(a, b):
    a, b = _both_nd(a, b)
    if a.shape != b.shape:
        return False
    return bool(all(equal(a, b)).item())


def fft(*a, **kw):  # namespace placeholder; see np.fft module functions below
    raise TypeError("use np.fft_ functions")


def histogram(a, bins=10, range=None):
    if isinstance(bins, int):
        # static bin count: compiled XLA path (traceable, stays on device);
        # counts cast to int32 to match the host path's integer semantics
        h, edges = _op("histogram_bounded", _as_nd(a), bins=bins,
                       range=tuple(range) if range else None)
        return h.astype("int32"), edges
    h, edges = _onp.histogram(_as_nd(a).asnumpy(), bins=bins, range=range)
    return NDArray(h.astype(_onp.int32)), NDArray(edges)


def index_update(a, key, value):
    """Functional scatter-update (TPU-native extension; a.at[key].set)."""
    return _indexing.index_update(_as_nd(a), key,
                                  value if not isinstance(value, NDArray)
                                  else value)


def index_add(a, key, value):
    return _indexing.index_add(_as_nd(a), key, value)


# -- extra surface ----------------------------------------------------------
signbit = _def_unary("signbit")
positive = _def_unary("positive")
deg2rad = _def_unary("deg2rad")
rad2deg = _def_unary("rad2deg")
exp2 = _def_unary("exp2")
i0 = _def_unary("i0")
sinc = _def_unary("sinc")
def nan_to_num(x, copy=True, nan=0.0, posinf=None, neginf=None):
    return _op("nan_to_num", _as_nd(x), nan=nan, posinf=posinf,
               neginf=neginf)
heaviside = _def_binary("heaviside")
float_power = _def_binary("float_power")


def divmod(x1, x2):
    a, b = _both_nd(x1, x2)
    return _op("true_divmod", a, b)


def digitize(x, bins, right=False):
    return _op("digitize", _as_nd(x), _as_nd(bins), right=right)


def corrcoef(x):
    return _op("corrcoef", _as_nd(x))


def cov(m):
    return _op("cov", _as_nd(m))


def append(arr, values, axis=None):
    a = _as_nd(arr)
    v = values if isinstance(values, NDArray) else array(values)
    if axis is None:
        return concatenate([a.reshape((-1,)), v.reshape((-1,))], axis=0)
    return concatenate([a, v], axis=axis)


def delete(arr, obj, axis=None):
    host = _as_nd(arr).asnumpy()
    return NDArray(_onp.delete(host, obj if not isinstance(obj, NDArray)
                               else obj.asnumpy(), axis=axis))


def insert(arr, obj, values, axis=None):
    host = _as_nd(arr).asnumpy()
    vals = values.asnumpy() if isinstance(values, NDArray) else values
    return NDArray(_onp.insert(host, obj, vals, axis=axis))


def trim_zeros(filt, trim="fb"):
    return NDArray(_onp.trim_zeros(_as_nd(filt).asnumpy(), trim))


def count_nonzero(a, axis=None):
    return sum(not_equal(_as_nd(a), 0).astype("int32"), axis=axis)


def _norm_q(q):
    qa = _onp.asarray(q.asnumpy() if isinstance(q, NDArray) else q,
                      dtype="float64")
    return float(qa) if qa.ndim == 0 else tuple(qa.tolist())


def quantile(a, q, axis=None, out=None, overwrite_input=None,
             interpolation="linear", keepdims=False):
    return _op("quantile", _as_nd(a), q=_norm_q(q), axis=_ax(axis),
               method=interpolation or "linear", keepdims=keepdims, out=out)


def percentile(a, q, axis=None, out=None, overwrite_input=None,
               interpolation="linear", keepdims=False):
    return _op("percentile", _as_nd(a), q=_norm_q(q), axis=_ax(axis),
               method=interpolation or "linear", keepdims=keepdims, out=out)


# numpy-parity stragglers over newly registered ops
def diagflat(v, k=0):
    return _op("diagflat", _as_nd(v), k=k)


def fill_diagonal(a, val, wrap=False):
    """In place like numpy: mutates ``a`` and returns None."""
    if _onp.isscalar(val):
        res = _op("fill_diagonal", _as_nd(a), val=val, wrap=wrap)
    else:
        res = _op("fill_diagonal", _as_nd(a), _as_nd(val), wrap=wrap)
    a._set_data(res._data)


def rollaxis(a, axis, start=0):
    return _op("rollaxis", _as_nd(a), axis=axis, start=start)


def polyval(p, x):
    return _op("polyval", _as_nd(p), _as_nd(x))


def blackman(M, dtype=None):
    return _op("blackman", M=int(M))


def hamming(M, dtype=None):
    return _op("hamming", M=int(M))


def hanning(M, dtype=None):
    return _op("hanning", M=int(M))


def tril_indices(n, k=0, m=None):
    return _op("tril_indices", n=int(n), k=int(k),
               m=int(m) if m is not None else None)
