"""mx.np.save / load / savez — numpy .npy/.npz wire-format interchange.

TPU-native counterpart of the reference's cnpy codec
(src/serialization/cnpy.cc:896, surfaced as mx.np.save/load in
python/mxnet/numpy/utils.py). The device side is JAX arrays in HBM, so
serialization is a host concern: arrays are fetched (wait + device→host copy)
and written with numpy's own writer, which *is* the wire format — files
round-trip bit-exactly with stock ``numpy.load``.

bfloat16 policy: ml_dtypes' bfloat16 has no portable .npy descr (stock numpy
reads it back as ``|V2`` raw bytes), so by default bfloat16 arrays are saved
as float32 — the upcast is value-exact and the file loads everywhere. Set
``MXTPU_NPY_BF16=raw`` to keep the 2-byte payload (readers then need
ml_dtypes to reinterpret). The chosen policy only affects dtype width on
disk, never values.
"""
from __future__ import annotations

import os

import numpy as _onp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["save", "savez", "savez_compressed", "load"]


def _to_host(a):
    if isinstance(a, NDArray):
        a = a.asnumpy()
    a = _onp.asarray(a)
    if a.dtype.name == "bfloat16" and \
            os.environ.get("MXTPU_NPY_BF16", "float32") != "raw":
        a = a.astype(_onp.float32)
    return a


def save(file, arr):
    """Write one array as .npy (numpy wire format, numpy.load-compatible)."""
    _onp.save(file, _to_host(arr))


def savez(file, *args, **kwds):
    """Write arrays as an uncompressed .npz archive."""
    _onp.savez(file, *[_to_host(a) for a in args],
               **{k: _to_host(v) for k, v in kwds.items()})


def savez_compressed(file, *args, **kwds):
    """Write arrays as a zip-deflated .npz archive."""
    _onp.savez_compressed(file, *[_to_host(a) for a in args],
                          **{k: _to_host(v) for k, v in kwds.items()})


def load(file, allow_pickle=False):
    """Read .npy → NDArray, or .npz → dict of name → NDArray.

    Object arrays are refused by default like numpy's own loader; device
    placement follows the current context (lazy, on first use).
    """
    data = _onp.load(file, allow_pickle=allow_pickle)
    if isinstance(data, _onp.lib.npyio.NpzFile):
        try:
            return {k: NDArray(_decode(data[k])) for k in data.files}
        finally:
            data.close()
    return NDArray(_decode(data))


def _decode(a):
    if a.dtype.kind == "V" and a.dtype.itemsize == 2:
        # raw-mode bfloat16 payload (see module docstring)
        import ml_dtypes

        return a.view(_onp.uint16).view(ml_dtypes.bfloat16)
    if a.dtype == _onp.object_:
        raise MXNetError("object arrays are not loadable as NDArray")
    return a
