"""mx.np.linalg — linear algebra (reference: src/operator/numpy/linalg/*).

All decompositions lower to XLA's native linalg custom calls via jax.numpy.
"""
from __future__ import annotations

from ..ops.registry import apply_op as _op


def _nd(x):
    from ..ndarray.ndarray import NDArray

    return x if isinstance(x, NDArray) else NDArray(x)


def norm(x, ord=None, axis=None, keepdims=False):
    return _op("norm", _nd(x), ord=ord,
               axis=tuple(axis) if isinstance(axis, (list, tuple)) else axis,
               keepdims=keepdims)


def inv(a):
    return _op("linalg_inv", _nd(a))


def pinv(a):
    return _op("linalg_pinv", _nd(a))


def det(a):
    return _op("linalg_det", _nd(a))


def slogdet(a):
    return _op("linalg_slogdet", _nd(a))


def cholesky(a):
    return _op("linalg_cholesky", _nd(a))


def qr(a, mode="reduced"):
    return _op("linalg_qr", _nd(a), mode=mode)


def svd(a, full_matrices=True, compute_uv=True):
    return _op("linalg_svd", _nd(a), full_matrices=full_matrices,
               compute_uv=compute_uv)


def eigh(a):
    return _op("linalg_eigh", _nd(a))


def eigvalsh(a):
    return _op("linalg_eigvalsh", _nd(a))


def solve(a, b):
    return _op("linalg_solve", _nd(a), _nd(b))


def lstsq(a, b, rcond=None):
    return _op("linalg_lstsq", _nd(a), _nd(b), rcond=rcond)


def matrix_power(a, n):
    return _op("linalg_matrix_power", _nd(a), n=n)


def matrix_rank(a):
    return _op("linalg_matrix_rank", _nd(a))


def multi_dot(arrays):
    return _op("linalg_multi_dot", *[_nd(a) for a in arrays])


def tensorsolve(a, b, axes=None):
    return _op("linalg_tensorsolve", _nd(a), _nd(b),
               axes=tuple(axes) if axes else None)


def tensorinv(a, ind=2):
    return _op("linalg_tensorinv", _nd(a), ind=ind)
