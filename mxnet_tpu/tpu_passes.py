"""Built-in "tpu" subgraph backend: pattern-match-and-replace passes.

TPU-native analog of the reference's subgraph properties
(src/operator/subgraph/subgraph_property.h:252 SubgraphProperty,
build_subgraph.cc partitioner; oneDNN's conv+bn+relu fusions are the
worked example). Here the unit of replacement is a Symbol-IR subgraph and
the replacement targets are Pallas kernels.

Shipped pass: **attention fusion** — rewrites the hand-written attention
pattern

    logits = matmul(q, kᵀ)            (or einsum bhqd,bhkd->bhqk)
    logits = logits * s  |  logits / s  |  matmul(q * s, kᵀ)   [optional]
    w      = softmax(logits, axis=-1)
    out    = matmul(w, v)             (or einsum bhqk,bhkd->bhqd)

into one ``flash_attention`` op (Pallas online-softmax kernel, no O(T²)
HBM materialization). Matched interior nodes must have no other consumers;
the head node is rewritten in place so downstream references survive.
"""
from __future__ import annotations

from .subgraph import register_backend, register_pass
from .symbol.symbol import Literal, Symbol, topo_sort

register_backend("tpu")


def _consumer_counts(nodes, entries):
    counts: dict[int, int] = {}
    for n in nodes:
        for e in n.inputs:
            if not isinstance(e, Literal):
                counts[id(e[0])] = counts.get(id(e[0]), 0) + 1
    for node, _ in entries:
        counts[id(node)] = counts.get(id(node), 0) + 1
    return counts


def _op_name(node):
    return node.op.name if node.op is not None else None


def _scalar_of(entry):
    """Literal / 0-d const entry → python float, else None."""
    if isinstance(entry, Literal):
        v = entry.value
        return float(v) if isinstance(v, (int, float)) else None
    node, _ = entry
    if node.is_const and getattr(node.value, "ndim", None) == 0:
        return float(node.value)
    return None


def _is_kt(entry):
    """Does this entry transpose the last two axes of its input?
    Returns the un-transposed producer entry, or None."""
    if isinstance(entry, Literal):
        return None
    node, idx = entry
    name = _op_name(node)
    if name == "transpose":
        axes = node.attrs.get("axes")
        if axes is not None:
            axes = tuple(axes)
            n = len(axes)
            want = tuple(range(n - 2)) + (n - 1, n - 2)
            if axes == want:
                return node.inputs[0]
    elif name == "swapaxes":
        a1 = node.attrs.get("axis1", 0)
        a2 = node.attrs.get("axis2", 1)
        if {a1, a2} in ({-1, -2}, {2, 3}):
            return node.inputs[0]
    return None


def _match_qk(node):
    """Match a q·kᵀ logits node → (q_entry, k_entry, scale) or None."""
    name = _op_name(node)
    if name == "matmul":
        q_e, kt_e = node.inputs[0], node.inputs[1]
        k_e = _is_kt(kt_e)
        if k_e is None:
            return None
        scale = 1.0
        # scale folded onto q: matmul(multiply(q, s), kT)
        if not isinstance(q_e, Literal):
            qn, _ = q_e
            if _op_name(qn) == "multiply":
                s = _scalar_of(qn.inputs[1]) or _scalar_of(qn.inputs[0])
                if s is not None:
                    other = qn.inputs[0] if _scalar_of(qn.inputs[1]) \
                        is not None else qn.inputs[1]
                    return other, k_e, s
        return q_e, k_e, scale
    if name == "einsum":
        sub = node.attrs.get("subscripts", "").replace(" ", "")
        if sub == "bhqd,bhkd->bhqk":
            return node.inputs[0], node.inputs[1], 1.0
    return None


def _entry_shape(entry):
    """Static shape of a graph entry when known: const value shape, or a
    var's recorded ``shape=`` from ``sym.var`` — else None."""
    if isinstance(entry, Literal):
        return None
    node, _ = entry
    if node.is_const:
        return tuple(node.value.shape)
    ann = node.attr_dict.get("__shape__")
    if ann:
        try:
            return tuple(int(x) for x in ann.strip("()").split(",") if x)
        except ValueError:
            return None
    return None


def _match_key_padding_mask(node, counts):
    """Match ``where(mask, logits, big_negative)`` where mask is statically
    known to be a (B, 1, 1, Tk) key-padding mask. Returns
    (logits_node, mask_entry) or None."""
    if _op_name(node) not in ("where", "_npi_where") or \
            counts.get(id(node), 0) != 1:
        return None
    cond_e, x_e, y_e = node.inputs
    neg = _scalar_of(y_e)
    if neg is None or neg > -1e9 or isinstance(x_e, Literal):
        return None
    shape = _entry_shape(cond_e)
    if shape is None or len(shape) != 4 or shape[1] != 1 or shape[2] != 1:
        return None
    return x_e[0], cond_e, shape


def _match_attention(out_node, counts):
    """Match out_node = matmul(softmax([mask](scale(q·kᵀ))), v). Returns
    (q_entry, k_entry, v_entry, scale, mask_entry_or_None, mask_shape)
    or None."""
    name = _op_name(out_node)
    if name == "matmul":
        w_e, v_e = out_node.inputs[0], out_node.inputs[1]
    elif name == "einsum" and out_node.attrs.get(
            "subscripts", "").replace(" ", "") == "bhqk,bhkd->bhqd":
        w_e, v_e = out_node.inputs[0], out_node.inputs[1]
    else:
        return None
    if isinstance(w_e, Literal):
        return None
    w, _ = w_e
    if _op_name(w) != "softmax" or counts.get(id(w), 0) != 1:
        return None
    if w.attrs.get("axis", -1) not in (-1, 3):
        return None
    if w.attrs.get("use_length") or w.attrs.get("temperature") not in (
            None, 1.0):
        return None
    s_e = w.inputs[0]
    if isinstance(s_e, Literal):
        return None
    s_node, _ = s_e
    # optional key-padding mask: softmax(where(mask, logits, -big))
    mask_e = mask_shape = None
    masked = _match_key_padding_mask(s_node, counts)
    if masked is not None:
        s_node, mask_e, mask_shape = masked
    scale_mult = 1.0
    logits = s_node
    # optional explicit scaling of the logits
    if _op_name(s_node) in ("multiply", "true_divide") and \
            counts.get(id(s_node), 0) == 1:
        sc = _scalar_of(s_node.inputs[1])
        if sc is None and _op_name(s_node) == "multiply":
            sc = _scalar_of(s_node.inputs[0])
            cand = s_node.inputs[1]
        else:
            cand = s_node.inputs[0]
        if sc is not None and not isinstance(cand, Literal):
            scale_mult = (1.0 / sc if _op_name(s_node) == "true_divide"
                          else sc)
            logits = cand[0]
    if counts.get(id(logits), 0) != 1:
        return None
    qk = _match_qk(logits)
    if qk is None:
        return None
    q_e, k_e, q_scale = qk
    return q_e, k_e, v_e, scale_mult * q_scale, mask_e, mask_shape


@register_pass("tpu")
def fuse_attention(sym: Symbol) -> Symbol:
    """Rewrite eligible attention subgraphs onto ``flash_attention`` —
    including the key-padding-masked form, whose (B, 1, 1, Tk) mask is
    lowered to segment ids (query side all-valid, key side the mask) so
    padded batches stay on the fused kernel."""
    from .ops.registry import get_op
    from .symbol.symbol import SymNode

    nodes = topo_sort(sym._entries)
    counts = _consumer_counts(nodes, sym._entries)
    flash = get_op("flash_attention")
    for node in nodes:
        m = _match_attention(node, counts)
        if m is None:
            continue
        q_e, k_e, v_e, scale, mask_e, mask_shape = m
        inputs = (q_e, k_e, v_e)
        if mask_e is not None:
            b, _, _, tk = mask_shape
            # only rewrite when q/k shapes are statically known to be
            # compatible: self-attention (Tq == Tk == mask Tk, same batch).
            # Cross-attention padding masks (Tq != Tk) would build segment
            # ids of the wrong length — leave those graphs alone
            q_shape, k_shape = _entry_shape(q_e), _entry_shape(k_e)
            if (q_shape is None or k_shape is None or len(q_shape) < 2 or
                    q_shape[-2] != tk or k_shape[-2] != tk or
                    q_shape[0] != b):
                continue
            # normalize truthiness to 0/1 ids the way where() would
            # (any nonzero mask value means "keep")
            flat = SymNode(op=get_op("reshape"),
                           attrs={"newshape": (b, tk)}, inputs=(mask_e,))
            k_seg = SymNode(op=get_op("not_equal"),
                            inputs=((flat, 0), Literal(0)))
            q_seg = SymNode(op=get_op("ones_like"), inputs=((k_seg, 0),))
            inputs = (q_e, k_e, v_e, (q_seg, 0), (k_seg, 0))
        # rewrite the head node in place: downstream (SymNode, idx)
        # references — including graph outputs — stay valid
        node.op = flash
        node.attrs = {"scale": scale, "causal": False}
        node.inputs = inputs
    return sym
