"""Built-in "tpu" subgraph backend: pattern-match-and-replace passes.

TPU-native analog of the reference's subgraph properties
(src/operator/subgraph/subgraph_property.h:252 SubgraphProperty,
build_subgraph.cc partitioner; oneDNN's conv+bn+relu fusions are the
worked example). Here the unit of replacement is a Symbol-IR subgraph and
the replacement targets are Pallas kernels.

Shipped pass: **attention fusion** — rewrites the hand-written attention
pattern

    logits = matmul(q, kᵀ)            (or einsum bhqd,bhkd->bhqk)
    logits = logits * s  |  logits / s  |  matmul(q * s, kᵀ)   [optional]
    w      = softmax(logits, axis=-1)
    out    = matmul(w, v)             (or einsum bhqk,bhkd->bhqd)

into one ``flash_attention`` op (Pallas online-softmax kernel, no O(T²)
HBM materialization). Matched interior nodes must have no other consumers;
the head node is rewritten in place so downstream references survive.
"""
from __future__ import annotations

from .subgraph import register_backend, register_pass
from .symbol.symbol import Literal, Symbol, topo_sort

register_backend("tpu")


def _consumer_counts(nodes, entries):
    counts: dict[int, int] = {}
    for n in nodes:
        for e in n.inputs:
            if not isinstance(e, Literal):
                counts[id(e[0])] = counts.get(id(e[0]), 0) + 1
    for node, _ in entries:
        counts[id(node)] = counts.get(id(node), 0) + 1
    return counts


def _op_name(node):
    return node.op.name if node.op is not None else None


def _scalar_of(entry):
    """Literal / 0-d const entry → python float, else None."""
    if isinstance(entry, Literal):
        v = entry.value
        return float(v) if isinstance(v, (int, float)) else None
    node, _ = entry
    if node.is_const and getattr(node.value, "ndim", None) == 0:
        return float(node.value)
    return None


def _is_kt(entry):
    """Does this entry transpose the last two axes of its input?
    Returns the un-transposed producer entry, or None."""
    if isinstance(entry, Literal):
        return None
    node, idx = entry
    name = _op_name(node)
    if name == "transpose":
        axes = node.attrs.get("axes")
        if axes is not None:
            axes = tuple(axes)
            n = len(axes)
            want = tuple(range(n - 2)) + (n - 1, n - 2)
            if axes == want:
                return node.inputs[0]
    elif name == "swapaxes":
        a1 = node.attrs.get("axis1", 0)
        a2 = node.attrs.get("axis2", 1)
        if {a1, a2} in ({-1, -2}, {2, 3}):
            return node.inputs[0]
    return None


def _match_qk(node):
    """Match a q·kᵀ logits node → (q_entry, k_entry, scale) or None."""
    name = _op_name(node)
    if name == "matmul":
        q_e, kt_e = node.inputs[0], node.inputs[1]
        k_e = _is_kt(kt_e)
        if k_e is None:
            return None
        scale = 1.0
        # scale folded onto q: matmul(multiply(q, s), kT)
        if not isinstance(q_e, Literal):
            qn, _ = q_e
            if _op_name(qn) == "multiply":
                s = _scalar_of(qn.inputs[1]) or _scalar_of(qn.inputs[0])
                if s is not None:
                    other = qn.inputs[0] if _scalar_of(qn.inputs[1]) \
                        is not None else qn.inputs[1]
                    return other, k_e, s
        return q_e, k_e, scale
    if name == "einsum":
        sub = node.attrs.get("subscripts", "").replace(" ", "")
        if sub == "bhqd,bhkd->bhqk":
            return node.inputs[0], node.inputs[1], 1.0
    return None


def _match_attention(out_node, counts):
    """Match out_node = matmul(softmax(scale(q·kᵀ)), v). Returns
    (q_entry, k_entry, v_entry, scale) or None."""
    name = _op_name(out_node)
    if name == "matmul":
        w_e, v_e = out_node.inputs[0], out_node.inputs[1]
    elif name == "einsum" and out_node.attrs.get(
            "subscripts", "").replace(" ", "") == "bhqk,bhkd->bhqd":
        w_e, v_e = out_node.inputs[0], out_node.inputs[1]
    else:
        return None
    if isinstance(w_e, Literal):
        return None
    w, _ = w_e
    if _op_name(w) != "softmax" or counts.get(id(w), 0) != 1:
        return None
    if w.attrs.get("axis", -1) not in (-1, 3):
        return None
    if w.attrs.get("use_length") or w.attrs.get("temperature") not in (
            None, 1.0):
        return None
    s_e = w.inputs[0]
    if isinstance(s_e, Literal):
        return None
    s_node, _ = s_e
    scale_mult = 1.0
    logits = s_node
    # optional explicit scaling of the logits
    if _op_name(s_node) in ("multiply", "true_divide") and \
            counts.get(id(s_node), 0) == 1:
        sc = _scalar_of(s_node.inputs[1])
        if sc is None and _op_name(s_node) == "multiply":
            sc = _scalar_of(s_node.inputs[0])
            cand = s_node.inputs[1]
        else:
            cand = s_node.inputs[0]
        if sc is not None and not isinstance(cand, Literal):
            scale_mult = (1.0 / sc if _op_name(s_node) == "true_divide"
                          else sc)
            logits = cand[0]
    if counts.get(id(logits), 0) != 1:
        return None
    qk = _match_qk(logits)
    if qk is None:
        return None
    q_e, k_e, q_scale = qk
    return q_e, k_e, v_e, scale_mult * q_scale


@register_pass("tpu")
def fuse_attention(sym: Symbol) -> Symbol:
    """Rewrite eligible attention subgraphs onto ``flash_attention``."""
    from .ops.registry import get_op

    nodes = topo_sort(sym._entries)
    counts = _consumer_counts(nodes, sym._entries)
    flash = get_op("flash_attention")
    for node in nodes:
        m = _match_attention(node, counts)
        if m is None:
            continue
        q_e, k_e, v_e, scale = m
        # rewrite the head node in place: downstream (SymNode, idx)
        # references — including graph outputs — stay valid
        node.op = flash
        node.attrs = {"scale": scale, "causal": False}
        node.inputs = (q_e, k_e, v_e)
    return sym
