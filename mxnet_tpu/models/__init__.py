"""mxnet_tpu.models — flat access to the model zoo.

Alias package so ``from mxnet_tpu.models import resnet50_v1`` works alongside
the reference-compatible ``gluon.model_zoo.vision`` path.
"""
from ..gluon.model_zoo.vision import *  # noqa: F401,F403
from ..gluon.model_zoo.vision import get_model  # noqa: F401
from ..gluon.model_zoo.vision.mlp import MLP, mlp  # noqa: F401
