"""Attribute scoping for symbols (reference: python/mxnet/attribute.py —
AttrScope attaching attrs to symbols created inside the scope)."""
from __future__ import annotations

import threading

__all__ = ["AttrScope"]


class AttrScope:
    _local = threading.local()

    def __init__(self, **kwargs):
        self._attrs = {k: str(v) for k, v in kwargs.items()}

    def get(self, attrs=None):
        out = dict(self._attrs)
        if attrs:
            out.update(attrs)
        return out

    @classmethod
    def current(cls):
        stack = getattr(cls._local, "stack", None)
        if stack:
            return stack[-1]
        if not hasattr(cls._local, "default"):
            cls._local.default = AttrScope()
        return cls._local.default

    def __enter__(self):
        stack = getattr(AttrScope._local, "stack", None)
        if stack is None:
            stack = AttrScope._local.stack = []
        # nested scopes merge outward-in
        merged = AttrScope()
        merged._attrs = {**AttrScope.current()._attrs, **self._attrs}
        stack.append(merged)
        return merged

    def __exit__(self, *exc):
        AttrScope._local.stack.pop()
