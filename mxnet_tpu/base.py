"""Base utilities: dtype handling, registries, error types.

TPU-native rewrite of the roles played by the reference's ``python/mxnet/base.py``
(lib loading / ``check_call``) and dmlc-core's registry. There is no C library to
load: the "backend" is JAX/XLA over PJRT, so this module only carries shared
plumbing (dtype canonicalization, a generic registry used by optimizers /
initializers / kvstore backends, and the MXNetError exception type).

Reference: python/mxnet/base.py, 3rdparty/dmlc-core registry pattern.
"""
from __future__ import annotations

import threading
import warnings as _warnings

import numpy as onp

__all__ = ["MXNetError", "Registry", "canonical_dtype", "dtype_name",
           "string_types", "warn_once"]

string_types = (str,)

# process-level dedup for fallback/degradation warnings: hot paths may hit
# the same unsupported configuration every step (or rebuild their wrapper
# object every epoch), and the useful signal is "this run degraded", once
_warned_keys: set = set()
_warned_lock = threading.Lock()


def warn_once(key, message, category=RuntimeWarning, stacklevel=2):
    """Emit ``message`` at most once per process for ``key``.

    Returns True when the warning fired. Used by the compiled-train-step
    fallbacks (and anything else that degrades gracefully) so repeated
    steps — or repeated ``compile_step`` calls on the same net — produce
    ONE warning per (reason, subject), not one per call."""
    with _warned_lock:
        if key in _warned_keys:
            return False
        _warned_keys.add(key)
    _warnings.warn(message, category, stacklevel=stacklevel + 1)
    return True


class MXNetError(RuntimeError):
    """Framework error type (reference: MXGetLastError / dmlc::Error)."""


# Single-chip element bound: XLA:TPU addresses buffers with 32-bit offsets,
# so one unsharded array may hold at most INT32_MAX elements. The reference
# gates the same boundary behind its INT64_TENSOR_SIZE build flag
# (src/libinfo.cc:39-161, tests/nightly/test_large_array.py); here larger
# arrays are served by sharding over a mesh axis instead, and crossing the
# bound on one chip raises a typed error rather than whatever XLA does.
INT32_ELEM_BOUND = 2 ** 31 - 1


def check_int32_bound(shape, what="array"):
    """Raise MXNetError if ``shape`` holds more than INT32_ELEM_BOUND
    elements (called before allocation on the shape-taking creation ops)."""
    n = 1
    for d in shape:
        n *= int(d)
    if n > INT32_ELEM_BOUND:
        raise MXNetError(
            f"{what} of shape {tuple(shape)} has {n:,} elements, over the "
            f"single-chip int32 index bound ({INT32_ELEM_BOUND:,}). Shard "
            "it over a device mesh axis (jax.sharding / Learner "
            "param_spec_fn) or reduce the shape; the reference's analog is "
            "the INT64_TENSOR_SIZE large-tensor build (src/libinfo.cc:39).")
    return shape


# ---------------------------------------------------------------------------
# dtypes
# ---------------------------------------------------------------------------
# JAX runs with x64 disabled (TPU-native: f32/bf16 are the MXU-friendly types).
# float64/int64 inputs are canonicalized by JAX itself; we keep names stable.
_DTYPE_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "int": "int32",
    "long": "int64",
    "bool_": "bool",
}


def canonical_dtype(dtype):
    """Return a numpy dtype for a user-supplied dtype spec (str/np.dtype/None)."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        dtype = _DTYPE_ALIASES.get(dtype, dtype)
        if dtype == "bfloat16":
            import jax.numpy as jnp

            return jnp.bfloat16
    return onp.dtype(dtype) if not _is_bfloat16(dtype) else dtype


def _is_bfloat16(dtype) -> bool:
    return getattr(dtype, "__name__", None) == "bfloat16" or str(dtype) == "bfloat16"


def dtype_name(dtype) -> str:
    """String name of a dtype ('float32', 'bfloat16', ...)."""
    if dtype is None:
        return "None"
    return str(onp.dtype(dtype)) if not _is_bfloat16(dtype) else "bfloat16"


# ---------------------------------------------------------------------------
# generic registry (reference: dmlc registry / mx.operator register patterns)
# ---------------------------------------------------------------------------
class Registry:
    """Name -> object registry with decorator support and alias handling."""

    def __init__(self, kind: str):
        self.kind = kind
        self._store: dict[str, object] = {}

    def register(self, obj=None, name=None):
        def _do(o):
            key = (name or getattr(o, "__name__", None) or str(o)).lower()
            self._store[key] = o
            return o

        if obj is None:
            return _do
        return _do(obj)

    def alias(self, name: str, target: str):
        self._store[name.lower()] = self._store[target.lower()]

    def get(self, name: str):
        key = name.lower() if isinstance(name, str) else name
        if key not in self._store:
            raise KeyError(
                f"{self.kind} '{name}' is not registered. "
                f"Known: {sorted(self._store)}"
            )
        return self._store[key]

    def find(self, name: str):
        return self._store.get(name.lower() if isinstance(name, str) else name)

    def list(self):
        return sorted(self._store)

    def __contains__(self, name):
        return (name.lower() if isinstance(name, str) else name) in self._store
