"""mxnet_tpu.serve — TPU-native inference subsystem (ISSUE 4).

``Predictor`` wraps a hybridized Block behind (a) a shape-bucket ladder
bounding the compiled-program set, (b) a futures-based dynamic batcher
coalescing concurrent requests into padded device batches, and (c) jax's
persistent compilation cache + a warmup manifest so a fresh process
serves at steady-state latency from the first request.

Quick start::

    net.hybridize()
    pred = net.predictor(example=x, max_batch=64)   # or serve.Predictor(net, x)
    pred.warmup("model.warmup.json")                # compile every bucket
    y = pred.predict(batch)                         # sync, any batch size
    fut = pred.submit(single_item)                  # dynamic batching
    fut.result()

See docs/DESIGN.md "Serving".
"""
from . import decode
from .bucketing import bucket_ladder, pick_bucket, split_sizes
from .decode import DecodeEngine, DecodeStream, EngineDeadError, ShedError
from .predictor import Predictor, load_manifest

__all__ = ["Predictor", "load_manifest", "bucket_ladder", "pick_bucket",
           "split_sizes", "decode", "DecodeEngine", "DecodeStream",
           "ShedError", "EngineDeadError"]
