"""mxnet_tpu.serve.decode — continuous-batching autoregressive decoding.

The LLM leg of the serving story (ISSUE 7, decode engine v2 in ISSUE 18):
a PAGED KV cache — a shared pool of fixed-size pages mapped through
per-slot page tables (:mod:`cache`) — three AOT-compiled program
families — bucketed ``prefill``, prefix-join ``prefill_ext`` and
fixed-shape ``decode_tick_k`` (:mod:`programs`) — a host-side radix
prefix cache sharing prompt-prefix pages across requests (:mod:`prefix`),
speculative multi-token verification (:mod:`spec`), and a
continuous-batching scheduler with streaming token futures, deadlines,
and load shedding (:mod:`engine`).

Quick start::

    eng = serve.decode.DecodeEngine(model, num_slots=8, speculate_k=4)
    eng.warmup("gpt.decode.manifest.json")   # compile everything up front
    stream = eng.submit(prompt_ids, max_new_tokens=32, deadline_ms=500)
    for tok in stream:                       # tokens as they are decoded
        ...
    stream.result()                          # or block for the full list

See docs/DESIGN.md "Decode engine v2".
"""
from .cache import KVCache, PageAllocator, PagedKVCache, SlotAllocator
from .engine import DecodeEngine, DecodeStream, EngineDeadError, ShedError
from .prefix import RadixPrefixCache
from .programs import DecodePrograms, load_decode_manifest
from .spec import (LastTokenDraft, NgramDraft, accept_longest_prefix,
                   make_draft)

__all__ = ["DecodeEngine", "DecodeStream", "ShedError", "EngineDeadError",
           "KVCache", "SlotAllocator", "PageAllocator", "PagedKVCache",
           "RadixPrefixCache", "DecodePrograms", "load_decode_manifest",
           "NgramDraft", "LastTokenDraft", "make_draft",
           "accept_longest_prefix"]
