"""mxnet_tpu.serve.decode — continuous-batching autoregressive decoding.

The LLM leg of the serving story (ISSUE 7): a slot-paged KV cache
(:mod:`cache`), exactly two AOT-compiled program families — bucketed
``prefill`` and fixed-shape ``decode_tick`` (:mod:`programs`) — and a
continuous-batching scheduler with streaming token futures, deadlines,
and load shedding (:mod:`engine`).

Quick start::

    eng = serve.decode.DecodeEngine(model, num_slots=8)
    eng.warmup("gpt.decode.manifest.json")   # compile everything up front
    stream = eng.submit(prompt_ids, max_new_tokens=32, deadline_ms=500)
    for tok in stream:                       # tokens as they are decoded
        ...
    stream.result()                          # or block for the full list

See docs/DESIGN.md "Continuous-batching decode".
"""
from .cache import KVCache, SlotAllocator
from .engine import DecodeEngine, DecodeStream, EngineDeadError, ShedError
from .programs import DecodePrograms, load_decode_manifest

__all__ = ["DecodeEngine", "DecodeStream", "ShedError", "EngineDeadError",
           "KVCache", "SlotAllocator", "DecodePrograms",
           "load_decode_manifest"]
