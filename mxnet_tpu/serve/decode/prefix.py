"""Host-side radix prefix cache over prompt token prefixes (SGLang-style).

A radix tree maps token-sequence prefixes to the KV-pool pages that
already hold their keys/values, so a request whose prompt shares a
templated system prompt with earlier traffic skips re-prefilling the
shared span: admission looks the prompt up, pins the matched path, maps
the shared pages into the new slot's page table (read-only), and
prefills only the suffix via the join program at the page-aligned
divergence offset.

Invariants the engine relies on:

- **Shared pages are never written.** A slot's in-program writes target
  positions >= its prompt length > the shared span, and the suffix
  scatter starts at the divergence page — so mapping a shared page into
  many tables concurrently is safe without copies.
- **Copy-on-write by recompute.** A divergent request never mutates a
  shared boundary page: its join starts at the last page-ALIGNED shared
  offset, recomputing its own copy of any partially-shared page into a
  private page. Divergence therefore costs at most one page of redundant
  prefill, and no page is ever cloned on device.
- **Refcounted eviction.** Every node on a request's matched/inserted
  path carries a pin (refcount) for the request's lifetime; ``evict``
  only frees LRU leaves with refcount 0, returning their page ids to the
  allocator. A page id lives in exactly one tree node, so eviction frees
  each page exactly once.

Pages are keyed by ABSOLUTE page index (position // page_tokens) and
attached to the deepest path node their last token reaches; a node split
keeps straddling pages with the deeper (original continuation) part, so
a later match can only use page j after matching the full prompt through
token (j+1) * page_tokens — partial-page hits never leak.
"""
from __future__ import annotations

from ...base import MXNetError

__all__ = ["RadixPrefixCache"]


class _Node:
    __slots__ = ("tokens", "children", "pages", "refs", "last_used",
                 "parent")

    def __init__(self, tokens, parent):
        self.tokens = list(tokens)   # edge label INTO this node
        self.children = {}           # first token -> _Node
        self.pages = {}              # absolute page index -> pool page id
        self.refs = 0                # live requests pinning this node
        self.last_used = 0
        self.parent = parent


class RadixPrefixCache:
    """Single-threaded (scheduler-owned) radix tree; see module docstring.

    ``page_tokens`` is the pool page size; all page bookkeeping is in
    absolute page indices over the prompt. A monotonic counter stands in
    for time in LRU ordering (deterministic, no clock reads).
    """

    def __init__(self, page_tokens):
        self.page_tokens = int(page_tokens)
        if self.page_tokens < 1:
            raise MXNetError(f"page_tokens must be >= 1, got {page_tokens}")
        self.root = _Node([], None)
        self._clock = 0
        self.hits = 0
        self.hit_tokens = 0

    # ------------------------------------------------------------------ walk
    def _tick(self):
        self._clock += 1
        return self._clock

    def _walk(self, tokens):
        """Longest match walk. Returns (matched_len, path, partial) where
        ``path`` is the fully-or-partially matched node chain (root
        excluded) and ``partial`` the offset into the last node's edge
        (0 = fully matched)."""
        node, depth, path = self.root, 0, []
        while depth < len(tokens):
            nxt = node.children.get(tokens[depth])
            if nxt is None:
                return depth, path, 0
            edge = nxt.tokens
            n = 0
            limit = min(len(edge), len(tokens) - depth)
            while n < limit and edge[n] == tokens[depth + n]:
                n += 1
            depth += n
            path.append(nxt)
            if n < len(edge):
                return depth, path, n
            node = nxt
        return depth, path, 0

    # ----------------------------------------------------------------- match
    def match(self, tokens, pin=True):
        """Longest reusable page-aligned prefix of ``tokens``.

        Returns ``(matched_tokens, page_ids, handle)``: ``page_ids`` maps
        absolute page index j (contiguous from 0) to a pool page id for
        every full page inside the match, ``matched_tokens`` =
        len(page_ids) * page_tokens, capped so at least one suffix token
        remains to prefill. ``handle`` pins the supporting path until
        :meth:`release` (None when ``pin`` is False or on a miss).
        """
        P = self.page_tokens
        depth, path, _ = self._walk(tokens)
        now = self._tick()
        avail = {}
        for node in path:
            for j, pid in node.pages.items():
                if (j + 1) * P <= depth:
                    avail[j] = pid
            node.last_used = now
        # usable prefix must be contiguous full pages from 0, and leave
        # >= 1 token of suffix for the join program's last-logit select
        cap = (len(tokens) - 1) // P
        run = 0
        while run < cap and run in avail:
            run += 1
        if run == 0:
            return 0, [], None
        pages = [avail[j] for j in range(run)]
        matched = run * P
        # pin only the path prefix actually supporting the used pages
        need = set(pages)
        handle = []
        for node in path:
            handle.append(node)
            need -= set(node.pages.values())
            if not need:
                break
        if pin:
            for node in handle:
                node.refs += 1
        else:
            handle = None
        self.hits += 1
        self.hit_tokens += matched
        return matched, pages, handle

    def release(self, handle):
        if not handle:
            return
        for node in handle:
            node.refs -= 1
            if node.refs < 0:
                raise MXNetError("radix node refcount underflow")

    # ---------------------------------------------------------------- insert
    def _split(self, node, offset):
        """Split ``node``'s edge at ``offset``; returns the new parent.
        Straddling pages stay with ``node`` (the deeper part)."""
        parent = node.parent
        mid = _Node(node.tokens[:offset], parent)
        node.tokens = node.tokens[offset:]
        node.parent = mid
        mid.children[node.tokens[0]] = node
        parent.children[mid.tokens[0]] = mid
        # mid starts unpinned: pins on ``node`` still protect it
        # structurally — eviction only removes refcount-0 LEAVES, and mid
        # has ``node`` as a child for as long as any handle pins it
        mid.last_used = node.last_used
        # depth of mid's end = depth(parent end) + offset; pages whose
        # last token is inside mid's span move to mid
        end = self._depth(mid)
        moved = {j: pid for j, pid in node.pages.items()
                 if (j + 1) * self.page_tokens <= end}
        for j in moved:
            del node.pages[j]
        mid.pages.update(moved)
        return mid

    def _depth(self, node):
        d = 0
        while node is not None:
            d += len(node.tokens)
            node = node.parent
        return d

    def insert(self, tokens, pages, pin=True):
        """Record that full pages ``{abs_index: page_id}`` of ``tokens``
        are resident. Returns ``(handle, adopted)``: ``adopted`` is the
        set of absolute page indices whose ids the tree took ownership of
        (the caller must stop freeing those); indices already covered by
        an equal-prefix insert are NOT adopted (the caller keeps its
        duplicate private). ``handle`` pins the path (release to unpin).
        """
        P = self.page_tokens
        for j in pages:
            if (j + 1) * P > len(tokens):
                raise MXNetError(
                    f"page {j} is not a full page of a {len(tokens)}-token "
                    "prompt")
        depth, path, partial = self._walk(tokens)
        node = path[-1] if path else self.root
        if partial:
            node = self._split(node, partial)
            path[-1] = node
        if depth < len(tokens):
            leaf = _Node(tokens[depth:], node)
            node.children[leaf.tokens[0]] = leaf
            path.append(leaf)
        now = self._tick()
        adopted = set()
        if path:
            # attach each offered page to the deepest node containing its
            # last token
            bounds = []
            d = 0
            for n in path:
                d += len(n.tokens)
                bounds.append((d, n))
            have = set()
            for n in path:
                have |= set(n.pages)
                n.last_used = now
            for j, pid in sorted(pages.items()):
                if j in have:
                    continue
                for d, n in bounds:
                    if (j + 1) * P <= d:
                        n.pages[j] = pid
                        adopted.add(j)
                        break
        handle = None
        if pin and path:
            handle = list(path)
            for n in handle:
                n.refs += 1
        return handle, adopted

    # ----------------------------------------------------------------- evict
    def _leaves(self):
        out = []
        stack = [self.root]
        while stack:
            n = stack.pop()
            if n is not self.root and not n.children:
                out.append(n)
            stack.extend(n.children.values())
        return out

    def evictable_pages(self):
        """Pages reclaimable right now (unpinned leaf chains)."""
        total = 0
        for leaf in self._leaves():
            n = leaf
            while n is not self.root and n.refs == 0:
                total += len(n.pages)
                # parent only counts if this is its sole child
                if n.parent is self.root or len(n.parent.children) > 1:
                    break
                n = n.parent
        return total

    def evict(self, need):
        """Free >= ``need`` pages if possible, LRU leaf chains first.
        Returns the freed pool page ids (possibly fewer than ``need``)."""
        freed = []
        while len(freed) < need:
            cands = [n for n in self._leaves() if n.refs == 0]
            if not cands:
                break
            victim = min(cands, key=lambda n: n.last_used)
            freed.extend(victim.pages.values())
            del victim.parent.children[victim.tokens[0]]
            victim.parent = None
        return freed

    # ------------------------------------------------------------- reporting
    def stats(self):
        nodes = pages = 0
        stack = [self.root]
        while stack:
            n = stack.pop()
            if n is not self.root:
                nodes += 1
                pages += len(n.pages)
            stack.extend(n.children.values())
        return {"nodes": nodes, "pages": pages, "hits": self.hits,
                "hit_tokens": self.hit_tokens}
