"""Speculative multi-token decoding: draft proposal + greedy verification.

The ``decode_tick_k`` program feeds K tokens per slot — column 0 the last
committed token, columns 1..K-1 a cheap host-side draft — and returns the
target model's argmax at every column in ONE batched pass. Greedy
accept-longest-prefix then commits the draft prefix the target agrees
with plus the target's own next token, so the committed sequence is
BITWISE the plain greedy sequence: column i's argmax is conditioned only
on committed tokens and draft columns < i, and a column is accepted only
when every draft token before it matched the target's argmax chain. A
worthless draft still commits 1 token per tick (the plain tick); a
perfect draft commits K. K is static — speculation adds exactly one
program shape, keeping the zero-recompile serving contract.

Drafts (``MXTPU_DECODE_DRAFT``):

- ``ngram`` (default): propose the continuation that followed the most
  recent earlier occurrence of the context's trailing n-gram (n = 3, 2,
  1 in order), falling back to repeating the last token. Free, surprisingly
  strong on templated/self-repetitive serving traffic.
- ``last``: repeat the last token K-1 times (the degenerate baseline).
"""
from __future__ import annotations

from ...base import MXNetError

__all__ = ["NgramDraft", "LastTokenDraft", "make_draft",
           "accept_longest_prefix"]


def accept_longest_prefix(draft, argmax_row):
    """Tokens committable from one speculative tick.

    ``draft``: the K-1 proposed tokens fed at columns 1..K-1;
    ``argmax_row``: the program's K argmax outputs. Returns m >= 1:
    commit ``argmax_row[:m]``. Column i's output is valid only when the
    token fed at column i matched the chain, i.e. draft[i-1] ==
    argmax_row[i-1]; m counts the valid prefix.
    """
    m = 1
    k = len(argmax_row)
    while m < k and int(draft[m - 1]) == int(argmax_row[m - 1]):
        m += 1
    return m


class LastTokenDraft:
    """Degenerate draft: repeat the last committed token."""

    name = "last"

    def propose(self, context, n):
        return [int(context[-1])] * n


class NgramDraft:
    """Suffix-matching n-gram draft over the request's own context.

    For each proposed token, find the most recent PRIOR occurrence of the
    context's trailing n-gram (longest n first) and propose the token
    that followed it; each proposal is appended to the working context so
    a single lookup can draft a whole span. O(len * n) per token over
    contexts bounded by max_len — host-side noise next to a tick.
    """

    name = "ngram"

    def __init__(self, max_n=3):
        if max_n < 1:
            raise MXNetError(f"ngram draft needs max_n >= 1, got {max_n}")
        self.max_n = int(max_n)

    def _next(self, ctx):
        L = len(ctx)
        for n in range(min(self.max_n, L - 1), 0, -1):
            tail = ctx[L - n:]
            for i in range(L - n - 1, -1, -1):
                if ctx[i:i + n] == tail:
                    return ctx[i + n]
        return ctx[-1]

    def propose(self, context, n):
        work = [int(t) for t in context]
        out = []
        for _ in range(n):
            t = int(self._next(work))
            out.append(t)
            work.append(t)
        return out


def make_draft(name):
    name = (name or "ngram").strip().lower()
    if name == "ngram":
        return NgramDraft()
    if name == "last":
        return LastTokenDraft()
    raise MXNetError(
        f"unknown draft {name!r} (MXTPU_DECODE_DRAFT): expected 'ngram' "
        "or 'last'")
