"""DecodeEngine: continuous batching over the three decode program families.

The host never computes on tensors — each scheduler tick it only feeds
operands (token ids, positions, page-table rows) to one of the three
AOT executables and applies bookkeeping to the results:

    tick:  expire deadlines -> admit pending into free slots (radix
           prefix lookup, page allocation, then the prefill or
           prefix-join program, bucketed batch x length) -> one
           decode_tick_k for ALL slots (K-1 drafted tokens verified per
           slot when speculation is on) -> commit the accepted prefix /
           retire finished requests

KV memory is PAGED (vLLM-style): a shared pool of
``page_tokens``-position pages backs every slot through per-slot page
tables, so resident bytes scale with live tokens and the pool may be
sized below num_slots * max_len (oversubscription sheds at admission or
starve-retires mid-flight — never crashes). A radix prefix cache maps
previously prefilled prompt prefixes to refcounted pages; a hit maps the
shared pages read-only into the new slot's table and prefills only the
suffix. Speculation (``MXTPU_SPECULATE_K``) drafts K-1 tokens on the
host (``MXTPU_DECODE_DRAFT``) and verifies them in one batched pass —
greedy accept-longest-prefix keeps the committed sequence bitwise equal
to plain greedy decoding.

``submit`` is thread-safe and returns a :class:`DecodeStream` — a
streaming token future: per-token callbacks fire from the scheduler
thread, ``result()`` blocks for the full generation, iteration yields
tokens as they land. Load past the queue-depth budget (or past its
deadline before ever reaching a slot) is SHED with :class:`ShedError`;
a request whose deadline expires mid-generation is EVICTED — its stream
finishes with the tokens produced so far and ``expired=True``.

Self-healing contract: a scheduler-thread crash can NEVER hang a client.
Transient program-run failures retry with capped exponential backoff
(``MXTPU_SERVE_RETRIES`` / ``MXTPU_SERVE_RETRY_BACKOFF_MS`` /
``MXTPU_SERVE_RETRY_MAX_MS``; ``serve.retries`` counts them); an
exception that survives the retries fails EVERY live, pending and queued
stream with :class:`EngineDeadError` carrying the real cause, marks the
engine dead (telemetry health check → ``/healthz`` 503,
``serve.scheduler_crashes``), and later ``submit`` raises immediately.
``drain()`` finishes accepted work while shedding new submissions;
``resume()`` reopens the gate. Chaos points: ``decode.prefill``,
``decode.tick`` (see mxnet_tpu.testing.chaos).
"""
from __future__ import annotations

import os
import queue
import threading
import time
from collections import deque

import numpy as onp

from ...base import MXNetError
from ...telemetry.registry import Histogram
from ...testing import chaos
from ..bucketing import pick_bucket
from .cache import PagedKVCache
from .prefix import RadixPrefixCache
from .programs import DecodePrograms
from .spec import accept_longest_prefix, make_draft

__all__ = ["DecodeEngine", "DecodeStream", "ShedError", "EngineDeadError"]

_STOP = object()


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class ShedError(MXNetError):
    """The engine refused (or dropped) a request to protect latency."""


class EngineDeadError(MXNetError):
    """The scheduler thread died; ``__cause__`` carries the real crash.

    Every stream that was live, pending or queued at crash time finishes
    with this error (never a hang), and every later ``submit`` raises it
    immediately. The engine's telemetry health check fails, so an
    attached exporter's ``/healthz`` answers 503."""


class DecodeStream:
    """Streaming token future for one submitted prompt.

    - ``on_token(token_id)`` fires from the scheduler thread per token;
    - iteration yields generated token ids as they arrive;
    - ``result(timeout)`` blocks until the stream finishes and returns
      the full generated-token list (raises if the request was shed).

    ``expired`` marks a deadline eviction (partial output), ``truncated``
    marks a generation clipped by KV capacity (cache length or page-pool
    starvation).
    """

    def __init__(self, prompt, max_new_tokens, deadline, on_token=None):
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.deadline = deadline      # absolute perf_counter() time or None
        self.tokens = []
        self.expired = False
        self.truncated = False
        self.trace = None             # RequestTrace when telemetry is on
        self.t_submit = time.perf_counter()
        self._t_last = None           # engine: last emit time (TTFT/TPOT)
        self._on_token = on_token
        self._cond = threading.Condition()
        self._done = False
        self._error = None

    # -- engine side -------------------------------------------------------
    def _emit(self, tok):
        with self._cond:
            self.tokens.append(tok)
            self._cond.notify_all()
        if self._on_token is not None:
            self._on_token(tok)

    def _finish(self, error=None):
        with self._cond:
            self._error = error
            self._done = True
            self._cond.notify_all()

    # -- client side -------------------------------------------------------
    @property
    def done(self):
        return self._done

    def result(self, timeout=None):
        with self._cond:
            if not self._cond.wait_for(lambda: self._done, timeout):
                raise MXNetError("DecodeStream.result timed out")
            if self._error is not None:
                raise self._error
            return list(self.tokens)

    def __iter__(self):
        i = 0
        while True:
            with self._cond:
                self._cond.wait_for(
                    lambda: self._done or len(self.tokens) > i)
                if i < len(self.tokens):
                    tok = self.tokens[i]
                else:
                    if self._error is not None:
                        raise self._error
                    return
            i += 1
            yield tok


class DecodeEngine:
    """Continuous-batching autoregressive decoding for a GPT-style model.

    Parameters
    ----------
    model : GPTModel-like block, optional
        Must expose ``forward_prefill_paged`` / ``forward_prefill_join``
        / ``forward_decode_paged`` / ``init_paged_cache``. May be omitted
        when ``programs`` (e.g. from ``DecodeEngine.from_export``)
        supplies traced graphs.
    num_slots : int
        Concurrent sequences per decode tick (the fixed decode program
        shape). Default: ``MXTPU_DECODE_SLOTS`` (8).
    max_len : int
        KV positions per slot (page-table capacity). Default:
        ``model.max_length``.
    max_prompt_len : int
        Longest admissible prompt; tops the prefill length ladder.
    prefill_batch : int
        Largest prefill batch; tops the prefill batch ladder.
    page_tokens : int
        KV page size in token positions. Default:
        ``MXTPU_KV_PAGE_TOKENS`` (128).
    kv_pages : int
        Pool size in pages. Default: ``MXTPU_KV_PAGES``, else
        num_slots * ceil(max_len / page_tokens) (full reservation).
        Sizing it lower oversubscribes capacity: bytes stay put while
        num_slots grows, which is the whole point of paging.
    speculate_k : int
        Tokens verified per decode tick; 1 (or ``MXTPU_SPECULATE_K``
        unset/0) disables speculation.
    draft : str
        Draft proposer for speculation: 'ngram' (default) or 'last'
        (``MXTPU_DECODE_DRAFT``).
    prefix_cache : bool
        Radix prefix cache over prompt prefixes. Default:
        ``MXTPU_PREFIX_CACHE`` (on).
    max_wait_us : int
        Idle-coalesce window before the first prefill of a burst.
        Default: ``MXTPU_DECODE_MAX_WAIT_US`` (2000).
    deadline_ms : int
        Default per-request deadline; 0 disables. Default:
        ``MXTPU_DECODE_DEADLINE_MS`` (0).
    max_queue : int
        Queue-depth shed threshold (pending, i.e. not-yet-slotted,
        requests). Default ``max(4 * num_slots, 16)``.
    cache_dir : str | None | False
        Persistent XLA compile cache dir (False disables), as Predictor.
    manifest : str | dict, optional
        Warmup manifest from a previous process: adopts its geometry and
        precompiles everything immediately (disk-hit compiles).
    """

    def __init__(self, model=None, *, num_slots=None, max_len=None,
                 max_prompt_len=None, prefill_batch=4, page_tokens=None,
                 kv_pages=None, speculate_k=None, draft=None,
                 prefix_cache=None, max_wait_us=None, deadline_ms=None,
                 max_queue=None, cache_dir=None, manifest=None,
                 programs=None, tp=None):
        from ... import telemetry as _tm
        from ...context import enable_compilation_cache

        self._tm = _tm
        if cache_dir is not False:
            self.cache_dir = enable_compilation_cache(cache_dir)
        else:
            self.cache_dir = None

        manifest_dict = None
        if manifest is not None:
            from .programs import load_decode_manifest

            manifest_dict = load_decode_manifest(manifest) \
                if isinstance(manifest, str) else dict(manifest)
            num_slots = int(manifest_dict["num_slots"])
            max_len = int(manifest_dict["max_len"])
            max_prompt_len = int(manifest_dict["max_prompt_len"])
            prefill_batch = int(manifest_dict["prefill_batch"])
            page_tokens = int(manifest_dict["page_tokens"])
            kv_pages = int(manifest_dict["kv_pages"])
            speculate_k = int(manifest_dict["speculate_k"])
            prefix_cache = bool(manifest_dict["prefix_cache"])

        if programs is not None:
            self.programs = programs
        else:
            if model is None:
                raise MXNetError(
                    "DecodeEngine needs a model (or programs from an "
                    "export)")
            num_slots = int(num_slots or _env_int("MXTPU_DECODE_SLOTS", 8))
            max_len = int(max_len or model.max_length)
            page_tokens = int(page_tokens or
                              _env_int("MXTPU_KV_PAGE_TOKENS", 128))
            if kv_pages is None:
                kv_pages = _env_int("MXTPU_KV_PAGES", 0) or None
            if speculate_k is None:
                speculate_k = _env_int("MXTPU_SPECULATE_K", 0)
            speculate_k = max(1, int(speculate_k))
            if prefix_cache is None:
                prefix_cache = bool(_env_int("MXTPU_PREFIX_CACHE", 1))
            if tp is None:
                tp = _env_int("MXTPU_SERVE_TP", 1)
            self.programs = DecodePrograms(
                model, num_slots=num_slots, max_len=max_len,
                prefill_batch=prefill_batch,
                max_prompt_len=max_prompt_len,
                page_tokens=page_tokens, kv_pages=kv_pages,
                speculate_k=speculate_k, prefix_cache=prefix_cache,
                tp=max(1, int(tp)))
        self.num_slots = self.programs.num_slots
        self.max_len = self.programs.max_len
        self.max_prompt_len = self.programs.max_prompt_len
        self.prefill_batch = self.programs.prefill_batch
        self.page_tokens = self.programs.page_tokens
        self.kv_pages = self.programs.kv_pages
        self.speculate_k = self.programs.speculate_k
        self.prefix_cache = self.programs.prefix_cache

        self._draft = None
        if self.speculate_k > 1:
            self._draft = make_draft(
                draft or os.environ.get("MXTPU_DECODE_DRAFT") or "ngram")

        self.max_wait_us = int(max_wait_us if max_wait_us is not None
                               else _env_int("MXTPU_DECODE_MAX_WAIT_US",
                                             2000))
        dl = deadline_ms if deadline_ms is not None \
            else _env_int("MXTPU_DECODE_DEADLINE_MS", 0)
        self.deadline_ms = int(dl)
        self.max_queue = int(max_queue if max_queue is not None
                             else max(4 * self.num_slots, 16))

        # -- device + scheduler state (owned by the worker thread) ---------
        self._cache = PagedKVCache(self.programs.cache_shape,
                                   self.programs.cache_dtype,
                                   num_slots=self.num_slots,
                                   max_len=self.max_len)
        self._prefix = RadixPrefixCache(self.page_tokens) \
            if self.prefix_cache else None
        self._slot_req = {}     # sid -> DecodeStream
        self._slot_pages = {}   # sid -> owned pool page ids
        self._slot_handles = {}  # sid -> radix pin handles to release
        self._cols = onp.zeros(self.num_slots, dtype="int32")
        self._last_tok = onp.zeros(self.num_slots, dtype="int32")

        self._q = queue.SimpleQueue()
        self._worker = None
        self._worker_lock = threading.Lock()
        self._closed = False
        self._dead = None        # scheduler crash exception, once fatal
        self._draining = False   # drain(): shed new submits, finish live

        # transient program-run failures retry before the crash path
        self._retries = _env_int("MXTPU_SERVE_RETRIES", 2)
        self._retry_backoff_ms = _env_int("MXTPU_SERVE_RETRY_BACKOFF_MS", 10)
        self._retry_max_ms = _env_int("MXTPU_SERVE_RETRY_MAX_MS", 1000)

        self._health_name = f"decode_engine:{id(self):x}"
        _tm.register_health(self._health_name, self._health)

        # stall heartbeats around the device syncs — where a hung chip
        # manifests on this path — plus the tokens/s window (single-device
        # engine: per-chip == total)
        self._hb_prefill = _tm.stall_heartbeat("serve.prefill")
        self._hb_tick = _tm.stall_heartbeat("serve.decode_tick")
        self._tps_mark = None

        # -- accounting (always on: these ARE the serving stats) -----------
        self._stats_lock = threading.Lock()
        self._n_requests = 0
        self._n_completed = 0
        self._n_shed = 0
        self._n_evicted = 0
        self._n_tokens = 0
        self._n_ticks = 0
        self._n_prefills = 0
        self._n_starved = 0
        self._n_prefix_hit_tokens = 0
        self._occupancy_sum = 0.0
        self._pending_count = 0
        self._ttft_ms = Histogram("serve.ttft_ms")
        self._tpot_ms = Histogram("serve.tpot_ms")
        self._spec_accept = Histogram("serve.spec_accept_len")

        if manifest_dict is not None:
            self.warmup()

    # ------------------------------------------------------------- warmup
    def warmup(self, manifest_path=None):
        """Precompile decode_tick_k + every (batch, len) prefill (and
        prefix-join) bucket; optionally write a manifest. After this the
        scheduler compiles nothing, whatever traffic arrives (asserted
        via the jit compile counter in tests/test_decode.py). Returns the
        manifest dict."""
        import json

        self.programs.warmup()
        manifest = self.programs.manifest_dict(cache_dir=self.cache_dir)
        if manifest_path:
            tmp = manifest_path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(manifest, fh, indent=1)
            os.replace(tmp, manifest_path)
        return manifest

    def export(self, prefix):
        """Serialize the traced graphs + params + manifest (see
        ``DecodePrograms.export``); returns the manifest path."""
        return self.programs.export(prefix)

    @classmethod
    def from_export(cls, prefix, **kwargs):
        """Rebuild a serving engine from ``export`` artifacts — no model
        class needed; with the persistent compile cache on, no XLA
        compiles either. Extra kwargs pass through (scheduler knobs)."""
        progs = DecodePrograms.from_export(prefix)
        eng = cls(programs=progs, **kwargs)
        eng.warmup()
        return eng

    # ------------------------------------------------------------- submit
    def submit(self, prompt, max_new_tokens=20, deadline_ms=None,
               on_token=None):
        """Enqueue one prompt; returns a :class:`DecodeStream`.

        Raises :class:`ShedError` immediately when the pending queue is
        at budget. ``deadline_ms`` (engine default when None, 0 = none)
        bounds TOTAL time: a request that can't start in time is shed,
        one that can't finish is evicted with partial output.
        """
        if self._dead is not None:
            raise EngineDeadError(
                f"DecodeEngine scheduler crashed: {self._dead!r}"
            ) from self._dead
        if self._closed:
            raise MXNetError("DecodeEngine is closed")
        if self._draining:
            with self._stats_lock:
                self._n_requests += 1
            self._shed_one()
            if self._tm.ON:
                self._tm.REGISTRY.counter("serve.requests").inc()
            raise ShedError(
                "DecodeEngine is draining: new work is shed until "
                "resume()")
        toks = self._normalize_prompt(prompt)
        if max_new_tokens < 1:
            raise MXNetError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        trace = self._tm.new_trace("serve.decode")
        with self._stats_lock:
            self._n_requests += 1
            over = self._pending_count >= self.max_queue
            if not over:
                self._pending_count += 1
        if self._tm.ON:
            self._tm.REGISTRY.counter("serve.requests").inc()
        if over:
            self._shed_one()
            self._tm.finish_trace(trace, status="shed")
            raise ShedError(
                f"decode queue at budget ({self.max_queue} pending); "
                "retry later or raise max_queue")
        dl_ms = self.deadline_ms if deadline_ms is None else int(deadline_ms)
        deadline = (time.perf_counter() + dl_ms * 1e-3) if dl_ms > 0 else None
        # clip generation to cache capacity: the last token's KV lands at
        # position len(prompt) + max_new - 2, which must stay < max_len
        budget = self.max_len - len(toks) + 1
        stream = DecodeStream(toks, min(int(max_new_tokens), budget),
                              deadline, on_token)
        stream.trace = trace
        if stream.max_new_tokens < max_new_tokens:
            stream.truncated = True
        self._start_worker()
        self._q.put(stream)
        return stream

    def _normalize_prompt(self, prompt):
        from ...ndarray.ndarray import NDArray

        if isinstance(prompt, NDArray):
            prompt = onp.asarray(prompt._data)
        toks = [int(t) for t in onp.asarray(prompt).reshape(-1)]
        if not toks:
            raise MXNetError("cannot decode from an empty prompt")
        if len(toks) > self.max_prompt_len:
            raise MXNetError(
                f"prompt length {len(toks)} exceeds max_prompt_len "
                f"{self.max_prompt_len}")
        return toks

    # ---------------------------------------------------------- scheduler
    def _start_worker(self):
        if self._worker is not None:
            return
        with self._worker_lock:
            if self._worker is None:
                t = threading.Thread(target=self._loop,
                                     name="mxtpu-decode-engine",
                                     daemon=True)
                self._worker = t
                t.start()

    def _loop(self):
        pending = deque()
        crash = None
        try:
            while not self._gather(pending):
                self._expire(pending)
                self._admit(pending)
                if self._slot_req:
                    self._tick()
        except BaseException as e:  # noqa: BLE001 — converted, never lost
            crash = e
        finally:
            if crash is not None:
                self._scheduler_crashed(crash, pending)
            else:
                self._drain(pending)

    def _scheduler_crashed(self, exc, pending):
        """Fatal scheduler error: mark dead, fail every stream with the
        real cause, flip the health check (→ /healthz 503)."""
        self._dead = exc
        self._closed = True
        tm = self._tm
        tm.REGISTRY.counter("serve.scheduler_crashes").inc()
        if tm.ON:
            tm.event("serve.scheduler_crash", error=repr(exc))
        err = EngineDeadError(
            f"DecodeEngine scheduler crashed: {exc!r}")
        err.__cause__ = exc
        self._drain(pending, err=err, status="error")

    def _run_retry(self, key, args, point):
        """One AOT program run behind the transient-failure retry policy:
        up to ``MXTPU_SERVE_RETRIES`` retries with exponential backoff
        capped at ``MXTPU_SERVE_RETRY_MAX_MS``; ``point`` is also a chaos
        injection site. Exhaustion re-raises into the crash path."""
        attempt = 0
        site = self.programs._site(key)
        self._tm.check_memory_admission(site)
        while True:
            try:
                chaos.fault_point(point)
                return self.programs.run(key, args)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — bounded retries
                # a device OOM is not transient: dump the ledger once and
                # skip the retry storm — the crash path reports upward
                if self._tm.memory_oom_forensics(site, e):
                    raise
                if attempt >= self._retries:
                    raise
                attempt += 1
                tm = self._tm
                tm.REGISTRY.counter("serve.retries").inc()
                if tm.ON:
                    tm.event("serve.retry", point=point, attempt=attempt,
                             error=repr(e))
                delay_ms = min(self._retry_backoff_ms * (1 << (attempt - 1)),
                               self._retry_max_ms)
                time.sleep(delay_ms * 1e-3)

    def _gather(self, pending):
        """Pull new requests off the queue. Blocks when fully idle;
        otherwise drains without waiting (the decode tick itself is the
        coalescing window once slots are live). Returns True on STOP."""
        idle = not self._slot_req and not pending
        try:
            item = self._q.get() if idle else self._q.get_nowait()
        except queue.Empty:
            return False
        if item is _STOP:
            return True
        pending.append(item)
        if idle and self.max_wait_us > 0:
            # a burst is likely arriving together: hold the first prefill
            # open briefly so it batches instead of running B=1
            deadline = time.perf_counter() + self.max_wait_us * 1e-6
            while len(pending) < self.prefill_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    item = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if item is _STOP:
                    return True
                pending.append(item)
        else:
            while True:
                try:
                    item = self._q.get_nowait()
                except queue.Empty:
                    break
                if item is _STOP:
                    return True
                pending.append(item)
        return False

    def _expire(self, pending):
        now = time.perf_counter()
        for stream in [s for s in pending
                       if s.deadline is not None and now > s.deadline]:
            pending.remove(stream)
            self._shed_one(admitted=True)
            self._tm.finish_trace(stream.trace, status="shed")
            stream._finish(ShedError(
                "deadline expired before the request reached a slot"))
        for sid in [s for s, st in self._slot_req.items()
                    if st.deadline is not None and now > st.deadline]:
            self._retire(sid, expired=True)

    # ------------------------------------------------------ page admission
    def _alloc_pages(self, n):
        """Claim n pool pages, evicting unpinned prefix-cache pages LRU
        first when the free list is short. None when impossible now."""
        if n == 0:
            return []
        cache = self._cache
        got = cache.pages.alloc(n)
        if got is not None:
            return got
        if self._prefix is not None:
            freed = self._prefix.evict(n - cache.pages.free_count)
            if freed:
                cache.pages.free(freed)
                got = cache.pages.alloc(n)
        return got

    def _prepare(self, stream):
        """Prefix lookup + page allocation for one pending stream.
        Returns the admission meta dict, or None when pages are short
        (caller decides: wait for retirements or shed)."""
        P = self.page_tokens
        plen = len(stream.prompt)
        if self._prefix is not None:
            matched, shared, handle = self._prefix.match(stream.prompt)
        else:
            matched, shared, handle = 0, [], None
        need = -(-plen // P) - len(shared)
        own = self._alloc_pages(need)
        if own is None:
            if handle:
                self._prefix.release(handle)
            return None
        return {"start": matched, "shared": shared, "own": own,
                "handle": handle}

    def _admit(self, pending):
        cache = self._cache
        while pending and cache.slots.free_count:
            group, metas = [], []
            while (pending and len(group) < self.prefill_batch
                   and len(group) < cache.slots.free_count):
                meta = self._prepare(pending[0])
                if meta is None:
                    if group or self._slot_req or (
                            self._prefix is not None
                            and self._prefix.evictable_pages() > 0):
                        # pages will free up (retirements / evictions
                        # racing pins); try again next tick
                        break
                    # nothing live, nothing evictable: this prompt can
                    # never fit — shed it instead of spinning forever
                    stream = pending.popleft()
                    self._shed_one(admitted=True)
                    self._tm.finish_trace(stream.trace, status="shed")
                    stream._finish(ShedError(
                        f"kv page pool exhausted: prompt needs "
                        f"{-(-len(stream.prompt) // self.page_tokens)} "
                        f"pages, pool has {cache.pages.free_count} free "
                        f"of {self.kv_pages}"))
                    continue
                group.append(pending.popleft())
                metas.append(meta)
            if not group:
                break
            # plain and join prefills are separate program families —
            # dispatch each subgroup through its own bucket
            plain = [(s, m) for s, m in zip(group, metas)
                     if m["start"] == 0]
            ext = [(s, m) for s, m in zip(group, metas) if m["start"] > 0]
            for sub in (plain, ext):
                if not sub:
                    continue
                try:
                    self._prefill(sub)
                except BaseException:
                    # hand the subgroup back so the crash path fails
                    # these streams with the real error
                    pending.extendleft(s for s, _ in reversed(sub))
                    raise

    def _prefill(self, sub):
        import jax

        cache = self._cache
        P = self.page_tokens
        ext = sub[0][1]["start"] > 0
        slots = [cache.slots.alloc() for _ in sub]
        B = pick_bucket(len(sub), self.programs.batch_ladder)
        T = pick_bucket(max(len(s.prompt) - m["start"] for s, m in sub),
                        self.programs.len_ladder)
        tokens = onp.zeros((B, T), dtype="int32")
        valid = onp.ones((B,), dtype="int32")
        start = onp.zeros((B,), dtype="int32")
        table = onp.full((B, cache.pages_per_slot + 1), cache.trash,
                         dtype="int32")
        t_q = time.perf_counter()  # queue phase: submit -> prefill pickup
        for i, ((stream, meta), sid) in enumerate(zip(sub, slots)):
            row = meta["shared"] + meta["own"]
            cache.table[sid, :] = cache.trash
            cache.table[sid, :len(row)] = row
            self._cols[sid] = len(row)
            self._slot_pages[sid] = list(meta["own"])
            self._slot_handles[sid] = [meta["handle"]] if meta["handle"] \
                else []
            suffix = stream.prompt[meta["start"]:]
            tokens[i, :len(suffix)] = suffix
            valid[i] = len(suffix)
            start[i] = meta["start"]
            table[i] = cache.table[sid]
            if stream.trace is not None:
                stream.trace.mark("queue", t_q)
        kind = "prefill_ext" if ext else "prefill"
        key = (kind, B, T)
        self.programs.ensure(kind, batch=B, length=T)
        tm = self._tm
        hb_on = tm.ON
        t_run = time.perf_counter()
        if hb_on:
            self._hb_prefill.begin()
        try:
            args = [jax.device_put(tokens), jax.device_put(valid)]
            if ext:
                args.append(jax.device_put(start))
            args += [jax.device_put(table), cache.k, cache.v]
            outs = self._run_retry(key, args, point="decode.prefill")
            cache.rebind(outs[1], outs[2])
            first = onp.asarray(outs[0])  # device sync: the TTFT tokens
        finally:
            if hb_on:
                self._hb_prefill.end()
                tm.REGISTRY.timer("serve.prefill.call").record(
                    time.perf_counter() - t_run)
        if tm.ON:
            tm.record_dispatch()
        with self._stats_lock:
            self._n_prefills += 1
            self._pending_count -= len(sub)
        for i, ((stream, meta), sid) in enumerate(zip(sub, slots)):
            plen = len(stream.prompt)
            cache.lengths[sid] = plen
            self._slot_req[sid] = stream
            if meta["start"]:
                with self._stats_lock:
                    self._n_prefix_hit_tokens += meta["start"]
                if tm.ON:
                    tm.REGISTRY.counter("serve.prefix_hit_tokens").inc(
                        meta["start"])
                if stream.trace is not None:
                    stream.trace.extra["prefix_hit_tokens"] = meta["start"]
            if self._prefix is not None:
                # publish this prompt's full pages for future sharers;
                # adopted pages change owner (tree frees them, not us)
                a0 = meta["start"] // P
                full = plen // P - a0
                offered = {a0 + t: meta["own"][t] for t in range(full)}
                handle, adopted = self._prefix.insert(stream.prompt,
                                                      offered)
                if handle:
                    self._slot_handles[sid].append(handle)
                if adopted:
                    keep = [pid for t, pid in enumerate(meta["own"])
                            if (a0 + t) not in adopted]
                    self._slot_pages[sid] = keep
            tok = int(first[i])
            self._last_tok[sid] = tok
            self._emit_tokens(stream, [tok])
            if len(stream.tokens) >= stream.max_new_tokens:
                self._retire(sid)
        self._set_slot_gauge()

    def _tick(self):
        import jax

        cache = self._cache
        P = self.page_tokens
        K = self.speculate_k
        W = cache.pages_per_slot
        live = sorted(self._slot_req)
        # grow page tables to cover this tick's K write positions; a slot
        # the pool can't serve is starved: it commits at most one more
        # token and retires truncated (shed capacity, never crash)
        starved = set()
        for sid in live:
            need = min(-(-(int(cache.lengths[sid]) + K) // P), W)
            short = need - int(self._cols[sid])
            if short > 0:
                got = self._alloc_pages(short)
                if got is None:
                    starved.add(sid)
                else:
                    c = int(self._cols[sid])
                    cache.table[sid, c:c + len(got)] = got
                    self._cols[sid] = c + len(got)
                    self._slot_pages[sid].extend(got)
        tokens = onp.zeros((self.num_slots, K), dtype="int32")
        tokens[:, 0] = self._last_tok
        drafts = {}
        if K > 1:
            for sid in live:
                stream = self._slot_req[sid]
                d = self._draft.propose(stream.prompt + stream.tokens,
                                        K - 1)
                drafts[sid] = d
                tokens[sid, 1:] = d
        key = ("decode", K)
        self.programs.ensure("decode")
        tm = self._tm
        hb_on = tm.ON
        t_run = time.perf_counter()
        if hb_on:
            self._hb_tick.begin()
        try:
            outs = self._run_retry(key, [
                jax.device_put(tokens), jax.device_put(cache.lengths),
                jax.device_put(cache.table), cache.k, cache.v],
                point="decode.tick")
            cache.rebind(outs[1], outs[2])
            rows = onp.asarray(outs[0])   # device sync: this tick's tokens
        finally:
            if hb_on:
                self._hb_tick.end()
                tm.REGISTRY.timer("serve.decode_tick.call").record(
                    time.perf_counter() - t_run)
        if tm.ON:
            tm.record_dispatch()
        occ = cache.occupancy()
        with self._stats_lock:
            self._n_ticks += 1
            self._occupancy_sum += occ
        for sid in live:
            stream = self._slot_req[sid]
            m = accept_longest_prefix(drafts[sid], rows[sid]) if K > 1 \
                else 1
            if K > 1:
                self._spec_accept.record(m)
                if tm.ON:
                    tm.REGISTRY.histogram("serve.spec_accept_len").record(m)
            ln = int(cache.lengths[sid])
            m = min(m, stream.max_new_tokens - len(stream.tokens),
                    cache.max_len - ln)
            if sid in starved:
                m = min(m, 1)
            toks = [int(t) for t in rows[sid][:m]]
            cache.lengths[sid] = ln + m
            self._last_tok[sid] = toks[-1]
            self._emit_tokens(stream, toks)
            if len(stream.tokens) >= stream.max_new_tokens:
                self._retire(sid)
            elif cache.lengths[sid] >= cache.max_len or sid in starved:
                stream.truncated = True
                if sid in starved:
                    with self._stats_lock:
                        self._n_starved += 1
                    if tm.ON:
                        tm.REGISTRY.counter("serve.kv_page_starved").inc()
                self._retire(sid)
        if tm.ON:
            # tokens/s/chip over a ~0.5 s window (single-device engine:
            # chips == 1, so per-chip is the engine rate)
            nowt = time.perf_counter()
            if self._tps_mark is None:
                self._tps_mark = (nowt, self._n_tokens)
            else:
                t0, n0 = self._tps_mark
                if nowt - t0 >= 0.5:
                    tm.REGISTRY.gauge("serve.tokens_per_s_chip").set(
                        (self._n_tokens - n0) / (nowt - t0))
                    self._tps_mark = (nowt, self._n_tokens)

    def _emit_tokens(self, stream, toks):
        """Emit a committed token run. The first token ever is TTFT; a
        multi-token (speculative) commit spreads the tick's wall time
        evenly across its tokens, so TPOT honestly reflects the
        amortized per-token latency."""
        if not toks:
            return
        now = time.perf_counter()
        tm = self._tm
        n = len(toks)
        i0 = 0
        if stream._t_last is None:
            ms = (now - stream.t_submit) * 1e3
            self._ttft_ms.record(ms)
            if stream.trace is not None:
                # prefill phase: picked up -> first token on host
                stream.trace.mark("prefill", now)
                stream.trace.extra["ttft_ms"] = ms
            if tm.ON:
                tm.REGISTRY.histogram("serve.ttft_ms").record(ms)
            i0 = 1
        if n - i0 > 0:
            ms = (now - stream._t_last) * 1e3 / (n - i0) \
                if stream._t_last is not None else 0.0
            for _ in range(n - i0):
                self._tpot_ms.record(ms)
                if tm.ON:
                    tm.REGISTRY.histogram("serve.tpot_ms").record(ms)
        stream._t_last = now
        with self._stats_lock:
            self._n_tokens += n
        if tm.ON:
            tm.REGISTRY.counter("serve.tokens_total").inc(n)
        for tok in toks:
            stream._emit(tok)

    def _retire(self, sid, expired=False):
        cache = self._cache
        stream = self._slot_req.pop(sid)
        cache.slots.free(sid)
        cache.reset_row(sid)
        self._cols[sid] = 0
        owned = self._slot_pages.pop(sid, [])
        if owned:
            cache.pages.free(owned)
        for handle in self._slot_handles.pop(sid, []):
            self._prefix.release(handle)
        self._last_tok[sid] = 0
        stream.expired = expired
        if stream.trace is not None:
            stream.trace.mark("decode")  # first token -> generation done
            stream.trace.extra["tokens"] = len(stream.tokens)
            if stream.truncated:
                stream.trace.extra["truncated"] = True
        self._tm.finish_trace(stream.trace,
                              status="evicted" if expired else "completed")
        stream._finish()
        with self._stats_lock:
            self._n_completed += 1
            if expired:
                self._n_evicted += 1
        if expired and self._tm.ON:
            self._tm.REGISTRY.counter("serve.evict_total").inc()
        self._set_slot_gauge()

    def _shed_one(self, admitted=False):
        with self._stats_lock:
            self._n_shed += 1
            if admitted:
                self._pending_count -= 1
        if self._tm.ON:
            self._tm.REGISTRY.counter("serve.shed_total").inc()

    def _set_slot_gauge(self):
        if self._tm.ON:
            self._tm.REGISTRY.gauge("serve.slots_live").set(
                len(self._slot_req))
            # KV residency for the memory ledger: pool bytes are static
            # per engine build (the gauge keys the ledger's kv line);
            # pages_live tracks actual token residency inside the pool
            self._tm.REGISTRY.gauge("mem.kv_cache_bytes").set(
                self._cache.nbytes)
            self._tm.REGISTRY.gauge("serve.kv_pages_live").set(
                self._cache.pages_live())

    def _drain(self, pending, err=None, status="closed"):
        if err is None:
            err = MXNetError("DecodeEngine closed before completion")
        for sid in list(self._slot_req):
            stream = self._slot_req.pop(sid)
            self._cache.slots.free(sid)
            self._tm.finish_trace(stream.trace, status=status)
            stream._finish(err)
        for stream in pending:
            self._shed_one(admitted=True)
            self._tm.finish_trace(stream.trace, status=status)
            stream._finish(err)
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not _STOP:
                self._shed_one(admitted=True)
                self._tm.finish_trace(item.trace, status=status)
                item._finish(err)

    # ----------------------------------------------------------- reporting
    def stats(self):
        """Engine accounting independent of the global telemetry gate."""
        with self._stats_lock:
            ticks = self._n_ticks
            occ = self._occupancy_sum / ticks if ticks else 0.0
            out = {
                "requests": self._n_requests,
                "completed": self._n_completed,
                "shed": self._n_shed,
                "evicted": self._n_evicted,
                "tokens": self._n_tokens,
                "ticks": ticks,
                "prefills": self._n_prefills,
                "pending": self._pending_count,
                "prefix_hit_tokens": self._n_prefix_hit_tokens,
                "page_starved": self._n_starved,
            }
        p50, p99 = self._ttft_ms.percentiles(50, 99)
        out["ttft_ms_p50"], out["ttft_ms_p99"] = p50, p99
        p50, p99 = self._tpot_ms.percentiles(50, 99)
        out["tpot_ms_p50"], out["tpot_ms_p99"] = p50, p99
        out["mean_slot_occupancy"] = occ
        out["slots_live"] = len(self._slot_req)
        out["num_slots"] = self.num_slots
        out["cache_bytes"] = self._cache.nbytes
        out["page_tokens"] = self.page_tokens
        out["kv_pages"] = self.kv_pages
        out["kv_pages_live"] = self._cache.pages_live()
        out["speculate_k"] = self.speculate_k
        if self.speculate_k > 1:
            out["spec_accept_mean"] = self._spec_accept.mean
            out["tokens_per_tick"] = (out["tokens"] / ticks) if ticks \
                else 0.0
        out["prefix_cache"] = self._prefix.stats() \
            if self._prefix is not None else None
        out["dead"] = self._dead is not None
        out["draining"] = self._draining
        out["programs"] = sorted(
            "|".join(str(k) for k in key)
            for key in self.programs._programs)
        return out

    # -------------------------------------------------------------- health
    def _health(self):
        if self._dead is not None:
            return False, f"scheduler crashed: {self._dead!r}"
        return True, {"slots_live": len(self._slot_req),
                      "draining": self._draining}

    @property
    def healthy(self):
        return self._dead is None

    # ---------------------------------------------------------- drain/resume
    def drain(self, timeout=None):
        """Shed new submissions (``ShedError``) while already-accepted
        work — live slots AND queued-but-unslotted requests — runs to
        completion. Blocks until idle (or ``timeout`` seconds); returns
        True when fully drained. ``resume()`` reopens the gate."""
        self._draining = True
        deadline = None if timeout is None \
            else time.perf_counter() + float(timeout)
        while True:
            with self._stats_lock:
                pending = self._pending_count
            if not self._slot_req and pending <= 0:
                return True
            if self._dead is not None or self._closed:
                return not self._slot_req
            if deadline is not None and time.perf_counter() > deadline:
                return False
            time.sleep(0.002)

    def resume(self):
        """Accept submissions again after :meth:`drain`."""
        self._draining = False

    # ------------------------------------------------------------ lifecycle
    def close(self):
        """Stop the scheduler (idempotent). Live and queued streams
        finish with an error; later ``submit`` raises."""
        try:
            self._tm.unregister_health(self._health_name)
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
        if self._closed:
            return
        self._closed = True
        worker = self._worker
        if worker is not None:
            self._q.put(_STOP)
            worker.join(timeout=30)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
