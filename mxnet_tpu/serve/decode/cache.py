"""Slot-paged KV cache: preallocated device residency + free-list reuse.

The decode engine's steady state must never allocate: the KV cache for
every concurrent request lives in TWO preallocated device buffers of shape
``[num_slots, layers, heads, max_len, head_dim]`` (vLLM's paged-KV insight
applied at slot granularity — one "page" per request keeps the fixed-shape
``decode_tick(num_slots)`` program compilable once). A request is admitted
by claiming a free slot id, its prompt's k/v are scattered into that slot
by the prefill program, and eviction is just returning the id to the free
list — no device work, the stale rows are masked off by the per-slot
length vector until the slot's next tenant overwrites them.
"""
from __future__ import annotations

import numpy as onp

from ...base import MXNetError

__all__ = ["SlotAllocator", "KVCache", "PageAllocator", "PagedKVCache"]


class SlotAllocator:
    """LIFO free list over ``num_slots`` ids. LIFO (not FIFO) reuse keeps
    the live-slot set dense in recently-touched cache rows."""

    def __init__(self, num_slots):
        if num_slots < 1:
            raise MXNetError(f"need at least one slot, got {num_slots}")
        self.num_slots = int(num_slots)
        self._free = list(range(self.num_slots - 1, -1, -1))
        self._live = set()

    def alloc(self):
        """Claim a slot id, or None when every slot is occupied."""
        if not self._free:
            return None
        sid = self._free.pop()
        self._live.add(sid)
        return sid

    def free(self, sid):
        if sid not in self._live:
            raise MXNetError(f"slot {sid} is not live (double free?)")
        self._live.remove(sid)
        self._free.append(sid)

    @property
    def live(self):
        return frozenset(self._live)

    @property
    def free_count(self):
        return len(self._free)

    def __len__(self):
        return self.num_slots


class KVCache:
    """The device-resident cache pair plus the host-side per-slot length
    vector the scheduler feeds to the decode program every tick.

    ``rebind(k, v)`` swaps in the arrays a donated-buffer program returned
    — under donation the previous pair is dead storage, so holding exactly
    one live generation of the cache is the entire memory contract.
    """

    def __init__(self, shape, dtype="float32"):
        import jax.numpy as jnp

        shape = tuple(int(d) for d in shape)
        if len(shape) != 5:
            raise MXNetError(
                "KV cache shape must be [num_slots, layers, heads, max_len, "
                f"head_dim], got {shape}")
        self.num_slots = shape[0]
        self.max_len = shape[3]
        # raw device arrays (not NDArrays): the engine feeds them straight
        # to AOT executables and rebinds their donated successors
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        # host copy: the scheduler reads/writes lengths every tick and the
        # decode program takes them as a tiny int32 operand
        self.lengths = onp.zeros(self.num_slots, dtype="int32")
        self.slots = SlotAllocator(self.num_slots)

    def rebind(self, k, v):
        self.k, self.v = k, v

    @property
    def nbytes(self):
        return int(self.k.size * self.k.dtype.itemsize * 2)

    def occupancy(self):
        return len(self.slots.live) / self.num_slots


class PageAllocator:
    """LIFO free list over ``num_pages`` KV-pool page ids.

    ``alloc(n)`` is all-or-nothing: it hands back n page ids or None when
    the pool can't cover the request — the scheduler decides whether to
    evict prefix-cache pages, wait for retirements, or shed. Exhaustion
    is therefore a scheduling outcome, never an exception mid-tick."""

    def __init__(self, num_pages):
        if num_pages < 1:
            raise MXNetError(f"need at least one page, got {num_pages}")
        self.num_pages = int(num_pages)
        self._free = list(range(self.num_pages - 1, -1, -1))
        self._live = set()

    def alloc(self, n=1):
        """Claim ``n`` page ids (all-or-nothing); None when short."""
        if n < 0:
            raise MXNetError(f"cannot alloc {n} pages")
        if n > len(self._free):
            return None
        ids = [self._free.pop() for _ in range(n)]
        self._live.update(ids)
        return ids

    def free(self, ids):
        for pid in ids:
            if pid not in self._live:
                raise MXNetError(
                    f"page {pid} is not live (double free?)")
            self._live.remove(pid)
            self._free.append(pid)

    @property
    def live(self):
        return frozenset(self._live)

    @property
    def free_count(self):
        return len(self._free)

    def __len__(self):
        return self.num_pages


class PagedKVCache:
    """Device-resident paged KV pool pair + the host page tables.

    The pool pair has shape ``[num_pages, layers, heads, page_tokens,
    head_dim]``; a slot's cache is one int32 page-table row of width
    ``W+1`` (W = ceil(max_len / page_tokens)) mapping logical page index
    to pool page id. ``trash`` (= num_pages, one past the pool) marks
    unmapped columns: in-program, ``one_hot(trash, num_pages)`` is the
    zero vector so writes routed there vanish, and gathers clip to a real
    page whose positions the kv mask never admits. Column W is
    permanently trash — it absorbs the (clipped) routing of speculative
    writes past the slot's capacity. Memory now scales with live tokens:
    ``nbytes`` at equal capacity shrinks by the pool/reservation ratio,
    and a pool sized below num_slots * W oversubscribes capacity safely
    (admission sheds, ticks starve-retire — never crash).
    """

    def __init__(self, shape, dtype="float32", *, num_slots, max_len):
        import jax.numpy as jnp

        shape = tuple(int(d) for d in shape)
        if len(shape) != 5:
            raise MXNetError(
                "paged KV pool shape must be [num_pages, layers, heads, "
                f"page_tokens, head_dim], got {shape}")
        self.num_pages = shape[0]
        self.page_tokens = shape[3]
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.pages_per_slot = -(-self.max_len // self.page_tokens)  # W
        self.trash = self.num_pages
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        self.lengths = onp.zeros(self.num_slots, dtype="int32")
        # host page tables, one row per slot; column W stays trash
        self.table = onp.full((self.num_slots, self.pages_per_slot + 1),
                              self.trash, dtype="int32")
        self.slots = SlotAllocator(self.num_slots)
        self.pages = PageAllocator(self.num_pages)

    def rebind(self, k, v):
        self.k, self.v = k, v

    def reset_row(self, sid):
        self.table[sid, :] = self.trash
        self.lengths[sid] = 0

    @property
    def nbytes(self):
        return int(self.k.size * self.k.dtype.itemsize * 2)

    def occupancy(self):
        return len(self.slots.live) / self.num_slots

    def pages_live(self):
        return self.num_pages - self.pages.free_count
