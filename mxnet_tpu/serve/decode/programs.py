"""The three AOT-compiled program families of the decode engine (v2).

Exactly three graph shapes exist (PyGraph's whole-iteration capture
applied to decoding — the host only feeds operands):

- ``prefill(bucket_batch, bucket_len)``: forward the whole right-padded
  prompt batch once (the exact flash-path compute of the plain forward),
  argmax the logits at each row's last valid position, and scatter the
  per-layer k/v page-chunk-wise into the pool pages each row's page-table
  operand maps. One traced graph per length bucket, compiled per batch
  bucket.
- ``prefill_ext(bucket_batch, bucket_len)``: the radix prefix-cache join
  — forward only the prompt SUFFIX from a page-aligned ``start`` offset,
  attending the gathered page view (shared prefix pages already
  resident) plus the suffix's own k/v, then scatter the suffix pages.
  Traced only when the prefix cache is enabled.
- ``decode_tick_k(num_slots, K)``: K tokens for EVERY slot against the
  gathered page view — fixed shape, traced and compiled exactly once.
  K = 1 is the plain tick; K > 1 verifies a K-1-token draft in one
  batched pass (speculative decoding). Static K keeps the program set
  fixed, so steady state never recompiles regardless of drafts,
  prefix hits, or which requests join or leave.

All three donate the pool pair (pool in, pool out — a single device
residency; on backends without donation support XLA falls back to
copying). ``export``/``from_export`` round-trip the traced graphs through
Symbol JSON + a params npz, so a fresh process can serve without the
model class — the SymbolBlock.imports analog for the decode engine.
"""
from __future__ import annotations

import json
import os
import time
import warnings

import numpy as onp

from ...base import MXNetError
from ..bucketing import bucket_ladder

__all__ = ["DecodePrograms", "load_decode_manifest"]

MANIFEST_VERSION = 2


def load_decode_manifest(path):
    with open(path) as fh:
        m = json.load(fh)
    if m.get("kind") != "decode_engine" or \
            m.get("version") != MANIFEST_VERSION:
        raise MXNetError(
            f"unsupported decode manifest in {path}: version="
            f"{m.get('version')!r} kind={m.get('kind')!r} (this build "
            f"reads version {MANIFEST_VERSION}; pre-paging manifests "
            "must be re-exported)")
    return m


def _compile(cop, examples, donate):
    """AOT-compile suppressing the backend's 'donation not implemented'
    warning (CPU): the fallback is a copy, which is correct — the donation
    request is for the TPU path."""
    with warnings.catch_warnings():
        warnings.filterwarnings("ignore", message=".*donat.*",
                                category=UserWarning)
        return cop.aot_compile(*examples, donate=donate)


class DecodePrograms:
    """Trace + compile + (de)serialize the engine's program table.

    Built either from a live model (``DecodePrograms(model, ...)``) or
    from an export directory (``DecodePrograms.from_export(prefix)``).
    """

    # donated operand indices (example-input space)
    _PREFILL_DONATE = (3, 4)   # (tokens, valid, table, kp, vp)
    _EXT_DONATE = (4, 5)       # (tokens, valid, start, table, kp, vp)
    _DECODE_DONATE = (3, 4)    # (tokens, positions, table, kp, vp)

    def __init__(self, model=None, *, num_slots, max_len, prefill_batch=4,
                 max_prompt_len=None, min_prompt_bucket=8, page_tokens=128,
                 kv_pages=None, speculate_k=1, prefix_cache=True,
                 tp=1, partition_rules=None, _from_export=None):
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.prefill_batch = int(prefill_batch)
        max_prompt_len = int(max_prompt_len or self.max_len)
        if max_prompt_len > self.max_len:
            raise MXNetError(
                f"max_prompt_len {max_prompt_len} exceeds cache max_len "
                f"{self.max_len}")
        self.max_prompt_len = max_prompt_len
        # clamp to max_len: a page larger than the whole cache row would
        # silently re-grow per-slot reservation past the slot-cache design
        self.page_tokens = min(int(page_tokens), self.max_len)
        if self.page_tokens < 1:
            raise MXNetError(
                f"page_tokens must be >= 1, got {page_tokens}")
        # W: page-table columns per slot (+1 sentinel column in-table)
        self.pages_per_slot = -(-self.max_len // self.page_tokens)
        self.kv_pages = int(kv_pages or
                            self.num_slots * self.pages_per_slot)
        if self.kv_pages < -(-self.max_prompt_len // self.page_tokens):
            raise MXNetError(
                f"kv_pages {self.kv_pages} cannot hold even one "
                f"max_prompt_len={self.max_prompt_len} prompt at "
                f"page_tokens={self.page_tokens}")
        self.speculate_k = max(1, int(speculate_k))
        if self.speculate_k > self.page_tokens:
            raise MXNetError(
                f"speculate_k {self.speculate_k} exceeds page_tokens "
                f"{self.page_tokens} (a tick must fit in one new page)")
        self.prefix_cache = bool(prefix_cache)
        self.batch_ladder = bucket_ladder(self.prefill_batch)
        self.len_ladder = bucket_ladder(
            max_prompt_len, min_bucket=min(min_prompt_bucket,
                                           max_prompt_len))
        self._model = model
        self._cops = {}         # "decode:<K>" | "prefill[_ext]:<T>" -> CachedOp
        self._graph_params = {}  # graph key -> ordered param names
        self._params = {}       # name -> raw device array
        self._programs = {}     # ("decode", K) | ("prefill"[_ext], B, T)
        self._costs = {}        # program key -> (flops, bytes_accessed)
        self._signatures = {}   # str key -> trace signature
        self.cache_shape = None  # [kv_pages, layers, heads, page_tokens, hd]
        self.cache_dtype = "float32"
        # tensor parallelism: the model's column-parallel serve layout,
        # traced at per-rank local shapes and replayed under shard_map
        # over a {'tp': tp} mesh — merged activations are concatenations,
        # so the served tokens stay BITWISE the unsharded model's
        self.tp = max(1, int(tp))
        self._mesh = None
        self._tp_places = {}     # param name -> (sharded dim, segments)
        self._in_shardings = {}  # program key -> per-arg NamedShardings
        if self.tp > 1:
            if _from_export is not None:
                raise MXNetError(
                    "tensor-parallel serving cannot load an export — "
                    "re-trace from the live model with tp set")
            if model is None:
                raise MXNetError("DecodePrograms needs a model for tp >= 2")
            if partition_rules is None:
                maker = getattr(model, "tp_partition_rules", None)
                if maker is None:
                    raise MXNetError(
                        "tp >= 2 needs partition_rules (or a model exposing "
                        "tp_partition_rules('serve'))")
                partition_rules = maker("serve")
            import jax

            from ...parallel.mesh import make_mesh

            if len(jax.devices()) < self.tp:
                raise MXNetError(
                    f"tp={self.tp} needs that many devices; "
                    f"{len(jax.devices())} visible")
            self._mesh = make_mesh({"tp": self.tp},
                                   devices=jax.devices()[:self.tp])
        self._tp_rules = partition_rules
        if _from_export is not None:
            self._load_export(_from_export)
        else:
            if model is None:
                raise MXNetError("DecodePrograms needs a model or an export")
            self._trace_all()

    @property
    def table_width(self):
        return self.pages_per_slot + 1

    # ----------------------------------------------------------------- trace
    def _collect_params(self):
        return [(name, p.data())
                for name, p in self._model.collect_params().items()
                if p._data is not None]

    def _trace_all(self):
        from ... import autograd

        if self.tp > 1:
            self._trace_all_tp()
            return
        params = self._collect_params()
        self._params = {name: arr._data for name, arr in params}
        with autograd.pause():
            self._trace_graphs(params)

    def _trace_graphs(self, params):
        names = [name for name, _ in params]
        K = self.speculate_k
        self._cops[f"decode:{K}"] = self._trace_decode(K, params)
        self._graph_params[f"decode:{K}"] = names
        for T in self.len_ladder:
            self._cops[f"prefill:{T}"] = self._trace_prefill(T, params)
            self._graph_params[f"prefill:{T}"] = names
            if self.prefix_cache:
                self._cops[f"prefill_ext:{T}"] = \
                    self._trace_prefill_ext(T, params)
                self._graph_params[f"prefill_ext:{T}"] = names

    def _trace_all_tp(self):
        """Trace every graph at per-rank LOCAL shapes: column-parallel
        parameters are temporarily swapped to their rank-0 local slices
        under an active serve-mode TPContext (the model emits tp_gather
        merges and sizes heads locally), then restored. Device residency
        for the compiled programs is the segment-permuted GLOBAL image of
        each sharded parameter, laid out so contiguous 1/tp blocks over
        'tp' ARE the per-rank local images."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from ... import autograd
        from ...ndarray.ndarray import NDArray
        from ...parallel import tp as _tpm
        from ...parallel.partition import match_partition_rules

        plist = [(name, p)
                 for name, p in self._model.collect_params().items()
                 if p._data is not None]
        specs = match_partition_rules(
            self._tp_rules, {n: p.data() for n, p in plist}, with_meta=True)
        places = {}
        for n, _ in plist:
            dim = _tpm.tp_dim(specs[n].spec)
            if dim is not None:
                places[n] = (dim, int(specs[n].meta.get("segments", 1)))
        self._tp_places = places
        for n, p in plist:
            full = p.data()._data
            if n in places:
                dim, seg = places[n]
                img = _tpm.global_image(onp.asarray(full), dim, self.tp,
                                        seg)
                ax = [None] * img.ndim
                ax[dim] = "tp"
                self._params[n] = jax.device_put(
                    jnp.asarray(img), NamedSharding(self._mesh, P(*ax)))
            else:
                self._params[n] = jax.device_put(
                    full, NamedSharding(self._mesh, P()))
        swapped = []
        ctx = _tpm.TPContext(self.tp, mode="serve")
        try:
            for n, p in plist:
                if n in places:
                    dim, seg = places[n]
                    loc = _tpm.local_slice(p.data().asnumpy(), dim, 0,
                                           self.tp, seg)
                    swapped.append((p, p._data))
                    p._data = NDArray(jnp.asarray(loc))
            params = [(n, p.data()) for n, p in plist]
            with _tpm.activate(ctx), autograd.pause():
                self._trace_graphs(params)
        finally:
            for p, full in swapped:
                p._data = full

    def _pool_pair(self):
        kp, vp = self._model.init_paged_cache(self.kv_pages,
                                              self.page_tokens)
        if self.cache_shape is None:
            shape = tuple(int(d) for d in kp.shape)
            if self.tp > 1:
                # the traced pool is per-rank local over heads; report the
                # GLOBAL pool geometry the engine allocates
                shape = shape[:2] + (shape[2] * self.tp,) + shape[3:]
            self.cache_shape = shape
            self.cache_dtype = str(kp.dtype)
        return kp, vp

    def _trace_decode(self, K, params):
        from ... import numpy as np
        from ...cached_op import trace

        model = self._model
        S = self.num_slots
        tokens = np.zeros((S, K), dtype="int32")
        positions = np.zeros((S,), dtype="int32")
        table = np.full((S, self.table_width), self.kv_pages,
                        dtype="int32")
        kp, vp = self._pool_pair()

        def fn(t, p, tab, k, v):
            logits, k2, v2 = model.forward_decode_paged(t, p, tab, k, v)
            nxt = np.argmax(logits, axis=-1).astype("int32")
            return nxt, k2, v2

        _, _, cop = trace(fn, [tokens, positions, table, kp, vp], params)
        cop._name = f"serve_decode_tick_k{K}"
        return cop

    def _trace_prefill(self, T, params):
        from ... import numpy as np
        from ...cached_op import trace

        model = self._model
        B = self.prefill_batch
        tokens = np.zeros((B, T), dtype="int32")
        valid = np.ones((B,), dtype="int32")
        table = np.full((B, self.table_width), self.kv_pages,
                        dtype="int32")
        kp, vp = self._pool_pair()

        def fn(tok, vl, tab, k, v):
            last, k2, v2 = model.forward_prefill_paged(tok, vl, tab, k, v)
            first = np.argmax(last, axis=-1).astype("int32")
            return first, k2, v2

        _, _, cop = trace(fn, [tokens, valid, table, kp, vp], params)
        cop._name = f"serve_prefill_{T}"
        return cop

    def _trace_prefill_ext(self, T, params):
        from ... import numpy as np
        from ...cached_op import trace

        model = self._model
        B = self.prefill_batch
        tokens = np.zeros((B, T), dtype="int32")
        valid = np.ones((B,), dtype="int32")
        start = np.zeros((B,), dtype="int32")
        table = np.full((B, self.table_width), self.kv_pages,
                        dtype="int32")
        kp, vp = self._pool_pair()

        def fn(tok, vl, st, tab, k, v):
            last, k2, v2 = model.forward_prefill_join(tok, vl, st, tab,
                                                      k, v)
            first = np.argmax(last, axis=-1).astype("int32")
            return first, k2, v2

        _, _, cop = trace(fn, [tokens, valid, start, table, kp, vp],
                          params)
        cop._name = f"serve_prefill_ext_{T}"
        return cop

    # --------------------------------------------------------------- compile
    def _zeros(self, shape, dtype):
        import jax.numpy as jnp

        return jnp.zeros(shape, dtype)

    @staticmethod
    def _site(key):
        if key[0] == "decode":
            return f"serve.decode_tick_k{key[1]}"
        if key[0] == "prefill_ext":
            return f"serve.prefill_ext_b{key[1]}_t{key[2]}"
        return f"serve.prefill_b{key[1]}_t{key[2]}"

    def ensure(self, kind, batch=None, length=None):
        """Compile (memoized) and return one executable."""
        if kind == "decode":
            key = ("decode", self.speculate_k)
        else:
            key = (kind, int(batch), int(length))
        prog = self._programs.get(key)
        if prog is not None:
            return prog
        from ...telemetry.watchdog import format_signature

        kp = self._zeros(self.cache_shape, self.cache_dtype)
        vp = self._zeros(self.cache_shape, self.cache_dtype)
        S = self.num_slots
        Wt = self.table_width
        if kind == "decode":
            cop = self._cops[f"decode:{self.speculate_k}"]
            examples = [self._zeros((S, self.speculate_k), "int32"),
                        self._zeros((S,), "int32"),
                        self._zeros((S, Wt), "int32"), kp, vp]
            donate = self._DECODE_DONATE
        else:
            cop = self._cops.get(f"{kind}:{length}")
            if cop is None:
                raise MXNetError(
                    f"no {kind} graph for length bucket {length} "
                    f"(ladder: {self.len_ladder}; prefix_cache="
                    f"{self.prefix_cache})")
            examples = [self._zeros((batch, length), "int32"),
                        self._zeros((batch,), "int32")]
            if kind == "prefill_ext":
                examples.append(self._zeros((batch,), "int32"))
                donate = self._EXT_DONATE
            else:
                donate = self._PREFILL_DONATE
            examples += [self._zeros((batch, Wt), "int32"), kp, vp]
        if self.tp > 1:
            prog = self._compile_tp(key, cop, examples, donate)
        else:
            args = examples + [self._params[n]
                               for n in self._graph_params[
                                   self._cop_key(key)]]
            prog = _compile(cop, args, donate)
        self._programs[key] = prog
        # per-program XLA cost, captured once per compile; run() credits
        # the flops counter with it at every dispatch
        from ... import telemetry as _tm

        site = self._site(key)
        cost = _tm.record_program_cost(site, prog)
        _tm.record_program_memory(site, prog)
        self._costs[key] = ((cost["flops"], cost["bytes_accessed"])
                            if cost else (0.0, 0.0))
        self._signatures["|".join(str(k) for k in key)] = format_signature(
            [getattr(x, "_data", x) for x in examples])
        return prog

    def _cop_key(self, key):
        if key[0] == "decode":
            return f"decode:{key[1]}"
        return f"{key[0]}:{key[2]}"

    def _compile_tp(self, key, cop, examples, donate):
        """AOT-compile one graph under shard_map on the 'tp' mesh: KV
        pools shard over the head axis, column-parallel params over their
        declared dim, everything else replicated. The executable bakes
        these input shardings, so ``run`` device_puts its operands to the
        recorded layouts before every call."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from ...parallel.mesh import shard_map_compat

        names = self._graph_params[self._cop_key(key)]
        pool = P(None, None, "tp")
        data_specs = [P()] * (len(examples) - 2) + [pool, pool]
        pspecs = []
        for n in names:
            if n in self._tp_places:
                ax = [None] * self._params[n].ndim
                ax[self._tp_places[n][0]] = "tp"
                pspecs.append(P(*ax))
            else:
                pspecs.append(P())
        in_specs = tuple(data_specs + pspecs)
        n_aux = len(getattr(cop, "_aux_targets", ()) or ())
        out_specs = (P(), pool, pool) + (P(),) * n_aux
        off = 1 if cop._uses_rng else 0
        if off:
            in_specs = (P(),) + in_specs
        fn = shard_map_compat(cop._raw_fn, self._mesh,
                              in_specs=in_specs, out_specs=out_specs)
        shardings = tuple(NamedSharding(self._mesh, s) for s in in_specs)
        self._in_shardings[key] = shardings
        argnums = tuple(sorted(int(i) + off for i in donate))
        datas = [getattr(x, "_data", x) for x in examples]
        if off:
            datas.insert(0, jax.random.PRNGKey(0))
        args = [jax.device_put(a, s) for a, s in zip(
            datas + [self._params[n] for n in names], shardings)]
        with warnings.catch_warnings():
            warnings.filterwarnings("ignore", message=".*donat.*",
                                    category=UserWarning)
            return jax.jit(
                fn, donate_argnums=argnums).lower(*args).compile()

    def run(self, key, datas):
        """Call a compiled program with raw device operands; appends the
        param tail (and a PRNG key for rng graphs) in trace order."""
        prog = self._programs[key]
        cop = self._cops[self._cop_key(key)]
        args = list(datas) + [self._params[n]
                              for n in self._graph_params[self._cop_key(key)]]
        if cop._uses_rng:
            from ... import random as _rnd

            args.insert(0, _rnd._next_key())
        if self.tp > 1:
            # the AOT executables bake their input shardings; re-lay small
            # host-made operands (a no-op for already-resident arrays)
            import jax

            args = [jax.device_put(getattr(a, "_data", a), s)
                    for a, s in zip(args, self._in_shardings[key])]
        from ... import telemetry as _tm

        if _tm.ON:
            _tm.record_flops(*self._costs.get(key, (0.0, 0.0)))
        outs = prog(*args)
        return outs if isinstance(outs, (tuple, list)) else (outs,)

    def warmup(self):
        """Compile the whole table: decode_tick_k + every (batch, len)
        prefill (and prefix-join) bucket. After this, serving compiles
        nothing. Tuned kernel configs (``MXTPU_TUNE=1``) preload first so
        each trace resolves its blocks from the persisted winners — the
        engine never tunes online."""
        from ...tune import preload as _tune_preload

        _tune_preload()
        self.ensure("decode")
        for T in self.len_ladder:
            for B in self.batch_ladder:
                self.ensure("prefill", batch=B, length=T)
                if self.prefix_cache:
                    self.ensure("prefill_ext", batch=B, length=T)

    # ------------------------------------------------------------- manifests
    def manifest_dict(self, cache_dir=None, graphs=None):
        from ...context import _probe_env_signature

        import jax

        return {
            "version": MANIFEST_VERSION,
            "kind": "decode_engine",
            "env_signature": _probe_env_signature(),
            "jax_version": getattr(jax, "__version__", "?"),
            "num_slots": self.num_slots,
            "max_len": self.max_len,
            "prefill_batch": self.prefill_batch,
            "max_prompt_len": self.max_prompt_len,
            "page_tokens": self.page_tokens,
            "kv_pages": self.kv_pages,
            "speculate_k": self.speculate_k,
            "prefix_cache": self.prefix_cache,
            "tp": self.tp,
            "batch_ladder": list(self.batch_ladder),
            "len_ladder": list(self.len_ladder),
            "cache_shape": list(self.cache_shape or ()),
            "cache_dtype": self.cache_dtype,
            "signatures": dict(sorted(self._signatures.items())),
            "cache_dir": cache_dir,
            "graphs": graphs,
            "created_unix": time.time(),
        }

    # ---------------------------------------------------------------- export
    @staticmethod
    def _n_data(key):
        if key.startswith("prefill_ext:"):
            return 6
        return 5

    def export(self, prefix):
        """Write the traced graphs + params + manifest; returns the
        manifest path. A fresh process rebuilds the full program table
        from these files alone (``from_export``) — no model class needed,
        and with the persistent compile cache on, no XLA compiles either.
        """
        if self.tp > 1:
            raise MXNetError(
                "export of a tensor-parallel decode engine is not "
                "supported: the traced graphs hold per-rank local shapes "
                "tied to this process's mesh — export from a tp=1 trace "
                "and pass tp at load time instead")
        graphs = {}
        for key, cop in self._cops.items():
            fname = f"{prefix}-{key.replace(':', '_')}-symbol.json"
            cop.sym.save(fname)
            graphs[key] = {"file": os.path.basename(fname),
                           "n_data": self._n_data(key),
                           "params": self._graph_params[key]}
        onp.savez(f"{prefix}-params.npz",
                  **{n: onp.asarray(a) for n, a in self._params.items()})
        m = self.manifest_dict(graphs=graphs)
        m["params_file"] = os.path.basename(f"{prefix}-params.npz")
        mpath = f"{prefix}-decode.manifest.json"
        tmp = mpath + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(m, fh, indent=1)
        os.replace(tmp, mpath)
        return mpath

    @classmethod
    def from_export(cls, prefix_or_manifest):
        """Rebuild the program table from ``export`` artifacts."""
        mpath = prefix_or_manifest
        if not mpath.endswith(".json"):
            mpath = f"{prefix_or_manifest}-decode.manifest.json"
        m = load_decode_manifest(mpath)
        self = cls(num_slots=m["num_slots"], max_len=m["max_len"],
                   prefill_batch=m["prefill_batch"],
                   max_prompt_len=m["max_prompt_len"],
                   page_tokens=m["page_tokens"], kv_pages=m["kv_pages"],
                   speculate_k=m["speculate_k"],
                   prefix_cache=m["prefix_cache"],
                   _from_export=(m, os.path.dirname(os.path.abspath(mpath))))
        return self

    def _load_export(self, export):
        import jax.numpy as jnp

        from ...cached_op import CachedOp
        from ...symbol.symbol import Symbol, topo_sort

        m, root = export
        self.cache_shape = tuple(int(d) for d in m["cache_shape"])
        self.cache_dtype = m["cache_dtype"]
        with onp.load(os.path.join(root, m["params_file"])) as z:
            self._params = {n: jnp.asarray(z[n]) for n in z.files}
        for key, g in m["graphs"].items():
            sym = Symbol.load(os.path.join(root, g["file"]))
            var_nodes = [n for n in topo_sort(sym._entries) if n.is_var]
            by_name = {n.name: n for n in var_nodes}
            # trace() names data inputs data0..dataN; params keep their
            # parameter names — rebuild the exact call order
            ordered, pnames = [], []
            for i in range(g["n_data"]):
                if f"data{i}" not in by_name:
                    raise MXNetError(
                        f"exported graph {key} is missing input data{i}")
                ordered.append(by_name[f"data{i}"])
            for pn in g["params"]:
                if pn in by_name:      # unused params drop out of the graph
                    ordered.append(by_name[pn])
                    pnames.append(pn)
            missing = set(by_name) - {n.name for n in ordered}
            if missing:
                raise MXNetError(
                    f"exported graph {key} has unbound inputs: "
                    f"{sorted(missing)}")
            self._cops[key] = CachedOp(sym, ordered,
                                       name=f"serve_{key.replace(':', '_')}")
            self._graph_params[key] = pnames
