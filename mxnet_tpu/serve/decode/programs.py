"""The two AOT-compiled program families of the decode engine.

Exactly two graph shapes exist (PyGraph's whole-iteration capture applied
to decoding — the host only feeds operands):

- ``prefill(bucket_batch, bucket_len)``: forward the whole right-padded
  prompt batch once, argmax the logits at each row's last valid position
  (the first generated token), and scatter the per-layer k/v into the
  assigned cache slots (``inv_index``/``hit`` route batch rows to slot
  rows in-program, so the donated cache is updated without a host-side
  copy). One traced graph per length bucket, compiled per batch bucket —
  the program set is O(log max_prompt_len · log prefill_batch).
- ``decode_tick(num_slots)``: one token for EVERY slot against the full
  cache — fixed shape, traced and compiled exactly once, so steady state
  never recompiles regardless of which requests join or leave.

Both families donate the cache pair (cache in, cache out — a single
device residency; on backends without donation support XLA falls back to
copying). ``export``/``from_export`` round-trip the traced graphs through
Symbol JSON + a params npz, so a fresh process can serve without the
model class — the SymbolBlock.imports analog for the decode engine.
"""
from __future__ import annotations

import json
import os
import time
import warnings

import numpy as onp

from ...base import MXNetError
from ..bucketing import bucket_ladder, pick_bucket

__all__ = ["DecodePrograms", "load_decode_manifest"]


def load_decode_manifest(path):
    with open(path) as fh:
        m = json.load(fh)
    if m.get("version") != 1 or m.get("kind") != "decode_engine":
        raise MXNetError(
            f"unsupported decode manifest in {path}: version="
            f"{m.get('version')!r} kind={m.get('kind')!r}")
    return m


def _compile(cop, examples, donate):
    """AOT-compile suppressing the backend's 'donation not implemented'
    warning (CPU): the fallback is a copy, which is correct — the donation
    request is for the TPU path."""
    with warnings.catch_warnings():
        warnings.filterwarnings("ignore", message=".*donat.*",
                                category=UserWarning)
        return cop.aot_compile(*examples, donate=donate)


class DecodePrograms:
    """Trace + compile + (de)serialize the engine's program table.

    Built either from a live model (``DecodePrograms(model, ...)``) or
    from an export directory (``DecodePrograms.from_export(prefix)``).
    """

    # donated operand indices (example-input space)
    _PREFILL_DONATE = (4, 5)   # (tokens, valid, inv_index, hit, kc, vc)
    _DECODE_DONATE = (2, 3)    # (tokens, positions, kc, vc)

    def __init__(self, model=None, *, num_slots, max_len, prefill_batch=4,
                 max_prompt_len=None, min_prompt_bucket=8, _from_export=None):
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.prefill_batch = int(prefill_batch)
        max_prompt_len = int(max_prompt_len or self.max_len)
        if max_prompt_len > self.max_len:
            raise MXNetError(
                f"max_prompt_len {max_prompt_len} exceeds cache max_len "
                f"{self.max_len}")
        self.max_prompt_len = max_prompt_len
        self.batch_ladder = bucket_ladder(self.prefill_batch)
        self.len_ladder = bucket_ladder(
            max_prompt_len, min_bucket=min(min_prompt_bucket,
                                           max_prompt_len))
        self._model = model
        self._cops = {}         # "decode" | "prefill:<T>" -> CachedOp
        self._graph_params = {}  # graph key -> ordered param names
        self._params = {}       # name -> raw device array
        self._programs = {}     # ("decode",) | ("prefill", B, T) -> Compiled
        self._costs = {}        # program key -> (flops, bytes_accessed)
        self._signatures = {}   # str key -> trace signature
        self.cache_shape = None  # [S, layers, heads, max_len, head_dim]
        self.cache_dtype = "float32"
        if _from_export is not None:
            self._load_export(_from_export)
        else:
            if model is None:
                raise MXNetError("DecodePrograms needs a model or an export")
            self._trace_all()

    # ----------------------------------------------------------------- trace
    def _collect_params(self):
        return [(name, p.data())
                for name, p in self._model.collect_params().items()
                if p._data is not None]

    def _trace_all(self):
        from ... import autograd

        params = self._collect_params()
        self._params = {name: arr._data for name, arr in params}
        names = [name for name, _ in params]
        with autograd.pause():
            self._cops["decode"] = self._trace_decode(params)
            self._graph_params["decode"] = names
            for T in self.len_ladder:
                self._cops[f"prefill:{T}"] = self._trace_prefill(T, params)
                self._graph_params[f"prefill:{T}"] = names

    def _trace_decode(self, params):
        from ... import numpy as np
        from ...cached_op import trace

        model = self._model
        S = self.num_slots
        tokens = np.zeros((S,), dtype="int32")
        positions = np.zeros((S,), dtype="int32")
        kc, vc = model.init_cache(S, self.max_len)
        self.cache_shape = tuple(int(d) for d in kc.shape)
        self.cache_dtype = str(kc.dtype)

        def fn(t, p, k, v):
            logits, k2, v2 = model.forward_decode(t, p, k, v)
            nxt = np.argmax(logits, axis=-1).astype("int32")
            return nxt, k2, v2

        _, _, cop = trace(fn, [tokens, positions, kc, vc], params)
        cop._name = "serve_decode_tick"
        return cop

    def _trace_prefill(self, T, params):
        from ... import numpy as np
        from ...cached_op import trace

        model = self._model
        S, B = self.num_slots, self.prefill_batch
        tokens = np.zeros((B, T), dtype="int32")
        valid = np.ones((B,), dtype="int32")
        inv_index = np.zeros((S,), dtype="int32")
        hit = np.zeros((S,), dtype="bool")
        kc, vc = model.init_cache(S, self.max_len)
        pad = self.max_len - T

        def fn(tok, vl, inv, h, k_cache, v_cache):
            last, k, v = model.forward_prefill(tok, vl)
            first = np.argmax(last, axis=-1).astype("int32")
            # route batch rows to their slots: gather-by-inv_index builds
            # a slot-shaped view of the new k/v, `hit` picks which slot
            # rows actually change — the rest keep the donated cache
            sel_k = np.take(k, inv, axis=0, mode="clip")
            sel_v = np.take(v, inv, axis=0, mode="clip")
            if pad:
                widths = ((0, 0), (0, 0), (0, 0), (0, pad), (0, 0))
                sel_k, sel_v = np.pad(sel_k, widths), np.pad(sel_v, widths)
            hm = h.reshape(-1, 1, 1, 1, 1)
            return (first, np.where(hm, sel_k, k_cache),
                    np.where(hm, sel_v, v_cache))

        _, _, cop = trace(fn, [tokens, valid, inv_index, hit, kc, vc],
                          params)
        cop._name = f"serve_prefill_{T}"
        return cop

    # --------------------------------------------------------------- compile
    def _zeros(self, shape, dtype):
        import jax.numpy as jnp

        return jnp.zeros(shape, dtype)

    def ensure(self, kind, batch=None, length=None):
        """Compile (memoized) and return one executable."""
        if kind == "decode":
            key = ("decode",)
        else:
            key = ("prefill", int(batch), int(length))
        prog = self._programs.get(key)
        if prog is not None:
            return prog
        from ...telemetry.watchdog import format_signature

        kc = self._zeros(self.cache_shape, self.cache_dtype)
        vc = self._zeros(self.cache_shape, self.cache_dtype)
        S = self.num_slots
        if kind == "decode":
            cop = self._cops["decode"]
            examples = [self._zeros((S,), "int32"),
                        self._zeros((S,), "int32"), kc, vc]
            donate = self._DECODE_DONATE
        else:
            cop = self._cops.get(f"prefill:{length}")
            if cop is None:
                raise MXNetError(
                    f"no prefill graph for length bucket {length} "
                    f"(ladder: {self.len_ladder})")
            examples = [self._zeros((batch, length), "int32"),
                        self._zeros((batch,), "int32"),
                        self._zeros((S,), "int32"),
                        self._zeros((S,), "bool"), kc, vc]
            donate = self._PREFILL_DONATE
        args = examples + [self._params[n]
                           for n in self._graph_params[self._cop_key(key)]]
        prog = _compile(cop, args, donate)
        self._programs[key] = prog
        # per-program XLA cost, captured once per compile; run() credits
        # the flops counter with it at every dispatch
        from ... import telemetry as _tm

        site = ("serve.decode_tick" if kind == "decode"
                else f"serve.prefill_b{batch}_t{length}")
        cost = _tm.record_program_cost(site, prog)
        _tm.record_program_memory(site, prog)
        self._costs[key] = ((cost["flops"], cost["bytes_accessed"])
                            if cost else (0.0, 0.0))
        self._signatures["|".join(str(k) for k in key)] = format_signature(
            [getattr(x, "_data", x) for x in examples])
        return prog

    @staticmethod
    def _cop_key(key):
        return "decode" if key[0] == "decode" else f"prefill:{key[2]}"

    def run(self, key, datas):
        """Call a compiled program with raw device operands; appends the
        param tail (and a PRNG key for rng graphs) in trace order."""
        prog = self._programs[key]
        cop = self._cops[self._cop_key(key)]
        args = list(datas) + [self._params[n]
                              for n in self._graph_params[self._cop_key(key)]]
        if cop._uses_rng:
            from ... import random as _rnd

            args.insert(0, _rnd._next_key())
        from ... import telemetry as _tm

        if _tm.ON:
            _tm.record_flops(*self._costs.get(key, (0.0, 0.0)))
        outs = prog(*args)
        return outs if isinstance(outs, (tuple, list)) else (outs,)

    def warmup(self):
        """Compile the whole table: decode_tick + every (batch, len)
        prefill bucket. After this, serving compiles nothing."""
        self.ensure("decode")
        for T in self.len_ladder:
            for B in self.batch_ladder:
                self.ensure("prefill", batch=B, length=T)

    # ------------------------------------------------------------- manifests
    def manifest_dict(self, cache_dir=None, graphs=None):
        from ...context import _probe_env_signature

        import jax

        return {
            "version": 1,
            "kind": "decode_engine",
            "env_signature": _probe_env_signature(),
            "jax_version": getattr(jax, "__version__", "?"),
            "num_slots": self.num_slots,
            "max_len": self.max_len,
            "prefill_batch": self.prefill_batch,
            "max_prompt_len": self.max_prompt_len,
            "batch_ladder": list(self.batch_ladder),
            "len_ladder": list(self.len_ladder),
            "cache_shape": list(self.cache_shape or ()),
            "cache_dtype": self.cache_dtype,
            "signatures": dict(sorted(self._signatures.items())),
            "cache_dir": cache_dir,
            "graphs": graphs,
            "created_unix": time.time(),
        }

    # ---------------------------------------------------------------- export
    def export(self, prefix):
        """Write the traced graphs + params + manifest; returns the
        manifest path. A fresh process rebuilds the full program table
        from these files alone (``from_export``) — no model class needed,
        and with the persistent compile cache on, no XLA compiles either.
        """
        graphs = {}
        for key, cop in self._cops.items():
            fname = f"{prefix}-{key.replace(':', '_')}-symbol.json"
            cop.sym.save(fname)
            graphs[key] = {"file": os.path.basename(fname),
                           "n_data": 4 if key == "decode" else 6,
                           "params": self._graph_params[key]}
        onp.savez(f"{prefix}-params.npz",
                  **{n: onp.asarray(a) for n, a in self._params.items()})
        m = self.manifest_dict(graphs=graphs)
        m["params_file"] = os.path.basename(f"{prefix}-params.npz")
        mpath = f"{prefix}-decode.manifest.json"
        tmp = mpath + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(m, fh, indent=1)
        os.replace(tmp, mpath)
        return mpath

    @classmethod
    def from_export(cls, prefix_or_manifest):
        """Rebuild the program table from ``export`` artifacts."""
        mpath = prefix_or_manifest
        if not mpath.endswith(".json"):
            mpath = f"{prefix_or_manifest}-decode.manifest.json"
        m = load_decode_manifest(mpath)
        self = cls(num_slots=m["num_slots"], max_len=m["max_len"],
                   prefill_batch=m["prefill_batch"],
                   max_prompt_len=m["max_prompt_len"],
                   _from_export=(m, os.path.dirname(os.path.abspath(mpath))))
        return self

    def _load_export(self, export):
        import jax.numpy as jnp

        from ...cached_op import CachedOp
        from ...symbol.symbol import Symbol, topo_sort

        m, root = export
        self.cache_shape = tuple(int(d) for d in m["cache_shape"])
        self.cache_dtype = m["cache_dtype"]
        with onp.load(os.path.join(root, m["params_file"])) as z:
            self._params = {n: jnp.asarray(z[n]) for n in z.files}
        for key, g in m["graphs"].items():
            sym = Symbol.load(os.path.join(root, g["file"]))
            var_nodes = [n for n in topo_sort(sym._entries) if n.is_var]
            by_name = {n.name: n for n in var_nodes}
            # trace() names data inputs data0..dataN; params keep their
            # parameter names — rebuild the exact call order
            ordered, pnames = [], []
            for i in range(g["n_data"]):
                if f"data{i}" not in by_name:
                    raise MXNetError(
                        f"exported graph {key} is missing input data{i}")
                ordered.append(by_name[f"data{i}"])
            for pn in g["params"]:
                if pn in by_name:      # unused params drop out of the graph
                    ordered.append(by_name[pn])
                    pnames.append(pn)
            missing = set(by_name) - {n.name for n in ordered}
            if missing:
                raise MXNetError(
                    f"exported graph {key} has unbound inputs: "
                    f"{sorted(missing)}")
            self._cops[key] = CachedOp(sym, ordered,
                                       name=f"serve_{key.replace(':', '_')}")
            self._graph_params[key] = pnames
