"""Shape bucketing: bound the compiled-program set to a batch-size ladder.

Every novel batch shape retraces a compiled program and pays a fresh XLA
compile; unconstrained traffic therefore grows the jit cache without bound
(TVM's ahead-of-time per-shape specialization, arxiv 1802.04799, is the
precedent for fixing the shape set up front). The ladder bounds it to
O(log max_batch) programs: an incoming batch of n rows is padded with
zeros up to the smallest bucket >= n, and outputs are sliced back to n.
Batches larger than ``max_batch`` split into max_batch-sized chunks plus
one ragged tail.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["bucket_ladder", "pick_bucket", "split_sizes", "padded_rows"]


def bucket_ladder(max_batch, min_bucket=1):
    """Ascending bucket sizes: powers of two from ``min_bucket`` capped by
    ``max_batch`` (always included, even when not a power of two).

    >>> bucket_ladder(64)
    [1, 2, 4, 8, 16, 32, 64]
    >>> bucket_ladder(48, min_bucket=4)
    [4, 8, 16, 32, 48]
    """
    max_batch, min_bucket = int(max_batch), int(min_bucket)
    if max_batch < 1 or min_bucket < 1:
        raise MXNetError(
            f"bucket ladder needs positive sizes, got max_batch={max_batch} "
            f"min_bucket={min_bucket}")
    if min_bucket > max_batch:
        raise MXNetError(
            f"min_bucket {min_bucket} exceeds max_batch {max_batch}")
    ladder, b = [], min_bucket
    while b < max_batch:
        ladder.append(b)
        b *= 2
    ladder.append(max_batch)
    return ladder


def pick_bucket(n, ladder):
    """Smallest bucket >= n (ladder is ascending); None when n overflows
    the ladder (the caller splits such batches first)."""
    for b in ladder:
        if b >= n:
            return b
    return None


def split_sizes(n, max_batch):
    """Chunk a batch of n rows into dispatchable sizes:
    full ``max_batch`` chunks plus one ragged tail.

    >>> split_sizes(70, 32)
    [32, 32, 6]
    """
    if n < 1:
        raise MXNetError(f"cannot serve an empty batch (n={n})")
    sizes = [max_batch] * (n // max_batch)
    if n % max_batch:
        sizes.append(n % max_batch)
    return sizes


def padded_rows(n, bucket):
    """Rows of zero-padding a batch of n pays in its bucket."""
    return bucket - n
