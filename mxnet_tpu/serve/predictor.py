"""serve.Predictor — the TPU-native inference fast path.

Wraps any hybridizable ``Block`` for traffic serving (ISSUE 4; the
north-star's "heavy traffic from millions of users" leg). Three layers:

- **Shape bucketing** (``bucketing.py``): one ahead-of-time compiled
  program per bucket in a powers-of-two ladder, so the program set is
  O(log max_batch) regardless of observed batch shapes. Inputs pad with
  zeros to their bucket; outputs slice back. TVM's per-shape AOT
  specialization (arxiv 1802.04799) is the precedent.
- **Dynamic batching**: ``submit()`` enqueues single-item requests and
  returns a ``Future``; a background dispatcher coalesces waiting
  requests into one padded device batch under a ``max_batch`` /
  ``max_wait_us`` policy. Host->device transfer of batch N+1 is issued
  while batch N computes (both are async under PJRT; results of N are
  only awaited after N+1 is dispatched), so transfer overlaps compute —
  PyGraph's capture-and-replay amortization (arxiv 2503.19779) applied
  to serving.
- **Persistent compilation**: ``context.enable_compilation_cache`` points
  jax's on-disk compilation cache at a directory keyed by the
  backend-probe environment signature, and ``warmup()`` precompiles
  every bucket (recording a manifest), so a fresh process restores
  steady-state latency — zero recompiles from the first request on.

The serving call path deliberately bypasses the imperative dispatch /
autograd layers: bucket programs are ``CachedOp.aot_compile`` executables
called with raw device arrays. Telemetry (when enabled) sees every
program call as one dispatch, plus serve-specific gauges/counters and a
latency histogram (p50/p99).
"""
from __future__ import annotations

import json
import os
import queue
import threading
import time
from concurrent.futures import Future

import numpy as onp

from ..base import MXNetError
from ..telemetry.registry import Histogram
from ..testing import chaos
from .bucketing import bucket_ladder, padded_rows, pick_bucket, split_sizes
from .decode.engine import EngineDeadError

__all__ = ["Predictor", "load_manifest"]

_STOP = object()


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class _Request:
    __slots__ = ("rows", "future", "t0", "trace")

    def __init__(self, rows, trace=None):
        self.rows = rows  # one host row per model input
        self.future = Future()
        self.t0 = time.perf_counter()
        # RequestTrace when telemetry is on, else None; also exposed as
        # future.trace so callers can read the phase decomposition
        self.trace = trace
        self.future.trace = trace


def load_manifest(path):
    """Read a warmup manifest written by ``Predictor.warmup(path)``."""
    with open(path) as fh:
        m = json.load(fh)
    if m.get("version") != 1:
        raise MXNetError(f"unsupported warmup manifest version in {path}: "
                         f"{m.get('version')!r}")
    return m


class Predictor:
    """Serve a hybridizable block behind bucketed, batched, AOT-compiled
    XLA programs.

    Parameters
    ----------
    block : HybridBlock (or SymbolBlock)
        The model. Its parameters are captured at construction; the block
        is traced ONCE in inference mode (``autograd.pause``) and each
        bucket is compiled ahead of time from that one graph.
    example : NDArray or tuple of NDArray, optional
        A representative input batch (any leading batch size) fixing the
        per-item shape and dtype of each model input. May be omitted when
        ``manifest`` supplies the specs.
    max_batch : int
        Largest device batch; also the top ladder bucket. Bigger
        ``predict()`` batches split into max_batch chunks.
    buckets : list[int], optional
        Explicit ladder (ascending, last == max_batch). Default: powers
        of two up to ``max_batch``.
    max_wait_us : int
        How long the dispatcher holds an underfull batch open for more
        ``submit()`` traffic before dispatching it anyway.
    cache_dir : str | None | False
        Persistent compilation cache directory. None (default) resolves
        through ``context.compilation_cache_dir()`` (keyed by the
        backend-probe env signature); False disables persistence.
    manifest : str, optional
        Path to a warmup manifest from a previous process: adopts its
        ladder/input specs and precompiles every bucket immediately
        (the XLA compiles hit the on-disk cache).
    """

    def __init__(self, block, example=None, *, max_batch=64, buckets=None,
                 max_wait_us=2000, cache_dir=None, manifest=None):
        from .. import telemetry as _tm
        from ..context import enable_compilation_cache
        from ..ndarray.ndarray import NDArray

        self._tm = _tm
        self._NDArray = NDArray
        if cache_dir is not False:
            self.cache_dir = enable_compilation_cache(cache_dir)
        else:
            self.cache_dir = None

        manifest_dict = None
        if manifest is not None:
            manifest_dict = load_manifest(manifest) \
                if isinstance(manifest, str) else dict(manifest)
            max_batch = int(manifest_dict["max_batch"])
            buckets = [int(b) for b in manifest_dict["buckets"]]

        self.max_batch = int(max_batch)
        self.buckets = [int(b) for b in buckets] if buckets \
            else bucket_ladder(self.max_batch)
        if sorted(self.buckets) != self.buckets or \
                self.buckets[-1] != self.max_batch:
            raise MXNetError(
                f"bucket ladder must ascend to max_batch={self.max_batch}, "
                f"got {self.buckets}")
        self.max_wait_us = int(max_wait_us)

        # -- input spec ----------------------------------------------------
        if example is not None:
            examples = example if isinstance(example, (tuple, list)) \
                else (example,)
            examples = [x if isinstance(x, NDArray) else NDArray(x)
                        for x in examples]
            if any(x.ndim < 1 for x in examples):
                raise MXNetError("example inputs need a leading batch axis")
            self._item_shapes = [x.shape[1:] for x in examples]
            self._dtypes = [onp.dtype(x.dtype) for x in examples]
        elif manifest_dict is not None:
            self._item_shapes = [tuple(s["item_shape"])
                                 for s in manifest_dict["inputs"]]
            self._dtypes = [onp.dtype(s["dtype"])
                            for s in manifest_dict["inputs"]]
        else:
            raise MXNetError(
                "Predictor needs an example input (or a warmup manifest) "
                "to fix input shapes/dtypes")

        # -- trace the serving graph once, in inference mode ---------------
        if not hasattr(block, "_serving_graph"):
            raise MXNetError(
                f"Predictor requires a hybridizable block, got "
                f"{type(block).__name__} (plain Blocks have no traceable "
                "graph — subclass HybridBlock)")
        self._block = block
        trace_inputs = tuple(self._zeros_batch(self.max_batch))
        cop, tree, param_arrays = block._serving_graph(trace_inputs)
        self._cop = cop
        self._tree = tree
        self._param_datas = [a._data for a in param_arrays]
        self._n_out = cop._n_main

        # -- program table -------------------------------------------------
        self._programs = {}     # bucket -> jax Compiled
        self._signatures = {}   # bucket -> "f32[8,16],..." trace signature
        self._program_costs = {}  # bucket -> (flops, bytes_accessed)
        self._compile_lock = threading.Lock()
        # stall heartbeat around the device sync in _resolve — the spot
        # where a hung device manifests on this path
        self._hb_resolve = _tm.stall_heartbeat("serve.dispatch")

        # -- batcher state -------------------------------------------------
        self._q = queue.SimpleQueue()
        self._worker = None
        self._worker_lock = threading.Lock()
        self._closed = False
        self._dead = None       # dispatcher crash exception, once fatal
        self._inflight = None   # the double-buffered batch (crash cleanup)
        self._pending_batch = None  # popped but not yet dispatched (ditto)

        # transient dispatch failures retry before failing the futures
        self._retries = _env_int("MXTPU_SERVE_RETRIES", 2)
        self._retry_backoff_ms = _env_int("MXTPU_SERVE_RETRY_BACKOFF_MS", 10)
        self._retry_max_ms = _env_int("MXTPU_SERVE_RETRY_MAX_MS", 1000)

        self._health_name = f"predictor:{id(self):x}"
        _tm.register_health(self._health_name, self._health)

        # -- accounting (always on: these ARE the serving stats) -----------
        self._n_requests = 0
        self._n_batches = 0
        self._n_padded_rows = 0
        self._n_batched_rows = 0  # rows that went through device batches
        self._occupancy_sum = 0.0
        self._latency_ms = Histogram("serve.latency_ms")
        self._stats_lock = threading.Lock()

        if manifest_dict is not None:
            self.warmup()

    # ------------------------------------------------------------------ gen
    def _zeros_batch(self, n):
        from ..ndarray.ndarray import NDArray
        import jax.numpy as jnp

        return [NDArray(jnp.zeros((n,) + shp, dt))
                for shp, dt in zip(self._item_shapes, self._dtypes)]

    def _check_dtype(self, i, got):
        want = self._dtypes[i]
        if onp.dtype(got) != want:
            raise MXNetError(
                f"input {i} dtype mismatch: predictor compiled for "
                f"{want.name}, got {onp.dtype(got).name} — cast the input "
                f"or rebuild the Predictor with a {onp.dtype(got).name} "
                "example")

    # ------------------------------------------------------------- programs
    def _ensure_program(self, bucket):
        prog = self._programs.get(bucket)
        if prog is not None:
            return prog
        with self._compile_lock:
            prog = self._programs.get(bucket)
            if prog is not None:
                return prog
            from ..telemetry.watchdog import format_signature

            examples = self._zeros_batch(bucket)
            prog = self._cop.aot_compile(*examples, *self._param_datas)
            self._signatures[bucket] = format_signature(
                [x._data for x in examples])
            # per-bucket XLA cost, captured once per compile (see
            # telemetry/costs.py) — credited at every dispatch below
            cost = self._tm.record_program_cost(f"serve.bucket{bucket}",
                                                prog)
            self._tm.record_program_memory(f"serve.bucket{bucket}", prog)
            self._program_costs[bucket] = (
                (cost["flops"], cost["bytes_accessed"]) if cost
                else (0.0, 0.0))
            self._programs[bucket] = prog
            return prog

    def warmup(self, manifest_path=None):
        """Precompile every bucket's program; optionally write a manifest.

        After warmup, serving any batch size causes ZERO further traces
        or compiles (asserted via the telemetry compile counters in
        tests/test_serve.py). With the persistent cache on, the XLA
        compiles inside warmup are disk hits on every process after the
        first, so a restart reaches steady-state latency before its
        first request. Returns the manifest dict.

        With the tuned kernel tier on (``MXTPU_TUNE=1``) the persisted
        per-bucket winners are preloaded FIRST, so every bucket's trace
        below resolves its kernel configs from memory — a serving
        process never measures candidates online.
        """
        from ..tune import preload as _tune_preload

        _tune_preload()
        for b in self.buckets:
            self._ensure_program(b)
        manifest = self._manifest_dict()
        if manifest_path:
            tmp = manifest_path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(manifest, fh, indent=1)
            os.replace(tmp, manifest_path)
        return manifest

    def _manifest_dict(self):
        from ..context import _probe_env_signature

        import jax

        return {
            "version": 1,
            "env_signature": _probe_env_signature(),
            "jax_version": getattr(jax, "__version__", "?"),
            "max_batch": self.max_batch,
            "buckets": list(self.buckets),
            "inputs": [{"item_shape": list(shp), "dtype": dt.name}
                       for shp, dt in zip(self._item_shapes, self._dtypes)],
            "signatures": {str(b): s for b, s in
                           sorted(self._signatures.items())},
            "cache_dir": self.cache_dir,
            "created_unix": time.time(),
        }

    # -------------------------------------------------------------- running
    def _run_program(self, bucket, datas):
        """Call the bucket's executable on raw device arrays; returns the
        MAIN output arrays (aux outputs, if any, are dropped — the trace
        runs in inference mode so there are none to write back)."""
        args = list(datas) + self._param_datas
        if self._cop._uses_rng:
            from .. import random as _rnd

            args.insert(0, _rnd._next_key())
        site = f"serve.bucket{bucket}"
        self._tm.check_memory_admission(site)
        try:
            outs = self._programs[bucket](*args)
        except Exception as e:
            self._tm.memory_oom_forensics(site, e)
            raise
        if not isinstance(outs, (tuple, list)):
            outs = (outs,)
        tm = self._tm
        if tm.ON:
            tm.record_dispatch()
            tm.record_flops(*self._program_costs.get(bucket, (0.0, 0.0)))
        return tuple(outs)[: self._n_out]

    def predict(self, data):
        """Synchronous bucketed forward of a whole batch.

        ``data``: NDArray (or numpy/jax array) with a leading batch axis,
        or a tuple of them for multi-input models. Batches larger than
        ``max_batch`` split into chunks; every chunk pads to its bucket
        and outputs are unpadded/concatenated back to exactly the input
        row count. Returns the block's output structure (NDArrays).
        """
        import jax.numpy as jnp

        from ..cached_op import unflatten_out

        if self._dead is not None:
            raise EngineDeadError(
                f"Predictor dispatcher crashed: {self._dead!r}"
            ) from self._dead
        if self._closed:
            raise MXNetError("Predictor is closed")
        NDArray = self._NDArray
        inputs = data if isinstance(data, (tuple, list)) else (data,)
        if len(inputs) != len(self._item_shapes):
            raise MXNetError(
                f"predictor compiled for {len(self._item_shapes)} inputs, "
                f"got {len(inputs)}")
        arrs = []
        for i, x in enumerate(inputs):
            x = x if isinstance(x, NDArray) else NDArray(x)
            self._check_dtype(i, x.dtype)
            if x.shape[1:] != self._item_shapes[i]:
                raise MXNetError(
                    f"input {i} item shape mismatch: predictor compiled "
                    f"for {self._item_shapes[i]}, got {x.shape[1:]}")
            arrs.append(x._data)
        n = arrs[0].shape[0]
        if any(a.shape[0] != n for a in arrs):
            raise MXNetError("all inputs must share the batch axis")

        chunk_flats, off = [], 0
        for size in split_sizes(n, self.max_batch):
            bucket = pick_bucket(size, self.buckets)
            self._ensure_program(bucket)
            pad = padded_rows(size, bucket)
            chunk = []
            for a in arrs:
                c = a[off:off + size]
                if pad:
                    c = jnp.concatenate(
                        [c, jnp.zeros((pad,) + c.shape[1:], c.dtype)])
                chunk.append(c)
            outs = self._run_program(bucket, chunk)
            chunk_flats.append([o[:size] for o in outs])
            self._account_batch(size, bucket, qdepth=0)
            off += size
        if len(chunk_flats) == 1:
            flat = chunk_flats[0]
        else:
            flat = [jnp.concatenate([c[j] for c in chunk_flats])
                    for j in range(self._n_out)]
        with self._stats_lock:
            self._n_requests += 1
        if self._tm.ON:
            self._tm.REGISTRY.counter("serve.requests").inc()
        return unflatten_out([NDArray(o) for o in flat], self._tree)

    # ------------------------------------------------------------ batching
    def submit(self, item):
        """Enqueue one request (a SINGLE item, no batch axis; tuple of
        items for multi-input models) for dynamic batching; returns a
        ``concurrent.futures.Future`` resolving to the item's output
        (numpy, in the block's output structure)."""
        if self._dead is not None:
            raise EngineDeadError(
                f"Predictor dispatcher crashed: {self._dead!r}"
            ) from self._dead
        if self._closed:
            raise MXNetError("Predictor is closed")
        items = item if isinstance(item, (tuple, list)) else (item,)
        if len(items) != len(self._item_shapes):
            raise MXNetError(
                f"predictor compiled for {len(self._item_shapes)} inputs, "
                f"got {len(items)}")
        rows = []
        for i, x in enumerate(items):
            if isinstance(x, self._NDArray):
                x = onp.asarray(x._data)
            else:
                x = onp.asarray(x)
            self._check_dtype(i, x.dtype)
            if tuple(x.shape) != self._item_shapes[i]:
                raise MXNetError(
                    f"submit() takes single items of shape "
                    f"{self._item_shapes[i]} for input {i}, got "
                    f"{tuple(x.shape)} — use predict() for whole batches")
            rows.append(x)
        req = _Request(rows, trace=self._tm.new_trace("serve.request"))
        with self._stats_lock:
            self._n_requests += 1
        if self._tm.ON:
            self._tm.REGISTRY.counter("serve.requests").inc()
        self._start_worker()
        self._q.put(req)
        return req.future

    def _start_worker(self):
        if self._worker is not None:
            return
        with self._worker_lock:
            if self._worker is None:
                t = threading.Thread(target=self._dispatch_loop,
                                     name="mxtpu-serve-dispatch",
                                     daemon=True)
                self._worker = t
                t.start()

    def _dispatch_loop(self):
        """Crash guard around the dispatcher: an uncaught error fails
        every queued and in-flight future with :class:`EngineDeadError`
        (real cause chained) and marks the predictor dead — clients get
        an exception, never a hang, and the telemetry health check fails
        (→ ``/healthz`` 503)."""
        try:
            self._dispatch_loop_impl()
        except BaseException as e:  # noqa: BLE001 — converted, never lost
            self._dispatcher_crashed(e)

    def _dispatcher_crashed(self, exc):
        self._dead = exc
        self._closed = True
        tm = self._tm
        tm.REGISTRY.counter("serve.scheduler_crashes").inc()
        if tm.ON:
            tm.event("serve.dispatcher_crash", error=repr(exc))
        err = EngineDeadError(f"Predictor dispatcher crashed: {exc!r}")
        err.__cause__ = exc
        pending, self._pending_batch = self._pending_batch, None
        for req in pending or ():
            tm.finish_trace(req.trace, status="error")
            if not req.future.done():
                req.future.set_exception(err)
        inflight, self._inflight = self._inflight, None
        if inflight is not None:
            for req in inflight[0]:
                tm.finish_trace(req.trace, status="error")
                if not req.future.done():
                    req.future.set_exception(err)
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                break
            if r is not _STOP:
                tm.finish_trace(r.trace, status="error")
                if not r.future.done():
                    r.future.set_exception(err)

    def _dispatch_loop_impl(self):
        """Dispatcher: coalesce -> pad -> transfer -> dispatch; resolve the
        PREVIOUS in-flight batch only after the next one is on the device
        (double buffering: transfer of N+1 overlaps compute of N)."""
        inflight = None
        stopping = False
        while not stopping:
            self._inflight = inflight
            try:
                first = self._q.get_nowait() if inflight is not None \
                    else self._q.get()
            except queue.Empty:
                # no follow-up traffic: settle the in-flight batch now
                # rather than withholding results while the line is idle
                self._resolve(inflight)
                inflight = None
                continue
            if first is _STOP:
                break
            if first.trace is not None:  # queue phase: submit -> picked up
                first.trace.mark("queue")
            batch = [first]
            # popped requests live in neither the queue nor _inflight until
            # dispatch returns: expose them so a loop crash fails their
            # futures instead of orphaning them
            self._pending_batch = batch
            deadline = time.perf_counter() + self.max_wait_us * 1e-6
            while len(batch) < self.max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _STOP:
                    stopping = True
                    break
                if nxt.trace is not None:
                    nxt.trace.mark("queue")
                batch.append(nxt)
            current = self._dispatch(batch)
            self._pending_batch = None
            self._resolve(inflight)
            inflight = current
        self._resolve(inflight)
        # drain whatever arrived after the stop sentinel
        leftovers = []
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                break
            if r is not _STOP:
                leftovers.append(r)
        while leftovers:
            chunk, leftovers = leftovers[:self.max_batch], \
                leftovers[self.max_batch:]
            self._pending_batch = chunk
            out = self._dispatch(chunk)
            self._pending_batch = None
            self._resolve(out)

    def _dispatch(self, batch):
        """Pad the coalesced requests into one device batch and launch the
        bucket program (both steps async). Returns (requests, outputs)."""
        import jax

        try:
            t_batch = time.perf_counter()  # batch phase: picked up -> here
            for req in batch:
                if req.trace is not None:
                    req.trace.mark("batch", t_batch)
            k = len(batch)
            bucket = pick_bucket(k, self.buckets)
            self._ensure_program(bucket)
            bufs = []
            for i, (shp, dt) in enumerate(zip(self._item_shapes,
                                              self._dtypes)):
                buf = onp.zeros((bucket,) + shp, dt)
                for r_i, req in enumerate(batch):
                    buf[r_i] = req.rows[i]
                bufs.append(buf)
            datas = [jax.device_put(b) for b in bufs]  # async H2D
            outs = self._run_retry(bucket, datas)      # async compute
            self._account_batch(k, bucket, qdepth=self._q.qsize())
            return batch, outs, bucket, time.perf_counter()
        except BaseException as e:  # noqa: BLE001 — fail the futures, not the loop
            for req in batch:
                self._tm.finish_trace(req.trace, status="error")
                if not req.future.done():
                    req.future.set_exception(e)
            return None

    def _run_retry(self, bucket, datas):
        """One program launch behind the transient-failure retry policy
        (``MXTPU_SERVE_RETRIES`` retries, exponential backoff capped at
        ``MXTPU_SERVE_RETRY_MAX_MS``); ``serve.dispatch`` is the chaos
        injection site. Exhaustion fails this batch's futures only — the
        dispatcher itself stays up for later traffic."""
        attempt = 0
        while True:
            try:
                chaos.fault_point("serve.dispatch")
                return self._run_program(bucket, datas)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — bounded retries
                if attempt >= self._retries:
                    raise
                attempt += 1
                tm = self._tm
                tm.REGISTRY.counter("serve.retries").inc()
                if tm.ON:
                    tm.event("serve.retry", point="serve.dispatch",
                             attempt=attempt, error=repr(e))
                delay_ms = min(self._retry_backoff_ms * (1 << (attempt - 1)),
                               self._retry_max_ms)
                time.sleep(delay_ms * 1e-3)

    def _resolve(self, inflight):
        """Block on an in-flight batch's device results and complete its
        futures with per-row host outputs."""
        if inflight is None:
            return
        from ..cached_op import unflatten_out

        batch, outs, bucket, t_disp = inflight
        tm = self._tm
        hb_on = tm.ON
        if hb_on:
            self._hb_resolve.begin()
        try:
            host = [onp.asarray(o) for o in outs]  # device sync happens here
        except BaseException as e:  # noqa: BLE001
            for req in batch:
                tm.finish_trace(req.trace, status="error")
                if not req.future.done():
                    req.future.set_exception(e)
            return
        finally:
            if hb_on:
                self._hb_resolve.end()
        now = time.perf_counter()
        if tm.ON:
            # dispatch->sync wall time per program: cost_report joins this
            # with the bucket's flops into achieved FLOP/s / MFU
            tm.REGISTRY.timer(f"serve.bucket{bucket}.call").record(
                now - t_disp)
        for i, req in enumerate(batch):
            out_rows = [h[i] for h in host]
            if req.trace is not None:
                req.trace.mark("compute", now)  # dispatch+device -> on host
            res = unflatten_out(out_rows, self._tree)
            if req.trace is not None:
                req.trace.mark("host")          # unpad/unflatten
                tm.finish_trace(req.trace)
            req.future.set_result(res)
            ms = (now - req.t0) * 1e3
            self._latency_ms.record(ms)
            if tm.ON:
                tm.REGISTRY.histogram("serve.latency_ms").record(ms)

    # ----------------------------------------------------------- accounting
    def _account_batch(self, k, bucket, qdepth):
        pad = padded_rows(k, bucket)
        occ = k / bucket
        with self._stats_lock:
            self._n_batches += 1
            self._n_padded_rows += pad
            self._n_batched_rows += k
            self._occupancy_sum += occ
        tm = self._tm
        if tm.ON:
            tm.REGISTRY.counter("serve.batches").inc()
            tm.REGISTRY.gauge("serve.queue_depth").set(qdepth)
            tm.REGISTRY.gauge("serve.batch_occupancy").set(occ)
            tm.REGISTRY.gauge("serve.padding_waste").set(
                pad / bucket if bucket else 0.0)
            tm.REGISTRY.counter("serve.padded_rows").inc(pad)
            tm.REGISTRY.counter("serve.batched_rows").inc(k)

    def stats(self):
        """Serving accounting independent of the global telemetry gate:
        request/batch/program counts, mean occupancy, padding waste, and
        latency percentiles (ms) over recent dynamic-batch traffic."""
        with self._stats_lock:
            n_b = self._n_batches
            pad, rows = self._n_padded_rows, self._n_batched_rows
            occ = self._occupancy_sum / n_b if n_b else 0.0
        p50, p99 = self._latency_ms.percentiles(50, 99)
        return {
            "requests": self._n_requests,
            "batches": n_b,
            "batched_rows": rows,
            "padded_rows": pad,
            "padding_waste": pad / (pad + rows) if pad + rows else 0.0,
            "mean_occupancy": occ,
            "programs": sorted(self._programs),
            "latency_ms_p50": p50,
            "latency_ms_p99": p99,
            "dead": self._dead is not None,
        }

    # -------------------------------------------------------------- health
    def _health(self):
        if self._dead is not None:
            return False, f"dispatcher crashed: {self._dead!r}"
        return True, {"closed": self._closed}

    @property
    def healthy(self):
        return self._dead is None

    # ------------------------------------------------------------ lifecycle
    def close(self):
        """Stop the dispatcher (idempotent). Outstanding futures resolve
        before the worker exits; later ``submit``/``predict`` raise."""
        try:
            self._tm.unregister_health(self._health_name)
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
        if self._closed:
            return
        self._closed = True
        worker = self._worker
        if worker is not None:
            self._q.put(_STOP)
            worker.join(timeout=30)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
