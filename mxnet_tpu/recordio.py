"""RecordIO: binary record files + image-record headers.

Reference: python/mxnet/recordio.py (MXRecordIO:36, MXIndexedRecordIO:215,
IRHeader:343, pack/unpack/pack_img) over dmlc-core recordio streams. Here the
storage engine is the native C++ library (src/io_native/recordio.cc) loaded
via ctypes, with a pure-python fallback; the file format is dmlc-recordio
compatible (magic 0xced7230a framing).
"""
from __future__ import annotations

import ctypes
import os
import struct

import numpy as onp

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xced7230a


class MXRecordIO:
    """Sequential record reader/writer (reference: recordio.py:36)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self._native = None
        self._handle = None
        self._fallback = None
        self._read_idx = 0
        self._index_cache = None
        self.open()

    def open(self):
        from .io._native import get_lib

        self._native = get_lib()
        if self.flag == "w":
            if self._native:
                self._handle = self._native.rio_writer_open(
                    self.uri.encode(), 0)
                if not self._handle:
                    raise MXNetError(f"cannot open {self.uri} for writing")
            else:
                self._fallback = open(self.uri, "wb")
        elif self.flag == "r":
            if self._native:
                self._handle = self._native.rio_reader_open(
                    self.uri.encode())
                if not self._handle:
                    raise MXNetError(f"cannot open {self.uri} for reading")
            else:
                self._fallback = open(self.uri, "rb")
        else:
            raise MXNetError(f"invalid flag {self.flag}")
        self._read_idx = 0

    def close(self):
        if self._native and self._handle:
            if self.flag == "w":
                self._native.rio_writer_close(self._handle)
            else:
                self._native.rio_reader_free(self._handle)
            self._handle = None
        if self._fallback:
            self._fallback.close()
            self._fallback = None

    def reset(self):
        self.close()
        self.open()

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- write --------------------------------------------------------------
    def write(self, buf: bytes):
        if self.flag != "w":
            raise MXNetError("recordio not opened for writing")
        if self._native:
            rc = self._native.rio_writer_write(self._handle, buf, len(buf))
            if rc != 0:
                raise MXNetError(f"record write failed (code {rc})")
        else:
            f = self._fallback
            f.write(struct.pack("<II", _MAGIC, len(buf)))
            f.write(buf)
            pad = (4 - (len(buf) & 3)) & 3
            if pad:
                f.write(b"\x00" * pad)

    # -- read ---------------------------------------------------------------
    def read(self):
        if self.flag != "r":
            raise MXNetError("recordio not opened for reading")
        if self._native:
            n = self._native.rio_reader_count(self._handle)
            if self._read_idx >= n:
                return None
            out = self._read_at(self._read_idx)
            self._read_idx += 1
            return out
        header = self._fallback.read(8)
        if len(header) < 8:
            return None
        magic, lrec = struct.unpack("<II", header)
        if magic != _MAGIC:
            return None
        length = lrec & ((1 << 29) - 1)
        data = self._fallback.read(length)
        pad = (4 - (length & 3)) & 3
        if pad:
            self._fallback.read(pad)
        return data

    def _read_at(self, idx):
        size = self._native.rio_reader_size(self._handle, idx)
        buf = ctypes.create_string_buffer(size)
        rc = self._native.rio_reader_get(self._handle, idx, buf)
        if rc != 0:
            raise MXNetError(f"record read failed at {idx}")
        return buf.raw


class MXIndexedRecordIO(MXRecordIO):
    """Random-access records with an .idx sidecar (reference: :215).

    The .idx file stores BYTE OFFSETS of record starts (stock MXNet im2rec
    convention), so shards produced by either toolchain interchange.
    """

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self._wpos = 0
        super().__init__(uri, flag)
        if flag == "r":
            self._off2ord = {}
            if self._native:
                n = self._native.rio_reader_count(self._handle)
                for i in range(n):
                    off = self._native.rio_reader_offset(self._handle, i)
                    self._off2ord[off] = i
            if os.path.exists(idx_path):
                with open(idx_path) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        if len(parts) >= 2:
                            key = key_type(parts[0])
                            self.idx[key] = int(parts[1])
                            self.keys.append(key)

    def close(self):
        if self.flag == "w" and (self._handle or self._fallback) and self.idx:
            with open(self.idx_path, "w") as f:
                for key in self.keys:
                    f.write(f"{key}\t{self.idx[key]}\n")
        super().close()

    def read_idx(self, key):
        offset = self.idx[key]
        if self._native:
            ordinal = self._off2ord.get(offset)
            if ordinal is None:
                raise MXNetError(
                    f"idx offset {offset} does not start a record in "
                    f"{self.uri} (corrupt or mismatched .idx)")
            return self._read_at(ordinal)
        # fallback: seek straight to the record
        pos = self._fallback.tell()
        self._fallback.seek(offset)
        out = self.read()
        self._fallback.seek(pos)
        return out

    def write_idx(self, key, buf):
        self.idx[key] = self._wpos
        self.keys.append(key)
        self.write(buf)
        self._wpos += 8 + len(buf) + ((4 - (len(buf) & 3)) & 3)


class IRHeader:
    """Image-record header (reference: recordio.py IRHeader:343).

    flag: number of extra float labels appended after the header.
    """

    __slots__ = ("flag", "label", "id", "id2")
    _FMT = "<IfQQ"

    def __init__(self, flag, label, id, id2=0):
        self.flag = flag
        self.label = label
        self.id = id
        self.id2 = id2


def pack(header: IRHeader, s: bytes) -> bytes:
    label = header.label
    if isinstance(label, (list, tuple, onp.ndarray)):
        label = onp.asarray(label, dtype=onp.float32)
        header = IRHeader(len(label), 0.0, header.id, header.id2)
        return struct.pack(IRHeader._FMT, header.flag, header.label,
                           header.id, header.id2) + label.tobytes() + s
    return struct.pack(IRHeader._FMT, header.flag, float(label), header.id,
                       header.id2) + s


def unpack(s: bytes):
    flag, label, id_, id2 = struct.unpack(
        IRHeader._FMT, s[:struct.calcsize(IRHeader._FMT)])
    payload = s[struct.calcsize(IRHeader._FMT):]
    if flag > 0:
        labels = onp.frombuffer(payload[:flag * 4], dtype=onp.float32)
        return IRHeader(flag, labels, id_, id2), payload[flag * 4:]
    return IRHeader(flag, label, id_, id2), payload


def pack_img(header: IRHeader, img, quality=95, img_fmt=".jpg") -> bytes:
    """Encode an image (numpy HWC uint8) into a record (PIL-backed)."""
    import io as _io

    from PIL import Image

    arr = onp.asarray(img)
    pil = Image.fromarray(arr)
    buf = _io.BytesIO()
    fmt = "JPEG" if img_fmt in (".jpg", ".jpeg") else "PNG"
    pil.save(buf, format=fmt, quality=quality)
    return pack(header, buf.getvalue())


def unpack_img(s: bytes, iscolor=1):
    import io as _io

    from PIL import Image

    header, payload = unpack(s)
    img = Image.open(_io.BytesIO(payload))
    if iscolor:
        img = img.convert("RGB")
    return header, onp.asarray(img)
