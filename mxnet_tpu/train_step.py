"""CompiledTrainStep: the WHOLE training step as one donated-buffer program.

TPU-native analog of the reference CachedOp's graph-level bulking (and of
PyGraph's whole-iteration CUDA-graph capture): forward + loss + backward +
gradient rescale + (under a mesh) the data-parallel all-reduce + the
registered optimizer recurrence trace into ONE ``jax.jit`` program with the
weight and optimizer-state buffers donated. Steady state is exactly one host
dispatch per step; the loss scalar (and BN moving-stat write-backs) are the
only things that come home.

Reuses the existing pieces instead of duplicating them:

- the forward is captured with ``_deferred_compute`` tracing and replayed by
  ``CachedOp``'s executor (``build_executor``) — the same machinery
  ``hybridize()`` uses;
- the backward is ``autograd.program_vjp`` INSIDE the trace — the transposed
  program is part of the step, not a host-side tape walk;
- the update unrolls ``Optimizer._register_step``'s pure per-tensor
  recurrence (the PR-1 declaration) per parameter;
- the data-parallel path runs the body under ``shard_map`` and reduces
  gradients with ``parallel.collectives.all_reduce``.

Hyper-parameters (lr / wd / t / rescale / loss scale) ride as RUNTIME
operands — an LR schedule or a ``DynamicLossScaler`` causes zero recompiles.
With a loss scaler the program additionally returns an overflow flag
computed in-program (finiteness of the scaled gradients); on overflow the
update is a ``where``-select no-op and the host skips the schedule commit,
matching the eager skip-on-overflow loop.
"""
from __future__ import annotations

import warnings

from .base import MXNetError
from . import telemetry as _telemetry

__all__ = ["CompiledTrainStep"]


class _Program:
    """One compiled step program + the trace metadata needed to drive it."""

    __slots__ = ("fn", "uses_rng", "aux_targets", "n_aux")

    def __init__(self, fn, uses_rng, aux_targets):
        self.fn = fn
        self.uses_rng = uses_rng
        self.aux_targets = aux_targets
        self.n_aux = len(aux_targets)


class CompiledTrainStep:
    """Callable ``(x, y) -> loss`` running the whole step as one program.

    Built via ``Trainer.compile_step(net, loss_fn)``. Semantics are those of
    the eager loop ``loss_fn(net(x), y).mean(); backward(); trainer.step(1)``
    — the loss is batch-normalized by the ``.mean()``, so the optimizer's
    ``rescale_grad`` is applied as-is (no per-call batch division).

    Falls back to the eager record/backward/``Trainer.step`` path (with a
    one-time warning, reason in ``.fallback_reason``) when the step cannot
    soundly compile: optimizer without a registered fusable recurrence
    (e.g. SGLD's host RNG), ``multi_precision`` master weights,
    ``update_on_kvstore``, a multi-worker kvstore (gradients reduce outside
    the program), or non-float trainables.
    """

    def __init__(self, trainer, net, loss_fn, mesh=None, loss_scaler=None,
                 name="train_step"):
        self.trainer = trainer
        self.net = net
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.loss_scaler = loss_scaler if loss_scaler is not None \
            else getattr(trainer, "_amp_loss_scaler", None)
        self.name = name
        self.fallback_reason = None
        self._warned = False
        self._cache = {}       # input signature -> _Program
        self._train_idx = None
        self._frozen = None
        self._state_keys = ()
        self._traces = 0       # trace-time count (observes recompiles)
        self._dispatches = 0   # compiled-program calls
        self._check_supported()

    # -- support matrix -----------------------------------------------------
    def _check_supported(self):
        tr = self.trainer
        opt = tr._optimizer
        if opt.fused_step is None:
            self.fallback_reason = (
                f"{type(opt).__name__} declares no fusable per-tensor step")
            return
        if opt.multi_precision:
            self.fallback_reason = ("multi_precision uses the per-param "
                                    "master-weight path")
            return
        if not tr._kv_initialized:
            tr._init_kvstore()
        if tr._kvstore is not None and tr._update_on_kvstore:
            self.fallback_reason = "update_on_kvstore runs the optimizer " \
                                   "on the store"
            return
        if tr._kvstore is not None and \
                not tr._kvstore.supports_compiled_step:
            self.fallback_reason = (
                f"kvstore '{tr._kvstore.type}' reduces gradients outside "
                "the program (num_workers > 1)")
            return
        if self.mesh is not None:
            from .parallel.mesh import AxisNames

            if AxisNames.DP not in self.mesh.axis_names:
                raise MXNetError(
                    f"compile_step mesh must carry a '{AxisNames.DP}' axis; "
                    f"got {self.mesh.axis_names}")

    # -- stepping -----------------------------------------------------------
    def __call__(self, x, y):
        if self.fallback_reason is not None:
            return self._eager_step(x, y)
        if self.mesh is not None:
            from .parallel.mesh import AxisNames

            n = self.mesh.shape[AxisNames.DP]
            if x.shape[0] % n:
                raise MXNetError(
                    f"batch {x.shape[0]} not divisible by the mesh's "
                    f"'{AxisNames.DP}' axis ({n} shards)")
        sig = (x.shape, str(x.dtype), y.shape, str(y.dtype))
        prog = self._cache.get(sig)
        if prog is None:
            prog = self._build(x, y)
            if prog is None:  # trace discovered an unsupported layout
                return self._eager_step(x, y)
            self._cache[sig] = prog
        return self._run(prog, x, y)

    # -- tracing ------------------------------------------------------------
    def _collect(self):
        """Partition parameters into trainables (trainer order) and frozen
        trace variables. EVERY initialized parameter of the net — including
        BN running stats and other ``grad_req='null'`` state — becomes an
        explicit graph input: an unmarked array would be captured as a baked
        CONSTANT by the tracer, so step N+1 would silently read step 0's
        stats (and a donated update could never reach them)."""
        tr = self.trainer
        train_idx = []
        for i, p in enumerate(tr._params):
            if p.grad_req == "null":
                continue
            if p._data is None:
                raise MXNetError(
                    f"parameter {p.name} not initialized — initialize the "
                    "net (and run a settle forward for deferred shapes) "
                    "before compile_step")
            train_idx.append(i)
        if not train_idx:
            return None, None, "no trainable parameters"
        seen = {id(tr._params[i]) for i in train_idx}
        frozen = []
        for pname, p in self.net.collect_params().items():
            if id(p) not in seen and p._data is not None:
                frozen.append((pname, p))
        import jax.numpy as jnp

        for i in train_idx:
            if not jnp.issubdtype(tr._params[i].data().dtype, jnp.floating):
                return None, None, \
                    f"non-float trainable parameter {tr._params[i].name}"
        return train_idx, frozen, None

    def _build(self, x, y):
        import jax
        import jax.numpy as jnp

        from . import _deferred_compute as dc
        from . import autograd as ag
        from .cached_op import build_executor

        tr = self.trainer
        opt = tr._optimizer
        with ag.train_mode():
            if any(p._data is None
                   for p in self.net.collect_params().values()):
                with ag.pause():  # settle deferred-shape init, no BN writes
                    self.net(x)
        train_idx, frozen, reason = self._collect()
        if reason is not None:
            self.fallback_reason = reason
            return None
        raw, state_keys, needs_t, _ = opt.fused_step
        for i in train_idx:
            if tr._states[i] is None:
                tr._states[i] = opt.create_state_multi_precision(
                    i, tr._params[i].data())
            if any(k not in tr._states[i] for k in state_keys):
                self.fallback_reason = (
                    f"optimizer state for {tr._params[i].name} lacks "
                    f"{state_keys} (restored from an older run?)")
                return None
        self._train_idx = train_idx
        self._frozen = frozen
        self._state_keys = state_keys

        # --- capture the forward+loss graph (the hybridize machinery) ------
        with ag.train_mode(), dc.context() as tctx:
            dvars = [dc.set_variable(x, "data0"), dc.set_variable(y, "label0")]
            wvars = [dc.set_variable(tr._params[i].data(), f"w{i}")
                     for i in train_idx]
            fvars = [dc.set_variable(p.data(), pname)
                     for pname, p in frozen]
            loss = self.loss_fn(self.net(x), y).mean()
            if loss._dc_sym is None:
                self.fallback_reason = \
                    "loss is not connected to the traced forward"
                return None
            entries = [loss._dc_sym] + [e for _, e in tctx.aux_updates]
            aux_targets = [t for t, _ in tctx.aux_updates]
            fwd, uses_rng = build_executor(entries, dvars + wvars + fvars)

        n_train = len(train_idx)
        n_aux = len(aux_targets)
        n_state = len(state_keys)
        scaler_on = self.loss_scaler is not None
        mesh = self.mesh
        site = f"train_step:{self.name}"
        attrs = (f"n_params={n_train} n_aux={n_aux} "
                 f"opt={type(opt).__name__} scaler={scaler_on} "
                 f"mesh={mesh is not None}")

        def body(ws, ss, fs, xb, yb, key, lrs, wds, ts, rescale, loss_scale):
            # executes at TRACE time only: the python loop unrolls into one
            # program, and the observers below count recompiles, not calls
            self._traces += 1
            _telemetry.record_compile(site, (ws, xb), attrs=attrs)
            if mesh is not None and uses_rng:
                from .parallel import collectives as coll

                # per-shard dropout masks: fold the shard index into the key
                key = jax.random.fold_in(key, coll.axis_index("dp"))

            def lfn(w_tuple):
                args = ([key] if uses_rng else []) + [xb, yb] + \
                    list(w_tuple) + list(fs)
                return fwd(*args)

            # backward INSIDE the trace, seeded with the loss scale so a
            # DynamicLossScaler update never retraces (autograd.program_vjp)
            outs, (grads,) = ag.program_vjp(lfn, (tuple(ws),), loss_scale)
            loss_v, aux = outs[0], list(outs[1:])
            if mesh is not None:
                from .parallel import collectives as coll

                # the data-parallel reduction, scheduled by XLA against the
                # backward it interleaves with (the kvstore pushpull role)
                grads = tuple(coll.all_reduce(g, "dp", op="mean")
                              for g in grads)
                loss_v = coll.all_reduce(loss_v, "dp", op="mean")
                aux = [coll.all_reduce(a, "dp", op="mean") for a in aux]
            # overflow = non-finite SCALED grads, the quantity the eager
            # LossScaler.has_overflow inspects (before unscale)
            finite = jnp.bool_(True)
            for g in grads:
                finite = jnp.logical_and(finite,
                                         jnp.all(jnp.isfinite(g)))
            overflow = jnp.logical_not(finite)
            new_ws, new_ss = [], []
            for k in range(n_train):
                g = grads[k] * rescale
                args = [ws[k], *ss[k], g, lrs[k], wds[k]]
                if needs_t:
                    args.append(ts[k])
                out = raw(*args)
                if n_state:
                    nw, ns = out[0], tuple(out[1:])
                else:
                    nw, ns = out, ()
                if scaler_on:
                    # skip-on-overflow as a select: the step ran, the
                    # weights didn't move (eager: trainer.step is skipped)
                    nw = jnp.where(overflow, ws[k], nw)
                    ns = tuple(jnp.where(overflow, s0, s1)
                               for s0, s1 in zip(ss[k], ns))
                new_ws.append(nw)
                new_ss.append(ns)
            return loss_v, tuple(aux), new_ws, new_ss, overflow

        fn = body
        if mesh is not None:
            from .parallel.mesh import P, shard_map_compat

            dp = P("dp")
            fn = shard_map_compat(
                body, mesh,
                in_specs=(P(), P(), P(), dp, dp, P(), P(), P(), P(), P(),
                          P()),
                out_specs=P())
        return _Program(jax.jit(fn, donate_argnums=(0, 1)), uses_rng,
                        aux_targets)

    # -- the compiled step --------------------------------------------------
    def _run(self, prog, x, y):
        import jax.numpy as jnp
        import numpy as onp

        tr = self.trainer
        opt = tr._optimizer
        idxs = self._train_idx
        keys = self._state_keys
        scaler = self.loss_scaler
        ws = [tr._params[i].data()._data for i in idxs]
        ss = [tuple(tr._states[i][k]._data for k in keys) for i in idxs]
        fs = [p.data()._data for _, p in self._frozen]
        if prog.uses_rng:
            from . import random as _rnd

            key = _rnd._next_key()
        else:
            key = jnp.zeros((2,), jnp.uint32)
        # scalar schedule inputs are RUNTIME operands (the trainer rule):
        # counts are STAGED, not committed — an overflow-skipped step must
        # leave the schedule exactly where the eager skip would
        counts, num_update = opt._staged_counts(idxs)
        ts = onp.asarray(counts, onp.float32)
        lrs = onp.asarray([opt._get_lr(i, num_update=num_update)
                           for i in idxs], onp.float32)
        wds = onp.asarray([opt._get_wd(i) for i in idxs], onp.float32)
        scale = float(scaler.loss_scale) if scaler is not None else 1.0
        rescale = onp.float32(tr._scale / scale)
        loss_scale = onp.float32(scale)
        self._dispatches += 1
        if _telemetry.ON:
            # ONE compiled-program call per step; this bypasses the
            # invoke() chokepoint, so count the dispatch here
            _telemetry.record_dispatch()
            with _telemetry.program_timer("train_step"):
                out = prog.fn(ws, ss, fs, x._data, y._data, key, lrs, wds,
                              ts, rescale, loss_scale)
        else:
            out = prog.fn(ws, ss, fs, x._data, y._data, key, lrs, wds, ts,
                          rescale, loss_scale)
        loss_v, aux, new_ws, new_ss, overflow = out
        for k, i in enumerate(idxs):
            tr._params[i].data()._set_data(new_ws[k])
            for sk, arr in zip(keys, new_ss[k]):
                tr._states[i][sk]._set_data(arr)
        # aux write-backs happen regardless of overflow: BN stats update
        # during the forward, before the eager loop could inspect grads
        for target, arr in zip(prog.aux_targets, aux):
            target._set_data(arr)
        if scaler is not None:
            ovf = bool(overflow)  # the step's only host sync (1 byte)
            scaler.update_scale(ovf)
        else:
            ovf = False
        if not ovf:
            opt._commit_counts(idxs)
        if _telemetry.ON:
            _telemetry.mark_step()
        from .ndarray.ndarray import NDArray

        return NDArray(loss_v)

    # -- the uncompiled fallback -------------------------------------------
    def _eager_step(self, x, y):
        from . import autograd as ag

        if not self._warned:
            warnings.warn(
                f"compile_step: falling back to the eager path — "
                f"{self.fallback_reason}", RuntimeWarning, stacklevel=3)
            self._warned = True
        tr = self.trainer
        scaler = self.loss_scaler
        with ag.record():
            loss = self.loss_fn(self.net(x), y).mean()
            head = loss if scaler is None else loss * float(scaler.loss_scale)
        head.backward()
        if scaler is not None:
            if scaler.has_overflow(tr._params):
                scaler.update_scale(True)
                if _telemetry.ON:
                    _telemetry.mark_step()
                return loss
            for p in tr._params:
                if p.grad_req != "null" and p._data is not None:
                    g = p.grad()
                    g._set_data(g._data / scaler.loss_scale)
            scaler.update_scale(False)
        tr.step(1)  # the loss carries the batch mean already
        return loss
