"""CompiledTrainStep: the WHOLE training step as one donated-buffer program.

TPU-native analog of the reference CachedOp's graph-level bulking (and of
PyGraph's whole-iteration CUDA-graph capture): forward + loss + backward +
gradient rescale + (under a mesh) the data-parallel reduction + the
registered optimizer recurrence trace into ONE ``jax.jit`` program with the
weight and optimizer-state buffers donated. Steady state is exactly one host
dispatch per step; the loss scalar (and BN moving-stat write-backs) are the
only things that come home.

Reuses the existing pieces instead of duplicating them:

- the forward is captured with ``_deferred_compute`` tracing and replayed by
  ``CachedOp``'s executor (``build_executor``) — the same machinery
  ``hybridize()`` uses;
- the backward is ``autograd.program_vjp`` INSIDE the trace — the transposed
  program is part of the step, not a host-side tape walk;
- the update unrolls ``Optimizer._register_step``'s pure per-tensor
  recurrence (the PR-1 declaration) per parameter;
- the data-parallel path runs the body under ``shard_map`` and reduces
  gradients with ``parallel.collectives``.

Hyper-parameters (lr / wd / t / rescale / loss scale) ride as RUNTIME
operands — an LR schedule or a ``DynamicLossScaler`` causes zero recompiles.
With a loss scaler the program additionally returns an overflow flag
computed in-program (finiteness of the scaled gradients); on overflow the
update is a ``where``-select no-op and the host skips the schedule commit,
matching the eager skip-on-overflow loop.

Sharded weight update (ZeRO-1)
------------------------------
With a data-parallel mesh the replicated schedule runs the *identical*
optimizer update on every replica — weight-update FLOPs and optimizer state
(2x weights for Adam) duplicated N ways. ``shard_update`` (auto-on when the
mesh's 'dp' axis has >= 2 shards and the optimizer's recurrence is
elementwise) applies the schedule of Xu et al., "Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training": the grad ``pmean``
becomes a ``reduce_scatter`` over flat per-dtype parameter buckets (padded
to a multiple of the dp extent), the recurrence runs only on each replica's
contiguous 1/N shard with optimizer state ALLOCATED sharded from
initialization, and an ``all_gather`` rebuilds the full weights — all
inside the same single donated-buffer program, where XLA overlaps the
collectives with the update on ICI. Per-replica update FLOPs and optimizer
state drop ~Nx.

Bit parity: with an elementwise optimizer, BOTH ``shard_update`` settings
dispatch the SAME compiled program — the ZeRO-1 schedule above, with state
entering as dp-sharded buckets. They differ only in state RESIDENCY
between steps: sharded keeps the persistent 1/N shard buckets (the memory
win), replicated keeps the classic per-param arrays in
``trainer._states`` and reshards them around each dispatch (inspectable
state and the pre-existing checkpoint layout, at the cost of one state
scatter + gather per step). Identical program + identical inputs means
bitwise-identical weights, unconditionally. Structurally different
sharded/replicated programs do NOT give that: XLA's global layout and
fusion passes then round a few gradient elements differently (1 ulp,
input-dependent), and neither ``optimization_barrier`` (expanded away
before fusion) nor ``reduce_precision`` pinning (reassociated across, and
a no-op in the CPU emitter) recovers parity. Non-elementwise fused
optimizers (trust-ratio / whole-tensor reductions) keep the per-tensor
psum update, replicated on every device.

Full-parameter sharding (ZeRO-3 / FSDP)
---------------------------------------
ZeRO-1 still keeps a FULL copy of every weight on every replica between
steps. ``shard_params`` goes the rest of the way: parameters AND optimizer
state live as per-layer flat buckets sharded 1/N over 'dp' end-to-end.
Which trainables shard is decided by regex partition rules
(``parallel.partition.match_partition_rules``; default: everything
non-scalar over 'dp'); ``parallel.partition.fsdp_groups`` folds them into
one ``BucketSpec`` per (layer, dtype) — scalars and explicitly-replicated
leaves pool into small replicated buckets updated identically everywhere.

Inside the single donated program each layer's bucket is ``all_gather``ed
just-in-time where the forward first needs it; with rematerialization on
(``MXTPU_FSDP_REMAT``, default ``dots`` = ``jax.checkpoint`` with the
``dots_saveable`` policy) the backward re-gathers instead of keeping full
weights live, so peak weight residency tracks the largest layer, not the
model. The gradient needs NO explicit reduce for sharded buckets: the vjp
transpose of a tiled ``all_gather`` IS ``psum_scatter``, so gradients
arrive pre-reduced in the owning shard's layout. The recurrence then runs
on resident shards and its outputs STAY sharded — there is no trailing
weight all-gather; the next step's forward gathers again. Per-replica
param + grad + optimizer-state residency all drop ~Nx (the residency
gauges ``train_step.param/grad/opt_state_bytes_per_replica`` report it).

Between steps ``Parameter._data`` is released: ``data()`` materializes a
full value on demand from the bucket (host gather — checkpoints and
inspection, not the hot path), ``set_data`` writes through into the
bucket, and checkpoints keep the classic per-param layout in both
directions. Because the FSDP program is STRUCTURALLY different from the
replicated/ZeRO-1 one, its trajectory may differ by XLA's input-dependent
1-ulp rounding (see above) — parity with the other modes is numerical
(tight tolerance), not bitwise; checkpoint round-trips remain bitwise.
"""
from __future__ import annotations

import os

from .base import MXNetError, warn_once
from . import telemetry as _telemetry

__all__ = ["CompiledTrainStep"]


def train_donate_argnums():
    """Donation spec for the whole-step programs: ``(0, 1)`` (weights,
    optimizer state) on accelerators, ``()`` on XLA:CPU.

    Buffer donation is the TPU memory win (update in place instead of
    holding two copies of params + state). On the CPU backend it buys
    nothing — host RAM is not the constraint — and XLA:CPU's donation
    aliasing is unsound under the multi-device host mesh: donated buffers
    can be freed while an aliased output chain still lives on them, and
    once the heap reuses the memory the live weights/state get scribbled
    (nondeterministic NaN/garbage a few steps later; reproduced by
    tests/test_multi_step.py parity after enough allocator churn).
    ``MXTPU_DONATE=0/1`` forces either behavior for A/B studies."""
    env = os.environ.get("MXTPU_DONATE")
    if env is not None:
        return (0, 1) if env.strip().lower() not in ("0", "false", "off") \
            else ()
    import jax

    return () if jax.default_backend() == "cpu" else (0, 1)


class _Program:
    """One compiled step program + the trace metadata needed to drive it."""

    __slots__ = ("fn", "uses_rng", "aux_targets", "n_aux", "sharded",
                 "fsdp", "coll_bytes", "coll_bytes_tp", "compiled", "flops",
                 "bytes_accessed", "k", "accum", "health_mode",
                 "health_groups")

    def __init__(self, fn, uses_rng, aux_targets, sharded=False, fsdp=False,
                 coll_bytes=(0, 0, 0), coll_bytes_tp=0, k=None, accum=1,
                 health_mode="off", health_groups=None):
        self.fn = fn
        self.uses_rng = uses_rng
        self.aux_targets = aux_targets
        self.n_aux = len(aux_targets)
        self.sharded = sharded
        self.fsdp = fsdp
        # (reduce_scatter, all_gather, psum) bytes per call, known at build
        # time — the host's only window into in-program collective traffic
        self.coll_bytes = coll_bytes
        # 'tp'-axis collective payload per call (megatron psums/gathers),
        # accounted by the op fallbacks during the eager trace
        self.coll_bytes_tp = coll_bytes_tp
        # the jax Compiled, bound at first _run via explicit lower+compile
        # (same single XLA compile the implicit jit call would pay, but
        # the executable handle stays reachable for cost_analysis)
        self.compiled = None
        self.flops = 0.0
        self.bytes_accessed = 0.0
        # multi-step super-step shape: k scanned optimizer steps, each
        # accumulating `accum` microbatches; k=None is the single-step path
        self.k = k
        self.accum = accum
        # in-program numerics monitor: MXTPU_NUMERICS mode baked into the
        # trace and the layer-group labels of its nonfinite-count vector
        # (None = monitor off, program emits no health outputs)
        self.health_mode = health_mode
        self.health_groups = health_groups


class _ShardedOptState:
    """ZeRO-1 optimizer state: flat per-dtype buckets sharded over 'dp'.

    Each state key of each bucket is ONE global ``(padded,)`` f32
    ``jax.Array`` under ``NamedSharding(mesh, P('dp'))`` — every replica
    materializes only its contiguous 1/N shard, from the very first
    allocation (``parallel.mesh.zeros_sharded``). While this is live it is
    the source of truth: the trainer's per-param ``_states`` stay ``None``
    and checkpoints gather back to the per-param layout (identical pickle
    format to the replicated path) and re-scatter on load.

    Gathering assumes all shards are addressable by this process (single
    controller / host-platform mesh); a multi-host checkpoint would use a
    distributed array serializer instead.
    """

    def __init__(self, mesh, opt, trainer, train_idx, buckets, state_keys):
        self.mesh = mesh
        self.opt = opt
        self.trainer = trainer
        self.train_idx = train_idx
        self.buckets = buckets          # [(dtype_str, ks, BucketSpec)]
        self.state_keys = state_keys
        self.state = []                 # per bucket: tuple over keys
        self._init()
        # gauges are samples, set once per build — no ON guard needed
        _telemetry.gauge("train_step.opt_state_bytes_per_replica").set(
            self.per_replica_state_bytes())
        _telemetry.gauge("train_step.opt_state_bytes_replicated").set(
            self.replicated_state_bytes())

    # -- allocation ---------------------------------------------------------
    def _init(self):
        from .parallel.mesh import zeros_sharded, P
        import jax.numpy as jnp

        tr, keys = self.trainer, self.state_keys
        for _, ks, bs in self.buckets:
            if not keys:
                self.state.append(())
                continue
            idxs = [self.train_idx[k] for k in ks]
            if all(tr._states[i] is None for i in idxs):
                # fresh run: allocate zeros DIRECTLY sharded — no replica
                # ever holds the full state (every registered elementwise
                # recurrence zero-initializes its state)
                self.state.append(tuple(
                    zeros_sharded(self.mesh, (bs.padded,), jnp.float32,
                                  P("dp"))
                    for _ in keys))
            else:
                # resumed/mixed: scatter the existing full state
                for i in idxs:
                    if tr._states[i] is None:
                        tr._states[i] = \
                            self.opt.create_state_multi_precision(
                                i, tr._params[i].data())
                self.state.append(self._scatter_bucket(ks, bs))
                for i in idxs:
                    tr._states[i] = None  # sharded buckets own it now

    def _scatter_bucket(self, ks, bs):
        import jax
        from .parallel.mesh import shard_1d

        tr = self.trainer
        sharding = shard_1d(self.mesh)
        return tuple(
            jax.device_put(bs.flatten_host(
                [tr._states[self.train_idx[k]][key].asnumpy() for k in ks]),
                sharding)
            for key in self.state_keys)

    # -- step rebind --------------------------------------------------------
    def rebind(self, new_state):
        """Adopt the program's donated-output state buckets."""
        self.state = [tuple(st) for st in new_state]

    # -- checkpoint bridge --------------------------------------------------
    def gather_states(self):
        """Per-param full state dicts (the replicated pickle layout)."""
        import numpy as onp
        from .ndarray.ndarray import NDArray

        out = [None] * len(self.trainer._params)
        for (_, ks, bs), st in zip(self.buckets, self.state):
            for key, arr in zip(self.state_keys, st):
                flat = onp.asarray(arr)  # gathers every shard to host
                for k, off, n, shape in zip(ks, bs.offsets, bs.sizes,
                                            bs.shapes):
                    i = self.train_idx[k]
                    if out[i] is None:
                        out[i] = {}
                    out[i][key] = NDArray(flat[off:off + n].reshape(shape))
        return out

    def scatter_from_trainer(self):
        """Re-shard after ``Trainer.load_states`` refilled ``_states``."""
        tr = self.trainer
        state = []
        for _, ks, bs in self.buckets:
            idxs = [self.train_idx[k] for k in ks]
            for i in idxs:
                if tr._states[i] is None:
                    tr._states[i] = self.opt.create_state_multi_precision(
                        i, tr._params[i].data())
            state.append(self._scatter_bucket(ks, bs))
            for i in idxs:
                tr._states[i] = None
        self.state = state

    # -- accounting ---------------------------------------------------------
    def per_replica_state_bytes(self):
        """Bytes of optimizer state ONE replica holds (its shards)."""
        total = 0
        for st in self.state:
            for arr in st:
                total += arr.addressable_shards[0].data.nbytes
        return total

    def replicated_state_bytes(self):
        """What the replicated path would hold per replica (full state)."""
        return sum(bs.total * 4 * len(self.state_keys)
                   for _, _, bs in self.buckets)


class _FSDPState:
    """FSDP residency: parameters AND optimizer state as per-layer flat
    buckets sharded 1/N over 'dp', end-to-end.

    Unlike ``_ShardedOptState`` (ZeRO-1: full weights between steps,
    sharded state only), nothing full-sized persists anywhere. On adoption
    the per-param ``Parameter._data`` buffers are released and replaced by
    bucket images (``BucketSpec.flatten_host`` + one ``device_put`` under
    ``P('dp')`` per sharded group; replicated pools go up whole);
    ``Parameter.data()`` then materializes a full value on demand from the
    bucket and ``set_data`` writes through into it — checkpoints and
    inspection keep working in the classic per-param layout. Re-traces of
    the step (new batch signature) need the stable NDArray objects the
    deferred-compute variables bind to, so ``materialize_into_params`` /
    ``release_params`` bracket each build.

    The checkpoint bridge (``gather_states``/``scatter_from_trainer``) and
    the residency gauges mirror ``_ShardedOptState`` so
    ``Trainer.save_states``/``load_states`` and dashboards are mode-
    agnostic. The single-controller gather caveat applies here too.

    dp x tp: a group with ``sharded == "tp"`` (a megatron rule matched it)
    holds ONE flat bucket of the GLOBAL length ``tp * BucketSpec.padded``
    under ``NamedSharding(mesh, P(('tp', 'dp')))`` — tp-major, so the
    contiguous 1/tp blocks are the per-rank LOCAL flat images, each
    dp-sharded exactly like a plain dp group. Inside the program the
    existing per-layer ``all_gather(..., 'dp')`` then rebuilds each tp
    rank's local image unchanged, and its AD transpose psum_scatters over
    'dp' only (correct: tp ranks own disjoint parameters). The host
    layouts (``parallel.tp.local_slice``/``merge_local``) are pure index
    permutations, so the per-param checkpoint layout stays bitwise.
    """

    def __init__(self, mesh, opt, trainer, train_idx, groups, state_keys,
                 tp_places=None, tp_size=1):
        self.mesh = mesh
        self.opt = opt
        self.trainer = trainer
        self.train_idx = train_idx
        self.groups = groups   # [(layer, dtype, ks, BucketSpec, sharded)]
        self.state_keys = state_keys
        self.tp_places = tp_places or {}  # train pos k -> (dim, segments)
        self.tp_size = int(tp_size)
        self.params = []       # per group: flat bucket jax.Array
        self.state = []        # per group: tuple over state keys
        self._where = {}       # train position k -> (group idx, slot idx)
        for gi, (_, _, ks, _, _) in enumerate(groups):
            for si, k in enumerate(ks):
                self._where[k] = (gi, si)
        self._adopt_params()
        self._init_state()
        p_shard = self.per_replica_param_bytes()
        _telemetry.gauge("train_step.param_bytes_per_replica").set(p_shard)
        _telemetry.gauge("train_step.param_bytes_replicated").set(
            self.replicated_param_bytes())
        # gradients exist only transiently in-program, pre-scattered into
        # the same shard layout — their residency bound IS the shard bytes
        _telemetry.gauge("train_step.grad_bytes_per_replica").set(p_shard)
        _telemetry.gauge("train_step.opt_state_bytes_per_replica").set(
            self.per_replica_state_bytes())
        _telemetry.gauge("train_step.opt_state_bytes_replicated").set(
            self.replicated_state_bytes())

    def _sharding(self, sharded):
        from .parallel.mesh import replicated, shard_1d

        if sharded == "tp":
            import jax

            from .parallel.mesh import P

            return jax.sharding.NamedSharding(self.mesh, P(("tp", "dp")))
        return shard_1d(self.mesh) if sharded else replicated(self.mesh)

    def _group_image(self, values, ks, bs, sh, dtype=None):
        """Host flat image for one group from full per-param arrays. tp
        groups concatenate the per-rank local flat images tp-major (each
        independently padded to the dp extent) — the exact layout
        ``P(('tp', 'dp'))`` shards contiguously."""
        kw = {"dtype": dtype} if dtype is not None else {}
        if sh != "tp":
            return bs.flatten_host(values, **kw)
        import numpy as onp

        from .parallel import tp as _tp

        outs = []
        for r in range(self.tp_size):
            locs = [_tp.local_slice(v, self.tp_places[k][0], r,
                                    self.tp_size, self.tp_places[k][1])
                    for k, v in zip(ks, values)]
            outs.append(bs.flatten_host(locs, **kw))
        return onp.concatenate(outs)

    # -- adoption -----------------------------------------------------------
    def _adopt_params(self):
        import jax

        tr = self.trainer
        for _, dt, ks, bs, sh in self.groups:
            img = self._group_image(
                [tr._params[self.train_idx[k]].data().asnumpy()
                 for k in ks], ks, bs, sh, dtype=dt)
            self.params.append(jax.device_put(img, self._sharding(sh)))
        # release the full per-param buffers; data()/set_data route here
        for k, i in enumerate(self.train_idx):
            p = tr._params[i]
            p._provider = (self, k)
            p._data = None

    def _init_state(self):
        from .parallel.mesh import P, zeros_sharded
        import jax.numpy as jnp

        tr, keys = self.trainer, self.state_keys
        for _, _, ks, bs, sh in self.groups:
            if not keys:
                self.state.append(())
                continue
            idxs = [self.train_idx[k] for k in ks]
            if all(tr._states[i] is None for i in idxs):
                if sh == "tp":
                    spec, length = P(("tp", "dp")), bs.padded * self.tp_size
                else:
                    spec, length = (P("dp"), bs.padded) if sh \
                        else (P(), bs.padded)
                self.state.append(tuple(
                    zeros_sharded(self.mesh, (length,), jnp.float32,
                                  spec)
                    for _ in keys))
            else:
                for i in idxs:
                    if tr._states[i] is None:
                        tr._states[i] = \
                            self.opt.create_state_multi_precision(
                                i, tr._params[i].data())
                self.state.append(self._scatter_group(ks, bs, sh))
                for i in idxs:
                    tr._states[i] = None  # the buckets own it now

    def _scatter_group(self, ks, bs, sh):
        import jax

        tr = self.trainer
        sharding = self._sharding(sh)
        return tuple(
            jax.device_put(self._group_image(
                [tr._states[self.train_idx[k]][key].asnumpy() for k in ks],
                ks, bs, sh),
                sharding)
            for key in self.state_keys)

    # -- Parameter provider hooks -------------------------------------------
    def _stitch(self, flat, k, si, bs):
        """One parameter's FULL value out of a tp group's global flat
        bucket: merge the per-rank local images (bitwise permutation)."""
        from .parallel import tp as _tp

        off, n = bs.offsets[si], bs.sizes[si]
        dim, seg = self.tp_places[k]
        parts = [flat[r * bs.padded + off: r * bs.padded + off + n]
                 .reshape(bs.shapes[si]) for r in range(self.tp_size)]
        return _tp.merge_local(parts, dim, segments=seg)

    def param_ndarray(self, k):
        """Materialize one adopted parameter's FULL value (host gather of
        its group bucket) — the checkpoint/inspection path."""
        import numpy as onp
        from .ndarray.ndarray import NDArray

        gi, si = self._where[k]
        _, _, _, bs, sh = self.groups[gi]
        flat = onp.asarray(self.params[gi])  # gathers every shard to host
        if sh == "tp":
            return NDArray(self._stitch(flat, k, si, bs))
        off, n = bs.offsets[si], bs.sizes[si]
        return NDArray(flat[off:off + n].reshape(bs.shapes[si]))

    def param_write(self, k, value):
        """Write-through ``set_data`` for an adopted parameter: rebuild the
        group's bucket image with the new slice (the load/re-init path)."""
        import jax
        import numpy as onp

        gi, si = self._where[k]
        _, dt, _, bs, sh = self.groups[gi]
        flat = onp.asarray(self.params[gi]).copy()
        off, n = bs.offsets[si], bs.sizes[si]
        v = onp.asarray(value).astype(onp.dtype(dt), copy=False)
        if sh == "tp":
            from .parallel import tp as _tp

            dim, seg = self.tp_places[k]
            for r in range(self.tp_size):
                flat[r * bs.padded + off: r * bs.padded + off + n] = \
                    _tp.local_slice(v, dim, r, self.tp_size, seg).reshape(-1)
        else:
            flat[off:off + n] = v.reshape(-1)
        self.params[gi] = jax.device_put(flat, self._sharding(sh))

    # -- re-trace bracket ---------------------------------------------------
    def materialize_into_params(self):
        """Temporarily restore full per-param ``_data`` (from the buckets)
        so a re-trace binds its variables to the stable NDArray objects the
        forward will read; ``release_params`` drops them again."""
        tr = self.trainer
        for k, i in enumerate(self.train_idx):
            if tr._params[i]._data is None:
                tr._params[i]._data = self.param_ndarray(k)

    def release_params(self):
        tr = self.trainer
        for i in self.train_idx:
            tr._params[i]._data = None

    # -- step rebind --------------------------------------------------------
    def rebind(self, new_params, new_state):
        """Adopt the program's donated-output param + state buckets."""
        self.params = list(new_params)
        self.state = [tuple(st) for st in new_state]

    # -- checkpoint bridge --------------------------------------------------
    def gather_states(self):
        """Per-param full state dicts (the replicated pickle layout)."""
        import numpy as onp
        from .ndarray.ndarray import NDArray

        out = [None] * len(self.trainer._params)
        for (_, _, ks, bs, sh), st in zip(self.groups, self.state):
            for key, arr in zip(self.state_keys, st):
                flat = onp.asarray(arr)
                for si, (k, off, n, shape) in enumerate(
                        zip(ks, bs.offsets, bs.sizes, bs.shapes)):
                    i = self.train_idx[k]
                    if out[i] is None:
                        out[i] = {}
                    if sh == "tp":
                        out[i][key] = NDArray(self._stitch(flat, k, si, bs))
                    else:
                        out[i][key] = NDArray(
                            flat[off:off + n].reshape(shape))
        return out

    def scatter_from_trainer(self):
        """Re-shard after ``Trainer.load_states`` refilled ``_states``."""
        tr = self.trainer
        state = []
        for _, _, ks, bs, sh in self.groups:
            idxs = [self.train_idx[k] for k in ks]
            for i in idxs:
                if tr._states[i] is None:
                    tr._states[i] = self.opt.create_state_multi_precision(
                        i, tr._params[i].data())
            state.append(self._scatter_group(ks, bs, sh))
            for i in idxs:
                tr._states[i] = None
        self.state = state

    # -- accounting ---------------------------------------------------------
    def per_replica_param_bytes(self):
        from .parallel.mesh import bytes_per_replica

        return sum(bytes_per_replica(b) for b in self.params)

    def replicated_param_bytes(self):
        """What unsharded residency would hold per replica (full weights).
        tp groups store per-rank LOCAL shapes — scale back up."""
        import numpy as onp

        return sum(bs.total * onp.dtype(dt).itemsize *
                   (self.tp_size if sh == "tp" else 1)
                   for _, dt, _, bs, sh in self.groups)

    def per_replica_state_bytes(self):
        from .parallel.mesh import bytes_per_replica

        return sum(bytes_per_replica(a) for st in self.state for a in st)

    def replicated_state_bytes(self):
        return sum(bs.total * 4 * len(self.state_keys) *
                   (self.tp_size if sh == "tp" else 1)
                   for _, _, _, bs, sh in self.groups)


class CompiledTrainStep:
    """Callable ``(x, y) -> loss`` running the whole step as one program.

    Built via ``Trainer.compile_step(net, loss_fn)``. Semantics are those of
    the eager loop ``loss_fn(net(x), y).mean(); backward(); trainer.step(1)``
    — the loss is batch-normalized by the ``.mean()``, so the optimizer's
    ``rescale_grad`` is applied as-is (no per-call batch division).

    ``shard_update`` (default: auto-on when the mesh carries a 'dp' axis of
    size >= 2 and the optimizer's recurrence is elementwise; forced by
    ``MXTPU_SHARD_UPDATE=0/1``) runs the ZeRO-1 reduce-scatter →
    shard-update → all-gather schedule with 1/N-sharded optimizer state —
    see the module docstring. Unsupported configurations keep the replicated
    in-program update with a one-time warning
    (reason in ``.shard_fallback_reason``).

    ``shard_params`` (default: auto-on when additionally the trainables
    total at least ``MXTPU_SHARD_PARAMS_AUTO_MB`` MiB, 256 by default —
    decided at first build, when shapes are known; forced by
    ``MXTPU_SHARD_PARAMS=0/1``) goes full FSDP: parameters AND optimizer
    state live dp-sharded between steps, gathered just-in-time per layer
    inside the program — see the module docstring. ``partition_rules``
    (ordered ``(regex, PartitionSpec)`` pairs, default
    ``parallel.partition.fsdp_rules()``) decide which trainables shard.
    FSDP supersedes ``shard_update`` (the weights are already sharded; the
    ZeRO-1 trailing all-gather would undo the point). Unsupported explicit
    requests keep the unsharded residency with a one-time warning (reason
    in ``.shard_params_fallback_reason``).

    A batch not divisible by the dp extent is padded IN-PROGRAM with
    zero-example-weight rows (the loss becomes the weighted mean over the
    real rows, so gradients and the loss value match the unpadded batch);
    ``strict_batch=True`` restores the hard error. Note each distinct
    trailing-batch shape compiles its own program, and BatchNorm batch
    statistics do see the padded rows.

    Falls back to the eager record/backward/``Trainer.step`` path (with a
    one-time warning per (reason, net), reason in ``.fallback_reason``) when
    the step cannot soundly compile: optimizer without a registered fusable
    recurrence (e.g. SGLD's host RNG), ``multi_precision`` master weights,
    ``update_on_kvstore``, a multi-worker kvstore (gradients reduce outside
    the program), or non-float trainables.
    """

    def __init__(self, trainer, net, loss_fn, mesh=None, loss_scaler=None,
                 name="train_step", shard_update=None, strict_batch=False,
                 shard_params=None, partition_rules=None):
        self.trainer = trainer
        self.net = net
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.loss_scaler = loss_scaler if loss_scaler is not None \
            else getattr(trainer, "_amp_loss_scaler", None)
        self.name = name
        self.strict_batch = strict_batch
        self.fallback_reason = None
        self.shard_update = False
        self.shard_fallback_reason = None
        self.shard_params = False
        self.shard_params_fallback_reason = None
        self.partition_rules = partition_rules
        self._shard_params_auto = False  # size threshold pending 1st build
        self._shard_state = None
        self._fsdp_state = None
        self._fsdp_groups = None
        self._tp_places = {}             # train pos k -> (dim, segments)
        self._fsdp_layer_bytes = ()      # [(layer, gather_b, scatter_b)]
        self._cache = {}       # input signature -> _Program
        self._train_idx = None
        self._frozen = None
        self._state_keys = ()
        self._buckets = None
        self._state_bucket_bytes = 0
        self._traces = 0       # trace-time count (observes recompiles)
        self._dispatches = 0   # compiled-program calls
        self.multi_step = None  # K scanned steps per dispatch (None = off)
        self.accumulate = 1     # microbatches psum'd per optimizer step
        self._check_supported()
        self._resolve_shard_params(shard_params)
        self._resolve_shard_update(shard_update)

    # -- support matrix -----------------------------------------------------
    def _check_supported(self):
        tr = self.trainer
        opt = tr._optimizer
        if opt.fused_step is None:
            self.fallback_reason = (
                f"{type(opt).__name__} declares no fusable per-tensor step")
            return
        if opt.multi_precision:
            self.fallback_reason = ("multi_precision uses the per-param "
                                    "master-weight path")
            return
        if not tr._kv_initialized:
            tr._init_kvstore()
        if tr._kvstore is not None and tr._update_on_kvstore:
            self.fallback_reason = "update_on_kvstore runs the optimizer " \
                                   "on the store"
            return
        if tr._kvstore is not None and \
                not tr._kvstore.supports_compiled_step:
            self.fallback_reason = (
                f"kvstore '{tr._kvstore.type}' reduces gradients outside "
                "the program (num_workers > 1)")
            return
        if self.mesh is not None:
            from .parallel.mesh import AxisNames

            if AxisNames.DP not in self.mesh.axis_names:
                raise MXNetError(
                    f"compile_step mesh must carry a '{AxisNames.DP}' axis; "
                    f"got {self.mesh.axis_names}")

    def _dp_size(self):
        if self.mesh is None:
            return 0
        from .parallel.mesh import AxisNames

        return int(self.mesh.shape[AxisNames.DP])

    def _tp_size(self):
        if self.mesh is None:
            return 1
        from .parallel.mesh import AxisNames

        return max(int(self.mesh.shape.get(AxisNames.TP, 1)), 1)

    def _shardable(self):
        """``(ok, reason)`` for BOTH flat-bucket sharded schedules (ZeRO-1
        and FSDP): a dp mesh of >= 2 shards and an elementwise fusable
        recurrence."""
        if self._dp_size() < 2:
            return False, "no mesh with a 'dp' axis of size >= 2"
        return self.trainer._optimizer.sharding_eligibility()

    def _resolve_shard_params(self, requested):
        """Decide parameter residency. ``MXTPU_SHARD_PARAMS=0/1`` overrides
        the argument; ``None`` = auto: on when shardable AND the trainables
        total at least ``MXTPU_SHARD_PARAMS_AUTO_MB`` MiB (256 by default)
        — that size check runs at first build, once shapes are known. An
        explicit request the configuration cannot honor keeps the unsharded
        parameter residency (ZeRO-1/replicated per ``shard_update``) and
        warns once per (reason, net)."""
        env = os.environ.get("MXTPU_SHARD_PARAMS")
        if env is not None:
            requested = env.strip().lower() not in ("0", "false", "off", "")
        if requested is False:
            return
        if self.fallback_reason is not None:
            return  # the whole step already falls back to eager
        ok, reason = self._shardable()
        if ok:
            if requested is None:
                self._shard_params_auto = True
            else:
                self.shard_params = True
            return
        if requested is None:
            return  # auto quietly keeps the existing schedule
        self.shard_params_fallback_reason = reason
        warn_once(("shard_params", reason, id(self.net)),
                  f"compile_step: full-parameter sharding unavailable — "
                  f"{reason}; keeping the unsharded parameter residency",
                  RuntimeWarning)

    def _resolve_shard_update(self, requested):
        """Decide the update schedule. ``MXTPU_SHARD_UPDATE=0/1`` overrides
        the argument; ``None`` = auto (on when shardable). A shard request
        the configuration cannot honor keeps the REPLICATED compiled path
        (not the eager fallback) and warns once per (reason, net)."""
        if self.shard_params:
            return  # FSDP owns the whole schedule; weights stay sharded
        env = os.environ.get("MXTPU_SHARD_UPDATE")
        if env is not None:
            requested = env.strip().lower() not in ("0", "false", "off", "")
        auto = requested is None
        if requested is False:
            return
        if self.fallback_reason is not None:
            return  # the whole step already falls back to eager
        ok, reason = self._shardable()
        if ok:
            self.shard_update = True
            return
        if auto and self.mesh is None:
            return  # plain single-device compile: nothing to announce
        self.shard_fallback_reason = reason
        warn_once(("shard_update", reason, id(self.net)),
                  f"compile_step: sharded weight update unavailable — "
                  f"{reason}; keeping the replicated update", RuntimeWarning)

    # -- multi-step configuration -------------------------------------------
    def compile_multi_step(self, multi_step, accumulate=1):
        """Switch this step to scanned super-step execution: ONE donated-
        buffer program ``lax.scan``s the whole step body over K stacked
        microbatches (``multi_step=K``), and/or accumulates gradients over
        G microbatches before each optimizer update (``accumulate=G``).

        The callable then takes STACKED inputs: ``[K, B, ...]`` with
        ``multi_step=K`` alone, ``[G, B, ...]`` with ``accumulate=G``
        alone, ``[K, G, B, ...]`` with both. ``multi_step`` is the nominal
        K — any leading extent compiles its own program (a shorter
        trailing group at epoch end reuses its program every epoch, so
        steady state stays at zero recompiles). Per-inner-step hypers
        (t/lr/wd) ride as a ``[K, n]`` runtime table indexed in-scan by
        the committed-step counter, so LR schedules advance per inner
        step with zero recompiles and an overflow-skipped inner step
        leaves the schedule untouched — exactly the eager skip. The loss
        scale itself is one runtime operand per super-step: the host
        replays the K per-inner-step overflow flags through
        ``LossScaler.replay`` at the super-step boundary (scale changes
        take effect at the next super-step; the applied update is
        identical because power-of-two scales cancel exactly against
        ``rescale``). Returns ``self``.

        Semantics match K sequential single-step dispatches bitwise for
        the replicated and ZeRO-1 residencies (same body, same bits) and
        to tight tolerance for FSDP (structurally different program).
        Batches must divide the dp extent exactly — the in-program pad
        path is per-signature and has no stacked analogue."""
        if multi_step is not None:
            multi_step = int(multi_step)
            if multi_step < 1:
                raise MXNetError(
                    f"multi_step must be >= 1, got {multi_step}")
        accumulate = int(accumulate)
        if accumulate < 1:
            raise MXNetError(f"accumulate must be >= 1, got {accumulate}")
        if self.fallback_reason is not None:
            raise MXNetError(
                "compile_multi_step: the step cannot compile "
                f"({self.fallback_reason}) and a stacked super-batch has "
                "no eager fallback")
        self.multi_step = multi_step
        self.accumulate = accumulate
        return self

    # -- stepping -----------------------------------------------------------
    def __call__(self, x, y):
        if self.multi_step is not None or self.accumulate > 1:
            return self._call_multi(x, y)
        if self.fallback_reason is not None:
            return self._eager_step(x, y)
        pad = self._validate_batch(x)
        sig = (x.shape, str(x.dtype), y.shape, str(y.dtype))
        prog = self._cache.get(sig)
        if prog is None:
            prog = self._build(x, y, pad=pad)
            if prog is None:  # trace discovered an unsupported layout
                return self._eager_step(x, y)
            self._cache[sig] = prog
        return self._run(prog, x, y)

    def _call_multi(self, x, y):
        if self.fallback_reason is not None:
            raise MXNetError(
                "multi-step dispatch cannot fall back to the eager loop "
                f"(stacked inputs): {self.fallback_reason}")
        k, x, y = self._split_super(x, y)
        g = self.accumulate
        sig = ("multi", g, x.shape, str(x.dtype), y.shape, str(y.dtype))
        prog = self._cache.get(sig)
        if prog is None:
            prog = self._build(x, y, pad=0, k=k, g=g)
            if prog is None:
                raise MXNetError(
                    "multi-step dispatch cannot fall back to the eager "
                    f"loop (stacked inputs): {self.fallback_reason}")
            self._cache[sig] = prog
        return self._run_multi(prog, x, y)

    def _split_super(self, x, y):
        """Validate the stacked super-batch layout; returns ``(k, x, y)``
        with inputs normalized to a leading step axis (accumulate-only
        calls gain a length-1 one)."""
        from .ndarray.ndarray import NDArray

        g = self.accumulate
        lead = 2 if g > 1 else 1
        if self.multi_step is None:
            # accumulate-only: [G, B, ...] -> [1, G, B, ...]
            if x.ndim < 2 or x.shape[0] != g:
                raise MXNetError(
                    f"accumulate={g} expects inputs stacked [G, batch, "
                    f"...]; got x of shape {tuple(x.shape)}")
            x = NDArray(x._data[None])
            y = NDArray(y._data[None])
        elif g > 1:
            if x.ndim < 3 or x.shape[1] != g:
                raise MXNetError(
                    f"multi_step with accumulate={g} expects inputs "
                    f"stacked [K, G, batch, ...]; got x of shape "
                    f"{tuple(x.shape)}")
        elif x.ndim < 2:
            raise MXNetError(
                "multi_step expects inputs stacked [K, batch, ...]; got "
                f"x of shape {tuple(x.shape)}")
        k = int(x.shape[0])
        if tuple(y.shape[:lead]) != tuple(x.shape[:lead]):
            raise MXNetError(
                f"stacked x/y leading axes disagree: {tuple(x.shape)} vs "
                f"{tuple(y.shape)}")
        if self.mesh is not None:
            n = self._dp_size()
            micro_b = int(x.shape[lead])
            if micro_b % n != 0:
                raise MXNetError(
                    f"multi-step microbatch {micro_b} not divisible by "
                    f"the mesh's 'dp' axis ({n} shards); the in-program "
                    "pad path has no stacked analogue — size batches to "
                    "the mesh (DataLoader last_batch='discard'/'rollover')")
        return k, x, y

    def _validate_batch(self, x):
        """Rows of in-program zero-weight padding needed to even the batch
        over the dp axis (0 when divisible, or no mesh). With
        ``strict_batch=True`` a ragged batch raises instead — the pre-pad
        contract."""
        if self.mesh is None:
            return 0
        n = self._dp_size()
        r = x.shape[0] % n
        if r == 0:
            return 0
        if self.strict_batch:
            raise MXNetError(
                f"batch {x.shape[0]} not divisible by the mesh's "
                f"'dp' axis ({n} shards) and strict_batch=True")
        return n - r

    # -- tracing ------------------------------------------------------------
    def _collect(self):
        """Partition parameters into trainables (trainer order) and frozen
        trace variables. EVERY initialized parameter of the net — including
        BN running stats and other ``grad_req='null'`` state — becomes an
        explicit graph input: an unmarked array would be captured as a baked
        CONSTANT by the tracer, so step N+1 would silently read step 0's
        stats (and a donated update could never reach them)."""
        tr = self.trainer
        train_idx = []
        for i, p in enumerate(tr._params):
            if p.grad_req == "null":
                continue
            if p._data is None:
                raise MXNetError(
                    f"parameter {p.name} not initialized — initialize the "
                    "net (and run a settle forward for deferred shapes) "
                    "before compile_step")
            train_idx.append(i)
        if not train_idx:
            return None, None, "no trainable parameters"
        seen = {id(tr._params[i]) for i in train_idx}
        frozen = []
        for pname, p in self.net.collect_params().items():
            if id(p) not in seen and p._data is not None:
                frozen.append((pname, p))
        import jax.numpy as jnp

        for i in train_idx:
            if not jnp.issubdtype(tr._params[i].data().dtype, jnp.floating):
                return None, None, \
                    f"non-float trainable parameter {tr._params[i].name}"
        return train_idx, frozen, None

    def _make_buckets(self, train_idx):
        """Per-dtype flat buckets over the trainables (positions into the
        train list), padded to the dp extent — the ZeRO-1 layout."""
        from .parallel.collectives import BucketSpec

        tr = self.trainer
        n = self._dp_size()
        by_dt = {}
        for k, i in enumerate(train_idx):
            by_dt.setdefault(str(tr._params[i].data().dtype), []).append(k)
        return [(dt, by_dt[dt],
                 BucketSpec([tuple(tr._params[train_idx[k]].data().shape)
                             for k in by_dt[dt]], n))
                for dt in sorted(by_dt)]

    def _build(self, x, y, pad=0, k=None, g=1):
        """Trace + compile one program for this input signature. Under FSDP
        the per-param buffers were released at adoption; re-traces need them
        back (the deferred-compute variables must bind to the SAME NDArray
        objects the forward reads), so builds are bracketed by
        materialize/release."""
        st = self._fsdp_state
        if st is None:
            return self._build_program(x, y, pad=pad, k=k, g=g)
        st.materialize_into_params()
        try:
            return self._build_program(x, y, pad=pad, k=k, g=g)
        finally:
            st.release_params()

    def _make_fsdp_groups(self, train_idx):
        """Expand the partition rules over the named trainables and fold
        them into the per-layer bucket schedule. Names come from the net's
        ``collect_params`` keys (the structured 'encoder.layers.0...' paths
        the rules are written against), falling back to ``Parameter.name``
        for trainer params outside the net."""
        from .parallel.partition import (fsdp_groups, fsdp_rules,
                                         match_partition_rules)

        tr = self.trainer
        name_of = {id(p): pname
                   for pname, p in self.net.collect_params().items()}
        names = [name_of.get(id(tr._params[i]), tr._params[i].name)
                 for i in train_idx]
        rules = self.partition_rules if self.partition_rules is not None \
            else fsdp_rules()
        specs = match_partition_rules(
            rules, {nm: tr._params[i].data()
                    for nm, i in zip(names, train_idx)}, with_meta=True)
        entries = [(k, nm, tuple(tr._params[i].data().shape),
                    str(tr._params[i].data().dtype))
                   for k, (nm, i) in enumerate(zip(names, train_idx))]
        tp_n = self._tp_size()
        groups = fsdp_groups(entries, specs, self._dp_size(), tp_size=tp_n)
        places = {}
        if tp_n > 1:
            from .parallel import tp as _tp

            for k, nm in enumerate(names):
                m = specs[nm]
                dim = _tp.tp_dim(m.spec)
                if dim is not None:
                    places[k] = (dim, int(m.meta.get("segments", 1)))
        self._tp_places = places
        return groups

    def _build_program(self, x, y, pad=0, k=None, g=1):
        import jax
        import jax.numpy as jnp
        import numpy as onp

        from . import _deferred_compute as dc
        from . import autograd as ag
        from .cached_op import build_executor
        from .ndarray.ndarray import NDArray

        tr = self.trainer
        opt = tr._optimizer
        multi = k is not None or g > 1
        if multi:
            # the forward traces on ONE microbatch; the scan supplies the
            # leading step (and accumulation) axes at run time
            if pad:
                raise MXNetError("multi-step programs take exact batches")
            idx = (0, 0) if g > 1 else (0,)
            x, y = NDArray(x._data[idx]), NDArray(y._data[idx])
        weighted = pad > 0
        with ag.train_mode():
            if any(p._data is None
                   for p in self.net.collect_params().values()):
                with ag.pause():  # settle deferred-shape init, no BN writes
                    self.net(x)
        train_idx, frozen, reason = self._collect()
        if reason is not None:
            self.fallback_reason = reason
            return None
        raw, state_keys, needs_t, _ = opt.fused_step
        fsdp = self.shard_params
        if self._shard_params_auto:
            # deferred auto decision, now that shapes are known; sticky —
            # every input signature's program shares one residency
            self._shard_params_auto = False
            if not fsdp:
                total = sum(tr._params[i].data()._data.nbytes
                            for i in train_idx)
                thresh_mb = float(os.environ.get(
                    "MXTPU_SHARD_PARAMS_AUTO_MB", "256"))
                fsdp = total >= thresh_mb * (1 << 20)
                self.shard_params = fsdp
        if fsdp:
            self.shard_update = False  # FSDP supersedes ZeRO-1
        sharded = self.shard_update
        # the flat-bucket ZeRO-1 schedule needs an elementwise recurrence
        # (it updates arbitrary chunk slices); other fused optimizers keep
        # the per-tensor psum update on a mesh
        bucketed = self.mesh is not None and opt.supports_sharded_update \
            and not fsdp
        for i in train_idx:
            if not sharded and not fsdp and tr._states[i] is None:
                tr._states[i] = opt.create_state_multi_precision(
                    i, tr._params[i].data())
            if tr._states[i] is not None and \
                    any(k not in tr._states[i] for k in state_keys):
                self.fallback_reason = (
                    f"optimizer state for {tr._params[i].name} lacks "
                    f"{state_keys} (restored from an older run?)")
                return None
        self._train_idx = train_idx
        self._frozen = frozen
        self._state_keys = state_keys

        # ONE program serves both shard_update settings: the ZeRO-1
        # schedule with state entering as dp-sharded buckets. The settings
        # differ only in state RESIDENCY between steps (persistent shards
        # vs per-param replicated arrays scattered/gathered around the
        # dispatch), so sharded and replicated trajectories are bitwise
        # identical by construction — the parity contract
        buckets = self._make_buckets(train_idx) if bucketed else None
        self._buckets = buckets
        self._state_bucket_bytes = sum(
            bs.padded * 4 for _, _, bs in buckets) * len(state_keys) \
            if bucketed else 0
        if sharded and self._shard_state is None:
            # the sharded state is per-net, not per-program: every input
            # shape's program reads the same buckets
            self._shard_state = _ShardedOptState(
                self.mesh, opt, tr, train_idx, buckets, state_keys)
            tr._shard_state = self._shard_state
        groups = None
        remat = None
        if fsdp:
            groups = self._fsdp_groups
            if groups is None:
                groups = self._make_fsdp_groups(train_idx)
                self._fsdp_groups = groups
            remat = os.environ.get("MXTPU_FSDP_REMAT",
                                   "dots").strip().lower()
            if remat not in ("dots", "full", "none"):
                raise MXNetError(
                    f"MXTPU_FSDP_REMAT={remat!r}: expected 'dots' (save "
                    "dot outputs), 'full' (save nothing) or 'none' (no "
                    "rematerialization)")
        tp_n = self._tp_size()
        if tp_n > 1 and not fsdp:
            raise MXNetError(
                "a mesh carrying a 'tp' axis of size >= 2 requires "
                "shard_params=True — the megatron layouts ride the FSDP "
                "bucket schedule")
        tp_places = self._tp_places if (fsdp and tp_n > 1) else {}

        # --- in-program numerics monitor setup (MXTPU_NUMERICS) ------------
        # 'off' leaves the program structurally untouched; cheap/full add a
        # health tuple (grad-norm, max-abs update, per-layer-group nonfinite
        # counts) as extra outputs riding the same dispatch. cheap folds its
        # grad stats into the overflow finiteness pass the off program pays
        # anyway; only full adds genuinely extra traversals (max|update|,
        # per-group norms).
        nmode = _telemetry.numerics_mode()
        if tp_n > 1:
            # per-group health attribution is not tp-aware (replicated
            # groups' tp-invariant stats would double-count under a
            # ('dp', 'tp') reduction): the in-program monitor stays off
            nmode = "off"
        monitor = nmode != "off"
        track_upd = nmode == "full"
        health_groups = None
        hg_of = None         # per-tensor path: train position -> group idx
        bucket_gids = None   # ZeRO-1 path: per-bucket flat group-id vectors
        if monitor:
            from .parallel.partition import layer_key
            if fsdp:
                # FSDP grads arrive as per-group bucket shards: the groups
                # ARE the (layer-keyed) health groups
                health_groups = tuple(layer for layer, _, _, _, _ in groups)
            else:
                name_of = {id(p): pname
                           for pname, p in self.net.collect_params().items()}
                labels, hg_of, idx_of = [], [], {}
                for i in train_idx:
                    nm = name_of.get(id(tr._params[i]), tr._params[i].name)
                    lk = layer_key(nm)
                    gi_ = idx_of.get(lk)
                    if gi_ is None:
                        gi_ = idx_of[lk] = len(labels)
                        labels.append(lk)
                    hg_of.append(gi_)
                health_groups = tuple(labels)
            n_hg = len(health_groups)
            if bucketed:
                # flat-bucket shards don't align with tensor boundaries: a
                # static group-id vector (pad rows -> sentinel n_hg) lets a
                # segment_sum recover exact per-group nonfinite counts
                import numpy as _onp

                bucket_gids = []
                for _dt, ks_, bs_ in (buckets or ()):
                    gv = _onp.full((bs_.padded,), n_hg, _onp.int32)
                    for k2, off, nsz in zip(ks_, bs_.offsets, bs_.sizes):
                        gv[off:off + nsz] = hg_of[k2]
                    bucket_gids.append(gv)

        # --- capture the forward+loss graph (the hybridize machinery) ------
        if weighted:
            # trace on PADDED shapes; the per-sample loss vector stays
            # un-meaned so the body can weight out the pad rows
            x_t = self._pad_rows(x, pad)
            y_t = self._pad_rows(y, pad)
        else:
            x_t, y_t = x, y
        import contextlib

        tp_ctx = None
        tp_swap = []
        tp_scope = contextlib.nullcontext()
        if tp_places:
            from .parallel import tp as _tp

            tp_ctx = _tp.TPContext(tp_n, mode="train")
            tp_scope = _tp.activate(tp_ctx)
            # trace with each megatron parameter's rank-0 LOCAL slice
            # bound to its variable — the traced shapes are the per-rank
            # shapes the shard_map replay feeds (trace values throwaway);
            # the active context makes the model blocks emit the matching
            # in-graph tp collectives
            for kk, (dim, seg) in tp_places.items():
                p = tr._params[train_idx[kk]]
                tp_swap.append((p, p._data))
                p._data = NDArray(jnp.asarray(_tp.local_slice(
                    p._data.asnumpy(), dim, 0, tp_n, seg)))
        try:
            with tp_scope, ag.train_mode(), dc.context() as tctx:
                dvars = [dc.set_variable(x_t, "data0"),
                         dc.set_variable(y_t, "label0")]
                wvars = [dc.set_variable(tr._params[i].data(), f"w{i}")
                         for i in train_idx]
                fvars = [dc.set_variable(p.data(), pname)
                         for pname, p in frozen]
                loss = self.loss_fn(self.net(x_t), y_t)
                if weighted:
                    if loss.ndim == 0 or loss.shape[0] != x_t.shape[0]:
                        raise MXNetError(
                            "partial-batch padding needs a per-sample loss "
                            f"(got shape {tuple(loss.shape)}); pass batches "
                            "divisible by the dp axis or strict_batch=True")
                else:
                    loss = loss.mean()
                if loss._dc_sym is None:
                    self.fallback_reason = \
                        "loss is not connected to the traced forward"
                    return None
                entries = [loss._dc_sym] + [e for _, e in tctx.aux_updates]
                aux_targets = [t for t, _ in tctx.aux_updates]
                fwd, uses_rng = build_executor(entries,
                                               dvars + wvars + fvars)
        finally:
            # restore the FULL per-param values: adoption (first build)
            # slices per-rank images out of them right after
            for p, full in tp_swap:
                p._data = full

        n_train = len(train_idx)
        n_aux = len(aux_targets)
        n_state = len(state_keys)
        scaler_on = self.loss_scaler is not None
        mesh = self.mesh
        n_dp = self._dp_size()
        site = f"train_step:{self.name}"
        attrs = (f"n_params={n_train} n_aux={n_aux} "
                 f"opt={type(opt).__name__} scaler={scaler_on} "
                 f"mesh={mesh is not None} sharded={sharded} pad={pad}")

        def grad_part(ws, fs, xb, yb, wv, key, loss_scale):
            # forward + loss + backward for ONE microbatch: returns the
            # (reduced) loss, the all_reduce'd aux updates and the LOCAL
            # gradients — the update half applies the dp reduction.
            # Executes at TRACE time only: the python loop unrolls into
            # one program.
            if mesh is not None and uses_rng:
                from .parallel import collectives as coll

                # per-shard dropout masks: fold the shard index into the key
                key = jax.random.fold_in(key, coll.axis_index("dp"))

            if fsdp:
                from .parallel import collectives as coll

                def expand(w_tuple):
                    # JIT weight materialization: all_gather each layer's
                    # flat shard right where the forward needs it; the
                    # transpose of these gathers IS the gradient
                    # psum_scatter, so grads come back pre-reduced in the
                    # owning shard's layout
                    full = [None] * n_train
                    for (_, _, ks, bs, sh), buf in zip(groups, w_tuple):
                        flat = coll.all_gather(buf, "dp", axis=0,
                                               tiled=True) if sh else buf
                        for k, arr in zip(ks, bs.unflatten(flat)):
                            full[k] = arr
                    return full

                def wrap(lfn):
                    # rematerialize the forward in the backward so full
                    # weights are re-gathered, not kept live; 'dots' saves
                    # matmul outputs (activations), the classic FSDP policy
                    if remat == "none":
                        return lfn
                    if remat == "full":
                        return jax.checkpoint(lfn)
                    return jax.checkpoint(
                        lfn, policy=jax.checkpoint_policies.dots_saveable)
            else:
                def expand(w_tuple):
                    return list(w_tuple)

                def wrap(lfn):
                    return lfn

            if weighted:
                from .parallel import collectives as coll

                # weighted mean over the REAL rows: pad rows carry weight 0,
                # so loss and gradients match the unpadded batch exactly
                wsum = jnp.sum(wv)
                if mesh is not None:
                    wsum = coll.all_reduce(wsum, "dp", op="sum")

                def lfn(w_tuple):
                    args = ([key] if uses_rng else []) + [xb, yb] + \
                        expand(w_tuple) + list(fs)
                    outs = fwd(*args)
                    return (jnp.sum(outs[0] * wv),) + tuple(outs[1:])

                # cotangent pre-divided by the true example count: local
                # grads then SUM-reduce to the full gradient
                outs, (grads,) = ag.program_vjp(wrap(lfn), (tuple(ws),),
                                                loss_scale / wsum)
                loss_v = outs[0] / wsum
                aux = list(outs[1:])
                if mesh is not None:
                    loss_v = coll.all_reduce(loss_v, "dp", op="sum")
            else:
                def lfn(w_tuple):
                    args = ([key] if uses_rng else []) + [xb, yb] + \
                        expand(w_tuple) + list(fs)
                    return fwd(*args)

                # backward INSIDE the trace, seeded with the loss scale so a
                # DynamicLossScaler update never retraces (program_vjp)
                outs, (grads,) = ag.program_vjp(wrap(lfn), (tuple(ws),),
                                                loss_scale)
                loss_v, aux = outs[0], list(outs[1:])
                if mesh is not None:
                    from .parallel import collectives as coll

                    loss_v = coll.all_reduce(loss_v, "dp", op="mean")
            if mesh is not None:
                from .parallel import collectives as coll

                aux = [coll.all_reduce(a, "dp", op="mean") for a in aux]
            return loss_v, tuple(aux), grads

        def _grad_pass(g):
            # (sum g^2, nonfinite count) in ONE variadic-reduce traversal.
            # This REPLACES the overflow path's all(isfinite) walk when the
            # monitor is on (finite == count 0), so the grad-side stats
            # cost no extra pass over off mode; separate jnp reductions
            # each re-walk the tensor — XLA:CPU scan bodies don't fuse
            # sibling reduces (measured ~5x the fused pass at K=16)
            return jax.lax.reduce(
                (jnp.square(g.astype(jnp.float32)),
                 (~jnp.isfinite(g)).astype(jnp.int32)),
                (jnp.float32(0), jnp.int32(0)),
                lambda a, b: (a[0] + b[0], a[1] + b[1]),
                tuple(range(g.ndim)))

        def _upd_pass(nw, w):
            # max |update|: a genuinely extra traversal of the new/old
            # weights, so it runs in full mode only (cheap reports 0)
            return jnp.max(jnp.abs((nw - w).astype(jnp.float32)))

        def _per_tensor_update(ws, ss, grads, lrs, wds, ts, rescale):
            # single-device + non-elementwise-mesh path: the original
            # per-tensor unroll
            # overflow = non-finite SCALED grads, the quantity the eager
            # LossScaler.has_overflow inspects (before unscale). With the
            # monitor on, the finite verdict comes from the fused stats
            # pass (finite == zero nonfinite count) instead of a second
            # all(isfinite) walk.
            finite = jnp.bool_(True)
            tstats = []
            for g in grads:
                if monitor:
                    sq, cnt = _grad_pass(g)
                    tstats.append((sq, cnt))
                    finite = jnp.logical_and(finite, cnt == 0)
                else:
                    finite = jnp.logical_and(finite,
                                             jnp.all(jnp.isfinite(g)))
            overflow = jnp.logical_not(finite)
            new_ws, new_ss = [], []
            for k in range(n_train):
                g = grads[k] * rescale
                args = [ws[k], *ss[k], g, lrs[k], wds[k]]
                if needs_t:
                    args.append(ts[k])
                out = raw(*args)
                if n_state:
                    nw, ns = out[0], tuple(out[1:])
                else:
                    nw, ns = out, ()
                if scaler_on:
                    # skip-on-overflow as a select: the step ran, the
                    # weights didn't move (eager: trainer.step is skipped)
                    nw = jnp.where(overflow, ws[k], nw)
                    ns = tuple(jnp.where(overflow, s0, s1)
                               for s0, s1 in zip(ss[k], ns))
                new_ws.append(nw)
                new_ss.append(ns)
            health = None
            if monitor:
                # grads here are already dp-reduced (replicated): plain
                # per-tensor reductions, no collectives
                gsq = jnp.float32(0)
                mx = jnp.float32(0)
                nf = [jnp.zeros((), jnp.int32) for _ in range(n_hg)]
                gnsq = [jnp.float32(0) for _ in range(n_hg)] \
                    if nmode == "full" else None
                for k in range(n_train):
                    # sum((g*r)^2) == r^2 * sum(g^2): the rescale factor
                    # folds in as a scalar after the reduction
                    sq, cnt = tstats[k]
                    gsq = gsq + sq
                    if track_upd:
                        mx = jnp.maximum(mx, _upd_pass(new_ws[k], ws[k]))
                    nf[hg_of[k]] = nf[hg_of[k]] + cnt
                    if gnsq is not None:
                        gnsq[hg_of[k]] = gnsq[hg_of[k]] + sq
                r2 = (rescale * rescale).astype(jnp.float32)
                health = (gsq * r2, mx, jnp.stack(nf)) + \
                    ((jnp.stack(gnsq) * r2,) if gnsq is not None else ())
            return new_ws, new_ss, overflow, health

        def _bucket_update(ws, ss, grads, lrs, wds, ts, rescale, grad_op):
            """The ZeRO-1 update on flat per-dtype buckets: reduce_scatter
            the flat gradient, run the recurrence only on this replica's
            contiguous 1/N shard (state enters and leaves as dp-sharded
            buckets), all_gather the updated weights — the classic
            two-phase expansion of an all-reduce, so it pays the bandwidth
            a psum would. This is the ONLY elementwise mesh update: both
            ``shard_update`` settings dispatch the same program (and hence
            the same bits); they differ in state residency handled by the
            host in ``_run``. Earlier variants compiled a structurally
            different replicated program — XLA's global layout/fusion
            passes then make input-dependent 1-ulp rounding differences
            appear in the gradients, and no amount of per-op pinning
            (optimization_barrier, reduce_precision) stops it."""
            from .parallel import collectives as coll

            # reduce each bucket; every replica owns one contiguous slice
            # of the fully-reduced gradient
            gred, finite = [], jnp.bool_(True)
            bstats = []
            for _, ks, bs in buckets:
                flat_g = bs.flatten([grads[k] for k in ks])
                g = coll.reduce_scatter(flat_g, "dp")
                if grad_op == "mean":
                    g = g / n_dp  # pmean == psum / N, elementwise
                gred.append(g)
                if monitor:
                    # finite verdict folded into the fused stats pass
                    # (finite == zero nonfinite count): the monitor's
                    # grad-side reductions replace the all(isfinite) walk
                    # the off program pays anyway, instead of adding one
                    sq, cnt = _grad_pass(g)
                    bstats.append((sq, cnt))
                    finite = jnp.logical_and(finite, cnt == 0)
                else:
                    finite = jnp.logical_and(finite,
                                             jnp.all(jnp.isfinite(g)))
            # each replica saw only its shards: AND the verdicts so the
            # where-select (run on shards) agrees everywhere
            finite = coll.all_reduce(finite.astype(jnp.int32), "dp",
                                     op="min") > 0
            overflow = jnp.logical_not(finite)
            # health accumulators run on the same disjoint shards the
            # update touches (pad rows are zero): shard-local reductions +
            # one tiny all_reduce at the end are exact. Per-group counts
            # come from a segment_sum over the static group-id vector
            # (sentinel n_hg absorbs the pad tail).
            gsq = jnp.float32(0)
            mx = jnp.float32(0)
            nf = jnp.zeros((n_hg + 1,), jnp.int32) if monitor else None
            gnsq = jnp.zeros((n_hg + 1,), jnp.float32) \
                if monitor and nmode == "full" else None
            new_ws = [None] * n_train
            new_ss = []
            for bi, ((_, ks, bs), g) in enumerate(zip(buckets, gred)):
                ksel = jnp.asarray(ks)
                w_in = bs.flatten([ws[k] for k in ks])
                lr_v = bs.spread(lrs[ksel])
                wd_v = bs.spread(wds[ksel])
                # pad tail gets t=1 so bias-correction terms stay finite
                # (the pad region is all-zero and discarded)
                t_v = bs.spread(ts[ksel], pad_value=1.0) if needs_t else None
                sl = lambda v: bs.shard_slice(v, "dp")  # noqa: E731
                w_sh = sl(w_in)
                nw, ns = _apply_chunk(w_sh, ss[bi], g, sl(lr_v),
                                      sl(wd_v),
                                      sl(t_v) if needs_t else None,
                                      rescale, overflow)
                if monitor:
                    bsq, bad = bstats[bi]
                    gsq = gsq + bsq
                    if track_upd:
                        mx = jnp.maximum(mx, _upd_pass(nw, w_sh))
                    gid_vec = jnp.asarray(bucket_gids[bi])
                    # per-group attribution is a scatter-add — ruinously
                    # slow inside an XLA:CPU scan — so it runs only when
                    # this bucket actually saw a nonfinite value (the
                    # group-id shard slice materializes inside the branch
                    # too); healthy steps pay the predicate + a zeros fill
                    nf = nf + jax.lax.cond(
                        bad > 0,
                        lambda g=g, gv=gid_vec, sl=sl: jax.ops.segment_sum(
                            (~jnp.isfinite(g)).astype(jnp.int32), sl(gv),
                            num_segments=n_hg + 1),
                        lambda: jnp.zeros((n_hg + 1,), jnp.int32))
                    if gnsq is not None:
                        gnsq = gnsq + jax.ops.segment_sum(
                            jnp.square(g.astype(jnp.float32)), sl(gid_vec),
                            num_segments=n_hg + 1)
                flat_nw = coll.all_gather(nw, "dp", axis=0, tiled=True)
                new_ss.append(ns)
                for k, arr in zip(ks, bs.unflatten(flat_nw)):
                    new_ws[k] = arr
            health = None
            if monitor:
                # SHARD-LOCAL accumulators only: the cross-replica
                # reduction is deferred to finalize_health so a K-step
                # scan pays it once per dispatch, not once per inner step
                # (grad sums pick up the rescale factor as a scalar:
                # sum((g*r)^2) == r^2 * sum(g^2))
                r2 = (rescale * rescale).astype(jnp.float32)
                health = (gsq * r2, mx, nf[:n_hg])
                if gnsq is not None:
                    health += (gnsq[:n_hg] * r2,)
            return new_ws, tuple(new_ss), overflow, health

        def _apply_chunk(w_c, st_c, g_c, lr_c, wd_c, t_c, rescale, overflow):
            """Run the recurrence on one flat chunk (a ZeRO-1 bucket shard
            or an FSDP group shard) with per-element hypers, applying the
            skip-on-overflow select — the one code path every flat-bucket
            schedule updates through."""
            args = [w_c, *st_c, g_c * rescale, lr_c, wd_c]
            if needs_t:
                args.append(t_c)
            out = raw(*args)
            if n_state:
                nw, ns = out[0], tuple(out[1:])
            else:
                nw, ns = out, ()
            if scaler_on:
                nw = jnp.where(overflow, w_c, nw)
                ns = tuple(jnp.where(overflow, s0, s1)
                           for s0, s1 in zip(st_c, ns))
            return nw, ns

        def _fsdp_update(ws, ss, grads, lrs, wds, ts, rescale, grad_op):
            """The FSDP update: ``ws``/``ss`` are the resident per-group
            bucket shards and ``grads`` arrived PRE-SCATTERED for sharded
            groups (the vjp transpose of the forward's tiled all_gather is
            psum_scatter) — sum-reduced, so mean semantics divide by the dp
            extent. Replicated pools all_reduce their local grads instead.
            The recurrence runs on each group's shard and the outputs STAY
            sharded: no trailing weight all-gather — the next step's
            forward gathers just-in-time again."""
            from .parallel import collectives as coll

            gred, finite = [], jnp.bool_(True)
            gstats = []
            for (_, _, ks, bs, sh), g in zip(groups, grads):
                if sh:
                    if grad_op == "mean":
                        g = g / n_dp  # pmean == psum / N, elementwise
                else:
                    g = coll.all_reduce(g, "dp", op=grad_op)
                gred.append(g)
                if monitor:
                    # fused stats pass doubles as the finite verdict
                    # (finite == zero nonfinite count), replacing the
                    # all(isfinite) walk the off program pays anyway
                    sq, cnt = _grad_pass(g)
                    gstats.append((sq, cnt))
                    finite = jnp.logical_and(finite, cnt == 0)
                else:
                    finite = jnp.logical_and(finite,
                                             jnp.all(jnp.isfinite(g)))
            # each replica inspected only its shards: AND the verdicts so
            # the where-select agrees everywhere — over BOTH axes under
            # dp x tp (tp ranks inspect disjoint megatron shards)
            verdict_axes = ("dp", "tp") if tp_n > 1 else "dp"
            finite = coll.all_reduce(finite.astype(jnp.int32), verdict_axes,
                                     op="min") > 0
            overflow = jnp.logical_not(finite)
            # health: sharded groups reduce over disjoint shards (psum'd at
            # the end); replicated pools see identical full grads on every
            # replica (no reduction — psumming them would count N times)
            gsq_sh = jnp.float32(0)
            gsq_rep = jnp.float32(0)
            mx = jnp.float32(0)
            nf_sh = [jnp.zeros((), jnp.int32) for _ in range(n_hg)] \
                if monitor else None
            nf_rep = [jnp.zeros((), jnp.int32) for _ in range(n_hg)] \
                if monitor else None
            gnsq_sh = [jnp.float32(0) for _ in range(n_hg)] \
                if monitor and nmode == "full" else None
            gnsq_rep = [jnp.float32(0) for _ in range(n_hg)] \
                if monitor and nmode == "full" else None
            new_ws, new_ss = [], []
            for gi, ((_, _, ks, bs, sh), g) in enumerate(zip(groups, gred)):
                ksel = jnp.asarray(ks)
                lr_v = bs.spread(lrs[ksel])
                wd_v = bs.spread(wds[ksel])
                t_v = bs.spread(ts[ksel], pad_value=1.0) if needs_t else None
                if sh:
                    sl = lambda v: bs.shard_slice(v, "dp")  # noqa: E731
                    lr_v, wd_v = sl(lr_v), sl(wd_v)
                    t_v = sl(t_v) if needs_t else None
                nw, ns = _apply_chunk(ws[gi], ss[gi], g, lr_v, wd_v, t_v,
                                      rescale, overflow)
                if monitor:
                    sq, cnt = gstats[gi]
                    if track_upd:
                        mx = jnp.maximum(mx, _upd_pass(nw, ws[gi]))
                    if sh:
                        gsq_sh = gsq_sh + sq
                        nf_sh[gi] = nf_sh[gi] + cnt
                        if gnsq_sh is not None:
                            gnsq_sh[gi] = gnsq_sh[gi] + sq
                    else:
                        gsq_rep = gsq_rep + sq
                        nf_rep[gi] = nf_rep[gi] + cnt
                        if gnsq_rep is not None:
                            gnsq_rep[gi] = gnsq_rep[gi] + sq
                new_ws.append(nw)
                new_ss.append(ns)
            health = None
            if monitor:
                # shard-local (sharded + replicated halves kept apart):
                # finalize_health psums the sharded half once per dispatch
                # — collectives inside the scan body serialize XLA:CPU's
                # rendezvous thunks every inner step
                r2 = (rescale * rescale).astype(jnp.float32)
                health = (gsq_sh * r2, gsq_rep * r2, mx,
                          jnp.stack(nf_sh), jnp.stack(nf_rep))
                if gnsq_sh is not None:
                    health += (jnp.stack(gnsq_sh) * r2,
                               jnp.stack(gnsq_rep) * r2)
            return new_ws, tuple(new_ss), overflow, health

        # the dp reduction op is build-static: weighted (padded) batches
        # must SUM their pre-divided local grads, whole batches pmean
        grad_op = "sum" if weighted else "mean"

        def update_part(ws, ss, grads, lrs, wds, ts, rescale):
            # dp-reduce the gradients and run the optimizer recurrence —
            # the second half of the step body, shared by the single-step
            # and scanned paths
            if fsdp:
                return _fsdp_update(ws, ss, grads, lrs, wds, ts, rescale,
                                    grad_op)
            if bucketed:
                return _bucket_update(ws, ss, grads, lrs, wds, ts, rescale,
                                      grad_op)
            if mesh is not None:
                from .parallel import collectives as coll

                # non-elementwise recurrence: reduce per tensor, then run
                # the full-tensor update replicated on every device
                grads = tuple(coll.all_reduce(g, "dp", op=grad_op)
                              for g in grads)
            return _per_tensor_update(ws, ss, grads, lrs, wds, ts, rescale)

        def finalize_health(h):
            # cross-replica reduction of the shard-local health
            # accumulators, normalized to (gsq, mx, nf[, gnsq]). Applied
            # ONCE per dispatch — on the [K]-stacked values after the scan
            # for the multi-step program — because collectives inside the
            # scan body run XLA:CPU's rendezvous thunks every inner step
            # (measured 3x step cost at K=16). Elementwise collectives, so
            # a leading K axis passes straight through.
            if h is None or mesh is None:
                return h
            from .parallel import collectives as coll

            if fsdp:
                gsq_sh, gsq_rep, mx, nf_sh, nf_rep = h[:5]
                out = (coll.all_reduce(gsq_sh, "dp", op="sum") + gsq_rep,
                       coll.all_reduce(mx, "dp", op="max"),
                       coll.all_reduce(nf_sh, "dp", op="sum") + nf_rep)
                if len(h) > 5:
                    out += (coll.all_reduce(h[5], "dp", op="sum") + h[6],)
                return out
            if bucketed:
                out = (coll.all_reduce(h[0], "dp", op="sum"),
                       coll.all_reduce(h[1], "dp", op="max"),
                       coll.all_reduce(h[2], "dp", op="sum"))
                if len(h) > 3:
                    out += (coll.all_reduce(h[3], "dp", op="sum"),)
                return out
            return h  # per-tensor health is computed on psum'd grads

        def body(ws, ss, fs, xb, yb, wv, key, lrs, wds, ts, rescale,
                 loss_scale):
            loss_v, aux, grads = grad_part(ws, fs, xb, yb, wv, key,
                                           loss_scale)
            new_ws, new_ss, overflow, health = update_part(
                ws, ss, grads, lrs, wds, ts, rescale)
            if health is None:
                return loss_v, aux, new_ws, new_ss, overflow
            return loss_v, aux, new_ws, new_ss, overflow, \
                finalize_health(health)

        # shard_map specs shared by the single-step and scanned wrappers
        if mesh is not None:
            from .parallel.mesh import P, shard_map_compat

            dp = P("dp")
            if fsdp:
                # per-leaf spec pytrees: sharded groups enter/leave as
                # their 1/N shards (tp groups as 1/(tp*N) of the global
                # tp-major bucket), replicated pools as full copies
                tp_dp = P(("tp", "dp"))

                def g_spec(sh):
                    if sh == "tp":
                        return tp_dp
                    return dp if sh else P()

                ws_spec = [g_spec(sh) for _, _, _, _, sh in groups]
                ss_spec = tuple(g_spec(sh) for _, _, _, _, sh in groups)
                out_ws = list(ws_spec)
                out_state = ss_spec
            else:
                ws_spec = P()
                ss_spec = dp if bucketed else P()
                out_ws = P()
                out_state = dp if bucketed else P()

        if multi:
            # --- scanned super-step: K optimizer steps (each accumulating
            # G microbatches) as ONE lax.scan over the step body ----------
            from .parallel.collectives import match_carry_vma

            # aux (BN moving stats) must flow BETWEEN inner steps: map each
            # aux target to its frozen-input position so the scan carries
            # those fs entries (the single-step trace reads fs once)
            fs_pos = {id(p.data()): j for j, (_, p) in enumerate(frozen)}
            aux_pos = []
            for t in aux_targets:
                j = fs_pos.get(id(t))
                if j is None:
                    self.fallback_reason = (
                        "multi-step scan: an aux-update target is not a "
                        "frozen parameter input")
                    return None
                aux_pos.append(j)

            def sub_fs(fs, aux_vals):
                fs = list(fs)
                for j, a in zip(aux_pos, aux_vals):
                    fs[j] = a
                return fs

            def one_step(ws, ss, fs, xb, yb, kb, lrs, wds, ts, rescale,
                         loss_scale):
                # one optimizer step = G accumulated microbatches. Grad
                # shapes differ from ws under FSDP (pre-scattered), so the
                # accumulator is seeded by microbatch 0 and an inner scan
                # sums the remaining G-1, threading BN aux sequentially
                if g == 1:
                    loss_v, aux, grads = grad_part(ws, fs, xb, yb, None,
                                                   kb, loss_scale)
                else:
                    loss_v, aux, grads = grad_part(ws, fs, xb[0], yb[0],
                                                   None, kb[0], loss_scale)

                    def acc(c, sl):
                        l_a, g_a, aux_c = c
                        xj, yj, kj = sl
                        l_j, aux_j, g_j = grad_part(
                            ws, sub_fs(fs, aux_c), xj, yj, None, kj,
                            loss_scale)
                        return (l_a + l_j,
                                tuple(a + b for a, b in zip(g_a, g_j)),
                                aux_j), None

                    carry = (loss_v, tuple(grads), aux)
                    if mesh is not None:
                        carry = match_carry_vma(
                            acc, carry, (xb[1], yb[1], kb[1]),
                            fallback_axis="dp")
                    (loss_v, grads, aux), _ = jax.lax.scan(
                        acc, carry, (xb[1:], yb[1:], kb[1:]))
                    # mean over the G microbatches: sum-then-divide equals
                    # the mean over the G*B super-batch
                    loss_v = loss_v / g
                    grads = tuple(gr / g for gr in grads)
                new_ws, new_ss, overflow, health = update_part(
                    ws, ss, grads, lrs, wds, ts, rescale)
                return loss_v, aux, new_ws, new_ss, overflow, health

            def super_fn(ws, ss, fs, xs, ys, keys, lrs_t, wds_t, ts_t,
                         rescale, loss_scale):
                # carry structures must match the body's OUTPUT structures
                # (lists for ws, residency-dependent for ss)
                ws = list(ws)
                ss = tuple(ss) if (fsdp or bucketed) else \
                    [tuple(s) for s in ss]
                aux0 = tuple(fs[j] for j in aux_pos)

                def step(carry, sl):
                    ws_c, ss_c, aux_c, c = carry
                    xj, yj, kj = sl
                    # per-inner-step hypers indexed by the COMMITTED count
                    # c, not the loop index: an overflow-skipped step must
                    # leave the schedule untouched, exactly the eager skip
                    loss_v, aux, new_ws, new_ss, ovf, health = one_step(
                        ws_c, ss_c, sub_fs(fs, aux_c), xj, yj, kj,
                        lrs_t[c], wds_t[c], ts_t[c], rescale, loss_scale)
                    if scaler_on:
                        c = c + 1 - ovf.astype(jnp.int32)
                    else:
                        c = c + 1
                    # health (when on) stacks to [K, ...] in the scan ys:
                    # per-inner-step provenance rides the same readback
                    ys_j = (loss_v, ovf) if health is None \
                        else (loss_v, ovf, health)
                    return (new_ws, new_ss, aux, c), ys_j

                carry = (ws, ss, aux0, jnp.zeros((), jnp.int32))
                proto = (xs[0], ys[0], keys[0])
                if mesh is not None:
                    carry = match_carry_vma(step, carry, proto,
                                            fallback_axis="dp")
                (ws, ss, aux, _), ys_out = jax.lax.scan(
                    step, carry, (xs, ys, keys))
                if monitor:
                    losses, ovfs, healths = ys_out
                    # ONE set of health collectives over the [K]-stacked
                    # shard-local rows for the whole super-step
                    return losses, aux, ws, ss, ovfs, \
                        finalize_health(healths)
                losses, ovfs = ys_out
                return losses, aux, ws, ss, ovfs

            if mesh is not None:
                x_sp = P(None, None, "dp") if g > 1 else P(None, "dp")
                inner_multi = shard_map_compat(
                    super_fn, mesh,
                    in_specs=(ws_spec, ss_spec, P(), x_sp, x_sp,
                              P(), P(), P(), P(), P(), P()),
                    # the health subtree (when on) is replicated: P() prefix
                    out_specs=(P(), P(), out_ws, out_state, P()) +
                              ((P(),) if monitor else ()))
            else:
                inner_multi = super_fn
            m_attrs = attrs + f" k={k} g={g}"

            def multi_fn(ws, ss, fs, xs, ys, keys, lrs_t, wds_t, ts_t,
                         rescale, loss_scale):
                # executes at TRACE time only — the observers count
                # recompiles, not calls (the scan body may be re-traced
                # abstractly by match_carry_vma; only this top-level
                # wrapper marks the compile site)
                self._traces += 1
                _telemetry.record_compile(site, (ws, xs), attrs=m_attrs)
                return inner_multi(ws, ss, fs, xs, ys, keys, lrs_t, wds_t,
                                   ts_t, rescale, loss_scale)

            fn = multi_fn
        elif mesh is not None:
            inner = shard_map_compat(
                body, mesh,
                in_specs=(ws_spec, ss_spec, P(), dp, dp,
                          dp if weighted else P(),
                          P(), P(), P(), P(), P(), P()),
                # the health subtree (when on) is replicated: P() prefix
                out_specs=(P(), P(), out_ws, out_state, P()) +
                          ((P(),) if monitor else ()))
            if weighted:
                b = int(x.shape[0])

                def padded(ws, ss, fs, xb, yb, key, lrs, wds, ts, rescale,
                           loss_scale):
                    # executes at TRACE time only — the observers count
                    # recompiles, not calls
                    self._traces += 1
                    _telemetry.record_compile(site, (ws, xb), attrs=attrs)
                    # pad IN-PROGRAM: the host hands the ragged batch as-is
                    xb = jnp.pad(xb, ((0, pad),) + ((0, 0),) * (xb.ndim - 1))
                    yb = jnp.pad(yb, ((0, pad),) + ((0, 0),) * (yb.ndim - 1))
                    wv = (jnp.arange(b + pad) < b).astype(jnp.float32)
                    return inner(ws, ss, fs, xb, yb, wv, key, lrs, wds, ts,
                                 rescale, loss_scale)

                fn = padded
            else:
                def unweighted(ws, ss, fs, xb, yb, key, lrs, wds, ts,
                               rescale, loss_scale):
                    self._traces += 1
                    _telemetry.record_compile(site, (ws, xb), attrs=attrs)
                    wv = jnp.zeros((n_dp,), jnp.float32)  # unused
                    return inner(ws, ss, fs, xb, yb, wv, key, lrs, wds, ts,
                                 rescale, loss_scale)

                fn = unweighted
        else:
            def no_mesh(ws, ss, fs, xb, yb, key, lrs, wds, ts, rescale,
                        loss_scale):
                self._traces += 1
                _telemetry.record_compile(site, (ws, xb), attrs=attrs)
                return body(ws, ss, fs, xb, yb, None, key, lrs, wds, ts,
                            rescale, loss_scale)

            fn = no_mesh
        coll_bytes = self._collective_bytes(train_idx, aux_targets, buckets,
                                            bucketed, weighted, scaler_on,
                                            groups=groups, remat=remat)
        tp_bytes = 0
        if tp_ctx is not None:
            # accounted by the op fallbacks while the trace replayed the
            # model eagerly on rank-0 local values
            tp_bytes = int(tp_ctx.psum_bytes + tp_ctx.gather_bytes)
        if multi:
            # per-dispatch payload scales with the k*g microbatches scanned
            coll_bytes = tuple(b * (k * g) for b in coll_bytes)
            tp_bytes *= k * g
        if fsdp and self._fsdp_state is None:
            # adoption AFTER the trace (it releases the per-param buffers
            # the trace just bound); like the ZeRO-1 state, the residency
            # is per-net — every input signature's program shares it
            self._fsdp_state = _FSDPState(self.mesh, opt, tr, train_idx,
                                          groups, state_keys,
                                          tp_places=tp_places,
                                          tp_size=tp_n)
            tr._shard_state = self._fsdp_state
            gathers = 1 if remat == "none" else 2  # backward re-gather
            self._fsdp_layer_bytes = tuple(
                (layer,
                 bs.padded * onp.dtype(dt).itemsize * gathers if sh else 0,
                 bs.padded * onp.dtype(dt).itemsize if sh else 0)
                for layer, dt, _, bs, sh in groups)
        return _Program(jax.jit(fn, donate_argnums=train_donate_argnums()),
                        uses_rng,
                        aux_targets, sharded=bucketed, fsdp=fsdp,
                        coll_bytes=coll_bytes, coll_bytes_tp=tp_bytes,
                        k=k if multi else None, accum=g,
                        health_mode=nmode,
                        health_groups=health_groups)

    @staticmethod
    def _pad_rows(arr, pad):
        """Host-side zero row padding (trace shapes only — runtime padding
        happens in-program)."""
        import jax.numpy as jnp

        from .ndarray.ndarray import NDArray

        return NDArray(jnp.pad(
            arr._data, ((0, pad),) + ((0, 0),) * (arr._data.ndim - 1)))

    def _collective_bytes(self, train_idx, aux_targets, buckets, bucketed,
                          weighted, scaler_on, groups=None, remat=None):
        """Statically-known per-step IN-PROGRAM collective payload (per
        replica): the dispatch site reports these since the host cannot
        observe in-program collectives. Replicated state residency adds
        its host-side scatter/gather resharding on top (in ``_run``).
        FSDP numbers are schedule-level (what the trace emits; XLA may CSE
        backward re-gathers)."""
        if self.mesh is None:
            return (0, 0, 0)
        import numpy as onp

        def nbytes(shape, dtype):
            n = 1
            for d in shape:
                n *= int(d)
            return n * onp.dtype(str(dtype)).itemsize

        aux_b = sum(nbytes(t.shape, t.dtype) for t in aux_targets)
        psum = 4 + aux_b  # loss scalar + BN stat means
        if weighted:
            psum += 4  # example-weight sum
        if groups is not None:  # FSDP
            rs = ag = 0
            gathers = 1 if remat == "none" else 2  # backward re-gather
            for _, dt, _, bs, sh in groups:
                b = bs.padded * onp.dtype(dt).itemsize
                if sh:
                    ag += b * gathers  # JIT weight gather(s)
                    rs += b            # grad psum_scatter (vjp transpose)
                else:
                    psum += b          # replicated-pool grad all_reduce
            psum += 4  # the AND-reduced finiteness verdict
            return (rs, ag, psum)
        if not bucketed:
            # non-elementwise fused optimizer: per-tensor grad psum
            grad_b = sum(nbytes(self.trainer._params[i].data().shape,
                                self.trainer._params[i].data().dtype)
                         for i in train_idx)
            return (0, 0, psum + grad_b)
        rs = ag = 0
        for dt, _, bs in buckets:
            b = bs.padded * onp.dtype(dt).itemsize
            rs += b
            ag += b
        psum += 4  # the AND-reduced finiteness verdict
        return (rs, ag, psum)

    def _scatter_replicated_state(self):
        """Flatten per-param optimizer state into dp-sharded bucket arrays
        (replicated residency, ``shard_update=False``). The program only
        ever sees sharded state; between steps the per-param arrays in
        ``trainer._states`` remain the source of truth, so inspection and
        checkpoints keep the classic layout at the cost of one state
        reshard each way per step.

        The buckets built here are DONATED (argnum 1), so they must never
        alias the live state arrays: ``flatten`` of a single-tensor bucket
        is a reshape (an alias), and once the states have been rebound to
        slices of last dispatch's sharded output, ``device_put`` at the
        already-matching sharding is a no-op on that alias — donating the
        result would free the buffer the live states still point at (the
        corruption only surfaces once the allocator reuses the memory).
        ``jnp.array(..., copy=True)`` pins a fresh buffer in the chain."""
        import jax
        import jax.numpy as jnp

        from .parallel.mesh import shard_1d

        tr = self.trainer
        idxs = self._train_idx
        sharding = shard_1d(self.mesh)
        return tuple(
            tuple(jax.device_put(
                jnp.array(bs.flatten(
                    [tr._states[idxs[k]][key]._data for k in ks]),
                    copy=True),
                sharding) for key in self._state_keys)
            for _, ks, bs in self._buckets)

    # -- the compiled step --------------------------------------------------
    def _assemble_inputs(self, prog):
        """Gather the donated weight/state operands for one dispatch,
        per the program's residency mode."""
        tr = self.trainer
        idxs = self._train_idx
        keys = self._state_keys
        if prog.fsdp:
            # FSDP: weights AND state are the resident bucket shards; no
            # full-sized value is ever assembled on the host
            ws = list(self._fsdp_state.params)
            ss = tuple(self._fsdp_state.state)
        elif prog.sharded and self.shard_update:
            ws = [tr._params[i].data()._data for i in idxs]
            ss = tuple(self._shard_state.state)
        elif prog.sharded:
            ws = [tr._params[i].data()._data for i in idxs]
            # replicated residency: scatter per-param state into the same
            # dp-sharded bucket arrays the sharded mode feeds — the ONE
            # program both modes dispatch (the parity contract)
            ss = self._scatter_replicated_state()
        else:
            ws = [tr._params[i].data()._data for i in idxs]
            ss = [tuple(tr._states[i][k]._data for k in keys) for i in idxs]
        fs = [p.data()._data for _, p in self._frozen]
        return ws, ss, fs

    def _dispatch(self, prog, args):
        """Compile on first use, account the dispatch, run the program."""
        self._dispatches += 1
        if prog.compiled is None:
            # first dispatch of this signature: lower + compile explicitly
            # — the one XLA compile the implicit jit call would pay anyway
            # (the traced body still reports record_compile, so the
            # watchdog sees it like any jit cache miss), but the Compiled
            # handle stays reachable for cost_analysis
            import warnings as _warnings

            with _warnings.catch_warnings():
                # CPU backends warn that donation is unimplemented; the
                # copy fallback is correct (the donation is for TPU)
                _warnings.filterwarnings("ignore", message=".*donat.*",
                                         category=UserWarning)
                prog.compiled = prog.fn.lower(*args).compile()
            cost = _telemetry.record_program_cost("train_step",
                                                  prog.compiled)
            if cost:
                prog.flops = cost["flops"]
                prog.bytes_accessed = cost["bytes_accessed"]
            _telemetry.record_program_memory("train_step", prog.compiled)
        # admission check + OOM forensics bracket BOTH dispatch paths: a
        # set lookup when admitted, a ledger dump when the device OOMs
        _telemetry.check_memory_admission("train_step")
        if not _telemetry.ON:
            try:
                return prog.compiled(*args)
            except Exception as e:
                _telemetry.memory_oom_forensics("train_step", e)
                raise
        # ONE compiled-program call per (super-)step; this bypasses the
        # invoke() chokepoint, so count the dispatch here
        _telemetry.record_dispatch()
        _telemetry.record_flops(prog.flops, prog.bytes_accessed)
        rs_b, ag_b, ps_b = prog.coll_bytes
        if prog.sharded and not self.shard_update:
            # replicated residency: the host-side state reshard is
            # scatter + gather traffic on top of the program's own
            rs_b += self._state_bucket_bytes
            ag_b += self._state_bucket_bytes
        _telemetry.record_collective(rs_b, ag_b, ps_b,
                                     tp_bytes=prog.coll_bytes_tp)
        if prog.fsdp:
            _telemetry.record_fsdp(self._fsdp_layer_bytes)
        with _telemetry.program_timer("train_step"):
            try:
                return prog.compiled(*args)
            except Exception as e:
                _telemetry.memory_oom_forensics("train_step", e)
                raise

    def _writeback(self, prog, new_ws, new_ss, aux):
        """Rebind the program's donated outputs into the host-visible
        parameter/state objects, per residency mode."""
        tr = self.trainer
        idxs = self._train_idx
        keys = self._state_keys
        if prog.fsdp:
            # outputs ARE the updated bucket shards: no per-param weight
            # writeback exists (or is wanted) — rebind the residency
            self._fsdp_state.rebind(new_ws, new_ss)
        elif prog.sharded and self.shard_update:
            for k, i in enumerate(idxs):
                tr._params[i].data()._set_data(new_ws[k])
            self._shard_state.rebind(new_ss)
        elif prog.sharded:
            for k, i in enumerate(idxs):
                tr._params[i].data()._set_data(new_ws[k])
            # gather updated shard buckets back into the per-param arrays
            for (_, ks, bs), st in zip(self._buckets, new_ss):
                for key, flat in zip(keys, st):
                    for k, off, n, shape in zip(ks, bs.offsets, bs.sizes,
                                                bs.shapes):
                        tr._states[idxs[k]][key]._set_data(
                            flat[off:off + n].reshape(shape))
        else:
            for k, i in enumerate(idxs):
                tr._params[i].data()._set_data(new_ws[k])
                for sk, arr in zip(keys, new_ss[k]):
                    tr._states[i][sk]._set_data(arr)
        # aux write-backs happen regardless of overflow: BN stats update
        # during the forward, before the eager loop could inspect grads
        for target, arr in zip(prog.aux_targets, aux):
            target._set_data(arr)

    def _record_health(self, prog, health, k_steps):
        """Fold the program's in-scan health outputs into the host-side
        numerics monitor. health = (grad_sq_norm, max_abs_update,
        nonfinite_counts[, group_sq_norms]) — scalars/[G] from the
        single-step program, [K]/[K, G] stacked from the scan."""
        import numpy as onp

        gsq = onp.atleast_1d(onp.asarray(health[0], onp.float64))
        mx = onp.atleast_1d(onp.asarray(health[1], onp.float64))
        nonfin = onp.asarray(health[2]).reshape(k_steps, -1)
        gn = None
        if len(health) > 3:
            gn = onp.sqrt(onp.asarray(
                health[3], onp.float64).reshape(k_steps, -1))
        _telemetry.record_step_health(
            prog.health_groups, onp.sqrt(gsq), mx, nonfin,
            group_norms=gn, nmode=prog.health_mode)

    def _run(self, prog, x, y):
        import jax.numpy as jnp
        import numpy as onp

        tr = self.trainer
        opt = tr._optimizer
        idxs = self._train_idx
        scaler = self.loss_scaler
        ws, ss, fs = self._assemble_inputs(prog)
        if prog.uses_rng:
            from . import random as _rnd

            key = _rnd._next_key()
        else:
            key = jnp.zeros((2,), jnp.uint32)
        # scalar schedule inputs are RUNTIME operands (the trainer rule):
        # counts are STAGED, not committed — an overflow-skipped step must
        # leave the schedule exactly where the eager skip would
        counts, num_update = opt._staged_counts(idxs)
        ts = onp.asarray(counts, onp.float32)
        lrs = onp.asarray([opt._get_lr(i, num_update=num_update)
                           for i in idxs], onp.float32)
        wds = onp.asarray([opt._get_wd(i) for i in idxs], onp.float32)
        scale = float(scaler.loss_scale) if scaler is not None else 1.0
        rescale = onp.float32(tr._scale / scale)
        loss_scale = onp.float32(scale)
        out = self._dispatch(prog, (ws, ss, fs, x._data, y._data, key, lrs,
                                    wds, ts, rescale, loss_scale))
        if prog.health_groups is not None:
            loss_v, aux, new_ws, new_ss, overflow, health = out
        else:
            loss_v, aux, new_ws, new_ss, overflow = out
            health = None
        self._writeback(prog, new_ws, new_ss, aux)
        if scaler is not None:
            ovf = bool(overflow)  # the step's only host sync (1 byte)
            scaler.update_scale(ovf)
        else:
            ovf = False
        if not ovf:
            opt._commit_counts(idxs)
        if health is not None:
            # a few scalars riding the dispatch the step already paid for
            self._record_health(prog, health, k_steps=1)
        if _telemetry.ON:
            _telemetry.mark_step()
        from .ndarray.ndarray import NDArray

        return NDArray(loss_v)

    def _run_multi(self, prog, x, y):
        import time as _time

        import jax.numpy as jnp
        import numpy as onp

        t_host0 = _time.perf_counter()
        tr = self.trainer
        opt = tr._optimizer
        idxs = self._train_idx
        scaler = self.loss_scaler
        k, g = prog.k, prog.accum
        ws, ss, fs = self._assemble_inputs(prog)
        if prog.uses_rng:
            from . import random as _rnd

            # one key PER MICROBATCH, drawn in the exact order the
            # sequential loop would draw them (RNG-trajectory parity)
            flat = [_rnd._next_key() for _ in range(k * g)]
            keys = jnp.stack(flat).reshape((k, g, 2) if g > 1 else (k, 2))
        else:
            keys = jnp.zeros((k, g, 2) if g > 1 else (k, 2), jnp.uint32)
        # per-inner-step hyper table: row j = what the j-th COMMITTED step
        # would stage; the program indexes rows by its in-scan committed
        # counter, so overflow skips freeze the schedule exactly like the
        # eager loop (and K sequential compiled steps)
        rows, nus = opt._staged_counts_k(idxs, k)
        ts = onp.asarray(rows, onp.float32)
        lrs = onp.asarray(
            [[opt._get_lr(i, num_update=nu) for i in idxs] for nu in nus],
            onp.float32)
        wd_row = [opt._get_wd(i) for i in idxs]
        wds = onp.asarray([wd_row] * k, onp.float32)
        scale = float(scaler.loss_scale) if scaler is not None else 1.0
        rescale = onp.float32(tr._scale / scale)
        loss_scale = onp.float32(scale)
        out = self._dispatch(prog, (ws, ss, fs, x._data, y._data, keys, lrs,
                                    wds, ts, rescale, loss_scale))
        if prog.health_groups is not None:
            losses, aux, new_ws, new_ss, ovfs, healths = out
        else:
            losses, aux, new_ws, new_ss, ovfs = out
            healths = None
        self._writeback(prog, new_ws, new_ss, aux)
        # the super-step's only host sync: the K overflow flags (K bytes)
        t_s0 = _time.perf_counter()
        flags = onp.asarray(ovfs)
        t_s1 = _time.perf_counter()
        if scaler is not None:
            clean = scaler.replay(flags)
        else:
            clean = k
        for _ in range(clean):
            opt._commit_counts(idxs)
        if healths is not None:
            # [K]-stacked health rows ride the same dispatch; the overflow
            # sync above already waited out the device
            self._record_health(prog, healths, k_steps=k)
        if _telemetry.ON:
            # host cost per trained step, the sync wait excluded (that
            # time is the device computing, not the host dispatching)
            host_ms = ((_time.perf_counter() - t_host0) -
                       (t_s1 - t_s0)) * 1e3 / k
            _telemetry.gauge("train.host_ms_per_step").set(host_ms)
            _telemetry.gauge("train.dispatches_per_step").set(1.0 / k)
            _telemetry.mark_step(inner_steps=k)
        from .ndarray.ndarray import NDArray

        return NDArray(losses)

    # -- the uncompiled fallback -------------------------------------------
    def _eager_step(self, x, y):
        from . import autograd as ag

        # one warning per (reason, net) — NOT per CompiledTrainStep: loops
        # that rebuild the step (e.g. per epoch) must not re-warn
        warn_once(("train_step_fallback", self.fallback_reason,
                   id(self.net)),
                  f"compile_step: falling back to the eager path — "
                  f"{self.fallback_reason}", RuntimeWarning, stacklevel=3)
        tr = self.trainer
        scaler = self.loss_scaler
        with ag.record():
            loss = self.loss_fn(self.net(x), y).mean()
            head = loss if scaler is None else loss * float(scaler.loss_scale)
        head.backward()
        if scaler is not None:
            if scaler.has_overflow(tr._params):
                scaler.update_scale(True)
                if _telemetry.ON:
                    _telemetry.mark_step()
                return loss
            for p in tr._params:
                if p.grad_req != "null" and p._data is not None:
                    g = p.grad()
                    g._set_data(g._data / scaler.loss_scale)
            scaler.update_scale(False)
        tr.step(1)  # the loss carries the batch mean already
        return loss
