"""Extension library loading — python modules AND versioned native ABI.

Reference: python/mxnet/library.py + the versioned C ABI
(include/mxnet/lib_api.h, MX_LIBRARY_VERSION, MXLoadLib c_api.cc:1522) for
out-of-tree custom ops / graph passes / subgraph properties. Two extension
models here:

- PYTHON module (.py): registers ops via mxnet_tpu.ops.register, custom
  ops via mxnet_tpu.operator.register, optimizers/initializers via their
  registries, or graph passes via mxnet_tpu.subgraph. ``load()`` imports
  it and invokes its ``register_ops()`` hook.
- NATIVE shared object (.so/.dylib): the versioned C contract of
  ``include/mxtpu/lib_api.h`` (MXTPU_EXT_ABI_VERSION; the loader refuses
  mismatched majors). v1 exposes enumerated elementwise f32 host kernels,
  registered as jit=False host ops — the TPU compute path belongs to
  Pallas/XLA, native extensions cover host-side kernels (decoders,
  samplers, metrics). Worked example:
  examples/extensions/lib_custom_op/relu6_ext.c.
"""
from __future__ import annotations

import importlib.util
import os
import sys

from .base import MXNetError

__all__ = ["load", "loaded_libraries", "ABI_VERSION"]

ABI_VERSION = 100  # must match include/mxtpu/lib_api.h

_loaded: dict[str, object] = {}


def load(path, verbose=True):
    """Load an extension (reference: mx.library.load).

    ``.py`` imports a python extension module (optional ``register_ops()``
    hook); ``.so``/``.dylib`` binds a native library over the versioned
    extensions ABI and registers every op it enumerates.
    """
    path = os.path.abspath(path)
    if not os.path.exists(path):
        raise MXNetError(f"extension {path} not found")
    if path in _loaded:
        return _loaded[path]
    if path.endswith((".so", ".dylib")):
        handle = _load_native(path)
    else:
        handle = _load_python(path)
    _loaded[path] = handle
    return handle


def _load_python(path):
    name = "mxnet_tpu_ext_" + os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    if spec is None or spec.loader is None:
        raise MXNetError(f"cannot import extension {path}")
    module = importlib.util.module_from_spec(spec)
    sys.modules[name] = module
    spec.loader.exec_module(module)
    if hasattr(module, "register_ops"):
        module.register_ops()
    return module


def _load_native(path):
    import ctypes

    import numpy as onp

    try:
        lib = ctypes.CDLL(path)
    except OSError as e:
        raise MXNetError(f"cannot dlopen extension {path}: {e}") from e
    for sym in ("mxtpu_ext_abi_version", "mxtpu_ext_num_ops",
                "mxtpu_ext_op_name", "mxtpu_ext_op_compute"):
        if not hasattr(lib, sym):
            raise MXNetError(
                f"extension {path} does not export required ABI symbol "
                f"{sym!r} (see include/mxtpu/lib_api.h)")
    lib.mxtpu_ext_abi_version.restype = ctypes.c_int
    lib.mxtpu_ext_num_ops.restype = ctypes.c_int
    lib.mxtpu_ext_op_name.restype = ctypes.c_char_p
    lib.mxtpu_ext_op_name.argtypes = [ctypes.c_int]
    lib.mxtpu_ext_op_compute.restype = ctypes.c_int
    lib.mxtpu_ext_op_compute.argtypes = [
        ctypes.c_int, ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64]

    got = int(lib.mxtpu_ext_abi_version())
    if got // 100 != ABI_VERSION // 100 or got % 100 > ABI_VERSION % 100:
        raise MXNetError(
            f"extension {path} was built against ABI {got}, this runtime "
            f"provides {ABI_VERSION} — major versions must match and the "
            "extension's minor may not exceed the runtime's")
    if hasattr(lib, "mxtpu_ext_init"):
        lib.mxtpu_ext_init.restype = ctypes.c_int
        rc = int(lib.mxtpu_ext_init())
        if rc:
            raise MXNetError(f"extension {path} init failed (rc={rc})")

    from .ops.registry import register

    def make_op(idx):
        def make_fn(**attrs):
            if attrs:  # v1 native ops take no attrs — reject, don't ignore
                raise MXNetError(
                    f"native extension ops accept no attrs, got "
                    f"{sorted(attrs)}")

            def f(x):
                arr = onp.ascontiguousarray(onp.asarray(x),
                                            dtype=onp.float32)
                out = onp.empty_like(arr)
                rc = lib.mxtpu_ext_op_compute(
                    idx,
                    arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    arr.size)
                if rc:
                    raise MXNetError(
                        f"native extension op failed (rc={rc})")
                return out
            return f
        return make_fn

    # validate the WHOLE enumeration before touching the registry, so a
    # bad entry (null name, collision with an existing op) cannot leave a
    # half-registered library behind
    names = []
    for i in range(int(lib.mxtpu_ext_num_ops())):
        raw = lib.mxtpu_ext_op_name(i)
        if not raw:
            raise MXNetError(f"extension {path}: op {i} has no name")
        names.append(raw.decode())
    from .ops.registry import _OPS

    taken = [n for n in names if n in _OPS]
    if taken:
        raise MXNetError(
            f"extension {path}: op names already registered: {taken}")
    for i, op_name in enumerate(names):
        register(op_name, make_op(i), differentiable=False, jit=False)
    lib._mxtpu_op_names = names  # introspection for tests/tools
    return lib


def loaded_libraries():
    return list(_loaded)
