"""Extension library loading.

Reference: python/mxnet/library.py + the versioned C ABI
(include/mxnet/lib_api.h, MXLoadLib c_api.cc:1522) for out-of-tree custom
ops / graph passes / subgraph properties. TPU-native extension model: an
extension is a PYTHON module (optionally backed by its own native code or
Pallas kernels) that registers ops via mxnet_tpu.ops.register, custom ops via
mxnet_tpu.operator.register, optimizers/initializers via their registries, or
graph passes via mxnet_tpu.subgraph. ``load()`` imports the module from a
file path and invokes its ``register_ops(registry)`` hook if present.
"""
from __future__ import annotations

import importlib.util
import os
import sys

from .base import MXNetError

__all__ = ["load", "loaded_libraries"]

_loaded: dict[str, object] = {}


def load(path, verbose=True):
    """Load an extension module from a .py file (reference: mx.library.load).

    The module may define ``register_ops()`` which is called after import.
    """
    path = os.path.abspath(path)
    if not os.path.exists(path):
        raise MXNetError(f"extension {path} not found")
    if path in _loaded:
        return _loaded[path]
    name = "mxnet_tpu_ext_" + os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    if spec is None or spec.loader is None:
        raise MXNetError(f"cannot import extension {path}")
    module = importlib.util.module_from_spec(spec)
    sys.modules[name] = module
    spec.loader.exec_module(module)
    if hasattr(module, "register_ops"):
        module.register_ops()
    _loaded[path] = module
    return module


def loaded_libraries():
    return list(_loaded)
