"""mx.nd — legacy NDArray namespace (compatibility layer).

Reference: python/mxnet/ndarray/ndarray.py (22.9k LoC of generated op
wrappers). This framework has ONE array type; the legacy namespace adapts
legacy call conventions (``dim`` instead of ``axis``, CamelCase op names,
``mx.nd.save/load`` binary containers) onto the numpy surface. New code should
use ``mx.np``.
"""
from __future__ import annotations

import numpy as _onp

from .base import MXNetError
from .ndarray.ndarray import NDArray, array
from . import numpy as _np
from . import numpy_extension as _npx
from . import random  # noqa: F401
from .engine import wait_all as waitall

# re-export the numpy surface under legacy names
zeros = _np.zeros
ones = _np.ones
full = _np.full
arange = _np.arange
empty = _np.empty
eye = _np.eye
zeros_like = _np.zeros_like
ones_like = _np.ones_like
add = _np.add
subtract = _np.subtract
multiply = _np.multiply
divide = _np.true_divide
power = _np.power
maximum = _np.maximum
minimum = _np.minimum
exp = _np.exp
log = _np.log
sqrt = _np.sqrt
square = _np.square
abs = _np.abs
sign = _np.sign
sin = _np.sin
cos = _np.cos
tanh = _np.tanh
sigmoid = _npx.sigmoid
relu = _npx.relu
dot = _np.dot
batch_dot = None  # set below
sum = _np.sum
mean = _np.mean
max = _np.max
min = _np.min
argmax = _np.argmax
argmin = _np.argmin
clip = _np.clip
where = _np.where
stack = _np.stack
split = _np.split
take = _np.take
one_hot = _np.one_hot
pick = _np.pick
topk = _np.topk
sort = _np.sort
argsort = _np.argsort
expand_dims = _np.expand_dims
squeeze = _np.squeeze
transpose = _np.transpose
reshape = _np.reshape
tile = _np.tile
repeat = _np.repeat
flip = _np.flip
norm = _np.linalg.norm
softmax = _npx.softmax
log_softmax = _npx.log_softmax
SequenceMask = _npx.sequence_mask
SequenceLast = _npx.sequence_last
SequenceReverse = _npx.sequence_reverse
Activation = _npx.activation
FullyConnected = _npx.fully_connected
Convolution = _npx.convolution
Pooling = _npx.pooling
Dropout = _npx.dropout
Embedding = _npx.embedding
LeakyReLU = _npx.leaky_relu


def concat(*data, dim=1):
    """Legacy concat uses ``dim`` (reference: nd.concat)."""
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = data[0]
    return _np.concatenate(list(data), axis=dim)


def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    a = lhs.transpose((0, 2, 1)) if transpose_a else lhs
    b = rhs.transpose((0, 2, 1)) if transpose_b else rhs
    return _np.matmul(a, b)


def flatten(data):
    return data.reshape((data.shape[0], -1))


def slice_axis(data, axis, begin, end):
    return _npx.slice_axis(data, axis=axis, begin=begin, end=end)


def broadcast_add(a, b):
    return _np.add(a, b)


broadcast_plus = broadcast_add


def broadcast_sub(a, b):
    return _np.subtract(a, b)


def broadcast_mul(a, b):
    return _np.multiply(a, b)


def broadcast_div(a, b):
    return _np.true_divide(a, b)


def broadcast_maximum(a, b):
    return _np.maximum(a, b)


def broadcast_minimum(a, b):
    return _np.minimum(a, b)


def elemwise_add(a, b):
    return _np.add(a, b)


def elemwise_sub(a, b):
    return _np.subtract(a, b)


def elemwise_mul(a, b):
    return _np.multiply(a, b)


# ---------------------------------------------------------------------------
# save / load — reference: NDArray::Save/Load (src/ndarray/ndarray.cc:1729,
# 1852) + python/mxnet/ndarray/utils.py:149,222. We use the .npz container
# (same role; portable numpy interchange like src/serialization/cnpy.cc).
# ---------------------------------------------------------------------------
def save(fname, data):
    # write through a file object: numpy's savez appends '.npz' to bare
    # paths, which would break the reference contract that
    # save(fname) + load(fname) round-trips for ANY name (.params etc.)
    with open(fname, "wb") as f:
        if isinstance(data, NDArray):
            _onp.savez(f, __single__=data.asnumpy())
        elif isinstance(data, list):
            _onp.savez(f, **{f"__list__{i}": d.asnumpy()
                             for i, d in enumerate(data)})
        elif isinstance(data, dict):
            _onp.savez(f, **{k: v.asnumpy() for k, v in data.items()})
        else:
            raise MXNetError(f"cannot save {type(data)}")


def load(fname):
    import os as _os

    if not _os.path.exists(fname) and _os.path.exists(fname + ".npz"):
        fname = fname + ".npz"  # files written by the pre-fix save()
    with _onp.load(fname) as z:
        keys = list(z.keys())
        if keys == ["__single__"]:
            return NDArray(z["__single__"])
        if keys and keys[0].startswith("__list__"):
            return [NDArray(z[f"__list__{i}"]) for i in range(len(keys))]
        return {k: NDArray(z[k]) for k in keys}


# -- generated-wrapper parity: resolve ANY registered op lazily ------------
# (reference: python/mxnet/ndarray op wrappers generated from the C op
# registry at import; here module __getattr__ resolves from ops.registry)
def __getattr__(name):
    from .ops.registry import _OPS, apply_op
    from .symbol import _LEGACY_NAMES

    op_name = _LEGACY_NAMES.get(name, name)
    if op_name not in _OPS:
        raise AttributeError(f"module 'mxnet_tpu.nd' has no attribute "
                             f"{name!r}")

    def wrapper(*inputs, **attrs):
        out = attrs.pop("out", None)
        arrs = [x if isinstance(x, NDArray) else
                (NDArray(x) if hasattr(x, "shape") else x) for x in inputs]
        return apply_op(op_name, *arrs, out=out, **attrs)

    wrapper.__name__ = name
    globals()[name] = wrapper
    return wrapper
