"""Checkpoint helpers (reference: python/mxnet/model.py —
save_checkpoint:189, load_checkpoint:238)."""
from __future__ import annotations

import numpy as onp

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["save_checkpoint", "load_checkpoint", "BatchEndParam"]


class BatchEndParam:
    """Callback payload (reference: model.py BatchEndParam namedtuple)."""

    def __init__(self, epoch=0, nbatch=0, eval_metric=None, locals=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals


def save_checkpoint(prefix, epoch, symbol=None, arg_params=None,
                    aux_params=None):
    """Save symbol + params at ``prefix-{epoch:04d}`` (reference: :189).

    arg_params may be a dict of NDArrays or a Gluon Block.
    """
    from .gluon.block import Block

    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    if isinstance(arg_params, Block):
        arg_params.save_parameters(f"{prefix}-{epoch:04d}.params.npz")
        return
    params = {}
    for name, arr in (arg_params or {}).items():
        params["arg:" + name] = arr.asnumpy()
    for name, arr in (aux_params or {}).items():
        params["aux:" + name] = arr.asnumpy()
    onp.savez(f"{prefix}-{epoch:04d}.params.npz", **params)


def load_checkpoint(prefix, epoch):
    """Load (symbol, arg_params, aux_params) (reference: :238)."""
    import os

    from .symbol.symbol import Symbol

    sym = None
    if os.path.exists(f"{prefix}-symbol.json"):
        sym = Symbol.load(f"{prefix}-symbol.json")
    path = f"{prefix}-{epoch:04d}.params.npz"
    arg_params, aux_params = {}, {}
    with onp.load(path) as z:
        for key in z.keys():
            if key.startswith("arg:"):
                arg_params[key[4:]] = NDArray(z[key])
            elif key.startswith("aux:"):
                aux_params[key[4:]] = NDArray(z[key])
            else:
                arg_params[key] = NDArray(z[key])
    return sym, arg_params, aux_params
