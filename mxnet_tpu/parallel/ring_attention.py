"""Ring attention: sequence/context parallelism for long sequences.

The reference has NO sequence parallelism (SURVEY §5.7) — this is a
first-class TPU-native capability of this framework. Design (blockwise /
ring attention): the sequence axis is sharded over the mesh's ``sp`` axis;
each device holds its Q, K, V shard, computes blockwise attention against the
K/V block it currently holds while the K/V blocks rotate around the ring via
``lax.ppermute`` (XLA lowers this to ICI neighbor exchanges that overlap with
the attention compute). Softmax is accumulated online (running max /
denominator), so the full T×T score matrix never materializes and max
sequence length scales linearly with the number of devices.

Composable: inside each step the local block computation routes through the
Pallas flash-attention kernel on TPU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError

__all__ = ["ring_attention", "ring_attention_sharded"]


def _block_attn(q, k, v, scale, mask_val=None):
    """One blockwise attention contribution with un-normalized accumulators.

    Returns (acc, m, l): acc = sum_j exp(s_ij - m_i) v_j, row max m, row sum l.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask_val is not None:
        s = jnp.where(mask_val, s, -1e30)
    m = jnp.max(s, axis=-1, keepdims=True)  # (b,h,q,1)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(p.dtype))
    return acc, m, l


def _merge(acc1, m1, l1, acc2, m2, l2):
    """Merge two online-softmax partial results."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    return acc1 * a1 + acc2 * a2, m, l1 * a1 + l2 * a2


def ring_attention(q, k, v, axis_name, scale=None, causal=False):
    """Per-shard ring attention body (call inside shard_map/pjit).

    q, k, v: the LOCAL sequence shard, shape (B, H, T_local, D). The global
    sequence is the concatenation over ``axis_name`` in ring order.
    """
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    tq = q.shape[2]

    def causal_mask(kv_owner):
        # global row index of q_i = my*tq + i; col of k_j = kv_owner*tq + j
        qi = my * tq + jax.lax.broadcasted_iota(jnp.int32, (tq, tq), 0)
        ki = kv_owner * tq + jax.lax.broadcasted_iota(jnp.int32, (tq, tq), 1)
        return (qi >= ki)[None, None]

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, _):
        acc, m, l, kr, vr, owner = carry
        mask = causal_mask(owner) if causal else None
        a2, m2, l2 = _block_attn(q, kr, vr, s, mask)
        acc, m, l = _merge(acc, m, l, a2, m2, l2)
        kr = lax.ppermute(kr, axis_name, perm)
        vr = lax.ppermute(vr, axis_name, perm)
        owner = ((owner - 1) % n).astype(jnp.int32)
        return (acc, m, l, kr, vr, owner), None

    b, h = q.shape[0], q.shape[1]
    acc0 = jnp.zeros((b, h, tq, d), jnp.float32)
    m0 = jnp.full((b, h, tq, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((b, h, tq, 1), jnp.float32)
    # initial accumulators are literal zeros (axis-invariant); promote them
    # to exactly the varying axes the loop body produces — not just the
    # ring axis: under a multi-axis mesh q/k/v can vary over dp/tp/pp too
    from .collectives import match_carry_vma

    carry0 = match_carry_vma(
        lambda c, _x: step(c, _x), (acc0, m0, l0, k, v, jnp.int32(my)), None,
        fallback_axis=axis_name)
    (acc, m, l, _, _, _), _ = lax.scan(step, carry0, None, length=n)
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, axis=None, scale=None,
                           causal=False):
    """User-facing entry: global (B, H, T, D) arrays, T sharded over ``sp``.

    Wraps :func:`ring_attention` in shard_map over ``mesh``; accepts framework
    NDArrays or jax arrays and returns the same kind.
    """
    from .mesh import shard_map_compat

    from ..ndarray.ndarray import NDArray
    from .mesh import AxisNames

    axis = axis or AxisNames.SP
    if axis not in mesh.shape:
        raise MXNetError(f"mesh has no axis {axis!r}; axes: "
                         f"{dict(mesh.shape)}")
    wrap = isinstance(q, NDArray)
    qd = q._data if isinstance(q, NDArray) else q
    kd = k._data if isinstance(k, NDArray) else k
    vd = v._data if isinstance(v, NDArray) else v
    spec = P(None, None, axis, None)
    fn = shard_map_compat(
        functools.partial(ring_attention, axis_name=axis, scale=scale,
                          causal=causal),
        mesh, in_specs=(spec, spec, spec), out_specs=spec)
    out = jax.jit(fn)(qd, kd, vd)
    return NDArray(out) if wrap else out
