"""mxnet_tpu.parallel — SPMD scale-out over TPU meshes.

This package is the TPU-native answer to everything the reference does with
NCCL + ps-lite (SURVEY §2.2, §5.8): instead of push/pull of gradients between
processes, the WHOLE training step is one pjit-compiled SPMD program over a
``jax.sharding.Mesh`` whose collectives ride ICI/DCN. Axes follow the
scaling-book convention: ``dp`` (data), ``tp`` (tensor/model), ``pp``
(pipeline), ``sp`` (sequence/context), ``ep`` (expert).

- mesh.py        — mesh construction + sharding helpers
- collectives.py — psum/all_gather/ppermute wrappers for shard_map kernels
- partition.py   — regex partition rules over named param trees (FSDP/tp)
- learner.py     — Learner: gluon Block -> jitted sharded train step
"""
from .mesh import (make_mesh, default_mesh, replicated, shard_batch,
                   shard_params, AxisNames)
from .collectives import (all_reduce, all_gather, reduce_scatter, ppermute,
                          axis_index, axis_size)
from .partition import (match_partition_rules, named_tree_map, fsdp_rules,
                        spec_axes)
from .learner import Learner, to_optax
from .ring_attention import ring_attention, ring_attention_sharded
from .pipeline import pipeline_apply, pipeline_sharded
from .moe import moe_apply, moe_sharded
from .five_axis import (build_five_axis_train_step, init_five_axis_params,
                        five_axis_specs)

__all__ = ["make_mesh", "default_mesh", "replicated", "shard_batch",
           "shard_params", "AxisNames", "all_reduce", "all_gather",
           "reduce_scatter", "ppermute", "axis_index", "axis_size",
           "Learner", "to_optax", "ring_attention",
           "ring_attention_sharded", "pipeline_apply", "pipeline_sharded",
           "moe_apply", "moe_sharded", "build_five_axis_train_step",
           "init_five_axis_params", "five_axis_specs",
           "match_partition_rules", "named_tree_map", "fsdp_rules",
           "spec_axes"]
