"""Device-mesh construction and sharding helpers.

TPU-native replacement for the reference's device-topology machinery
(src/kvstore/gpu_topology.h tree solver; comm device lists): on TPU the
topology is a torus XLA already understands, so the framework's job is only to
pick logical axis names and sizes. Shardings are expressed as
jax.sharding.NamedSharding over the mesh.
"""
from __future__ import annotations

import jax
import numpy as onp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError

__all__ = ["AxisNames", "make_mesh", "default_mesh", "replicated",
           "shard_batch", "shard_params", "shard_map_compat", "P",
           "shard_1d", "zeros_sharded", "axis_extent",
           "bytes_per_replica"]


class AxisNames:
    DP = "dp"   # data parallel
    TP = "tp"   # tensor/model parallel
    PP = "pp"   # pipeline parallel
    SP = "sp"   # sequence/context parallel
    EP = "ep"   # expert parallel


def make_mesh(axes: dict[str, int] | None = None, devices=None) -> Mesh:
    """Build a Mesh from {axis_name: size}. Sizes must multiply to #devices.

    ``make_mesh({'dp': 4, 'tp': 2})`` on 8 devices. Pass -1 for one axis to
    absorb the remainder (like reshape).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not axes:
        axes = {AxisNames.DP: n}
    names = list(axes)
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise MXNetError("only one mesh axis may be -1")
    if -1 in sizes:
        known = 1
        for s in sizes:
            if s != -1:
                known *= s
        sizes[sizes.index(-1)] = n // known
    total = 1
    for s in sizes:
        total *= s
    if total != n:
        raise MXNetError(f"mesh axes {dict(zip(names, sizes))} do not cover "
                         f"{n} devices")
    arr = onp.array(devices).reshape(sizes)
    return Mesh(arr, names)


def default_mesh() -> Mesh:
    """All local devices on a single 'dp' axis."""
    return make_mesh()


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, axis: str = AxisNames.DP) -> NamedSharding:
    """Shard dim 0 (batch) over ``axis``; everything else replicated."""
    return NamedSharding(mesh, P(axis))


def shard_map_compat(f, mesh: Mesh, in_specs, out_specs):
    """``shard_map`` across the jax API moves (experimental -> top level,
    check_rep -> check_vma). Replication checking is disabled: the compiled
    train step mixes per-shard values (``axis_index``-folded RNG keys) with
    psum'ed results, which the static rep checker over-rejects on some
    versions."""
    if hasattr(jax, "shard_map"):  # jax >= 0.6
        try:
            return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False)
        except TypeError:
            return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def shard_1d(mesh: Mesh, axis: str = AxisNames.DP) -> NamedSharding:
    """Shard a flat (1-D) buffer over ``axis`` — the layout of the ZeRO-1
    optimizer-state buckets (each replica owns one contiguous 1/N slice)."""
    return NamedSharding(mesh, P(axis))


def axis_extent(mesh: Mesh, axis: str) -> int:
    """Size of ``axis`` in ``mesh`` (0 when the axis is absent)."""
    return int(mesh.shape.get(axis, 0))


def zeros_sharded(mesh: Mesh, shape, dtype, spec) -> jax.Array:
    """Allocate zeros directly under ``NamedSharding(mesh, spec)``.

    The allocation happens INSIDE a jitted program with an output sharding
    constraint, so no replica ever materializes the full buffer — each
    device writes only its shard. This is how the sharded weight update
    gets optimizer state that is 1/N-sized from the very first step, not
    full-sized-then-resharded.
    """
    sharding = NamedSharding(mesh, spec)
    import jax.numpy as jnp

    fn = jax.jit(lambda: jnp.zeros(shape, dtype), out_shardings=sharding)
    return fn()


def bytes_per_replica(arr) -> int:
    """Bytes of ``arr`` ONE replica actually holds: the first addressable
    shard's buffer size (uniform shards — every 1/N residency claim in the
    sharded train step is this number), or the whole buffer for an
    unsharded array."""
    shards = getattr(arr, "addressable_shards", None)
    if shards:
        return shards[0].data.nbytes
    return arr.nbytes


def shard_params(mesh: Mesh, spec_fn=None):
    """Return a function NDArray/jax.Array -> NamedSharding for parameters.

    By default parameters are replicated (pure DP). ``spec_fn(name, shape)``
    may return a PartitionSpec for tensor-parallel layouts (e.g. shard the
    hidden dim of big matmuls over 'tp').
    """
    def f(name, arr):
        if spec_fn is not None:
            spec = spec_fn(name, tuple(arr.shape))
            if spec is not None:
                return NamedSharding(mesh, spec)
        return NamedSharding(mesh, P())

    return f
