"""One training step over ALL five parallelism axes in a single program.

The reference framework's distributed story is data parallelism plus manual
model parallelism (SURVEY §2.2); this module is the TPU-native superset: a
single jit-compiled SPMD training step over a ``Mesh`` with axis groups

    dp — batch sharding (gradient psum)
    tp — Megatron-style column/row-parallel attention projections (psum)
    pp — GPipe pipeline over stacked stages (ppermute ring)
    sp — ring attention over the sequence axis (ppermute ring)
    ep — mixture-of-experts token dispatch (all_to_all)

Model: a residual pre-norm transformer stack. Each pipeline stage is one
block: RMSNorm → multi-head ring attention (qkv column-parallel over tp,
output row-parallel + psum) → RMSNorm → top-1 MoE FFN (experts sharded over
ep, tokens split/all_to_all'd/gathered). The whole fwd+bwd+SGD update is one
XLA program; every collective rides the mesh (ICI on real hardware).

This is what ``__graft_entry__.dryrun_multichip`` compiles each round to
certify the multi-chip story without real chips.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError
from .moe import moe_apply
from .pipeline import pipeline_apply
from .ring_attention import ring_attention

__all__ = ["five_axis_specs", "init_five_axis_params",
           "build_five_axis_train_step"]

_FIVE = ("dp", "tp", "pp", "sp", "ep")


def _rmsnorm(x, g):
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * jax.lax.rsqrt(var + 1e-6) * g


def five_axis_specs(n_heads):
    """PartitionSpecs for the stage-stacked parameter pytree (leading axis =
    pipeline stage, sharded over pp)."""
    return {
        "ln1": P("pp", None),
        "wq": P("pp", None, "tp"),
        "wk": P("pp", None, "tp"),
        "wv": P("pp", None, "tp"),
        "wo": P("pp", "tp", None),
        "ln2": P("pp", None),
        "gate": P("pp", None, None),
        "w1": P("pp", "ep", None, None),
        "w2": P("pp", "ep", None, None),
    }


def init_five_axis_params(rng, n_stages, d_model, n_heads, n_experts, d_ff,
                          n_classes, dtype=jnp.float32):
    """Stage-stacked transformer-MoE parameters (host numpy → jax)."""
    import numpy as onp

    r = onp.random.RandomState(rng)
    s = 0.05

    def w(*shape):
        return jnp.asarray(r.randn(*shape).astype("float32") * s, dtype)

    stages = {
        "ln1": jnp.ones((n_stages, d_model), dtype),
        "wq": w(n_stages, d_model, d_model),
        "wk": w(n_stages, d_model, d_model),
        "wv": w(n_stages, d_model, d_model),
        "wo": w(n_stages, d_model, d_model),
        "ln2": jnp.ones((n_stages, d_model), dtype),
        "gate": w(n_stages, d_model, n_experts),
        "w1": w(n_stages, n_experts, d_model, d_ff),
        "w2": w(n_stages, n_experts, d_ff, d_model),
    }
    return {"stages": stages, "out_w": w(d_model, n_classes)}


def _block(p, x, n_heads, moe_capacity):
    """One transformer block on one device's shard. x: (mb, T_local, D)."""
    mb, t, d = x.shape
    tp_n = lax.psum(1, "tp")
    h_local = n_heads // tp_n

    # -- attention: column-parallel qkv (local out-features), ring over sp --
    h = _rmsnorm(x, p["ln1"])

    def heads(a):  # (mb, T, d/tp) -> (mb, h_local, T, hd)
        return a.reshape(mb, t, h_local, -1).transpose(0, 2, 1, 3)

    q, k, v = (heads(h @ p[n]) for n in ("wq", "wk", "wv"))
    attn = ring_attention(q, k, v, axis_name="sp", causal=True)
    attn = attn.transpose(0, 2, 1, 3).reshape(mb, t, -1)
    # row-parallel output projection: partial matmul + psum over tp
    attn = lax.psum(attn @ p["wo"], "tp")
    x = x + attn

    # -- MoE FFN: tokens split over ep, all_to_all dispatch, gather back --
    h2 = _rmsnorm(x, p["ln2"]).reshape(mb * t, d)
    ep_n = lax.psum(1, "ep")
    ep_i = lax.axis_index("ep")
    chunk = (mb * t) // ep_n
    xe = lax.dynamic_slice_in_dim(h2, ep_i * chunk, chunk, axis=0)
    ye = moe_apply(xe, p["gate"], p["w1"], p["w2"], axis_name="ep",
                   capacity=moe_capacity)
    yfull = lax.all_gather(ye, "ep", axis=0, tiled=True)
    return x + yfull.reshape(mb, t, d)


def _loss_body(params, x, y, n_heads, num_microbatches, moe_capacity):
    """Per-shard loss (inside shard_map). x: (B_local, T_local, D) block of
    the (dp, sp)-sharded input; y: (B_local, T_local) int labels."""
    b, t, d = x.shape
    mb = b // num_microbatches  # divisibility checked in validate()
    xmb = x.reshape(num_microbatches, mb, t, d)
    stage_fn = functools.partial(_block, n_heads=n_heads,
                                 moe_capacity=moe_capacity)
    out = pipeline_apply(stage_fn, params["stages"], xmb, axis_name="pp")
    out = out.reshape(b, t, d)
    logits = out @ params["out_w"]  # (B_local, T_local, C)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, y[..., None], axis=-1)[..., 0]
    # mean over the global batch: psum the (dp, sp)-sharded partial sums
    total = lax.psum(jnp.sum(nll), ("dp", "sp"))
    count = lax.psum(jnp.float32(nll.size), ("dp", "sp"))
    # the value is already equal on every tp/pp/ep member (psum over tp,
    # pipeline psum over pp, all_gather over ep) but may still be TYPED as
    # varying over some of them; pmean over exactly those axes certifies
    # replication so out_specs=P() holds
    from .collectives import _vma

    val = total / count
    rem = tuple(sorted(_vma(val)))
    return lax.pmean(val, rem) if rem else val


def build_five_axis_train_step(mesh, n_heads, lr=0.1, num_microbatches=None,
                               moe_capacity=8):
    """Compile fwd+bwd+SGD over a 5-axis mesh. Returns (step, place).

    ``place(params, x, y)`` pins arrays to their mesh shardings;
    ``step(params, x, y) -> (new_params, loss)`` is the jit'd program.
    Constraints (all checked): stage count == pp size; n_heads % tp == 0;
    experts % ep == 0; local tokens % ep == 0.
    """
    missing = [a for a in _FIVE if a not in mesh.shape]
    if missing:
        raise MXNetError(
            f"five-axis step needs mesh axes {_FIVE}; missing {missing}")
    num_microbatches = num_microbatches or max(mesh.shape["pp"], 1)
    if n_heads % mesh.shape["tp"]:
        raise MXNetError(f"n_heads {n_heads} not divisible by tp size "
                         f"{mesh.shape['tp']}")

    stage_specs = five_axis_specs(n_heads)
    param_specs = {"stages": stage_specs, "out_w": P(None, None)}
    x_spec, y_spec = P("dp", "sp", None), P("dp", "sp")

    def validate(params, x):
        """Trace-time shape checks (static shapes; raises before compile).

        pipeline_apply consumes exactly ONE stage per pp shard — a stage
        count that merely *divides* pp would shard to local length >1 and
        silently drop layers, so equality is required, not divisibility.
        """
        pp, ep = mesh.shape["pp"], mesh.shape["ep"]
        dp, sp = mesh.shape["dp"], mesh.shape["sp"]
        for name, leaf in params["stages"].items():
            if leaf.shape[0] != pp:
                raise MXNetError(
                    f"stage leaf {name!r} has {leaf.shape[0]} stages but the "
                    f"mesh has pp={pp}; the pipeline runs exactly one stage "
                    "per pp shard (extra stages would be silently dropped)")
        n_experts = params["stages"]["gate"].shape[-1]
        if n_experts % ep:
            raise MXNetError(
                f"n_experts {n_experts} not divisible by ep size {ep}")
        b, t = x.shape[0], x.shape[1]
        if b % dp or t % sp:
            raise MXNetError(
                f"batch {b} / seq {t} not divisible by dp={dp} / sp={sp}")
        b_local, t_local = b // dp, t // sp
        if b_local % num_microbatches:
            raise MXNetError(
                f"local batch {b_local} not divisible by "
                f"{num_microbatches} microbatches")
        tokens = (b_local // num_microbatches) * t_local
        if tokens % ep:
            raise MXNetError(
                f"local microbatch tokens {tokens} not divisible by ep size "
                f"{ep}; the MoE dispatch would silently truncate tokens")

    from .mesh import shard_map_compat

    loss_sm = shard_map_compat(
        functools.partial(_loss_body, n_heads=n_heads,
                          num_microbatches=num_microbatches,
                          moe_capacity=moe_capacity),
        mesh,
        in_specs=(param_specs, x_spec, y_spec),
        out_specs=P(),
    )

    def step(params, x, y):
        validate(params, x)
        loss, grads = jax.value_and_grad(loss_sm)(params, x, y)
        new = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return new, loss

    def place(params, x, y):
        def pin(tree, specs):
            return jax.tree_util.tree_map(
                lambda a, sp: jax.device_put(a, NamedSharding(mesh, sp)),
                tree, specs)

        return (pin(params, param_specs),
                jax.device_put(x, NamedSharding(mesh, x_spec)),
                jax.device_put(y, NamedSharding(mesh, y_spec)))

    return jax.jit(step), place
