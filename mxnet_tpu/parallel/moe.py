"""Expert parallelism: mixture-of-experts with experts sharded over 'ep'.

Not present in the reference (SURVEY §2.2: EP absent). TPU-native design:
expert weights are stacked on a leading expert axis sharded over ``ep``;
tokens are top-1 routed, exchanged between devices with ``lax.all_to_all``
(ICI), processed by the local experts, and returned. Capacity-factor dropping
keeps shapes static for XLA.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..base import MXNetError

__all__ = ["moe_apply", "moe_sharded"]


def moe_apply(x, gate_w, expert_w1, expert_w2, axis_name="ep", capacity=None):
    """Per-shard MoE body (call inside shard_map).

    x: (T_local, D) local token shard; gate_w: (D, E_total) replicated;
    expert_w1: (E_local, D, H), expert_w2: (E_local, H, D) — local experts.
    Top-1 routing with per-expert capacity; overflow tokens pass through.
    """
    n_dev = lax.psum(1, axis_name)
    t_local, d = x.shape
    e_local = expert_w1.shape[0]
    e_total = e_local * n_dev
    cap = capacity or max(1, (t_local // e_total) * 2)

    logits = x @ gate_w  # (T, E_total)
    expert_id = jnp.argmax(logits, axis=-1)  # (T,)
    gate = jax.nn.softmax(logits, axis=-1)
    gate_val = jnp.take_along_axis(gate, expert_id[:, None], axis=1)[:, 0]

    # slot each token into its expert's capacity buffer (static shapes)
    onehot = jax.nn.one_hot(expert_id, e_total, dtype=jnp.int32)  # (T, E)
    pos = jnp.cumsum(onehot, axis=0) * onehot  # 1-based slot per token
    slot = jnp.sum(pos, axis=-1) - 1  # (T,)
    keep = slot < cap
    # dispatch buffer: (E_total, cap, D)
    dispatch = jnp.zeros((e_total, cap, d), x.dtype)
    tok_idx = jnp.where(keep, expert_id, 0)
    slot_idx = jnp.where(keep, slot, 0)
    contrib = jnp.where(keep[:, None], x, 0.0)
    dispatch = dispatch.at[tok_idx, slot_idx].add(contrib)

    # all_to_all: every device sends each expert-group to its owner
    # (E_total, cap, D) -> split E_total over devices -> concat on a new axis
    shaped = dispatch.reshape(n_dev, e_local, cap, d)
    recv = lax.all_to_all(shaped, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)  # (n_dev, e_local, cap, d)
    recv = recv.transpose(1, 0, 2, 3).reshape(e_local, n_dev * cap, d)

    # local expert MLPs (batched over local experts)
    h = jax.nn.relu(jnp.einsum("ecd,edh->ech", recv, expert_w1))
    y = jnp.einsum("ech,ehd->ecd", h, expert_w2)

    # route results back to the source devices
    y = y.reshape(e_local, n_dev, cap, d).transpose(1, 0, 2, 3)
    back = lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)
    back = back.reshape(e_total, cap, d)

    out = back[tok_idx, slot_idx]  # (T, D)
    out = jnp.where(keep[:, None], out * gate_val[:, None], x)  # overflow: pass-through
    return out


def moe_sharded(x, gate_w, expert_w1, expert_w2, mesh, axis="ep",
                capacity=None):
    """User-facing MoE layer over a mesh: tokens sharded over ``ep``,
    experts sharded over ``ep``, gate replicated."""
    from .mesh import shard_map_compat

    from ..ndarray.ndarray import NDArray

    if axis not in mesh.shape:
        raise MXNetError(f"mesh has no axis {axis!r}")
    unwrap = lambda a: a._data if isinstance(a, NDArray) else a  # noqa: E731
    xd, gw, w1, w2 = map(unwrap, (x, gate_w, expert_w1, expert_w2))
    fn = shard_map_compat(
        functools.partial(moe_apply, axis_name=axis, capacity=capacity),
        mesh,
        in_specs=(P(axis), P(), P(axis), P(axis)),
        out_specs=P(axis),
    )
    out = jax.jit(fn)(xd, gw, w1, w2)
    return NDArray(out) if isinstance(x, NDArray) else out
