"""Regex partition rules over named parameter trees.

The production idiom for declaring sharding layouts (EasyLM / fmengine
lineage, SNIPPETS [3]): an ORDERED list of ``(regex, PartitionSpec)`` rules
is matched against the slash-joined path of every leaf in a named parameter
tree. First matching rule wins; scalar (and size-1) leaves are never
partitioned; a leaf no rule covers is an explicit error naming the offending
path — silent replication of a 30k x 4k embedding is exactly the bug this
API exists to prevent.

A rule may be a 3-tuple ``(regex, PartitionSpec, meta)`` carrying layout
metadata the PartitionSpec itself cannot: ``meta={"segments": S}`` marks a
weight as S stacked logical blocks along its sharded dimension (the fused
QKV projection: S=3), so tensor-parallel slicing splits each block
per-rank instead of splitting the stack.

Consumers sharing the vocabulary:

- ``parallel.five_axis`` layouts (tp/pp/ep specs over stage-stacked trees)
  can be written as rules and expanded with ``match_partition_rules`` —
  rules mixing 'dp' with 'tp'/'pp' compose on one mesh because a
  PartitionSpec is just named mesh axes.
- ``Trainer.compile_step(shard_params=True)`` (FSDP): the rules decide
  which trainables live dp-sharded. On a dp x tp mesh, rules naming 'tp'
  declare megatron column/row splits executed INSIDE the same compiled
  step. ``fsdp_groups`` folds both kinds into per-layer flat buckets
  (``collectives.BucketSpec``) — the gather/scatter schedule of the
  compiled step.
"""
from __future__ import annotations

import collections
import re

from jax.sharding import PartitionSpec as PS

from ..base import MXNetError

__all__ = ["named_tree_map", "match_partition_rules", "spec_axes",
           "fsdp_rules", "layer_key", "fsdp_groups", "RuleMatch"]


#: One matched rule: the PartitionSpec, the rule's metadata dict, and the
#: regex pattern that matched (None for the scalar exemption / direct
#: specs) — kept so downstream errors can name the offending RULE, not
#: just the leaf path.
RuleMatch = collections.namedtuple("RuleMatch", ["spec", "meta", "pattern"])


def named_tree_map(fn, tree, sep="/"):
    """Map ``fn(path, leaf)`` over a nested dict/list/tuple tree, building
    the slash-joined path from the keys/indices along the way. Anything
    that is not a dict/list/tuple is a leaf (jax arrays, NDArrays,
    Parameters, scalars). Returns a tree of the same structure."""
    def walk(path, node):
        if isinstance(node, dict):
            return {k: walk(f"{path}{sep}{k}" if path else str(k), v)
                    for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            out = [walk(f"{path}{sep}{i}" if path else str(i), v)
                   for i, v in enumerate(node)]
            return tuple(out) if isinstance(node, tuple) else out
        return fn(path, node)
    return walk("", tree)


def _leaf_shape(path, leaf):
    shape = getattr(leaf, "shape", None)
    if shape is None:
        if isinstance(leaf, (int, float, complex, bool)):
            return ()
        raise MXNetError(
            f"parameter {path!r} has no known shape (deferred init?); "
            "initialize the tree before matching partition rules")
    if any(d is None or d <= 0 for d in shape):
        # gluon marks not-yet-inferred dims with 0/-1 (parameter._shape_known)
        raise MXNetError(
            f"parameter {path!r} has unresolved shape {tuple(shape)}; run a "
            "settle forward before matching partition rules")
    return tuple(int(d) for d in shape)


def _expand_rules(rules):
    """Normalize 2-/3-tuple rules to ``(regex, spec, meta)``."""
    out = []
    for rule in rules:
        if len(rule) == 2:
            pattern, spec = rule
            meta = {}
        elif len(rule) == 3:
            pattern, spec, meta = rule
        else:
            raise MXNetError(
                "partition rules are (regex, PartitionSpec) or (regex, "
                f"PartitionSpec, meta) tuples; got {rule!r}")
        out.append((pattern, spec, dict(meta or {})))
    return out


def match_partition_rules(rules, tree, sep="/", with_meta=False):
    """Expand ``rules`` — an ordered iterable of ``(regex, PartitionSpec)``
    or ``(regex, PartitionSpec, meta)`` — over ``tree``, returning a
    same-structure tree of PartitionSpecs (or :class:`RuleMatch` triples
    with ``with_meta=True``).

    Contract (the SNIPPETS [3] semantics, hardened):
    - scalar and size-1 leaves get ``PS()`` without consulting the rules
      (partitioning a scalar is never meaningful);
    - the FIRST rule whose regex ``re.search``-matches the leaf's path
      wins — order your specific rules before the catch-all;
    - a leaf no rule matches raises ``MXNetError`` naming the path.
    """
    rules = _expand_rules(rules)

    def get(path, leaf):
        shape = _leaf_shape(path, leaf)
        size = 1
        for d in shape:
            size *= d
        if not shape or size == 1:
            return RuleMatch(PS(), {}, None) if with_meta else PS()
        for pattern, spec, meta in rules:
            if re.search(pattern, path) is not None:
                return RuleMatch(spec, meta, pattern) if with_meta else spec
        raise MXNetError(
            f"no partition rule matched parameter {path!r} "
            f"(shape {shape}); add a rule or a catch-all ('.*', PS(...))")

    return named_tree_map(get, tree, sep=sep)


def spec_axes(spec):
    """The set of mesh axis names a PartitionSpec mentions (entries may be
    None, a name, or a tuple of names)."""
    axes = set()
    for entry in tuple(spec):
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            axes.update(entry)
        else:
            axes.add(entry)
    return axes


def fsdp_rules():
    """The default full-parameter-sharding rule set: every non-scalar
    trainable shards over 'dp' (match_partition_rules exempts scalar and
    size-1 leaves on its own)."""
    return ((r".*", PS("dp")),)


def layer_key(name, sep="."):
    """The gather/scatter granule a parameter belongs to: its owning
    layer's name prefix ('encoder.layers.0.attn_qkv.weight' and '....bias'
    gather together; a bare name is its own layer)."""
    return name.rsplit(sep, 1)[0] if sep in name else name


def _spec_of(value):
    """``(spec, meta, pattern)`` from a plain PartitionSpec or RuleMatch."""
    if isinstance(value, RuleMatch):
        return value.spec, value.meta, value.pattern
    return value, {}, None


def fsdp_groups(entries, specs, n_shards, axis="dp", sep=".",
                tp_axis="tp", tp_size=1):
    """Fold flat named trainables into the per-layer bucket schedule.

    ``entries``: ordered ``(key, name, shape, dtype_str)`` tuples (key is
    the caller's position index); ``specs``: ``{name: PartitionSpec}`` (or
    ``{name: RuleMatch}`` from ``match_partition_rules(with_meta=True)``).

    Leaves whose spec mentions ``axis`` group into one ``BucketSpec`` per
    (layer, dtype) sharded 1/N over ``axis``; the rest (scalars, size-1,
    explicitly replicated leaves) pool into per-dtype replicated buckets
    updated identically on every shard. On a dp x tp mesh (``tp_size >=
    2``) a spec naming ``tp_axis`` declares a megatron split: the group's
    BucketSpec is built over the per-rank LOCAL shapes (each tp rank owns
    a disjoint 1/tp of the tensor, itself dp-sharded 1/N) and ``sharded``
    is the string ``"tp"``. Any other axis is rejected with an error
    naming the offending RULE pattern — a misconfigured rule list must be
    debuggable from the message alone.

    Returns ``[(layer, dtype, keys, BucketSpec, sharded)]`` in
    first-appearance order (the schedule order of the compiled program),
    ``sharded in (False, True, "tp")``.
    """
    from . import tp as _tp
    from .collectives import BucketSpec

    supported = {axis} | ({tp_axis} if tp_size > 1 else set())
    grouped = {}   # (layer, dtype, sharded) -> [(key, shape)]
    order = []
    for key, name, shape, dtype in entries:
        spec, meta, pattern = _spec_of(specs[name])
        axes = spec_axes(spec)
        extra = axes - supported
        if extra:
            rule = (f"rule {pattern!r}" if pattern is not None
                    else f"spec {spec}")
            if "pp" in extra:
                hint = ("pipeline-stage layouts are scheduled by "
                        "parallel.pipeline (schedule_1f1b), not sharded "
                        "inside the dp x tp step")
            elif tp_axis in extra:
                hint = (f"'{tp_axis}' rules need a mesh carrying a "
                        f"'{tp_axis}' axis of size >= 2 — compose one "
                        "with make_mesh({'dp': ..., 'tp': ...})")
            else:
                hint = ("other axis layouts belong to parallel.five_axis "
                        "/ parallel.learner")
            raise MXNetError(
                f"partition {rule} matched {name!r} but names mesh axes "
                f"{sorted(extra)} unsupported inside compile_step; {hint}")
        if tp_axis in axes:
            dim = _tp.tp_dim(spec, axis=tp_axis)
            segments = int(meta.get("segments", 1))
            what = (f"{name!r} (rule {pattern!r})" if pattern is not None
                    else f"{name!r}")
            _tp._check_divisible(shape, dim, tp_size, segments, what=what)
            shape = _tp.local_shape(shape, dim, tp_size, segments)
            sharded = "tp"
        else:
            sharded = axis in axes
        gk = (layer_key(name, sep=sep) if sharded else "_replicated",
              dtype, sharded)
        if gk not in grouped:
            grouped[gk] = []
            order.append(gk)
        grouped[gk].append((key, shape))
    out = []
    for layer, dtype, sharded in order:
        items = grouped[(layer, dtype, sharded)]
        keys = [k for k, _ in items]
        shapes = [s for _, s in items]
        bs = BucketSpec(shapes, n_shards if sharded else 1)
        out.append((layer, dtype, keys, bs, sharded))
    return out
