"""Regex partition rules over named parameter trees.

The production idiom for declaring sharding layouts (EasyLM / fmengine
lineage, SNIPPETS [3]): an ORDERED list of ``(regex, PartitionSpec)`` rules
is matched against the slash-joined path of every leaf in a named parameter
tree. First matching rule wins; scalar (and size-1) leaves are never
partitioned; a leaf no rule covers is an explicit error naming the offending
path — silent replication of a 30k x 4k embedding is exactly the bug this
API exists to prevent.

Two consumers share the vocabulary:

- ``parallel.five_axis`` layouts (tp/pp/ep specs over stage-stacked trees)
  can be written as rules and expanded with ``match_partition_rules`` —
  rules mixing 'dp' with 'tp'/'pp' compose on one mesh because a
  PartitionSpec is just named mesh axes.
- ``Trainer.compile_step(shard_params=True)`` (FSDP): the rules decide
  which trainables live dp-sharded. ``fsdp_groups`` then folds the sharded
  leaves into per-layer flat buckets (``collectives.BucketSpec``) — the
  gather/scatter schedule of the compiled step.
"""
from __future__ import annotations

import re

from jax.sharding import PartitionSpec as PS

from ..base import MXNetError

__all__ = ["named_tree_map", "match_partition_rules", "spec_axes",
           "fsdp_rules", "layer_key", "fsdp_groups"]


def named_tree_map(fn, tree, sep="/"):
    """Map ``fn(path, leaf)`` over a nested dict/list/tuple tree, building
    the slash-joined path from the keys/indices along the way. Anything
    that is not a dict/list/tuple is a leaf (jax arrays, NDArrays,
    Parameters, scalars). Returns a tree of the same structure."""
    def walk(path, node):
        if isinstance(node, dict):
            return {k: walk(f"{path}{sep}{k}" if path else str(k), v)
                    for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            out = [walk(f"{path}{sep}{i}" if path else str(i), v)
                   for i, v in enumerate(node)]
            return tuple(out) if isinstance(node, tuple) else out
        return fn(path, node)
    return walk("", tree)


def _leaf_shape(path, leaf):
    shape = getattr(leaf, "shape", None)
    if shape is None:
        if isinstance(leaf, (int, float, complex, bool)):
            return ()
        raise MXNetError(
            f"parameter {path!r} has no known shape (deferred init?); "
            "initialize the tree before matching partition rules")
    if any(d is None or d <= 0 for d in shape):
        # gluon marks not-yet-inferred dims with 0/-1 (parameter._shape_known)
        raise MXNetError(
            f"parameter {path!r} has unresolved shape {tuple(shape)}; run a "
            "settle forward before matching partition rules")
    return tuple(int(d) for d in shape)


def match_partition_rules(rules, tree, sep="/"):
    """Expand ``rules`` — an ordered iterable of ``(regex, PartitionSpec)``
    — over ``tree``, returning a same-structure tree of PartitionSpecs.

    Contract (the SNIPPETS [3] semantics, hardened):
    - scalar and size-1 leaves get ``PS()`` without consulting the rules
      (partitioning a scalar is never meaningful);
    - the FIRST rule whose regex ``re.search``-matches the leaf's path
      wins — order your specific rules before the catch-all;
    - a leaf no rule matches raises ``MXNetError`` naming the path.
    """
    rules = [(r, spec) for r, spec in rules]

    def get(path, leaf):
        shape = _leaf_shape(path, leaf)
        size = 1
        for d in shape:
            size *= d
        if not shape or size == 1:
            return PS()
        for rule, spec in rules:
            if re.search(rule, path) is not None:
                return spec
        raise MXNetError(
            f"no partition rule matched parameter {path!r} "
            f"(shape {shape}); add a rule or a catch-all ('.*', PS(...))")

    return named_tree_map(get, tree, sep=sep)


def spec_axes(spec):
    """The set of mesh axis names a PartitionSpec mentions (entries may be
    None, a name, or a tuple of names)."""
    axes = set()
    for entry in tuple(spec):
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            axes.update(entry)
        else:
            axes.add(entry)
    return axes


def fsdp_rules():
    """The default full-parameter-sharding rule set: every non-scalar
    trainable shards over 'dp' (match_partition_rules exempts scalar and
    size-1 leaves on its own)."""
    return ((r".*", PS("dp")),)


def layer_key(name, sep="."):
    """The gather/scatter granule a parameter belongs to: its owning
    layer's name prefix ('encoder.layers.0.attn_qkv.weight' and '....bias'
    gather together; a bare name is its own layer)."""
    return name.rsplit(sep, 1)[0] if sep in name else name


def fsdp_groups(entries, specs, n_shards, axis="dp", sep="."):
    """Fold flat named trainables into the per-layer bucket schedule.

    ``entries``: ordered ``(key, name, shape, dtype_str)`` tuples (key is
    the caller's position index); ``specs``: ``{name: PartitionSpec}`` from
    ``match_partition_rules``. Leaves whose spec mentions ``axis`` group
    into one ``BucketSpec`` per (layer, dtype) sharded 1/N over ``axis``;
    the rest (scalars, size-1, explicitly replicated leaves) pool into
    per-dtype replicated buckets updated identically on every shard. A
    spec mentioning any OTHER mesh axis is rejected — tensor-parallel
    layouts compose at the five_axis/Learner level, not inside the
    dp-compiled step.

    Returns ``[(layer, dtype, keys, BucketSpec, sharded)]`` in
    first-appearance order (the schedule order of the compiled program).
    """
    from .collectives import BucketSpec

    grouped = {}   # (layer, dtype, sharded) -> [(key, shape)]
    order = []
    for key, name, shape, dtype in entries:
        spec = specs[name]
        axes = spec_axes(spec)
        if axes - {axis}:
            raise MXNetError(
                f"partition rule for {name!r} names mesh axes "
                f"{sorted(axes - {axis})}; compile_step shards parameters "
                f"over '{axis}' only — tensor/pipeline-parallel specs "
                "belong to parallel.five_axis / parallel.learner")
        sharded = axis in axes
        gk = (layer_key(name, sep=sep) if sharded else "_replicated",
              dtype, sharded)
        if gk not in grouped:
            grouped[gk] = []
            order.append(gk)
        grouped[gk].append((key, shape))
    out = []
    for layer, dtype, sharded in order:
        items = grouped[(layer, dtype, sharded)]
        keys = [k for k, _ in items]
        shapes = [s for _, s in items]
        bs = BucketSpec(shapes, n_shards if sharded else 1)
        out.append((layer, dtype, keys, bs, sharded))
    return out
