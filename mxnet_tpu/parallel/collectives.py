"""Collective wrappers for use inside shard_map'ed kernels.

Reference mapping (SURVEY §5.8): ncclReduce/ncclBcast (kvstore_nccl.h:285,402)
and ps-lite push/pull become XLA collectives over ICI/DCN. These helpers are
thin names over jax.lax so framework code and user kernels share a vocabulary.
"""
from __future__ import annotations

import jax
from jax import lax

__all__ = ["all_reduce", "all_gather", "reduce_scatter", "ppermute",
           "all_to_all", "axis_index", "axis_size", "BucketSpec"]


def all_reduce(x, axis_name, op="sum"):
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"unknown reduce op {op}")


def all_gather(x, axis_name, axis=0, tiled=False):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0):
    return lax.psum_scatter(x, axis_name,
                            scatter_dimension=scatter_dimension, tiled=True)


def ppermute(x, axis_name, perm):
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
    return lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=tiled)


class BucketSpec:
    """Flatten/pad layout for sharding a list of tensors over a mesh axis.

    The ZeRO-1 weight-update schedule (Xu et al., "Automatic Cross-Replica
    Sharding of Weight Update") works on FLAT per-dtype buckets: tensors are
    concatenated, padded up to a multiple of the axis size, reduce-scattered
    so each replica owns a contiguous 1/N shard, updated shard-locally, and
    all-gathered back. This object is the static layout arithmetic shared by
    the trace-time body and the host-side state manager: sizes/offsets per
    tensor, the padded total, and the per-replica shard length.
    """

    __slots__ = ("shapes", "sizes", "offsets", "total", "padded", "n_shards",
                 "shard")

    def __init__(self, shapes, n_shards):
        self.shapes = [tuple(int(d) for d in s) for s in shapes]
        self.sizes = []
        for s in self.shapes:
            n = 1
            for d in s:
                n *= d
            self.sizes.append(n)
        self.offsets = []
        off = 0
        for n in self.sizes:
            self.offsets.append(off)
            off += n
        self.total = off
        self.n_shards = int(n_shards)
        self.padded = -(-self.total // self.n_shards) * self.n_shards
        self.shard = self.padded // self.n_shards

    @property
    def pad(self):
        return self.padded - self.total

    def flatten(self, xs, pad_value=0):
        """Concatenate ``xs`` (matching ``shapes``) into one padded flat
        vector. Traceable (jnp) — used inside the compiled step body."""
        import jax.numpy as jnp

        flat = jnp.concatenate([x.reshape(-1) for x in xs]) if len(xs) > 1 \
            else xs[0].reshape(-1)
        if self.pad:
            flat = jnp.pad(flat, (0, self.pad), constant_values=pad_value)
        return flat

    def unflatten(self, flat):
        """Split a padded flat vector back into tensors of ``shapes``
        (discards the pad tail)."""
        return [flat[o:o + n].reshape(s)
                for o, n, s in zip(self.offsets, self.sizes, self.shapes)]

    def flatten_host(self, xs, dtype="float32", pad_value=0):
        """Host-side (numpy) counterpart of ``flatten``: concatenate
        ``xs`` into one padded flat vector WITHOUT touching the device.
        The one code path every residency manager uses to build bucket
        images (ZeRO-1 state scatter, FSDP param/state adoption) — the
        layout arithmetic lives here, not at each call site."""
        import numpy as onp

        flat = onp.full((self.padded,), pad_value, dtype=onp.dtype(dtype))
        for x, off, n in zip(xs, self.offsets, self.sizes):
            flat[off:off + n] = onp.asarray(x).reshape(-1)
        return flat

    def spread(self, per_tensor, pad_value=0.0):
        """Per-tensor scalars -> per-element flat vector (padded). Static
        repeat lengths, so this never retraces on value changes."""
        import jax.numpy as jnp

        v = jnp.repeat(per_tensor, jnp.asarray(self.sizes),
                       total_repeat_length=self.total)
        if self.pad:
            v = jnp.pad(v, (0, self.pad), constant_values=pad_value)
        return v

    def shard_slice(self, flat, axis_name):
        """This replica's contiguous 1/N slice of a padded flat vector."""
        idx = lax.axis_index(axis_name)
        return lax.dynamic_slice_in_dim(flat, idx * self.shard, self.shard)


def axis_index(axis_name):
    return lax.axis_index(axis_name)


def axis_size(axis_name):
    return lax.psum(1, axis_name)


def _vma(x):
    """The varying-manual-axes set of a value/aval ({} on older jax)."""
    try:
        aval = x if hasattr(x, "vma") else jax.typeof(x)
        return getattr(aval, "vma", frozenset())
    except Exception:  # noqa: BLE001 — outside shard_map / old jax
        return frozenset()


def match_carry_vma(step_fn, carry, *xs_protos, fallback_axis=None):
    """Promote literal-zero scan carries to the loop body's varying axes.

    Under shard_map, jax tracks which mesh axes a value *varies* over (vma).
    A scan carry initialized from literals is axis-invariant, but the loop
    body usually returns values varying over the axes its collectives /
    ``axis_index`` touch — and scan requires carry types to be identical
    across iterations. This runs ``jax.eval_shape`` on one abstract step
    (zero FLOPs) and ``lax.pcast``s each init leaf up to the vma the body
    produces. No-op when the vma system is absent (older jax).

    If the abstract eval itself fails, falls back to promoting every leaf
    over ``fallback_axis`` (the caller's primary ring axis) — the carry is
    guaranteed to vary over at least that axis, and an unpromoted carry
    would only re-surface later as an opaque scan carry-type mismatch.
    """
    if not (hasattr(jax, "typeof") and hasattr(lax, "pcast")):
        return carry

    def up(leaf, aval):
        need = tuple(sorted(_vma(aval) - _vma(leaf)))
        return lax.pcast(leaf, need, to="varying") if need else leaf

    def promote_fallback(tree):
        if fallback_axis is None:
            return tree
        ax = (fallback_axis,) if isinstance(fallback_axis, str) \
            else tuple(fallback_axis)

        def one(leaf):
            need = tuple(a for a in ax if a not in _vma(leaf))
            return lax.pcast(leaf, need, to="varying") if need else leaf

        return jax.tree_util.tree_map(one, tree)

    # iterate to a vma fixpoint: the carry feeds back into the body, so one
    # abstract pass can under-approximate (bounded by the mesh's axis count)
    for _ in range(8):
        try:
            out = jax.eval_shape(lambda c: step_fn(c, *xs_protos)[0], carry)
        except Exception:  # noqa: BLE001 — abstract eval failed
            return promote_fallback(carry)
        grew = any(
            _vma(a) - _vma(c)
            for c, a in zip(jax.tree_util.tree_leaves(carry),
                            jax.tree_util.tree_leaves(out)))
        if not grew:
            return carry
        carry = jax.tree_util.tree_map(up, carry, out)
    return carry
