"""Collective wrappers for use inside shard_map'ed kernels.

Reference mapping (SURVEY §5.8): ncclReduce/ncclBcast (kvstore_nccl.h:285,402)
and ps-lite push/pull become XLA collectives over ICI/DCN. These helpers are
thin names over jax.lax so framework code and user kernels share a vocabulary.
"""
from __future__ import annotations

import jax
from jax import lax

__all__ = ["all_reduce", "all_gather", "reduce_scatter", "ppermute",
           "all_to_all", "axis_index", "axis_size"]


def all_reduce(x, axis_name, op="sum"):
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"unknown reduce op {op}")


def all_gather(x, axis_name, axis=0, tiled=False):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0):
    return lax.psum_scatter(x, axis_name,
                            scatter_dimension=scatter_dimension, tiled=True)


def ppermute(x, axis_name, perm):
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
    return lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=tiled)


def axis_index(axis_name):
    return lax.axis_index(axis_name)


def axis_size(axis_name):
    return lax.psum(1, axis_name)
