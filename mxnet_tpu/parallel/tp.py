"""Tensor-parallel (megatron) support: the trace-time context and the
segmented host layouts.

Training and serving both trace the model graph ONCE with eager values
and replay it inside ``shard_map`` (the deferred-compute contract). A
tensor-parallel model is therefore traced with each parameter's LOCAL
shard bound to its variable — the traced shapes are the per-rank shapes
— while a thread-local :class:`TPContext` tells the model blocks to
emit the matching in-graph collectives (``ops.tp_collectives``) and to
size head counts locally. Outside an active context every hook here is
an identity, so the single-device model is structurally untouched.

Host layouts: a rule may declare ``meta={"segments": S}`` for weights
that are S stacked logical blocks along the sharded dimension (the
fused QKV projection: S=3). Rank r's local image then takes the r-th
1/tp slice of EACH block, so per-rank math stays the plain megatron
column split. :func:`local_slice` / :func:`merge_local` /
:func:`global_image` / :func:`from_global_image` are pure index
permutations — checkpoint round-trips through them are bitwise.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

from ..base import MXNetError

TP_AXIS = "tp"

__all__ = ["TPContext", "current", "activate", "tp_copy", "tp_sum",
           "tp_gather", "tp_dim", "local_shape", "local_slice",
           "merge_local", "global_image", "from_global_image"]


class TPContext:
    """Active while a tensor-parallel model graph is traced (or run).

    ``mode`` picks the collective placement the blocks emit:

    - ``"train"``: megatron f/g — ``tp_copy`` at each parallel region's
      entry, row-parallel second layers exiting through ``tp_sum``.
    - ``"serve"``: column-parallel only, merged by ``tp_gather`` (a
      concatenation — no cross-rank arithmetic), so the served values
      are BITWISE those of the unsharded model.

    The byte accumulators are filled by the eager fallbacks of the
    registered collectives during the trace — the build's only window
    into the in-program tp traffic (``collective_bytes.tp``).
    """

    __slots__ = ("size", "axis", "mode", "rank", "psum_bytes",
                 "gather_bytes")

    def __init__(self, size, mode="train", axis=TP_AXIS, rank=0):
        size = int(size)
        if size < 2:
            raise MXNetError(f"TPContext needs size >= 2, got {size}")
        if mode not in ("train", "serve"):
            raise MXNetError(f"TPContext mode must be 'train' or 'serve', "
                             f"got {mode!r}")
        self.size = size
        self.axis = axis
        self.mode = mode
        self.rank = int(rank)   # whose local values the eager trace carries
        self.psum_bytes = 0
        self.gather_bytes = 0

    def local_heads(self, num_heads):
        if num_heads % self.size:
            raise MXNetError(
                f"tensor parallelism over {self.size} ranks needs a head "
                f"count divisible by it; got {num_heads} heads")
        return num_heads // self.size


_tls = threading.local()


def current():
    """The active :class:`TPContext`, or ``None`` (single-device math)."""
    return getattr(_tls, "ctx", None)


@contextmanager
def activate(ctx):
    prev = current()
    _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev


# -- graph hooks (identity without an active context) ----------------------

def tp_copy(x):
    """Megatron *f*: identity forward, gradient psum over 'tp'. Place at
    the ENTRY of each tensor-parallel region so everything upstream
    (replicated activations, dp-sharded parameters) receives the full,
    tp-invariant gradient."""
    ctx = current()
    if ctx is None:
        return x
    from ..ops.registry import apply_op

    return apply_op("tp_copy", x, axis=ctx.axis)


def tp_sum(x):
    """Megatron *g*: psum over 'tp' forward, identity gradient — the exit
    of a row-parallel layer (its local output is a partial sum)."""
    ctx = current()
    if ctx is None:
        return x
    from ..ops.registry import apply_op

    return apply_op("tp_sum", x, axis=ctx.axis)


def tp_gather(x, dim=-1):
    """Tiled all_gather over 'tp' forward, slice-own-chunk gradient — the
    exit of a column-parallel layer into replicated math. Forward is a
    concatenation: the merged activations are bitwise the unsharded
    model's (the serving parity contract)."""
    ctx = current()
    if ctx is None:
        return x
    from ..ops.registry import apply_op

    d = dim if dim >= 0 else x.ndim + dim
    return apply_op("tp_gather", x, axis=ctx.axis, size=ctx.size, dim=d)


# -- host layout arithmetic -------------------------------------------------

def tp_dim(spec, axis=TP_AXIS):
    """Index of the dimension ``spec`` shards over ``axis``, or None."""
    dims = []
    for i, e in enumerate(tuple(spec)):
        names = tuple(e) if isinstance(e, (tuple, list)) else (e,)
        if axis in names:
            dims.append(i)
    if not dims:
        return None
    if len(dims) > 1:
        raise MXNetError(
            f"partition spec {spec} names '{axis}' on more than one "
            "dimension; tensor parallelism shards exactly one")
    return dims[0]


def _check_divisible(shape, dim, size, segments, what="parameter"):
    n = int(shape[dim])
    want = size * segments
    if n % want:
        seg = f" x segments={segments}" if segments > 1 else ""
        raise MXNetError(
            f"{what} dimension {dim} of extent {n} is not divisible by "
            f"tp={size}{seg}")


def local_shape(shape, dim, size, segments=1):
    _check_divisible(shape, dim, size, segments)
    s = list(shape)
    s[dim] //= size
    return tuple(s)


def _seg_view(arr, dim, size, segments):
    import numpy as onp

    a = onp.asarray(arr)
    n = a.shape[dim]
    pre, post = a.shape[:dim], a.shape[dim + 1:]
    v = a.reshape(pre + (segments, size, n // (size * segments)) + post)
    return a, v, pre, post


def local_slice(arr, dim, rank, size, segments=1):
    """Rank ``rank``'s local image of a full host array: the r-th 1/size
    chunk of each of the ``segments`` stacked blocks along ``dim``."""
    import numpy as onp

    a = onp.asarray(arr)
    _check_divisible(a.shape, dim, size, segments)
    _, v, pre, post = _seg_view(a, dim, size, segments)
    out = onp.take(v, int(rank), axis=len(pre) + 1)
    return onp.ascontiguousarray(
        out.reshape(pre + (a.shape[dim] // size,) + post))


def merge_local(parts, dim, segments=1):
    """Inverse of :func:`local_slice` over all ranks: per-rank local
    images back to the full array (pure index permutation — bitwise)."""
    import numpy as onp

    size = len(parts)
    p0 = onp.asarray(parts[0])
    pre, post = p0.shape[:dim], p0.shape[dim + 1:]
    loc = p0.shape[dim]
    if loc % segments:
        raise MXNetError(
            f"local extent {loc} not divisible by segments={segments}")
    stk = onp.stack(
        [onp.asarray(p).reshape(pre + (segments, loc // segments) + post)
         for p in parts], axis=len(pre) + 1)
    return onp.ascontiguousarray(
        stk.reshape(pre + (size * loc,) + post))


def global_image(arr, dim, size, segments=1):
    """Permutation of the FULL array whose contiguous 1/size blocks along
    ``dim`` are the per-rank local images — the host layout a flat
    bucket sharded tp-major sees. Identity when ``segments == 1``."""
    import numpy as onp

    if segments == 1:
        return onp.asarray(arr)
    a, v, pre, post = _seg_view(arr, dim, size, segments)
    k = len(pre)
    return onp.ascontiguousarray(onp.swapaxes(v, k, k + 1).reshape(a.shape))


def from_global_image(arr, dim, size, segments=1):
    """Inverse of :func:`global_image`."""
    import numpy as onp

    if segments == 1:
        return onp.asarray(arr)
    a = onp.asarray(arr)
    n = a.shape[dim]
    pre, post = a.shape[:dim], a.shape[dim + 1:]
    v = a.reshape(pre + (size, segments, n // (size * segments)) + post)
    k = len(pre)
    return onp.ascontiguousarray(onp.swapaxes(v, k, k + 1).reshape(a.shape))
