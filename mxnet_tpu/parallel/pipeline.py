"""Pipeline parallelism: GPipe-style microbatched execution over a 'pp' axis.

The reference has NO pipeline parallelism (SURVEY §2.2: PP absent). TPU-native
design for homogeneous stages (the transformer/MLP-stack case): per-stage
parameters are STACKED on a leading axis sharded over ``pp``; inside
shard_map each device holds its stage's slice and activations flow around the
ring via ``lax.ppermute`` while microbatches stream through — the classic
GPipe schedule (S + M - 1 ticks for S stages, M microbatches). Everything is
jax-native and differentiable, so fwd+bwd+update compiles to one SPMD program
with XLA overlapping the ICI sends with stage compute.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..base import MXNetError

__all__ = ["pipeline_apply", "pipeline_sharded", "schedule_1f1b",
           "layer_ranges"]


def layer_ranges(num_layers, num_stages):
    """Contiguous layer-range stage assignment: ``[(lo, hi), ...]`` per
    stage (hi exclusive), remainder layers to the EARLIER stages so the
    last stage — which also carries the LM head — stays lightest. This is
    the assignment a 'pp' partition rule's stage index refers to."""
    num_layers, num_stages = int(num_layers), int(num_stages)
    if num_stages < 1 or num_layers < num_stages:
        raise MXNetError(
            f"cannot split {num_layers} layers over {num_stages} pipeline "
            "stages (need at least one layer per stage)")
    base, extra = divmod(num_layers, num_stages)
    out, lo = [], 0
    for s in range(num_stages):
        hi = lo + base + (1 if s < extra else 0)
        out.append((lo, hi))
        lo = hi
    return out


def schedule_1f1b(num_stages, num_microbatches):
    """The 1F1B (one-forward-one-backward) per-stage action schedule.

    Returns a list over stages; stage s's entry is the ordered tuple of
    ``("F", i)`` / ``("B", i)`` actions it executes over microbatches
    ``i < num_microbatches``: ``min(S - s - 1, M)`` warmup forwards, then
    a steady state alternating one forward with one backward, then the
    cooldown backwards. Unlike GPipe (all M forwards before any
    backward), a stage holds at most ``S - s`` activation stashes — the
    schedule the scanned ``accumulate=G`` microbatch axis interleaves
    when training rides pipeline stages.
    """
    S, M = int(num_stages), int(num_microbatches)
    if S < 1 or M < 1:
        raise MXNetError(
            f"schedule_1f1b needs num_stages >= 1 and num_microbatches >= "
            f"1, got {num_stages} x {num_microbatches}")
    out = []
    for s in range(S):
        warmup = min(S - s - 1, M)
        acts = [("F", i) for i in range(warmup)]
        for i in range(M - warmup):
            acts.append(("F", warmup + i))
            acts.append(("B", i))
        for i in range(M - warmup, M):
            acts.append(("B", i))
        out.append(tuple(acts))
    return out


def pipeline_apply(stage_fn, stage_params, microbatches, axis_name="pp"):
    """Run the pipeline body on ONE device's shard (call inside shard_map).

    stage_fn(params_slice, x) -> activation of the same shape class.
    stage_params: pytree whose leaves have a leading axis of LOCAL length 1
        (the global leading axis is the stage count, sharded over pp).
    microbatches: (M, mb, ...) — full microbatch stream (replicated).

    Returns (M, mb, ...) outputs as produced by the LAST stage (zeros on the
    other shards; the caller selects/reduces stage S-1's copy).
    """
    n_stage = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    params_local = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    right = [(i, (i + 1) % n_stage) for i in range(n_stage)]

    mb_shape = microbatches.shape[1:]
    total = m + n_stage - 1

    def tick(carry, t):
        buf, outs = carry
        # stage 0 injects microbatch t (when within range); others use the
        # activation that arrived from the left neighbor
        inject = jnp.where(t < m, t, m - 1)
        x_in = jnp.where(my == 0, microbatches[inject], buf)
        active = jnp.logical_and(my <= t, t - my < m)
        y = stage_fn(params_local, x_in)
        y = jnp.where(active, y, jnp.zeros_like(y))
        # last stage writes its finished microbatch t - (S-1)
        out_idx = jnp.clip(t - (n_stage - 1), 0, m - 1)
        is_out = jnp.logical_and(my == n_stage - 1, active)
        outs = outs.at[out_idx].set(
            jnp.where(is_out, y, outs[out_idx]))
        # rotate activations one stage to the right
        buf = lax.ppermute(y, axis_name, right)
        return (buf, outs), None

    buf0 = jnp.zeros(mb_shape, microbatches.dtype)
    outs0 = jnp.zeros((m,) + mb_shape, microbatches.dtype)
    # literal-zero carries are axis-invariant; promote to the exact varying
    # axes the tick body produces (pp from the schedule masks, plus any axes
    # the stage_fn's own collectives leave varying — dp/sp/ep under a
    # multi-axis mesh)
    from .collectives import match_carry_vma

    buf0, outs0 = match_carry_vma(tick, (buf0, outs0), jnp.int32(0),
                                  fallback_axis=axis_name)
    (_, outs), _ = lax.scan(tick, (buf0, outs0), jnp.arange(total))
    # broadcast the last stage's outputs to every shard so the caller gets
    # identical values regardless of which shard it reads
    outs = lax.psum(
        jnp.where(my == n_stage - 1, outs, jnp.zeros_like(outs)), axis_name)
    return outs


def pipeline_sharded(stage_fn, stacked_params, x, mesh, num_microbatches,
                     axis="pp"):
    """User-facing GPipe runner.

    stacked_params: pytree with leading STAGE axis (length = mesh.shape[pp]),
        will be sharded P('pp') over the mesh.
    x: (batch, ...) input; split into ``num_microbatches`` along axis 0.
    Returns the pipeline output with the original batch layout.
    """
    from .mesh import shard_map_compat

    from ..ndarray.ndarray import NDArray

    if axis not in mesh.shape:
        raise MXNetError(f"mesh has no axis {axis!r}")
    n_stage = mesh.shape[axis]
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != n_stage:
            raise MXNetError(
                f"stacked stage axis has length {leaf.shape[0]} but the "
                f"{axis!r} mesh axis has {n_stage} devices — one stage per "
                "device is required (pipeline_apply uses params[0] locally)")
    wrap = isinstance(x, NDArray)
    xd = x._data if wrap else x
    batch = xd.shape[0]
    if batch % num_microbatches:
        raise MXNetError(f"num_microbatches ({num_microbatches}) must divide the batch size ({batch})")
    mb = batch // num_microbatches
    xmb = xd.reshape((num_microbatches, mb) + xd.shape[1:])
    pd = jax.tree_util.tree_map(
        lambda p: p._data if isinstance(p, NDArray) else p, stacked_params)

    pspec = jax.tree_util.tree_map(
        lambda p: P(axis, *([None] * (p.ndim - 1))), pd)
    fn = shard_map_compat(
        functools.partial(pipeline_apply, stage_fn, axis_name=axis),
        mesh,
        in_specs=(pspec, P()),
        out_specs=P(),
    )
    out = jax.jit(fn)(pd, xmb)
    out = out.reshape((batch,) + out.shape[2:])
    return NDArray(out) if wrap else out
