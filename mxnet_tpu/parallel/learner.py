"""Learner: compile a Gluon block into ONE sharded SPMD training step.

This is the TPU-native performance path replacing the reference's
Trainer+KVStore pipeline (gluon/trainer.py:407 _allreduce_grads → kvstore
push/pull → fused optimizer ops). Instead of moving gradients through a store,
forward + backward + optimizer update compile into a single pjit program over a
Mesh: XLA inserts the gradient all-reduces on ICI (the NCCL/ps-lite role) and
overlaps them with backward compute (the P3 priority-store role,
src/kvstore/p3store_dist.h — here done by XLA's latency-hiding scheduler).

Parameters/optimizer state are donated buffers → true in-place HBM updates.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["Learner", "to_optax"]


def to_optax(optimizer):
    """Translate an mxnet_tpu Optimizer into an optax GradientTransformation.

    Covers the optimizers used by the north-star configs; pass an optax
    transformation directly for anything else.
    """
    from .. import optimizer as opt_mod

    if isinstance(optimizer, optax.GradientTransformation):
        return optimizer
    lr = optimizer.learning_rate
    chain = []
    if optimizer.clip_gradient is not None:
        chain.append(optax.clip(optimizer.clip_gradient))
    if isinstance(optimizer, opt_mod.AdamW):
        chain.append(optax.adamw(lr, b1=optimizer.beta1, b2=optimizer.beta2,
                                 eps=optimizer.epsilon,
                                 weight_decay=optimizer.wd))
    elif isinstance(optimizer, opt_mod.Adam):
        chain.append(optax.adam(lr, b1=optimizer.beta1, b2=optimizer.beta2,
                                eps=optimizer.epsilon))
        if optimizer.wd:
            chain.insert(0, optax.add_decayed_weights(optimizer.wd))
    elif isinstance(optimizer, opt_mod.LAMB):
        chain.append(optax.lamb(lr, b1=optimizer.beta1, b2=optimizer.beta2,
                                eps=optimizer.epsilon,
                                weight_decay=optimizer.wd))
    elif isinstance(optimizer, opt_mod.SGD):
        if optimizer.wd:
            chain.append(optax.add_decayed_weights(optimizer.wd))
        chain.append(optax.sgd(lr, momentum=optimizer.momentum or None))
    else:
        raise MXNetError(f"no optax mapping for {type(optimizer).__name__}; "
                         f"pass an optax.GradientTransformation instead")
    return optax.chain(*chain) if len(chain) > 1 else chain[0]


class Learner:
    """Sharded train-step compiler.

    Parameters
    ----------
    net : gluon.Block — the model (params must be initialized).
    loss_fn : callable(pred, label) -> loss array (gluon.loss works).
    optimizer : mxnet_tpu Optimizer or optax transformation.
    mesh : jax.sharding.Mesh | None — defaults to all devices on 'dp'.
    param_spec_fn : callable(name, shape) -> PartitionSpec | None — tensor/
        expert-parallel parameter layouts; default replicates.
    """

    def __init__(self, net, loss_fn, optimizer, mesh=None, param_spec_fn=None,
                 remat=False):
        from .mesh import default_mesh, shard_batch, shard_params, replicated
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.net = net
        self.loss_fn = loss_fn
        self.mesh = mesh if mesh is not None else default_mesh()
        self.tx = to_optax(optimizer)
        self._param_spec_fn = param_spec_fn
        # rematerialization: recompute forward activations in backward
        # instead of storing them — trades ~1/3 more FLOPs for activation
        # memory, enabling larger batches (reference analog:
        # MXNET_BACKWARD_DO_MIRROR, src/nnvm/gradient.cc mirror pass)
        self._remat = remat
        self._shard_in = shard_batch(self.mesh)
        self._repl = replicated(self.mesh)
        self._params = None  # collected lazily (deferred shapes need a fwd)
        self._step_fn = None
        self._opt_state = None
        self._traced_for = None

    def _collect(self):
        from .mesh import shard_params

        params = self.net.collect_params()
        for name, p in params.items():
            if p.grad_req != "null" and p._data is None:
                raise MXNetError(
                    f"parameter {name} is still uninitialized after the "
                    "settle forward — initialize it or set grad_req='null'")
        self._param_names = [name for name, p in params.items()
                             if p.grad_req != "null"]
        self._params = {name: params[name] for name in self._param_names}
        pf = shard_params(self.mesh, self._param_spec_fn)
        self._param_shardings = [pf(n, self._params[n].data())
                                 for n in self._param_names]

    # -- tracing ------------------------------------------------------------
    def _build(self, x, y):
        from .. import _deferred_compute as dc
        from .. import autograd as ag
        from ..cached_op import build_executor

        with ag.train_mode():  # BN batch stats + dropout active in the trace
            if any(p._data is None
                   for p in self.net.collect_params().values()):
                with ag.pause():  # predict mode: no BN stat side effects
                    self.net(x)  # settle deferred-shape parameter init
            self._collect()
            with dc.context() as tctx:
                data_vars = [dc.set_variable(x, "data0"),
                             dc.set_variable(y, "label0")]
                param_vars = []
                for name in self._param_names:
                    arr = self._params[name].data()
                    param_vars.append(dc.set_variable(arr, name))
                out = self.loss_fn(self.net(x), y)
                loss = out.mean()
                entries = [loss._dc_sym] + [e for _, e in tctx.aux_updates]
                self._aux_targets = [t for t, _ in tctx.aux_updates]
                fwd, uses_rng = build_executor(entries,
                                               data_vars + param_vars)
        if self._remat:
            fwd = jax.checkpoint(fwd)
        self._uses_rng = uses_rng
        n_aux = len(self._aux_targets)

        def train_step(plist, opt_state, xb, yb, key):
            def lfn(pl):
                args = ([key] if uses_rng else []) + [xb, yb] + list(pl)
                outs = fwd(*args)
                return outs[0], outs[1:]

            (loss_v, aux), grads = jax.value_and_grad(lfn, has_aux=True)(
                tuple(plist))
            updates, new_state = self.tx.update(grads, opt_state, tuple(plist))
            new_p = optax.apply_updates(tuple(plist), updates)
            new_p = tuple(np_.astype(p.dtype)
                          for np_, p in zip(new_p, plist))
            return loss_v, new_p, new_state, aux

        in_sh = (tuple(self._param_shardings), None, self._shard_in,
                 self._shard_in, self._repl)
        # pin updated-param shardings to the declared layouts so step N+1's
        # args match step N's outputs (otherwise XLA's chosen out-shardings
        # drift, e.g. a bias picking up a 'tp' spec)
        out_sh = (self._repl, tuple(self._param_shardings), None, None)
        self._step_fn = jax.jit(train_step, donate_argnums=(0, 1),
                                in_shardings=in_sh, out_shardings=out_sh)
        if self._opt_state is None:
            self._opt_state = self.tx.init(
                tuple(p.data()._data for p in self._params.values()))

    # -- stepping -----------------------------------------------------------
    def step(self, x, y):
        """One fused fwd+bwd+update step. Returns the (scalar) loss NDArray."""
        from .. import random as _rnd

        sig = (x.shape, str(x.dtype), y.shape, str(y.dtype))
        if self._step_fn is None or self._traced_for != sig:
            self._build(x, y)
            self._traced_for = sig
        key = _rnd._next_key() if self._uses_rng else jnp.zeros((2,),
                                                                jnp.uint32)
        plist = tuple(self._params[n].data()._data for n in self._param_names)
        loss_v, new_p, new_state, aux = self._step_fn(
            plist, self._opt_state, x._data, y._data, key)
        for name, data in zip(self._param_names, new_p):
            self._params[name].data()._set_data(data)
        self._opt_state = new_state
        for target, data in zip(self._aux_targets, aux):
            target._set_data(data)
        return NDArray(loss_v)


    # -- checkpointing (reference analog: Trainer.save_states +
    # Block.save_parameters; SURVEY §5.4 'orbax-style sharded checkpoint
    # with the same logical naming') ------------------------------------
    def _checkpoint_tree(self):
        """Single source of the checkpoint pytree: trainable params,
        NON-trainable state (BN running stats etc.), optimizer state."""
        if self._params is None:
            raise MXNetError("Learner has not traced yet — run a step "
                             "before checkpoint operations (shapes and "
                             "shardings come from the live state)")
        aux = {n: p.data()._data
               for n, p in self.net.collect_params().items()
               if p.grad_req == "null" and p._data is not None}
        return {
            "params": {n: self._params[n].data()._data
                       for n in self._param_names},
            "aux": aux,
            "opt_state": self._opt_state,
        }

    def save_checkpoint(self, directory):
        """Write params + aux + optimizer state with their shardings via
        orbax; each host writes its own shards, so multi-host checkpoints
        scale."""
        import os

        import orbax.checkpoint as ocp

        with ocp.StandardCheckpointer() as saver:
            saver.save(os.path.abspath(directory), self._checkpoint_tree(),
                       force=True)

    def restore_checkpoint(self, directory):
        import os

        import orbax.checkpoint as ocp

        template = self._checkpoint_tree()
        with ocp.StandardCheckpointer() as loader:
            restored = loader.restore(os.path.abspath(directory), template)
        for n in self._param_names:
            self._params[n].data()._set_data(restored["params"][n])
        all_params = self.net.collect_params()
        for n, arr in restored["aux"].items():
            all_params[n].data()._set_data(arr)
        self._opt_state = restored["opt_state"]
