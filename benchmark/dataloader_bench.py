"""DataLoader worker-model micro-benchmark: serial vs threads vs processes.

The per-sample work simulates a decode/augment pipeline that holds the GIL
(byte-level python work + small numpy ops) — the workload class the
reference forks processes for (python/mxnet/gluon/data/dataloader.py).
Spawned process workers should beat thread workers decisively here; thread
workers only win when per-sample work is pure GIL-releasing numpy.

Run:  python benchmark/dataloader_bench.py
Writes benchmark/dataloader_results.json.
"""
import json
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

from mxnet_tpu import gluon  # noqa: E402
from mxnet_tpu.gluon.data import DataLoader  # noqa: E402


def decode_heavy(seed):
    """GIL-bound fake decode (~1.5ms, the cost class of a small JPEG):
    python-level byte loop + huffman-ish table lookups hold the GIL."""
    rng = onp.random.RandomState(int(seed))
    raw = rng.bytes(48 * 48 * 3)
    table = list(range(256))
    acc = 0
    for b in raw:  # python-level loop: the GIL-bound part of a decoder
        acc = (acc * 31 + table[b]) & 0xFFFFFFFF
        table[b & 0xFF] = (table[b] + 1) & 0xFF
    img = onp.frombuffer(raw, onp.uint8).reshape(48, 48, 3)
    img = img.astype("float32") / 255.0
    img[0, 0, 0] += (acc % 7) * 1e-9  # keep the loop honest
    return img


def run(loader, batches):
    t0 = time.time()
    n = 0
    for x in loader:
        n += x.shape[0]
        if n >= batches * 64:
            break
    return n / (time.time() - t0)


# ---------------------------------------------------------------------------
# transport-level throughput: bytes/s through the worker->parent channel,
# decode cost excluded. Meaningful on ONE core — it measures copy/IPC
# bandwidth, not parallel speedup: shm moves a batch with two memcpys while
# a pickled queue serializes it through a 64 KiB pipe.
# ---------------------------------------------------------------------------
_T_SHAPE = (4 * 1024 * 1024,)  # 16 MiB float32 per batch
_T_ITERS = 12
_T_NBYTES = 16 * 1024 * 1024


def _pin_cpu_child():
    from mxnet_tpu.context import pin_process_to_cpu

    pin_process_to_cpu()


def _shm_sender(q):
    _pin_cpu_child()
    from mxnet_tpu.gluon.data.dataloader import _to_shm

    arr = onp.ones(_T_SHAPE, "float32")
    for _ in range(_T_ITERS):
        segments = []
        q.put(_to_shm(arr, segments))
        for s in segments:
            s.close()


def _pickle_sender(q):
    _pin_cpu_child()
    arr = onp.ones(_T_SHAPE, "float32")
    for _ in range(_T_ITERS):
        q.put(arr)


def _recv_shm(q):
    # symmetric endpoint work: both receivers end with an OWNED host array
    # (unpickling already materializes one on the queue path, so the shm
    # path maps the segment and pays exactly one memcpy — device placement
    # is deliberately excluded from both sides: it is not transport)
    from multiprocessing import shared_memory

    _tag, name, shape, dtype = q.get(timeout=120)
    shm = shared_memory.SharedMemory(name=name)
    onp.array(onp.ndarray(shape, onp.dtype(dtype), buffer=shm.buf))
    shm.close()
    shm.unlink()


def _recv_pickle(q):
    q.get(timeout=120)  # unpickle materializes the owned host array


def _transport_bps(sender, recv):
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    q = ctx.Queue(maxsize=2)
    p = ctx.Process(target=sender, args=(q,), daemon=True)
    # children inherit the env at exec time: pin them to CPU BEFORE they
    # re-import this module (same hazard DataLoader._ensure_pool guards —
    # an unpinned child would race the parent for the TPU runtime)
    from mxnet_tpu.context import spawn_cpu_pinned_env

    with spawn_cpu_pinned_env():
        p.start()
    recv(q)  # first batch excluded: absorbs spawn + import warmup
    t0 = time.perf_counter()
    for _ in range(_T_ITERS - 1):
        recv(q)
    dt = time.perf_counter() - t0
    p.join(timeout=10)
    return (_T_ITERS - 1) * _T_NBYTES / dt


def bench_transport():
    """Returns {shm_bytes_per_sec, pickle_queue_bytes_per_sec, ratio}."""
    shm = _transport_bps(_shm_sender, _recv_shm)
    pkl = _transport_bps(_pickle_sender, _recv_pickle)
    return {"shm_MBps": round(shm / 1e6, 1),
            "pickle_queue_MBps": round(pkl / 1e6, 1),
            "shm_over_pickle": round(shm / pkl, 2),
            "batch_MiB": _T_NBYTES // (1024 * 1024)}


def main():
    n = 512
    ds = gluon.data.SimpleDataset(
        onp.arange(n, dtype="float32")).transform(decode_heavy)
    nb = n // 64
    results = {}
    serial = DataLoader(ds, batch_size=64)
    results["serial"] = run(serial, nb)
    threads = DataLoader(ds, batch_size=64, num_workers=4, thread_pool=True)
    results["threads_4"] = run(threads, nb)
    procs = DataLoader(ds, batch_size=64, num_workers=4)
    for _ in procs:  # absorb spawn+import warmup in a full epoch
        pass
    results["processes_4"] = run(procs, nb)
    results["unit"] = "samples/sec"
    results["process_vs_thread"] = results["processes_4"] / \
        results["threads_4"]
    results["transport"] = bench_transport()
    results["cores"] = len(os.sched_getaffinity(0)) \
        if hasattr(os, "sched_getaffinity") else os.cpu_count()
    if results["cores"] == 1:
        results["note"] = ("single-core host: GIL-bound decode cannot "
                           "parallelize under ANY worker model; process "
                           "workers pay transport overhead with no "
                           "compute win. Re-run on a multi-core host for "
                           "the representative comparison.")
    out = os.path.join(os.path.dirname(__file__),
                       "dataloader_results.json")
    with open(out, "w") as f:
        json.dump({k: (round(v, 1) if isinstance(v, float) else v)
                   for k, v in results.items()}, f, indent=1)
    print(json.dumps(results))
    return results


if __name__ == "__main__":
    main()
