#!/usr/bin/env python
"""Per-operator latency harness (reference: benchmark/opperf/opperf.py —
runs every registered op with profiler timing).

Times each op's eager dispatch (compiled-cache hit path) on the local device
with canonical inputs. Output: one JSON line per op, or a table with --table.

    python benchmark/opperf.py [--ops add,matmul,...] [--table] [--size 1024]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as onp  # noqa: E402


def op_specs(n):
    """Canonical inputs per op family (shapes sized by --size)."""
    import mxnet_tpu as mx
    from mxnet_tpu import np

    sq = (n, n)
    vec = np.array(onp.random.uniform(0.5, 1.5, sq).astype("float32"))
    vec2 = np.array(onp.random.uniform(0.5, 1.5, sq).astype("float32"))
    idx = np.array(onp.random.randint(0, n, (n,)))
    specs = {}
    unary = ["abs", "exp", "log", "sqrt", "square", "sin", "cos", "tanh",
             "sigmoid", "relu", "erf", "floor", "negative", "reciprocal"]
    for name in unary:
        specs[name] = ([vec], {})
    binary = ["add", "subtract", "multiply", "true_divide", "maximum",
              "minimum", "power"]
    for name in binary:
        specs[name] = ([vec, vec2], {})
    specs["matmul"] = ([vec, vec2], {})
    specs["dot"] = ([vec, vec2], {})
    specs["sum"] = ([vec], {"axis": None, "keepdims": False})
    specs["mean"] = ([vec], {"axis": None, "keepdims": False})
    specs["max"] = ([vec], {"axis": 1, "keepdims": False})
    specs["argmax"] = ([vec], {"axis": 1, "keepdims": False})
    specs["softmax"] = ([vec], {"axis": -1})
    specs["log_softmax"] = ([vec], {"axis": -1})
    specs["transpose"] = ([vec], {"axes": None})
    specs["reshape"] = ([vec], {"newshape": (n * n,)})
    specs["concatenate"] = ([vec, vec2], {"axis": 0})
    specs["sort"] = ([vec], {"axis": -1})
    specs["take"] = ([vec, idx], {"axis": 0, "mode": "clip"})
    specs["cumsum"] = ([vec], {"axis": 1})
    specs["layer_norm"] = (
        [vec, np.ones((n,)), np.zeros((n,))], {"axis": -1, "eps": 1e-5})
    specs["einsum"] = ([vec, vec2], {"subscripts": "ij,jk->ik"})
    return specs


def sync(arr):
    return onp.asarray(arr._data.ravel()[0])


def bench_op(name, args, attrs, warmup=3, iters=20):
    from mxnet_tpu.ops.registry import apply_op

    for _ in range(warmup):
        out = apply_op(name, *args, **attrs)
        out = out[0] if isinstance(out, tuple) else out
    sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = apply_op(name, *args, **attrs)
        out = out[0] if isinstance(out, tuple) else out
    sync(out)
    return (time.perf_counter() - t0) / iters


def bench_dispatch_overhead(n_calls=2000, chain_len=64):
    """Bare per-op python-dispatch cost vs the amortized per-op cost inside
    one hybridized program — the apples comparison the reference's
    packed-func FFI was built around (benchmark/python/ffi/benchmark_ffi.py
    times 2x2-sized calls exactly like this; SURVEY N14: the FFI rework
    bought ~10x over ctypes because per-call overhead dominates tiny ops).

    Here "dispatch" = registry lookup + per-(op,attrs) jit-cache hit +
    PJRT enqueue; compute on a 2x2 input is negligible, so the µs/call IS
    the overhead. The hybrid column divides one jitted chain of
    ``chain_len`` adds by its length: what CachedOp amortizes away."""
    from mxnet_tpu import np
    from mxnet_tpu.cached_op import trace

    a = np.ones((2, 2))
    b = np.ones((2, 2))
    out = a + b
    sync(out)  # warm the jit cache for this (op, shape, dtype)
    t0 = time.perf_counter()
    for _ in range(n_calls):
        out = a + b
    sync(out)
    eager_us = (time.perf_counter() - t0) / n_calls * 1e6

    def chain(x):
        for _ in range(chain_len):
            x = x + b
        return x

    _, _, cop = trace(chain, [a], [("b", b)])
    sync(cop(a, b))
    t1 = time.perf_counter()
    reps = max(1, n_calls // chain_len)
    for _ in range(reps):
        out = cop(a, b)
    sync(out)
    hybrid_us = (time.perf_counter() - t1) / reps / chain_len * 1e6
    return {"eager_dispatch_us_per_op": round(eager_us, 2),
            "hybridized_us_per_op": round(hybrid_us, 2),
            "eager_over_hybrid": round(eager_us / hybrid_us, 1),
            "workload": "2x2 add, warm jit cache",
            "n_calls": n_calls, "chain_len": chain_len}


def bench_eager_vs_hybrid(n, warmup=3, iters=20):
    """The dispatch-cost story (reference built a packed-func FFI because
    this number matters: benchmark/python/ffi/): one forward of a small
    MLP as (a) per-op eager dispatch and (b) one whole-graph CachedOp.
    The ratio is the per-op overhead the hybridized path amortizes."""
    import mxnet_tpu as mx
    from mxnet_tpu import np
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    for _ in range(4):
        net.add(nn.Dense(n, activation="relu", in_units=n))
    net.initialize()
    x = np.array(onp.random.uniform(-1, 1, (32, n)).astype("float32"))

    def timed(fn):
        for _ in range(warmup):
            out = fn(x)
        sync(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(x)
        sync(out)
        return (time.perf_counter() - t0) / iters

    eager_ms = timed(net) * 1e3
    net.hybridize()
    hybrid_ms = timed(net) * 1e3
    return {"workload": f"mlp4x{n}_batch32", "eager_ms": round(eager_ms, 4),
            "hybridized_ms": round(hybrid_ms, 4),
            "eager_over_hybrid": round(eager_ms / hybrid_ms, 2)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", default=None,
                    help="comma-separated subset (default: all specs)")
    ap.add_argument("--size", type=int, default=1024)
    ap.add_argument("--table", action="store_true")
    ap.add_argument("--out", default=None,
                    help="also write the full result set to this JSON file")
    args = ap.parse_args()

    from mxnet_tpu.context import default_backend

    specs = op_specs(args.size)
    names = args.ops.split(",") if args.ops else sorted(specs)
    results = []
    for name in names:
        if name not in specs:
            print(f"# no spec for op {name!r}", file=sys.stderr)
            continue
        op_args, attrs = specs[name]
        try:
            dt = bench_op(name, op_args, attrs)
        except Exception as e:  # noqa: BLE001
            print(f"# {name} failed: {e}", file=sys.stderr)
            continue
        results.append({"op": name, "avg_time_ms": round(dt * 1e3, 4),
                        "backend": default_backend(),
                        "size": args.size})
    compare = bench_eager_vs_hybrid(min(args.size, 512))
    compare["backend"] = default_backend()
    dispatch = bench_dispatch_overhead()
    dispatch["backend"] = default_backend()
    if args.table:
        print(f"{'op':<20}{'avg ms':>12}")
        for r in results:
            print(f"{r['op']:<20}{r['avg_time_ms']:>12.4f}")
        print(json.dumps(compare))
        print(json.dumps(dispatch))
    else:
        for r in results:
            print(json.dumps(r))
        print(json.dumps(compare))
        print(json.dumps(dispatch))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump({"per_op": results, "eager_vs_hybrid": compare,
                       "dispatch_overhead": dispatch}, fh, indent=1)


if __name__ == "__main__":
    main()
