"""Benchmarks on the local accelerator. Prints ONE JSON line — always.

Default metric mirrors the reference's headline benchmark
(example/image-classification/benchmark_score.py; docs/.../faq/perf.md —
V100 fp16 ResNet-50 batch 128: 2355.04 img/s, BASELINE.md). Select with
argv[1] or BENCH env: resnet (default) | resnet_train | train_step |
train_step_sharded (or ``train_step --shard-update``) |
train_step_fsdp (or ``train_step --shard-params``) |
train_step_multi (or ``train_step --multi-step K``) | lstm_lm |
bert_pretrain | bert_large_pretrain | optimizer_step |
telemetry_overhead | serve | serve_llm | checkpoint.

Robustness contract (round-1 postmortem): any failure — backend init,
compile, OOM — still emits a parseable JSON line with an "error" field and
exits 0, so the driver always records a result. Every mode reports MFU
(achieved model FLOP/s over the chip's peak bf16 FLOP/s).
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as onp

BASELINE_RESNET_INFER = 2355.04  # V100 fp16 batch 128 (perf.md:210)
BASELINE_RESNET_TRAIN = 363.69   # V100 fp32 batch 128 training (perf.md:254)
BASELINE_BERT_TOKENS = 10000.0   # A100-class tokens/sec/chip anchor (BASELINE.md)
BASELINE_LSTM_TOKENS = 20000.0   # fused-cuDNN LSTM PTB anchor, tokens/s
# (BASELINE config 3 asks for 'parity with the fused-RNN GPU path'; 20k
# tok/s is the order of a cuDNN 2x650 LSTM at batch 20 on a V100-class
# part — a nominal anchor, the config's bar is qualitative parity)

# analytic model cost per work item (2 FLOPs per MAC)
RESNET50_FWD_FLOPS = 4.089e9          # per image, 224x224
RESNET50_TRAIN_FLOPS = 3 * RESNET50_FWD_FLOPS
BERT_PARAMS = {"base": 110e6, "large": 340e6}

def _device_info():
    # peak bf16 FLOP/s comes from telemetry.costs (one table for bench,
    # step_report MFU and cost_report; MXTPU_PEAK_FLOPS overrides — the
    # only way to get an MFU on a CPU host)
    try:
        import jax

        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", str(dev))
    except Exception:
        return "unknown", None
    try:
        from mxnet_tpu.telemetry.costs import peak_flops_info

        return kind, peak_flops_info()["peak"]
    except Exception:
        return kind, None


def _peak_source():
    try:
        from mxnet_tpu.telemetry.costs import peak_flops_info

        return peak_flops_info()["source"]
    except Exception:
        return None


def _mfu(flops_per_sec):
    _, peak = _device_info()
    if peak is None:
        return None
    return round(flops_per_sec / peak, 4)


def _sync(data):
    # device->host readback: the only reliable barrier on every PJRT backend
    return onp.asarray(data.ravel()[0] if hasattr(data, "ravel") else data)


def _mem_section(top_k=0):
    """Compact memory-ledger slice for a bench JSON (per-program static
    peaks, live-bytes high water, headroom vs the configured limit)."""
    from mxnet_tpu import telemetry

    rep = telemetry.memory_report(top_k)
    return {"program_peak_bytes":
                {site: ent["peak_bytes"]
                 for site, ent in sorted(rep["programs"].items())},
            "live_bytes": rep["live"]["live_bytes"],
            "live_bytes_high_water": rep["live_bytes_high_water"],
            "limit_bytes": rep["limit_bytes"],
            "headroom_fraction": rep["headroom_fraction"]}


def _with_numerics(nmode, fn):
    """Run ``fn`` with MXTPU_NUMERICS pinned (the mode is read at program
    BUILD time, so an on/off comparison needs a fresh compile per leg)."""
    old = os.environ.get("MXTPU_NUMERICS")
    os.environ["MXTPU_NUMERICS"] = nmode
    try:
        return fn()
    finally:
        if old is None:
            os.environ.pop("MXTPU_NUMERICS", None)
        else:
            os.environ["MXTPU_NUMERICS"] = old


def bench_resnet_infer():
    import mxnet_tpu as mx
    from mxnet_tpu.cached_op import trace
    from mxnet_tpu.gluon.model_zoo import vision

    BATCH, WARMUP, ITERS = 128, 3, 10
    net = vision.resnet50_v1()
    net.initialize()
    net.cast("bfloat16")
    x = mx.np.zeros((BATCH, 3, 224, 224), dtype="bfloat16")
    params = [(name, p.data())
              for name, p in net.collect_params().items()
              if p._data is not None]
    _, _, cop = trace(lambda a: net(a), [x], params)
    arrs = [x] + [arr for _, arr in params]
    for _ in range(WARMUP):
        _sync(cop(*arrs)._data)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = cop(*arrs)
    _sync(out._data)
    dt = time.perf_counter() - t0
    img_s = BATCH * ITERS / dt
    return {"metric": "resnet50_bf16_infer_batch128",
            "value": round(img_s, 2), "unit": "img/s",
            "vs_baseline": round(img_s / BASELINE_RESNET_INFER, 3),
            "mfu": _mfu(img_s * RESNET50_FWD_FLOPS)}


def bench_resnet_train():
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import vision

    from mxnet_tpu import amp

    # "compiled" (default) = Trainer.compile_step, the whole step as ONE
    # donated-buffer program; "learner" = the pre-existing parallel.Learner
    # path (forward+backward program + fused optimizer program)
    path = os.environ.get("BENCH_RESNET_TRAIN_PATH", "compiled")
    BATCH, WARMUP, ITERS = 128, 2, 8
    net = vision.resnet50_v1(classes=1000)
    net.initialize()
    amp.init("bfloat16")  # MXU ops run bf16, params/optimizer state fp32
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = mx.np.random.uniform(size=(BATCH, 3, 224, 224)).astype("bfloat16")
    y = mx.np.random.randint(0, 1000, size=(BATCH,)).astype("float32")
    if path == "compiled":
        trainer = gluon.Trainer(net.collect_params(),
                                mx.optimizer.SGD(learning_rate=0.1,
                                                 momentum=0.9))
        step = trainer.compile_step(net, loss_fn)
        if step.fallback_reason is not None:
            raise RuntimeError("compile_step fell back: "
                               + step.fallback_reason)
    else:
        learner = parallel.Learner(net, loss_fn,
                                   mx.optimizer.SGD(learning_rate=0.1,
                                                    momentum=0.9))
        step = learner.step
    for _ in range(WARMUP):
        _sync(step(x, y)._data)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss = step(x, y)
    _sync(loss._data)
    dt = time.perf_counter() - t0
    img_s = BATCH * ITERS / dt
    return {"metric": "resnet50_train_batch128",
            "value": round(img_s, 2), "unit": "img/s",
            "vs_baseline": round(img_s / BASELINE_RESNET_TRAIN, 3),
            "path": path,  # workload variant: keeps rounds comparable
            "mfu": _mfu(img_s * RESNET50_TRAIN_FLOPS)}


def bench_train_step():
    """Whole-step compilation (Trainer.compile_step: ONE donated-buffer
    program per step) against the eager record/backward/``Trainer.step``
    loop, on an MLP+BN classifier. Reports compiled steps/s, the
    compiled/eager ratio, dispatches/step, compile counts (from telemetry,
    measured outside the timed loops), the numerics-monitor overhead
    (steps/s with MXTPU_NUMERICS=cheap vs off) and the static memory
    ledger. BENCH_TRAIN_STEP_SMALL=1 shrinks the model/iterations for the
    not-slow suite."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd as ag, gluon, telemetry
    from mxnet_tpu.gluon import nn

    small = os.environ.get("BENCH_TRAIN_STEP_SMALL", "") == "1"
    B, H, WARMUP, ITERS = (32, 64, 2, 10) if small else (128, 512, 3, 30)

    def make_net():
        mx.random.seed(7)
        net = nn.Sequential()
        net.add(nn.Dense(H, activation="relu"), nn.BatchNorm(),
                nn.Dense(H, activation="relu"), nn.Dense(10))
        net.initialize()
        return net

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rs = onp.random.RandomState(0)
    x = mx.nd.array(rs.standard_normal((B, H)).astype("float32"))
    y = mx.nd.array(rs.randint(0, 10, (B,)).astype("float32"))
    opt_args = ("sgd", {"learning_rate": 0.05, "momentum": 0.9})

    net_e = make_net()
    tr_e = gluon.Trainer(net_e.collect_params(), *opt_args)

    def eager_step():
        with ag.record():
            loss = loss_fn(net_e(x), y).mean()
        loss.backward()
        tr_e.step(1)
        return loss

    for _ in range(WARMUP):
        _sync(eager_step()._data)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss = eager_step()
    _sync(loss._data)
    eager_sps = ITERS / (time.perf_counter() - t0)

    def timed_compiled():
        net_c = make_net()
        tr_c = gluon.Trainer(net_c.collect_params(), *opt_args)
        st = tr_c.compile_step(net_c, loss_fn)
        if st.fallback_reason is not None:
            raise RuntimeError("compile_step fell back: "
                               + st.fallback_reason)
        for _ in range(WARMUP):
            _sync(st(x, y)._data)
        t0 = time.perf_counter()
        for _ in range(ITERS):
            loss = st(x, y)
        _sync(loss._data)
        return st, ITERS / (time.perf_counter() - t0)

    # numerics monitor overhead: same net/loop compiled with the in-program
    # health outputs (cheap, the default) vs without (off)
    step, compiled_sps = _with_numerics("cheap", timed_compiled)
    _, off_sps = _with_numerics("off", timed_compiled)

    # accounting pass AFTER the timed loops: telemetry on, a few steps,
    # read dispatches/recompiles per step from the accountant
    was_on = telemetry.is_enabled()
    telemetry.reset()
    telemetry.enable()
    try:
        for _ in range(3):
            _sync(step(x, y)._data)
        rows = telemetry.step_report()
    finally:
        telemetry.enable() if was_on else telemetry.disable()
    disp = max(r["dispatches"] for r in rows) if rows else -1
    recomp = sum(r["recompiles"] for r in rows) if rows else -1
    flops_step = max((r.get("flops", 0) for r in rows), default=0)
    mfus = [r["mfu"] for r in rows if r.get("mfu") is not None]
    # per-program view: XLA cost_analysis flops joined with the
    # train_step.call timer (telemetry.cost_report)
    prog = telemetry.cost_report().get("train_step") or {}
    return {"metric": "train_step_compiled_mlp",
            "value": round(compiled_sps, 2), "unit": "steps/s",
            "vs_baseline": round(compiled_sps / max(eager_sps, 1e-9), 3),
            "eager_steps_per_sec": round(eager_sps, 2),
            "dispatches_per_step": disp,
            "recompiles_after_warmup": recomp,
            "compiled_programs": step._traces,
            "flops_per_step": int(flops_step),
            "achieved_flops_per_sec":
                (round(prog["achieved_flops_s"], 1)
                 if prog.get("achieved_flops_s") else None),
            "peak_flops_source": _peak_source(),
            "numerics_off_steps_per_sec": round(off_sps, 2),
            "numerics_overhead_pct":
                round(100.0 * (off_sps - compiled_sps) /
                      max(off_sps, 1e-9), 2),
            "memory": _mem_section(),
            "mfu": round(mfus[-1], 4) if mfus else None}


def bench_train_step_sharded():
    """ZeRO-1 sharded weight update (``compile_step(..., shard_update=True)``)
    against the replicated update on the same dp mesh, Adam on an MLP.
    Both settings dispatch the same compiled program (the parity contract),
    so steps/s should match within noise; the win is optimizer-state
    memory. Reports sharded steps/s, the sharded/replicated ratio,
    per-replica vs replicated optimizer-state bytes (from the telemetry
    gauges), and per-step collective bytes. Select with
    ``bench.py train_step --shard-update``. BENCH_TRAIN_STEP_SMALL=1
    shrinks the model/iterations for the not-slow suite."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, telemetry
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel.mesh import make_mesh

    small = os.environ.get("BENCH_TRAIN_STEP_SMALL", "") == "1"
    B, H, WARMUP, ITERS = (32, 64, 2, 10) if small else (256, 1024, 3, 30)
    mesh = make_mesh()  # every local device on the dp axis
    n_dp = mesh.shape["dp"]
    if n_dp < 2:
        raise RuntimeError(f"sharded update needs dp >= 2, have {n_dp}")

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rs = onp.random.RandomState(0)
    x = mx.nd.array(rs.standard_normal((B, H)).astype("float32"))
    y = mx.nd.array(rs.randint(0, 10, (B,)).astype("float32"))

    def run(shard):
        mx.random.seed(7)
        net = nn.Sequential()
        net.add(nn.Dense(H, activation="relu"),
                nn.Dense(H, activation="relu"), nn.Dense(10))
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 1e-3})
        step = tr.compile_step(net, loss_fn, mesh=mesh, shard_update=shard)
        if step.fallback_reason is not None:
            raise RuntimeError("compile_step fell back: "
                               + step.fallback_reason)
        for _ in range(WARMUP):
            _sync(step(x, y)._data)
        t0 = time.perf_counter()
        for _ in range(ITERS):
            loss = step(x, y)
        _sync(loss._data)
        return step, ITERS / (time.perf_counter() - t0)

    step_s, sharded_sps = run(True)
    _, replicated_sps = run(False)

    # the state-bytes gauges are sampled once at build time — read them
    # before the accounting reset below wipes them
    per_replica = telemetry.gauge(
        "train_step.opt_state_bytes_per_replica").value
    replicated = telemetry.gauge(
        "train_step.opt_state_bytes_replicated").value

    # accounting pass AFTER the timed loops: telemetry on, a few sharded
    # steps, read per-step dispatch and collective traffic
    was_on = telemetry.is_enabled()
    telemetry.reset()
    telemetry.enable()
    try:
        for _ in range(3):
            _sync(step_s(x, y)._data)
        rows = telemetry.step_report()
    finally:
        telemetry.enable() if was_on else telemetry.disable()
    disp = max(r["dispatches"] for r in rows) if rows else -1
    recomp = sum(r["recompiles"] for r in rows) if rows else -1
    coll = max(r["collective_bytes"] for r in rows) if rows else -1
    return {"metric": "train_step_sharded_update_mlp",
            "value": round(sharded_sps, 2), "unit": "steps/s",
            "vs_baseline": round(sharded_sps / max(replicated_sps, 1e-9), 3),
            "replicated_steps_per_sec": round(replicated_sps, 2),
            "dp_size": n_dp,
            "opt_state_bytes_per_replica": int(per_replica),
            "opt_state_bytes_replicated": int(replicated),
            "collective_bytes_per_step": int(coll),
            "dispatches_per_step": disp,
            "recompiles_after_warmup": recomp,
            "compiled_programs": step_s._traces,
            "mfu": None}


def bench_train_step_fsdp():
    """Full-parameter sharding (``compile_step(..., shard_params=True)``)
    against ZeRO-1 and the fully replicated update on the same dp mesh,
    Adam on an MLP. FSDP moves param + grad + optimizer-state residency to
    1/N per replica at the cost of per-layer just-in-time all_gathers, so
    steps/s trails the replicated program on a host mesh where collectives
    are memcpys and memory is no object — the win column is the residency
    bytes. Reports FSDP steps/s, the FSDP/replicated ratio, ZeRO-1 and
    replicated steps/s, per-replica vs replicated param/grad/state bytes
    (from the telemetry gauges sampled at build), and per-step collective
    bytes. Select with ``bench.py train_step --shard-params``.
    BENCH_TRAIN_STEP_SMALL=1 shrinks the model/iterations for the
    not-slow suite."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, telemetry
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel.mesh import make_mesh

    small = os.environ.get("BENCH_TRAIN_STEP_SMALL", "") == "1"
    B, H, WARMUP, ITERS = (32, 64, 2, 10) if small else (256, 1024, 3, 30)
    mesh = make_mesh()  # every local device on the dp axis
    n_dp = mesh.shape["dp"]
    if n_dp < 2:
        raise RuntimeError(f"param sharding needs dp >= 2, have {n_dp}")

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rs = onp.random.RandomState(0)
    x = mx.nd.array(rs.standard_normal((B, H)).astype("float32"))
    y = mx.nd.array(rs.randint(0, 10, (B,)).astype("float32"))

    def run(mode):
        mx.random.seed(7)
        net = nn.Sequential()
        net.add(nn.Dense(H, activation="relu"),
                nn.Dense(H, activation="relu"), nn.Dense(10))
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 1e-3})
        step = tr.compile_step(net, loss_fn, mesh=mesh,
                               shard_params=(mode == "fsdp"),
                               shard_update=(mode == "zero1"))
        if step.fallback_reason is not None:
            raise RuntimeError("compile_step fell back: "
                               + step.fallback_reason)
        for _ in range(WARMUP):
            _sync(step(x, y)._data)
        t0 = time.perf_counter()
        for _ in range(ITERS):
            loss = step(x, y)
        _sync(loss._data)
        sps = ITERS / (time.perf_counter() - t0)
        # the residency gauges are sampled once per build; read them before
        # the next mode's build overwrites them
        g = {k: telemetry.gauge(f"train_step.{k}").value
             for k in ("param_bytes_per_replica", "param_bytes_replicated",
                       "grad_bytes_per_replica",
                       "opt_state_bytes_per_replica",
                       "opt_state_bytes_replicated")}
        return step, sps, g

    _, replicated_sps, _ = run("replicated")
    _, zero1_sps, zero1_g = run("zero1")
    step_f, fsdp_sps, fsdp_g = run("fsdp")
    assert step_f.shard_params is True

    # accounting pass AFTER the timed loops: telemetry on, a few FSDP
    # steps, read per-step dispatch and collective traffic
    was_on = telemetry.is_enabled()
    telemetry.reset()
    telemetry.enable()
    try:
        for _ in range(3):
            _sync(step_f(x, y)._data)
        rows = telemetry.step_report()
    finally:
        telemetry.enable() if was_on else telemetry.disable()
    disp = max(r["dispatches"] for r in rows) if rows else -1
    recomp = sum(r["recompiles"] for r in rows) if rows else -1
    coll = max(r["collective_bytes"] for r in rows) if rows else -1
    return {"metric": "train_step_fsdp_mlp",
            "value": round(fsdp_sps, 2), "unit": "steps/s",
            "vs_baseline": round(fsdp_sps / max(replicated_sps, 1e-9), 3),
            "replicated_steps_per_sec": round(replicated_sps, 2),
            "zero1_steps_per_sec": round(zero1_sps, 2),
            "dp_size": n_dp,
            "param_bytes_per_replica": int(fsdp_g["param_bytes_per_replica"]),
            "param_bytes_replicated": int(fsdp_g["param_bytes_replicated"]),
            "grad_bytes_per_replica": int(fsdp_g["grad_bytes_per_replica"]),
            "opt_state_bytes_per_replica":
                int(fsdp_g["opt_state_bytes_per_replica"]),
            "opt_state_bytes_replicated":
                int(fsdp_g["opt_state_bytes_replicated"]),
            "zero1_opt_state_bytes_per_replica":
                int(zero1_g["opt_state_bytes_per_replica"]),
            "collective_bytes_per_step": int(coll),
            "dispatches_per_step": disp,
            "recompiles_after_warmup": recomp,
            "compiled_programs": step_f._traces,
            "mfu": None}


def bench_train_step_tp():
    """Megatron tensor parallelism composed with FSDP inside the compiled
    step (``compile_step(shard_params=True)`` on a dp x tp mesh with 'tp'
    partition rules): a GPT block trained under the mesh named by
    ``--mesh dpNxtpM`` (BENCH_MESH, default dp4xtp2) against plain FSDP
    with every device on dp. On a host mesh where collectives are memcpys
    the win column is residency — each replica holds 1/(dp*tp) of the
    megatron groups — and the per-axis collective_bytes.dp/.tp split shows
    where the traffic goes. Reports steps/s both ways, the tp/dp-only
    ratio, the per-replica vs replicated param bytes, per-axis collective
    bytes per step, and the dispatch/recompile accounting. Select with
    ``bench.py train_step --mesh dp4xtp2``. BENCH_TRAIN_STEP_SMALL=1
    shrinks the model/iterations for the not-slow suite."""
    import re as _re

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, initializer, telemetry
    from mxnet_tpu.gluon.model_zoo.gpt import gpt_tiny, gpt_tp_rules
    from mxnet_tpu.parallel.mesh import make_mesh

    small = os.environ.get("BENCH_TRAIN_STEP_SMALL", "") == "1"
    spec = os.environ.get("BENCH_MESH", "") or "dp4xtp2"
    m = _re.fullmatch(r"dp(\d+)xtp(\d+)", spec)
    if m is None:
        raise RuntimeError(f"BENCH_MESH must look like dp4xtp2, got {spec!r}")
    n_dp, n_tp = int(m.group(1)), int(m.group(2))
    if small:
        V, B, T, LAYERS, UNITS, WARMUP, ITERS = 67, 8, 12, 2, 64, 2, 8
    else:
        V, B, T, LAYERS, UNITS, WARMUP, ITERS = 384, 16, 32, 4, 128, 3, 20

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rs = onp.random.RandomState(0)
    x = mx.np.array(rs.randint(0, V, (B, T)).astype("int32"))
    y = mx.np.array(rs.randint(0, V, (B, T)).astype("int32"))

    def run(mesh_axes, rules):
        mx.random.seed(7)
        net = gpt_tiny(vocab_size=V, dropout=0.0, num_layers=LAYERS,
                       units=UNITS, num_heads=4, max_length=max(T, 16))
        net.initialize(initializer.Normal(0.05))
        net(x)  # settle shapes
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 1e-3})
        step = tr.compile_step(net, loss_fn, mesh=make_mesh(mesh_axes),
                               shard_params=True, partition_rules=rules)
        for _ in range(WARMUP):
            _sync(step(x, y)._data)
        if step.fallback_reason is not None:
            raise RuntimeError("compile_step fell back: "
                               + step.fallback_reason)
        t0 = time.perf_counter()
        for _ in range(ITERS):
            loss = step(x, y)
        _sync(loss._data)
        sps = ITERS / (time.perf_counter() - t0)
        g = {k: telemetry.gauge(f"train_step.{k}").value
             for k in ("param_bytes_per_replica", "param_bytes_replicated")}
        return step, sps, g

    _, dp_sps, _ = run({"dp": n_dp * n_tp}, None)
    step_t, tp_sps, tp_g = run({"dp": n_dp, "tp": n_tp},
                               gpt_tp_rules("train"))
    if not step_t.shard_params:
        raise RuntimeError(step_t.shard_params_fallback_reason)

    # accounting pass AFTER the timed loops: telemetry on, a few dp x tp
    # steps, read the per-step dispatch and per-axis collective traffic
    was_on = telemetry.is_enabled()
    telemetry.reset()
    telemetry.enable()
    try:
        d0 = telemetry.counter("collective_bytes.dp").value
        t0 = telemetry.counter("collective_bytes.tp").value
        for _ in range(3):
            _sync(step_t(x, y)._data)
        rows = telemetry.step_report()
        dp_bytes = (telemetry.counter("collective_bytes.dp").value - d0) // 3
        tp_bytes = (telemetry.counter("collective_bytes.tp").value - t0) // 3
    finally:
        telemetry.enable() if was_on else telemetry.disable()
    disp = max(r["dispatches"] for r in rows) if rows else -1
    recomp = sum(r["recompiles"] for r in rows) if rows else -1
    return {"metric": "train_step_tp_gpt",
            "value": round(tp_sps, 2), "unit": "steps/s",
            "vs_baseline": round(tp_sps / max(dp_sps, 1e-9), 3),
            "dp_only_steps_per_sec": round(dp_sps, 2),
            "mesh": spec, "dp_size": n_dp, "tp_size": n_tp,
            "param_bytes_per_replica": int(tp_g["param_bytes_per_replica"]),
            "param_bytes_replicated": int(tp_g["param_bytes_replicated"]),
            "collective_bytes_dp_per_step": int(dp_bytes),
            "collective_bytes_tp_per_step": int(tp_bytes),
            "dispatches_per_step": disp,
            "recompiles_after_warmup": recomp,
            "compiled_programs": step_t._traces,
            "mfu": None}


def bench_train_step_multi():
    """Scanned super-step execution (``compile_step(multi_step=K)``): K
    optimizer steps per dispatch via ``lax.scan``, fed by a
    ``DevicePrefetcher`` that stacks + stages the next super-batch while
    the current one computes. Sweeps K over {1, 4, 16} on the dp mesh and
    reports steps/s, HOST ms per step (dispatch-side cost, the quantity
    the scan amortizes — device compute per step is constant on a host
    mesh) and dispatches/step (1/K). K=1 runs through the same scanned
    machinery, so the sweep isolates the super-step amortization. Select
    with ``bench.py train_step --multi-step K`` (K = the headline row;
    every swept K lands in ``sweep``). BENCH_TRAIN_STEP_SMALL=1 shrinks
    the model/iterations for the not-slow suite."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, telemetry
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.data import DevicePrefetcher
    from mxnet_tpu.parallel.mesh import make_mesh

    small = os.environ.get("BENCH_TRAIN_STEP_SMALL", "") == "1"
    B, H, WARMUP, ITERS = (32, 64, 1, 4) if small else (64, 256, 2, 12)
    ks = [1, 4] if small else [1, 4, 16]
    want_k = int(os.environ.get("BENCH_MULTI_STEP", "0")) or ks[-1]
    if want_k not in ks:
        ks.append(want_k)
    mesh = make_mesh()
    n_dp = mesh.shape["dp"]

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rs = onp.random.RandomState(0)
    x_np = rs.standard_normal((B, H)).astype("float32")
    y_np = rs.randint(0, 10, (B,)).astype("float32")

    def run_k(k):
        mx.random.seed(7)
        net = nn.Sequential()
        net.add(nn.Dense(H, activation="relu"), nn.BatchNorm(),
                nn.Dense(H, activation="relu"), nn.Dense(10))
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9})
        step = tr.compile_step(net, loss_fn, mesh=mesh, multi_step=k)
        batches = [(x_np, y_np)] * (k * (WARMUP + ITERS))
        pf = DevicePrefetcher(batches, multi_step=k)
        it = iter(pf)
        # telemetry stays ON for the whole sweep leg: the host-ms gauge
        # and super-step rows are the measurement (same overhead at
        # every K, so the ratios are clean)
        telemetry.reset()
        for _ in range(WARMUP):
            _sync(step(*next(it))._data)
        c0 = telemetry.compile_count()
        host_ms = []
        t0 = time.perf_counter()
        for _ in range(ITERS):
            loss = step(*next(it))
            host_ms.append(telemetry.gauge("train.host_ms_per_step").value)
        _sync(loss._data)
        dt = time.perf_counter() - t0
        pf.close()
        row = telemetry.last_step() or {}
        return {"steps_per_sec": round(k * ITERS / dt, 2),
                "host_ms_per_step": round(sum(host_ms) / len(host_ms), 4),
                "dispatches_per_step":
                    round(row.get("dispatches_per_step", -1), 4),
                "recompiles_after_warmup":
                    telemetry.compile_count() - c0,
                "compiled_programs": step._traces}

    was_on = telemetry.is_enabled()
    telemetry.enable()
    try:
        # the sweep runs with the in-program numerics monitor on (cheap,
        # the default); one extra off leg at the headline K measures its
        # steps/s overhead — same dispatches/step both ways by design
        sweep = {str(k): _with_numerics("cheap", lambda k=k: run_k(k))
                 for k in ks}
        off = _with_numerics("off", lambda: run_k(want_k))
    finally:
        telemetry.enable() if was_on else telemetry.disable()
    head = sweep[str(want_k)]
    base = sweep[str(ks[0])]
    return {"metric": f"train_step_multi_step_k{want_k}",
            "value": head["steps_per_sec"], "unit": "steps/s",
            "vs_baseline": round(head["steps_per_sec"] /
                                 max(base["steps_per_sec"], 1e-9), 3),
            "host_ms_per_step": head["host_ms_per_step"],
            "host_ms_speedup_vs_k1":
                round(base["host_ms_per_step"] /
                      max(head["host_ms_per_step"], 1e-9), 2),
            "dispatches_per_step": head["dispatches_per_step"],
            "recompiles_after_warmup": head["recompiles_after_warmup"],
            "dp_size": int(n_dp),
            "numerics_off_steps_per_sec": off["steps_per_sec"],
            "numerics_overhead_pct":
                round(100.0 * (off["steps_per_sec"] - head["steps_per_sec"])
                      / max(off["steps_per_sec"], 1e-9), 2),
            "sweep": sweep,
            "memory": _mem_section(),
            "mfu": None}


def bench_lstm_lm():
    """LSTM language model training step over the fused lax.scan RNN
    (BASELINE config 3: 'LSTM PTB LM — parity with fused-RNN GPU path').
    PTB-shaped: vocab 10k, 2x650 LSTM, batch 20, bptt 35."""
    import mxnet_tpu as mx
    from mxnet_tpu import amp, gluon, parallel
    from mxnet_tpu.gluon.model_zoo.rnn_lm import rnn_lm

    B, T, WARMUP, ITERS = 20, 35, 2, 8
    net = rnn_lm(vocab_size=10000, embed_size=650, hidden_size=650,
                 num_layers=2, dropout=0.5)
    net.initialize()
    amp.init("bfloat16")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def lm_loss(logits, labels):
        return loss_fn(logits.reshape(-1, 10000),
                       labels.reshape(-1)).mean()

    learner = parallel.Learner(net, lm_loss,
                               mx.optimizer.SGD(learning_rate=1.0))
    x = mx.np.random.randint(0, 10000, size=(B, T))
    y = mx.np.random.randint(0, 10000, size=(B, T)).astype("float32")
    for _ in range(WARMUP):
        _sync(learner.step(x, y)._data)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss = learner.step(x, y)
    _sync(loss._data)
    dt = time.perf_counter() - t0
    tok_s = B * T * ITERS / dt
    return {"metric": "lstm_lm_ptb_train", "value": round(tok_s, 1),
            "unit": "tokens/s",
            "vs_baseline": round(tok_s / BASELINE_LSTM_TOKENS, 3),
            "mfu": None}


def bench_bert_pretrain(size="base"):
    """BERT MLM+NSP pretraining step, bf16, one chip (configs 4 and the
    BERT-Large north-star metric)."""
    import mxnet_tpu as mx
    from mxnet_tpu import amp, gluon, parallel
    from mxnet_tpu.gluon.model_zoo.bert import bert_base, BERTForPretraining

    from mxnet_tpu.gluon.model_zoo.bert import bert_large

    B = 32 if size == "base" else 8
    T, WARMUP, ITERS = 128, 2, 8
    maker = bert_base if size == "base" else bert_large
    bert = maker(max_length=T, dropout=0.1, dtype="float32")
    model = BERTForPretraining(bert, vocab_size=30522)

    padded = os.environ.get("BENCH_BERT_PADDED", "1") == "1"
    if padded:
        # realistic padded batches: a fixed 7/8-valid key-padding mask per
        # row keeps attention on the fused segment-ids flash path (the
        # HLO carries the masked kernel, not an O(T²) where-mask)
        class _PaddedBERT(gluon.HybridBlock):
            def __init__(self, inner, t_valid):
                super().__init__()
                self.inner = inner
                self._t_valid = t_valid

            def forward(self, tokens):
                vlen = mx.np.full((tokens.shape[0],), self._t_valid,
                                  dtype="float32")
                return self.inner(tokens, None, vlen)

        model = _PaddedBERT(model, T * 7 // 8)
    model.initialize()
    amp.convert_hybrid_block(model, "bfloat16")
    amp.init("bfloat16")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def pretrain_loss(pair, labels):
        mlm_scores, nsp_scores = pair
        mlm_labels, nsp_labels = labels[:, :-1], labels[:, -1]
        return loss_fn(mlm_scores, mlm_labels).mean() + \
            loss_fn(nsp_scores, nsp_labels).mean()

    learner = parallel.Learner(model, pretrain_loss,
                               mx.optimizer.AdamW(learning_rate=1e-4,
                                                  wd=0.01),
                               remat=(size == "large"))
    tokens = mx.np.random.randint(0, 30522, size=(B, T))
    labels = mx.np.concatenate([
        mx.np.random.randint(0, 30522, size=(B, T)),
        mx.np.random.randint(0, 2, size=(B, 1))], axis=1).astype("float32")
    for _ in range(WARMUP):
        _sync(learner.step(tokens, labels)._data)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss = learner.step(tokens, labels)
    _sync(loss._data)
    dt = time.perf_counter() - t0
    tok_s = B * T * ITERS / dt
    return {"metric": f"bert_{size}_pretrain_bf16_tokens_per_sec",
            "value": round(tok_s, 1), "unit": "tokens/s",
            "vs_baseline": round(tok_s / BASELINE_BERT_TOKENS, 3),
            "padded": padded,  # workload variant: keeps rounds comparable
            "mfu": _mfu(tok_s * 6 * BERT_PARAMS[size])}


def _resnet50_param_shapes():
    """ResNet-50-shaped tensor set: stem conv + BN pair, 16 bottleneck
    blocks (3 conv kernels + 3 BN gamma/beta pairs each), a downsample
    conv + BN pair per stage, and the fc head — 163 tensors, ~25M params."""
    shapes = [(64, 3, 7, 7), (64,), (64,)]
    for blocks, cin, cmid in [(3, 256, 64), (4, 512, 128),
                              (6, 1024, 256), (3, 2048, 512)]:
        shapes += [(cin, cin // 2 if cin > 256 else 64, 1, 1), (cin,),
                   (cin,)]  # stage downsample projection
        for _ in range(blocks):
            shapes += [(cmid, cin, 1, 1), (cmid,), (cmid,),
                       (cmid, cmid, 3, 3), (cmid,), (cmid,),
                       (cin, cmid, 1, 1), (cin,), (cin,)]
    shapes += [(1000, 2048), (1000,)]
    return shapes


def _build_param_set(shapes, seed=0):
    import jax.numpy as jnp

    from mxnet_tpu.gluon.parameter import Parameter

    rng = onp.random.RandomState(seed)
    params = []
    for j, shp in enumerate(shapes):
        p = Parameter(name=f"p{j}", shape=shp)
        p.initialize()
        p.set_data(jnp.asarray(rng.standard_normal(shp), jnp.float32))
        p.grad()._set_data(
            jnp.asarray(rng.standard_normal(shp), jnp.float32))
        params.append(p)
    return params


def bench_optimizer_step():
    """Fused vs per-param optimizer step over a ResNet-50-sized synthetic
    parameter set (~160 tensors, ~25M params): Trainer.update with the
    fused multi-tensor path on vs off. Reports updates/sec both ways and
    per-step compiled-call counts (fused: O(#buckets); per-param:
    O(#params))."""
    from mxnet_tpu import gluon, optimizer

    shapes = _resnet50_param_shapes()

    def build():
        return _build_param_set(shapes)

    WARMUP, ITERS = 3, 10

    def run(fuse):
        import jax

        params = build()
        tr = gluon.Trainer(params, optimizer.SGD(learning_rate=0.01,
                                                 momentum=0.9))
        tr._fuse = fuse
        for _ in range(WARMUP):
            tr.update(32)
        jax.block_until_ready([p.data()._data for p in params])
        d0 = tr._fused_dispatches
        t0 = time.perf_counter()
        for _ in range(ITERS):
            tr.update(32)
        jax.block_until_ready([p.data()._data for p in params])
        dt = time.perf_counter() - t0
        dispatch = (tr._fused_dispatches - d0) // ITERS if fuse \
            else len(params)
        return len(params) * ITERS / dt, dispatch

    fused_ups, fused_disp = run(True)
    pp_ups, pp_disp = run(False)
    return {"metric": "optimizer_step_fused_resnet50_161tensors",
            "value": round(fused_ups, 1), "unit": "updates/s",
            "vs_baseline": round(fused_ups / max(pp_ups, 1e-9), 3),
            "per_param_updates_per_sec": round(pp_ups, 1),
            "dispatches_fused": fused_disp,
            "dispatches_per_param": pp_disp,
            "mfu": None}


def bench_telemetry_overhead():
    """Enabled-telemetry overhead on the fused optimizer_step bench.

    One trainer, jit caches warmed once, then interleaved off/on timing
    trials; the reported overhead is the ratio of the min-of-trials each
    way — robust to one-off scheduler noise. A second surface covers the
    serve submit path with per-request tracing live (exporter off): the
    RequestTrace allocation + phase marks ride the same interleaved
    pairwise-min protocol. BENCH_TELEM_SMALL=1 shrinks the tensor set
    (for the not-slow test); the acceptance bar is < 2%.
    """
    import jax

    from mxnet_tpu import gluon, optimizer, telemetry

    shapes = _resnet50_param_shapes()
    small = os.environ.get("BENCH_TELEM_SMALL", "") == "1"
    if small:
        shapes = shapes[:40]
    params = _build_param_set(shapes)
    tr = gluon.Trainer(params, optimizer.SGD(learning_rate=0.01,
                                             momentum=0.9))

    # the small set's per-iter time is tiny, so buy noise robustness with
    # more, longer trials — still ~2s of measurement
    WARMUP, ITERS, TRIALS = (3, 25, 8) if small else (3, 10, 5)

    was_on = telemetry.is_enabled()
    try:
        # warm the jit caches under BOTH modes so neither timed loop pays
        # a trace (the observer is baked in at trace time either way; only
        # the runtime ON checks differ between modes)
        for enabled in (False, True):
            telemetry.enable() if enabled else telemetry.disable()
            for _ in range(WARMUP):
                tr.update(32)
        jax.block_until_ready([p.data()._data for p in params])

        def timed(enabled):
            telemetry.enable() if enabled else telemetry.disable()
            t0 = time.perf_counter()
            for _ in range(ITERS):
                tr.update(32)
            jax.block_until_ready([p.data()._data for p in params])
            return time.perf_counter() - t0

        t_off, t_on = [], []
        for _ in range(TRIALS):
            t_off.append(timed(False))
            t_on.append(timed(True))
    finally:
        telemetry.enable() if was_on else telemetry.disable()

    # tracing surface: batched submits through a warmed Predictor with
    # max_wait_us=0 — telemetry on allocates a RequestTrace + 4 phase
    # marks per request; off is a single bool check (new_trace -> None)
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn

    mx.random.seed(5)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
    net.initialize()
    net.hybridize()
    pred = net.predictor(example=mx.nd.array(
        onp.zeros((8, 16), "float32")), max_batch=8, max_wait_us=0)
    S_WARM, S_ITERS, S_TRIALS = 10, (40 if small else 80), 6
    item = onp.zeros(16, "float32")

    def timed_serve(enabled):
        telemetry.enable() if enabled else telemetry.disable()
        t0 = time.perf_counter()
        for _ in range(S_ITERS):
            # 8 in-flight futures per wave: the trace cost is per request,
            # the dispatch handoff cost amortizes over the wave
            for f in [pred.submit(item) for _ in range(8)]:
                f.result(60)
        return time.perf_counter() - t0

    try:
        pred.warmup()
        for enabled in (False, True):
            telemetry.enable() if enabled else telemetry.disable()
            for _ in range(S_WARM):
                pred.submit(item).result(60)
        s_off, s_on = [], []
        for _ in range(S_TRIALS):
            s_off.append(timed_serve(False))
            s_on.append(timed_serve(True))
    finally:
        pred.close()
        telemetry.enable() if was_on else telemetry.disable()

    # each off/on pair runs back-to-back, so ambient load is comparable
    # within a pair; the min over pair ratios filters box noise that a
    # min-of-each-side comparison cannot (no trial window may be quiet)
    overhead = min(on / max(off, 1e-12)
                   for off, on in zip(t_off, t_on)) - 1.0
    pct = overhead * 100.0
    serve_pct = (min(on / max(off, 1e-12)
                     for off, on in zip(s_off, s_on)) - 1.0) * 100.0
    return {"metric": "telemetry_overhead_optimizer_step",
            "value": round(pct, 3), "unit": "%",
            "vs_baseline": round(pct / 2.0, 3),  # fraction of the 2% budget
            "threshold_pct": 2.0,
            "n_tensors": len(shapes),
            "updates_per_sec_off": round(len(shapes) * ITERS / min(t_off), 1),
            "updates_per_sec_on": round(len(shapes) * ITERS / min(t_on), 1),
            "serve_tracing_overhead_pct": round(serve_pct, 3),
            "serve_req_per_sec_off":
                round(8 * S_ITERS * 1.0 / min(s_off), 1),
            "serve_req_per_sec_on":
                round(8 * S_ITERS * 1.0 / min(s_on), 1),
            "mfu": None}


def bench_serve():
    """Inference fast path (serve.Predictor): 64 concurrent single-item
    clients through the shape-bucketed dynamic batcher vs the same thread
    harness doing naive per-request eager forwards on a non-hybridized
    copy of the net. Reports req/s both ways, the serve/eager ratio
    (acceptance bar: >= 3x), batch/dispatch accounting, padding waste,
    p50/p99 latency, and compile counts — steady-state compiles after
    warmup() must be 0. BENCH_SERVE_SMALL=1 shrinks clients/model for
    the not-slow suite."""
    import threading

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.gluon import nn

    small = os.environ.get("BENCH_SERVE_SMALL", "") == "1"
    CLIENTS, REQS, FEAT, HID = (16, 4, 32, 64) if small else (64, 8, 128, 256)

    def make_net(hybrid):
        mx.random.seed(11)
        net = nn.HybridSequential()
        net.add(nn.Dense(HID, activation="relu"),
                nn.Dense(HID, activation="relu"), nn.Dense(10))
        net.initialize()
        if hybrid:
            net.hybridize()
        return net

    rs = onp.random.RandomState(3)
    items = rs.standard_normal((CLIENTS * REQS, FEAT)).astype("float32")

    def drive(worker):
        # identical harness both ways: CLIENTS threads, REQS requests
        # each, all released together; throughput over the joined wall
        barrier = threading.Barrier(CLIENTS + 1)
        errs = []

        def client(cid):
            try:
                barrier.wait()
                for r in range(REQS):
                    worker(items[cid * REQS + r])
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errs.append(e)

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(CLIENTS)]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        if errs:
            raise errs[0]
        return CLIENTS * REQS / dt

    was_on = telemetry.is_enabled()
    telemetry.reset()
    telemetry.enable()
    try:
        # baseline: per-request eager forward, one item per call
        net_e = make_net(hybrid=False)

        def eager_worker(item):
            _sync(net_e(mx.nd.array(item[None, :]))._data)

        for k in range(3):  # warm the per-op programs
            eager_worker(items[k])
        eager_rps = drive(eager_worker)

        # fast path: warmed Predictor, futures-based dynamic batching
        pred = make_net(hybrid=True).predictor(
            example=mx.nd.array(items[:CLIENTS]), max_batch=CLIENTS)
        pred.warmup()
        compiles_warmup = int(telemetry.metrics()["jit.compiles"])
        yref = net_e(mx.nd.array(items[:1])).asnumpy()
        ygot = pred.predict(mx.nd.array(items[:1])).asnumpy()
        onp.testing.assert_allclose(ygot, yref, rtol=2e-4, atol=2e-4)

        c0 = telemetry.metrics()["jit.compiles"]
        serve_rps = drive(lambda item: pred.submit(item).result(120))
        compiles_steady = int(telemetry.metrics()["jit.compiles"] - c0)
        st = pred.stats()
        pred.close()
    finally:
        telemetry.enable() if was_on else telemetry.disable()

    return {"metric": "serve_dynamic_batch_64clients",
            "value": round(serve_rps, 1), "unit": "req/s",
            "vs_baseline": round(serve_rps / max(eager_rps, 1e-9), 3),
            "eager_req_per_sec": round(eager_rps, 1),
            "clients": CLIENTS, "requests": CLIENTS * REQS,
            "dispatches": st["batches"],
            "mean_occupancy": st["mean_occupancy"],
            "padding_waste": st["padding_waste"],
            "latency_ms_p50": st["latency_ms_p50"],
            "latency_ms_p99": st["latency_ms_p99"],
            "compiles_warmup": compiles_warmup,
            "compiles_steady": compiles_steady,
            "mfu": None}


def bench_serve_llm():
    """Continuous-batching decode (serve.decode.DecodeEngine): 64
    concurrent clients with ragged prompt lengths streaming greedy tokens
    from gpt_tiny, vs the same thread harness running the naive
    per-request ``generate(use_cache=False)`` rolling-window loop.
    Reports generated tokens/s both ways, the engine/naive ratio, p50/p99
    TTFT and per-token latency from the telemetry Histograms, slot
    occupancy, and compile counts — steady-state compiles after warmup()
    must be 0. BENCH_SERVE_LLM_SMALL=1 shrinks clients/model for the
    not-slow suite.

    Decode-v2 variants (CLI flags on ``bench.py serve_llm`` / env):
    ``--speculate K`` (BENCH_SPECULATE_K) verifies K tokens per tick;
    ``--prefix-shared PCT`` (BENCH_PREFIX_SHARED) gives PCT%% of clients
    a shared multi-page prompt prefix so the radix cache skips its
    re-prefill; ``--paged`` (BENCH_PAGED=1) doubles num_slots while
    pinning the page pool to the UN-doubled reservation — 2x concurrency
    at equal KV bytes; ``--tp N`` (BENCH_SERVE_TP) serves the model
    tensor-parallel over a {'tp': N} mesh — column-sharded weights,
    head-sharded KV pools, greedy output still bitwise vs naive."""
    import threading

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.gluon.model_zoo import gpt_tiny
    from mxnet_tpu.serve.decode import DecodeEngine

    small = os.environ.get("BENCH_SERVE_LLM_SMALL", "") == "1"
    if small:
        CLIENTS, MAX_NEW, SLOTS, UNITS, LAYERS, MAX_LEN, MAX_PROMPT = \
            (8, 4, 4, 32, 2, 64, 12)
    else:
        CLIENTS, MAX_NEW, SLOTS, UNITS, LAYERS, MAX_LEN, MAX_PROMPT = \
            (64, 16, 16, 64, 2, 128, 48)
    # generation length knob: the default workload is prefill-heavy
    # (prompts ~ MAX_PROMPT, few new tokens); raising MAX_NEW makes the
    # measurement decode-dominated, where per-tick levers (speculation)
    # show up in wall clock instead of being Amdahl-capped by prefill
    MAX_NEW = int(os.environ.get("BENCH_MAX_NEW", "") or MAX_NEW)
    MAX_NEW = min(MAX_NEW, MAX_LEN - MAX_PROMPT)
    VOCAB = 256
    tp = int(os.environ.get("BENCH_SERVE_TP", "1") or 1)
    speculate = int(os.environ.get("BENCH_SPECULATE_K", "0") or 0)
    prefix_pct = max(0, min(100, int(
        os.environ.get("BENCH_PREFIX_SHARED", "0") or 0)))
    paged2x = os.environ.get("BENCH_PAGED", "") == "1"
    v2 = bool(speculate or prefix_pct or paged2x)
    PAGE = 8 if small else 16  # v2 variants only; default clamps to max_len

    mx.random.seed(23)
    net = gpt_tiny(vocab_size=VOCAB, dropout=0.0, num_layers=LAYERS,
                   units=UNITS, num_heads=4, max_length=MAX_LEN)
    net.initialize()
    rs = onp.random.RandomState(7)
    prompts = [[int(t) for t in rs.randint(1, VOCAB,
                                           size=rs.randint(1, MAX_PROMPT))]
               for _ in range(CLIENTS)]
    if prefix_pct:
        # a shared "system prompt" covering >= 1 full page, so the radix
        # cache can map it read-only into every sharer's page table
        span = max(PAGE, (MAX_PROMPT - 4) // PAGE * PAGE)
        shared = [int(t) for t in rs.randint(1, VOCAB, size=span)]
        for i in range(CLIENTS * prefix_pct // 100):
            tail = 1 + rs.randint(max(1, MAX_PROMPT - span))
            prompts[i] = shared + prompts[i][:tail]

    def drive(worker):
        # identical harness both ways: one thread per client, all released
        # together; tokens/s over the joined wall clock
        barrier = threading.Barrier(CLIENTS + 1)
        errs, tokens = [], [0] * CLIENTS

        def client(cid):
            try:
                barrier.wait()
                tokens[cid] = len(worker(prompts[cid]))
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errs.append(e)

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(CLIENTS)]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        if errs:
            raise errs[0]
        return sum(tokens) / dt, sum(tokens)

    was_on = telemetry.is_enabled()
    telemetry.reset()
    telemetry.enable()
    try:
        # baseline: the naive rolling-window loop, one forward per token
        def naive_worker(prompt):
            out = net.generate(prompt, max_new_tokens=MAX_NEW,
                               temperature=0.0, use_cache=False)
            return out[len(prompt):]

        naive_worker(prompts[0])  # warm the window program
        naive_tps, _ = drive(naive_worker)

        slots = SLOTS * 2 if paged2x else SLOTS
        kw = dict(num_slots=slots, max_len=MAX_LEN,
                  max_prompt_len=MAX_PROMPT, prefill_batch=min(slots, 4),
                  max_queue=2 * CLIENTS, cache_dir=False)
        if v2:
            kw.update(page_tokens=PAGE, speculate_k=max(1, speculate),
                      prefix_cache=True)
        if tp > 1:
            kw["tp"] = tp
        if paged2x:
            # equal-bytes contract: the pool stays at the UN-doubled
            # slot reservation while num_slots doubles
            kw["kv_pages"] = SLOTS * (-(-MAX_LEN // PAGE))
        eng = DecodeEngine(net, **kw)
        eng.warmup()
        compiles_warmup = int(telemetry.metrics()["jit.compiles"])
        # greedy parity spot check before timing anything
        want = naive_worker(prompts[0])
        got = eng.submit(prompts[0], max_new_tokens=MAX_NEW).result(120)
        if got != [int(t) for t in want]:
            raise AssertionError(
                f"engine/naive greedy divergence: {got} vs {want}")

        c0 = telemetry.metrics()["jit.compiles"]
        f0 = telemetry.metrics().get("telemetry.flops", 0.0)
        t_drive = time.perf_counter()
        engine_tps, n_tokens = drive(
            lambda p: eng.submit(p, max_new_tokens=MAX_NEW).result(300))
        wall = time.perf_counter() - t_drive
        f1 = telemetry.metrics().get("telemetry.flops", 0.0)
        compiles_steady = int(telemetry.metrics()["jit.compiles"] - c0)
        # per-request phase decomposition (queue -> prefill -> decode) of
        # the traces the engine finished during the drive
        lat = (telemetry.latency_report("serve.decode")
               or {}).get("serve.decode") or {}
        tps_chip = telemetry.gauge("serve.tokens_per_s_chip").value
        st = eng.stats()
        mem = _mem_section()  # while the engine (KV cache, slots) is live
        eng.close()
    finally:
        telemetry.enable() if was_on else telemetry.disable()

    achieved = (f1 - f0) / max(wall, 1e-9)
    return {"metric": "serve_llm_continuous_batching",
            "value": round(engine_tps, 1), "unit": "tok/s",
            "vs_baseline": round(engine_tps / max(naive_tps, 1e-9), 3),
            "naive_tok_per_sec": round(naive_tps, 1),
            "clients": CLIENTS, "tokens": n_tokens,
            "ticks": st["ticks"], "prefills": st["prefills"],
            "mean_slot_occupancy": round(st["mean_slot_occupancy"], 3),
            "ttft_ms_p50": st["ttft_ms_p50"],
            "ttft_ms_p99": st["ttft_ms_p99"],
            "tpot_ms_p50": st["tpot_ms_p50"],
            "tpot_ms_p99": st["tpot_ms_p99"],
            "latency_ms_p99": (lat.get("total_ms") or {}).get("p99"),
            "latency_p99_decomposition_ms": lat.get("p99_attribution_ms"),
            "tokens_per_s_chip": round(tps_chip, 1) if tps_chip else None,
            "shed": st["shed"], "evicted": st["evicted"],
            "compiles_warmup": compiles_warmup,
            "compiles_steady": compiles_steady,
            "speculate_k": st["speculate_k"],
            "spec_accept_mean": (round(st["spec_accept_mean"], 3)
                                 if "spec_accept_mean" in st else None),
            "tp": tp,
            "prefix_shared_pct": prefix_pct,
            "prefix_hit_tokens": st["prefix_hit_tokens"],
            "prompt_tokens": sum(len(p) for p in prompts),
            "page_tokens": st["page_tokens"],
            "kv_pages": st["kv_pages"],
            "num_slots": st["num_slots"],
            "paged_2x_slots": paged2x,
            "page_starved": st["page_starved"],
            "kv_cache_bytes": st["cache_bytes"],
            "achieved_flops_per_sec": round(achieved, 1),
            "peak_flops_source": _peak_source(),
            "memory": mem,
            "mfu": _mfu(achieved)}


def bench_checkpoint():
    """Checkpoint save stall: p99 step time of a compiled train loop with
    NO saves vs SYNC saves vs ASYNC saves (every EVERY steps), plus the
    `checkpoint.save_stall_ms` histogram per regime. Headline is the
    async p99 step-time inflation over the no-checkpoint baseline in
    percent (the acceptance bar is <10%); `vs_baseline` carries the
    sync-vs-async p99 stall ratio (how much stall the background writer
    removes from the step boundary). BENCH_CHECKPOINT_SMALL=1 shrinks
    the model/iterations for the not-slow suite."""
    import shutil
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, telemetry
    from mxnet_tpu.checkpoint import CheckpointManager
    from mxnet_tpu.gluon import nn

    small = os.environ.get("BENCH_CHECKPOINT_SMALL", "") == "1"
    B, H, WARMUP, ITERS, EVERY = (16, 32, 2, 12, 2) if small \
        else (64, 256, 5, 100, 5)

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rs = onp.random.RandomState(0)
    x = mx.nd.array(rs.standard_normal((B, H)).astype("float32"))
    y = mx.nd.array(rs.randint(0, 10, (B,)).astype("float32"))

    def make():
        mx.random.seed(7)
        net = nn.Sequential()
        net.add(nn.Dense(H, activation="relu"), nn.Dense(H),
                nn.Dense(10))
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05})
        step = tr.compile_step(net, loss_fn)
        return net, tr, step

    def run(mode):
        telemetry.reset()  # per-regime checkpoint.* metrics
        net, tr, step = make()
        mgr, tmpd, times = None, None, []
        try:
            if mode != "none":
                tmpd = tempfile.mkdtemp(prefix="mxtpu_bench_ckpt_")
                mgr = CheckpointManager(tmpd, trainer=tr, net=net, keep=2,
                                        async_save=(mode == "async"))
            for _ in range(WARMUP):
                _sync(step(x, y)._data)
            for i in range(1, ITERS + 1):
                t0 = time.perf_counter()
                _sync(step(x, y)._data)
                if mgr is not None and i % EVERY == 0:
                    mgr.save(i)
                times.append(time.perf_counter() - t0)
            if mgr is not None:
                mgr.wait()
        finally:
            if mgr is not None:
                mgr.close()
            if tmpd:
                shutil.rmtree(tmpd, ignore_errors=True)
        arr = onp.asarray(times) * 1e3
        stall = telemetry.REGISTRY.histogram("checkpoint.save_stall_ms")
        s50, s99 = stall.percentiles(50, 99)
        return {"p50_ms": round(float(onp.percentile(arr, 50)), 3),
                "p99_ms": round(float(onp.percentile(arr, 99)), 3),
                "mean_ms": round(float(arr.mean()), 3),
                "stall_ms_p50": round(s50, 3) if s50 is not None else None,
                "stall_ms_p99": round(s99, 3) if s99 is not None else None}

    base, sync, async_ = run("none"), run("sync"), run("async")
    p99_delta_pct = 100.0 * (async_["p99_ms"] - base["p99_ms"]) \
        / max(base["p99_ms"], 1e-9)
    stall_ratio = (sync["stall_ms_p99"] or 0.0) \
        / max(async_["stall_ms_p99"] or 0.0, 1e-9)
    return {"metric": "checkpoint_async_p99_step_inflation",
            "value": round(p99_delta_pct, 2), "unit": "%",
            "vs_baseline": round(stall_ratio, 3),
            "steps": ITERS, "save_every": EVERY,
            "no_ckpt": base, "sync_save": sync, "async_save": async_,
            "async_under_10pct": bool(p99_delta_pct < 10.0),
            "mfu": None}


def _kernel_bench_specs(small):
    """The tuned-vs-default measurement matrix: three kernel families
    across the serving bucket ladder's shape classes."""
    from mxnet_tpu import tune

    if small:
        return [
            tune.attention_spec("flash_fwd", 1, 2, 64, 64, 32,
                                causal=True),
            tune.rows_spec("layer_norm", 128, 128),
            tune.rows_spec("softmax", 128, 128),
        ]
    specs = []
    # attention over three (batch*heads, seq) ladder rungs — GPT decode
    # prefill shapes (causal) at head_dim 64
    for b, t in ((1, 128), (2, 256), (4, 512)):
        specs.append(tune.attention_spec("flash_fwd", b, 4, t, t, 64,
                                         causal=True))
    # row-wise kernels over three row-bucket rungs at d_model 256
    for rows in (128, 512, 2048):
        specs.append(tune.rows_spec("layer_norm", rows, 256))
        specs.append(tune.rows_spec("softmax", rows, 256))
    return specs


def bench_kernels():
    """Tuned-vs-default kernel latency across the bucket ladder.

    Runs the autotuner's own measurement harness (compile-once then
    interleaved pairwise-min trials) per (kernel, bucket) spec and
    reports each spec's default-config time, winner, and speedup. On the
    CPU mesh Pallas runs in interpret mode, where the XLA lowering
    usually wins — exactly the "never silently slower" contract the
    resolve tier enforces; the tuned win reported here is real measured
    time but validates the MECHANISM, not TPU block tuning (see the
    tpu_note field). BENCH_KERNELS_SMALL=1 shrinks the matrix for the
    not-slow smoke.
    """
    from mxnet_tpu import telemetry, tune
    from mxnet_tpu.context import default_backend

    on_cpu = default_backend() == "cpu"
    if on_cpu:
        # exercise the Pallas kernel paths (interpret mode) so candidates
        # differ; without this every config lowers to the same XLA ref
        os.environ.setdefault("MXTPU_PALLAS_INTERPRET", "1")
    small = os.environ.get("BENCH_KERNELS_SMALL", "") == "1"
    specs = _kernel_bench_specs(small)
    tune.reset()
    os.environ.setdefault("MXTPU_TUNE_CACHE",
                          os.path.join(tempfile.gettempdir(),
                                       f"mxtpu_bench_tune_{os.getpid()}.json"))
    wd_before = dict(telemetry.watchdog_stats())
    results = tune.autotune(specs, trials=(2 if small else 4),
                            max_per_axis=(2 if small else 3), save=True)
    rows = []
    for r in results:
        rows.append({"key": r["key"], "winner": r["winner"],
                     "config": r["config"],
                     "default_us": round(r["default_us"], 1),
                     "best_us": round(r["best_us"], 1),
                     "speedup_vs_default":
                         round(r["speedup_vs_default"], 3)})
    kernels_with_win = sorted({r["kernel"] for r in results
                               if r["speedup_vs_default"] > 1.0})
    speedups = [r["speedup_vs_default"] for r in results]
    geo = float(onp.exp(onp.mean(onp.log(onp.maximum(speedups, 1e-9)))))
    return {"metric": "kernel_tuned_vs_default_geomean_speedup",
            "value": round(geo, 3), "unit": "x",
            "vs_baseline": round(max(speedups), 3),
            "specs": len(results),
            "kernels_with_win": kernels_with_win,
            "watchdog_silent": telemetry.watchdog_stats() == wd_before,
            "measurements": tune.status()["measurements"],
            "cache_path": tune.cache_path(),
            "rows": rows,
            "tpu_note": ("CPU interpret mode: Pallas kernels run through "
                         "the Pallas interpreter, so the XLA-native "
                         "candidate usually wins and the tuned tier's "
                         "speedup comes from routing around the "
                         "interpreted kernel — mechanism validation; "
                         "block-level TPU wins need hardware"
                         if on_cpu else None),
            "mfu": None}


def bench_tune():
    """One offline tuning sweep over a small serving ladder: the workflow
    ``tools/tune_kernels.py`` automates, measured. Reports sweep wall
    time, entries persisted, and that a fresh in-process tier then
    resolves every ladder bucket without re-measuring."""
    from mxnet_tpu import tune

    small = os.environ.get("BENCH_KERNELS_SMALL", "") == "1"
    if default_backend_is_cpu():
        os.environ.setdefault("MXTPU_PALLAS_INTERPRET", "1")
    os.environ["MXTPU_TUNE"] = "1"
    os.environ.setdefault("MXTPU_TUNE_CACHE",
                          os.path.join(tempfile.gettempdir(),
                                       f"mxtpu_bench_tune_{os.getpid()}.json"))
    tune.reset()
    specs = tune.ladder_specs(batch_ladder=(1, 2) if small else (1, 2, 4),
                              len_ladder=(64,) if small else (64, 128),
                              num_heads=2, head_dim=32, units=128,
                              families=("flash_fwd", "layer_norm"))
    t0 = time.perf_counter()
    results = tune.autotune(specs, trials=2, max_per_axis=2)
    sweep_s = time.perf_counter() - t0
    measured = tune.status()["measurements"]

    # fresh-process simulation: drop the in-process tier, preload from
    # disk, resolve every spec — zero additional measurements
    tune.reset()
    loaded = tune.preload()
    before = tune.status()
    for s in specs:
        cfg = tune.resolve(s["kernel"], tune.spec_key(s))
        assert cfg != "default"
    after = tune.status()
    return {"metric": "tune_sweep_wall_time", "value": round(sweep_s, 3),
            "unit": "s", "vs_baseline": 0.0,
            "specs": len(specs), "entries_persisted": loaded,
            "sweep_measurements": measured,
            "reload_measurements": after["measurements"] - measured,
            "reload_misses": after["misses"] - before["misses"],
            "cache_path": tune.cache_path(),
            "mfu": None}


def default_backend_is_cpu():
    from mxnet_tpu.context import default_backend

    return default_backend() == "cpu"


def _accel_expected():
    """True when this machine is configured for an accelerator, so a CPU
    result must be reported as a failure rather than published silently:
    - MXTPU_EXPECT_ACCEL=1 (explicit operator statement — most reliable),
    - JAX_PLATFORMS names a non-CPU platform,
    - a PJRT plugin is importable in this interpreter (an importable
      ``axon`` site hook or any registered ``jax_plugins`` entry point) —
      detection by import machinery, not deployment-specific path grepping.
    """
    if os.environ.get("MXTPU_EXPECT_ACCEL", "") == "1":
        return True
    plats = os.environ.get("JAX_PLATFORMS", "")
    if any(p.strip() not in ("", "cpu") for p in plats.split(",")):
        return True
    import importlib.metadata
    import importlib.util

    try:
        if importlib.util.find_spec("axon") is not None:
            return True
    except (ImportError, ValueError):
        pass
    # jax discovers plugins both via jax_plugins.* namespace packages and
    # via entry points; mirror both mechanisms, skipping cpu-only plugins
    try:
        spec = importlib.util.find_spec("jax_plugins")
    except (ImportError, ValueError):
        spec = None
    if spec is not None and spec.submodule_search_locations:
        import pkgutil

        if any(m.name != "cpu" for m in
               pkgutil.iter_modules(spec.submodule_search_locations)):
            return True
    try:
        return any(ep.name != "cpu" for ep in
                   importlib.metadata.entry_points(group="jax_plugins"))
    except Exception:  # noqa: BLE001 — metadata backends vary
        return False


def main():
    which = (sys.argv[1] if len(sys.argv) > 1 else
             os.environ.get("BENCH", "resnet"))
    if which == "train_step" and "--shard-update" in sys.argv[2:]:
        which = "train_step_sharded"
    if which == "train_step" and "--shard-params" in sys.argv[2:]:
        which = "train_step_fsdp"
    if which == "train_step" and "--multi-step" in sys.argv[2:]:
        which = "train_step_multi"
        i = sys.argv.index("--multi-step")
        if len(sys.argv) > i + 1 and sys.argv[i + 1].isdigit():
            os.environ["BENCH_MULTI_STEP"] = sys.argv[i + 1]
    if which == "train_step" and "--mesh" in sys.argv[2:]:
        which = "train_step_tp"
        i = sys.argv.index("--mesh")
        if len(sys.argv) > i + 1:
            os.environ["BENCH_MESH"] = sys.argv[i + 1]
    if which == "serve_llm":
        argv = sys.argv[2:]
        if "--tp" in argv:
            i = sys.argv.index("--tp")
            if len(sys.argv) > i + 1 and sys.argv[i + 1].isdigit():
                os.environ["BENCH_SERVE_TP"] = sys.argv[i + 1]
        if "--speculate" in argv:
            i = sys.argv.index("--speculate")
            if len(sys.argv) > i + 1 and sys.argv[i + 1].isdigit():
                os.environ["BENCH_SPECULATE_K"] = sys.argv[i + 1]
        if "--prefix-shared" in argv:
            i = sys.argv.index("--prefix-shared")
            if len(sys.argv) > i + 1 and sys.argv[i + 1].isdigit():
                os.environ["BENCH_PREFIX_SHARED"] = sys.argv[i + 1]
        if "--paged" in argv:
            os.environ["BENCH_PAGED"] = "1"
    import functools

    result = {"metric": which, "value": 0.0, "unit": "",
              "vs_baseline": 0.0, "mfu": None}
    try:
        fn = {"resnet": bench_resnet_infer,
              "resnet_train": bench_resnet_train,
              "train_step": bench_train_step,
              "train_step_sharded": bench_train_step_sharded,
              "train_step_fsdp": bench_train_step_fsdp,
              "train_step_tp": bench_train_step_tp,
              "train_step_multi": bench_train_step_multi,
              "lstm_lm": bench_lstm_lm,
              "bert_pretrain": bench_bert_pretrain,
              "bert_large_pretrain": functools.partial(bench_bert_pretrain,
                                                       "large"),
              "optimizer_step": bench_optimizer_step,
              "telemetry_overhead": bench_telemetry_overhead,
              "serve": bench_serve,
              "serve_llm": bench_serve_llm,
              "checkpoint": bench_checkpoint,
              "tune": bench_tune,
              "kernels": bench_kernels}[which]
        # resolve the backend up front through the hardened probe: a hung
        # or dead TPU runtime must not kill the bench (round-1 failure:
        # raw RuntimeError) — and must not silently publish a CPU number
        # either (round-2 failure: 10 img/s recorded as if it were the
        # result). The bench can afford one generous init: default the
        # probe budget to 600 s here unless the operator set one.
        os.environ.setdefault("MXTPU_BACKEND_PROBE_TIMEOUT_S", "600")
        from mxnet_tpu.context import backend_probe_was_cached, \
            default_backend, last_backend_probe_error

        backend = default_backend()
        result["backend"] = backend
        result["device"] = _device_info()[0]
        # fail-fast accounting: True when the verdict came from the disk
        # cache (no fresh subprocess probe was paid this run). Failure
        # verdicts persist MXTPU_PROBE_FAIL_TTL_S (default 1 day), so a
        # dead accelerator costs the 600 s budget once, not per bench.
        result["probe_verdict_cached"] = backend_probe_was_cached()
        if backend == "cpu" and _accel_expected() \
                and os.environ.get("BENCH_ALLOW_CPU", "") != "1":
            # TPU expected but unreachable: this is a failure to diagnose.
            # Emit the verbatim plugin error / hang stack instead of
            # spending minutes measuring the host (set BENCH_ALLOW_CPU=1
            # to force a CPU measurement anyway).
            err = last_backend_probe_error() or \
                "accelerator expected but backend resolved to cpu " \
                "(no probe diagnostic captured)"
            # root cause established by repeated long-budget probes during
            # the round-4 build: with the tunnel down, make_c_api_client
            # blocks ~25 minutes inside the axon plugin and then raises
            # 'UNAVAILABLE: TPU backend setup/compile error (Unavailable)'.
            # A probe timeout below that threshold therefore reports the
            # hang stack; the underlying failure is the tunnel endpoint
            # being unavailable, not a client-side deadlock. Only annotate
            # timeout-shaped failures — a fast probe error has its own
            # (different) root cause and must not be misattributed.
            timeout_shaped = any(m in err for m in
                                 ("timed out", "deadline", "hung init",
                                  "Timeout ("))
            note = (" | known failure mode: axon make_c_api_client blocks "
                    "~25min then raises UNAVAILABLE (tunnel endpoint "
                    "down); set MXTPU_BACKEND_PROBE_TIMEOUT_S=1600 to "
                    "capture the UNAVAILABLE error verbatim if the bench "
                    "budget allows") if timeout_shaped else ""
            result["error"] = "TPU unreachable: " + err[:3000] + note
        else:
            result.update(fn())
    except BaseException as e:  # noqa: BLE001 — always emit the JSON line
        result["error"] = f"{type(e).__name__}: {e}"[:3500]
    print(json.dumps(result))
    sys.stdout.flush()
    if "error" in result:
        sys.exit(0)  # partial data beats rc=1 with no line (round-1 lesson)


if __name__ == "__main__":
    main()
