"""Benchmark: ResNet-50 inference throughput on the local accelerator.

Mirrors the reference's headline benchmark
(example/image-classification/benchmark_score.py; numbers in
docs/.../faq/perf.md — V100 fp16 batch 128: 2355.04 img/s, BASELINE.md).
Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import time

BASELINE_IMG_S = 2355.04  # V100 fp16, ResNet-50, batch 128 (perf.md:210)
BATCH = 128
WARMUP = 3
ITERS = 10


def main():
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import amp
    from mxnet_tpu.cached_op import trace
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.resnet50_v1()
    net.initialize()
    # bf16 everywhere: MXU-native inference precision
    net.cast("bfloat16")
    x = mx.np.zeros((BATCH, 3, 224, 224), dtype="bfloat16")
    params = [(name, p.data())
              for name, p in net.collect_params().items()
              if p._data is not None]
    _, _, cop = trace(lambda a: net(a), [x], params)
    arrs = [x] + [arr for _, arr in params]

    import numpy as onp

    def sync(arr):
        # device->host readback: the only reliable barrier on every PJRT
        # backend (block_until_ready is a no-op on some tunneled platforms)
        return onp.asarray(arr._data[0, 0])

    for _ in range(WARMUP):
        out = cop(*arrs)
        sync(out)

    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = cop(*arrs)
    sync(out)
    dt = time.perf_counter() - t0

    img_s = BATCH * ITERS / dt
    print(json.dumps({
        "metric": "resnet50_bf16_infer_batch128",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }))


if __name__ == "__main__":
    main()
