"""Benchmarks on the local accelerator. Prints ONE JSON line.

Default metric mirrors the reference's headline benchmark
(example/image-classification/benchmark_score.py; docs/.../faq/perf.md —
V100 fp16 ResNet-50 batch 128: 2355.04 img/s, BASELINE.md). Select with
argv[1] or BENCH env: resnet (default) | resnet_train | bert_pretrain.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as onp

BASELINE_RESNET_INFER = 2355.04  # V100 fp16 batch 128 (perf.md:210)
BASELINE_RESNET_TRAIN = 363.69   # V100 fp32 batch 128 training (perf.md:254)
BASELINE_BERT_TOKENS = 10000.0   # A100-class tokens/sec/chip anchor (BASELINE.md)


def _sync(data):
    # device->host readback: the only reliable barrier on every PJRT backend
    return onp.asarray(data.ravel()[0] if hasattr(data, "ravel") else data)


def bench_resnet_infer():
    import mxnet_tpu as mx
    from mxnet_tpu.cached_op import trace
    from mxnet_tpu.gluon.model_zoo import vision

    BATCH, WARMUP, ITERS = 128, 3, 10
    net = vision.resnet50_v1()
    net.initialize()
    net.cast("bfloat16")
    x = mx.np.zeros((BATCH, 3, 224, 224), dtype="bfloat16")
    params = [(name, p.data())
              for name, p in net.collect_params().items()
              if p._data is not None]
    _, _, cop = trace(lambda a: net(a), [x], params)
    arrs = [x] + [arr for _, arr in params]
    for _ in range(WARMUP):
        _sync(cop(*arrs)._data)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = cop(*arrs)
    _sync(out._data)
    dt = time.perf_counter() - t0
    img_s = BATCH * ITERS / dt
    return {"metric": "resnet50_bf16_infer_batch128",
            "value": round(img_s, 2), "unit": "img/s",
            "vs_baseline": round(img_s / BASELINE_RESNET_INFER, 3)}


def bench_resnet_train():
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import vision

    from mxnet_tpu import amp

    BATCH, WARMUP, ITERS = 128, 2, 8
    net = vision.resnet50_v1(classes=1000)
    net.initialize()
    amp.init("bfloat16")  # MXU ops run bf16, params/optimizer state fp32
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    learner = parallel.Learner(net, loss_fn,
                               mx.optimizer.SGD(learning_rate=0.1,
                                                momentum=0.9))
    x = mx.np.random.uniform(size=(BATCH, 3, 224, 224)).astype("bfloat16")
    y = mx.np.random.randint(0, 1000, size=(BATCH,)).astype("float32")
    for _ in range(WARMUP):
        _sync(learner.step(x, y)._data)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss = learner.step(x, y)
    _sync(loss._data)
    dt = time.perf_counter() - t0
    img_s = BATCH * ITERS / dt
    return {"metric": "resnet50_train_batch128",
            "value": round(img_s, 2), "unit": "img/s",
            "vs_baseline": round(img_s / BASELINE_RESNET_TRAIN, 3)}


def bench_bert_pretrain(size="base"):
    """BERT MLM+NSP pretraining step, bf16, one chip (configs 4 and the
    BERT-Large north-star metric)."""
    import mxnet_tpu as mx
    from mxnet_tpu import amp, gluon, parallel
    from mxnet_tpu.gluon.model_zoo.bert import bert_base, BERTForPretraining

    from mxnet_tpu.gluon.model_zoo.bert import bert_large

    B = 32 if size == "base" else 8
    T, WARMUP, ITERS = 128, 2, 8
    maker = bert_base if size == "base" else bert_large
    bert = maker(max_length=T, dropout=0.1, dtype="float32")
    model = BERTForPretraining(bert, vocab_size=30522)
    model.initialize()
    amp.convert_hybrid_block(model, "bfloat16")
    amp.init("bfloat16")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def pretrain_loss(pair, labels):
        mlm_scores, nsp_scores = pair
        mlm_labels, nsp_labels = labels[:, :-1], labels[:, -1]
        return loss_fn(mlm_scores, mlm_labels).mean() + \
            loss_fn(nsp_scores, nsp_labels).mean()

    learner = parallel.Learner(model, pretrain_loss,
                               mx.optimizer.AdamW(learning_rate=1e-4,
                                                  wd=0.01),
                               remat=(size == "large"))
    tokens = mx.np.random.randint(0, 30522, size=(B, T))
    labels = mx.np.concatenate([
        mx.np.random.randint(0, 30522, size=(B, T)),
        mx.np.random.randint(0, 2, size=(B, 1))], axis=1).astype("float32")
    for _ in range(WARMUP):
        _sync(learner.step(tokens, labels)._data)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss = learner.step(tokens, labels)
    _sync(loss._data)
    dt = time.perf_counter() - t0
    tok_s = B * T * ITERS / dt
    return {"metric": f"bert_{size}_pretrain_bf16_tokens_per_sec",
            "value": round(tok_s, 1), "unit": "tokens/s",
            "vs_baseline": round(tok_s / BASELINE_BERT_TOKENS, 3)}


def main():
    which = (sys.argv[1] if len(sys.argv) > 1 else
             os.environ.get("BENCH", "resnet"))
    import functools

    fn = {"resnet": bench_resnet_infer,
          "resnet_train": bench_resnet_train,
          "bert_pretrain": bench_bert_pretrain,
          "bert_large_pretrain": functools.partial(bench_bert_pretrain,
                                                   "large")}[which]
    print(json.dumps(fn()))


if __name__ == "__main__":
    main()
