#!/usr/bin/env python
"""Offline Pallas-kernel autotuner — pre-populate the tuning cache.

Run this once per environment (and per model shape class) so serving
processes start with every (kernel, bucket) winner on disk and never
measure candidates online:

    # tune the ladder a GPT-style decode service will trace
    python tools/tune_kernels.py --batch-ladder 1,2,4,8 \
        --len-ladder 128,256,512 --num-heads 8 --head-dim 64 \
        --units 512 --families flash_fwd,flash_bwd,layer_norm

    # then serve with the tuned tier on
    MXTPU_TUNE=1 python serve_my_model.py   # Predictor/DecodeEngine
                                            # warmup preloads winners

On a CPU-only box pass --interpret to exercise the Pallas paths through
the interpreter (mechanism check; block winners only transfer from real
hardware). The cache lands at ``context.tuning_cache_path()`` (override:
``MXTPU_TUNE_CACHE``), keyed by the backend-probe env signature — a
cache tuned under one environment is never replayed into another.

Exit code 0 on success; prints one JSON line per tuned spec and a
summary line at the end.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _ints(s):
    return tuple(int(x) for x in s.split(",") if x.strip())


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--batch-ladder", type=_ints, default=(1, 2, 4, 8))
    ap.add_argument("--len-ladder", type=_ints, default=(128, 256, 512))
    ap.add_argument("--num-heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--units", type=int, default=512,
                    help="d_model for the row-wise kernels (LayerNorm "
                         "rows are batch*len wide, units deep)")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--families", default="flash_fwd,layer_norm",
                    help="comma list from: flash_fwd, flash_bwd, "
                         "layer_norm, softmax")
    ap.add_argument("--no-seg", action="store_true",
                    help="tune the plain attention variant instead of "
                         "the segment-ids one the serving prefill uses")
    ap.add_argument("--trials", type=int, default=None,
                    help="measurement rounds per candidate "
                         "(default MXTPU_TUNE_TRIALS or 3)")
    ap.add_argument("--max-per-axis", type=int, default=3,
                    help="power-of-two block candidates per axis")
    ap.add_argument("--interpret", action="store_true",
                    help="set MXTPU_PALLAS_INTERPRET=1 (CPU mechanism "
                         "check)")
    ap.add_argument("--cache", default=None,
                    help="override the cache path (MXTPU_TUNE_CACHE)")
    args = ap.parse_args(argv)

    if args.interpret:
        os.environ["MXTPU_PALLAS_INTERPRET"] = "1"
    if args.cache:
        os.environ["MXTPU_TUNE_CACHE"] = args.cache

    from mxnet_tpu import tune

    families = tuple(f.strip() for f in args.families.split(",")
                     if f.strip())
    bad = [f for f in families if f not in
           ("flash_fwd", "flash_bwd", "layer_norm", "softmax")]
    if bad:
        ap.error(f"unknown kernel families: {bad}")
    specs = tune.ladder_specs(args.batch_ladder, args.len_ladder,
                              args.num_heads, args.head_dim, args.units,
                              dtype=args.dtype, seg=not args.no_seg,
                              families=families)

    def emit(line):
        print(line, flush=True)

    results = tune.autotune(specs, trials=args.trials,
                            max_per_axis=args.max_per_axis,
                            verbose=emit)
    path = tune.save()
    wins = sum(1 for r in results if r["winner"] not in ("default",))
    print(json.dumps({
        "tuned_specs": len(results),
        "non_default_winners": wins,
        "measurements": tune.status()["measurements"],
        "cache_path": path,
        "next": "serve with MXTPU_TUNE=1; warmup preloads these winners",
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
