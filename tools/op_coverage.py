"""Op-surface accounting vs the reference's NNVM registrations.

Prints how every `NNVM_REGISTER_OP(name)` in the reference's src/operator
maps onto this framework's registry: matched directly, matched via alias /
snake-case, or residual with the reason it has no standalone counterpart
(backward nodes are autodiff-derived here; fusion/TensorRT/MKLDNN/TVM
internals are subsumed by XLA).

Run:  JAX_PLATFORMS=cpu python tools/op_coverage.py [/path/to/reference]
"""
import os
import re
import subprocess
import sys

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

RESIDUAL_REASONS = (
    ("_backward", "backward node — derived by jax autodiff, not a "
                  "standalone op here"),
    ("Backward", "backward node — derived by jax autodiff"),
    ("_grad", "gradient helper — autodiff-derived"),
    ("_FusedOp", "NVRTC pointwise fusion engine internal — XLA fuses"),
    ("_TensorRT", "TensorRT subgraph op — gated stub by design"),
    ("_sg_mkldnn", "oneDNN subgraph op — CPU fast path not needed"),
    ("_contrib_tvm", "TVMOp bridge — out of scope per SURVEY"),
    ("_CuDNN", "cuDNN-specific variant — XLA lowers the base op"),
    ("CuDNN", "cuDNN-specific variant"),
    ("_Native", "legacy C plugin bridge"),
    ("_NDArray", "legacy C plugin bridge"),
    ("_CrossDevice", "multi-GPU copy node — PJRT transfers subsume"),
    ("_Custom", "custom-op C bridge — mx.operator implements in python"),
    ("_image_", "image op — covered under image namespace name"),
    ("_split_v2_backward", "backward node"),
    ("name", "macro artifact in reference source, not an op"),
)


def residual_reason(name):
    for prefix, why in RESIDUAL_REASONS:
        if name.startswith(prefix) or name == prefix:
            return why
    if "backward" in name.lower():
        return "backward node — derived by jax autodiff"
    return None


def main():
    ref = sys.argv[1] if len(sys.argv) > 1 else "/root/reference"
    out = subprocess.run(
        ["grep", "-rhoE", r"NNVM_REGISTER_OP\(([A-Za-z0-9_]+)\)",
         os.path.join(ref, "src/operator"), "--include=*.cc"],
        capture_output=True, text=True).stdout
    ref_names = sorted({m.group(1) for m in
                        re.finditer(r"NNVM_REGISTER_OP\((\w+)\)", out)})

    import mxnet_tpu  # noqa: F401 — registers everything
    from mxnet_tpu.ops.registry import _OPS

    ours = set(_OPS)
    matched, residual, unmapped = [], [], []
    for r in ref_names:
        snake = re.sub(r"(?<!^)(?=[A-Z])", "_", r).lower().lstrip("_")
        if {r, snake, r.lstrip("_"), r.lower()} & ours:
            matched.append(r)
        elif residual_reason(r):
            residual.append((r, residual_reason(r)))
        else:
            unmapped.append(r)
    print(f"reference NNVM registrations: {len(ref_names)}")
    print(f"matched by name/alias:        {len(matched)}")
    print(f"residual (by design):         {len(residual)}")
    for name, why in residual:
        print(f"    {name:<40} {why}")
    print(f"UNMAPPED (gaps):              {len(unmapped)}")
    for name in unmapped:
        print(f"    {name}")
    return len(unmapped)


if __name__ == "__main__":
    sys.exit(1 if main() else 0)
