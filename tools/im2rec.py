#!/usr/bin/env python
"""Pack an image folder / .lst file into RecordIO shards.

Reference: tools/im2rec.py. Usage:
    python tools/im2rec.py <prefix> <root> [--list] [--recursive]
Creates <prefix>.lst / <prefix>.rec / <prefix>.idx.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as onp  # noqa: E402


def make_list(prefix, root, recursive=False, exts=(".jpg", ".jpeg", ".png",
                                                   ".npy")):
    """One class per top-level folder; --recursive walks nested dirs too."""
    items = []
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    for label, cls in enumerate(classes):
        folder = os.path.join(root, cls)
        if recursive:
            files = []
            for dirpath, _, fnames in os.walk(folder):
                for fname in fnames:
                    files.append(os.path.relpath(
                        os.path.join(dirpath, fname), root))
            files.sort()
        else:
            files = [os.path.join(cls, f)
                     for f in sorted(os.listdir(folder))]
        for rel in files:
            if rel.lower().endswith(exts):
                items.append((len(items), label, rel))
    with open(prefix + ".lst", "w") as f:
        for idx, label, path in items:
            f.write(f"{idx}\t{label}\t{path}\n")
    return items


def make_rec(prefix, root, quality=95):
    from mxnet_tpu import recordio
    from PIL import Image

    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    with open(prefix + ".lst") as f:
        for line in f:
            idx, label, path = line.strip().split("\t")
            full = os.path.join(root, path)
            if full.endswith(".npy"):
                img = onp.load(full)
            else:
                img = onp.asarray(Image.open(full).convert("RGB"))
            header = recordio.IRHeader(0, float(label), int(idx))
            rec.write_idx(int(idx), recordio.pack_img(header, img,
                                                      quality=quality))
    rec.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("prefix")
    ap.add_argument("root")
    ap.add_argument("--list", action="store_true",
                    help="only generate the .lst file")
    ap.add_argument("--recursive", action="store_true",
                    help="walk nested directories under each class folder")
    ap.add_argument("--quality", type=int, default=95)
    args = ap.parse_args()
    make_list(args.prefix, args.root, recursive=args.recursive)
    if not args.list:
        make_rec(args.prefix, args.root, args.quality)


if __name__ == "__main__":
    main()
