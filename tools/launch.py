#!/usr/bin/env python
"""Multi-process job launcher.

Reference: tools/launch.py (dmlc_tracker — spawns scheduler/servers/workers
with DMLC_ROLE env vars, :72-110). TPU-native redesign: there is no parameter
server; the launcher spawns N identical WORKER processes wired together by
jax.distributed (coordinator = worker 0). This is the local recipe the
distributed tests use (SURVEY §4: multi-node-without-cluster), and the same
env contract a real multi-host TPU job uses (one process per host).

Env contract consumed by mxnet_tpu.kvstore:
    MXTPU_DIST_COORD  - coordinator address host:port
    MXTPU_DIST_NPROC  - number of processes
    MXTPU_DIST_RANK   - this process's rank

Usage:
    python tools/launch.py -n 3 [--launcher local] python my_script.py args...
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch_local(n, command, env_extra=None):
    import time

    port = _free_port()
    procs = []
    for rank in range(n):
        env = dict(os.environ)
        env.update(env_extra or {})
        env["MXTPU_DIST_COORD"] = f"127.0.0.1:{port}"
        env["MXTPU_DIST_NPROC"] = str(n)
        env["MXTPU_DIST_RANK"] = str(rank)
        procs.append(subprocess.Popen(command, env=env))
    # poll all workers: one crashing must kill the siblings immediately,
    # or survivors block inside jax.distributed.initialize for minutes
    rc = 0
    alive = list(procs)
    while alive:
        time.sleep(0.2)
        for p in list(alive):
            ret = p.poll()
            if ret is None:
                continue
            alive.remove(p)
            if ret != 0 and rc == 0:
                rc = ret
                for q in alive:
                    q.terminate()
    for p in procs:
        p.wait()
    return rc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("--launcher", default="local", choices=["local"])
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if not args.command:
        ap.error("no command given")
    sys.exit(launch_local(args.num_workers, args.command))


if __name__ == "__main__":
    main()
