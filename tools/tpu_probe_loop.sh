#!/bin/bash
# Background TPU-availability probe loop (round-4 diagnosis: with the axon
# tunnel down, make_c_api_client blocks ~25 min then raises UNAVAILABLE).
# Each attempt gets a generous budget; on the first success it immediately
# runs the full evidence pipeline (tools/tpu_evidence.py) so the first
# minutes of tunnel availability produce numbers.
LOG=${1:-/tmp/tpu_probe.log}
echo "== probe loop start $(date -u +%FT%TZ) ==" >> "$LOG"
while true; do
  START=$(date +%s)
  timeout 1700 python - <<'EOF' >> "$LOG" 2>&1
import faulthandler, sys, datetime
faulthandler.dump_traceback_later(1650, exit=True)
print(f"-- probe attempt {datetime.datetime.utcnow().isoformat()}Z", flush=True)
import jax
devs = jax.devices()
print("DEVICES:", devs, flush=True)
if any(d.platform != "cpu" for d in devs):
    print("TPU_UP", flush=True)
    sys.exit(42)
EOF
  RC=$?
  END=$(date +%s)
  echo "-- attempt rc=$RC elapsed=$((END-START))s" >> "$LOG"
  if [ "$RC" = "42" ]; then
    echo "== TPU UP — running evidence pipeline ==" >> "$LOG"
    cd /root/repo && python tools/tpu_evidence.py >> "$LOG" 2>&1
    echo "== evidence pipeline done rc=$? ==" >> "$LOG"
    # keep looping in case more runs are wanted, but slow down
    sleep 1800
  else
    sleep 30
  fi
done
