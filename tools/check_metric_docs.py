#!/usr/bin/env python3
"""Lint: every ``serve.*`` / ``telemetry.*`` / ``checkpoint.*`` /
``fault.*`` / ``train.*`` / ``collective.*`` / ``collective_bytes.*`` /
``tune.*`` metric name created anywhere in ``mxnet_tpu/``
must appear in docs/DESIGN.md (the Observability metric inventory), and
every ``MXTPU_*`` environment variable actually read from the
environment must appear in docs/ENV_VARS.md — so the exported
namespaces and the documentation cannot drift.

Literal metric names must appear verbatim; f-string names (dynamic
buckets like ``serve.bucket{bucket}.call``) are checked by their literal
prefix up to the first ``{``. Env vars are collected only at READ sites
(``os.environ.get/[]``, ``os.getenv``, the local ``_env_*`` helpers) so
docstring mentions don't count as definitions; dynamic families read by
prefix scan (``MXTPU_FAULT_*``) are covered by the prefix-constant
assignment matching the documented family row. Exits non-zero listing
the undocumented names. Run directly or via
tests/test_observability_v2.py.
"""
from __future__ import annotations

import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
DESIGN = ROOT / "docs" / "DESIGN.md"
ENV_VARS = ROOT / "docs" / "ENV_VARS.md"

# any Registry accessor or direct metric-class construction carrying a
# name in a linted namespace, e.g. REGISTRY.counter("serve.requests") or
# Histogram("serve.ttft_ms", ...)
_CREATE = re.compile(
    r"(?:counter|gauge|timer|histogram|Counter|Gauge|Timer|Histogram)\(\s*"
    r"(f?)([\"'])"
    r"((?:serve|telemetry|checkpoint|fault|train|mem|numerics"
    r"|collective_bytes|collective|tune)"
    r"\.[^\"']*)\2")


def collect(src_root=None):
    """{name_or_prefix: [file:line, ...]} over mxnet_tpu/**/*.py."""
    src_root = pathlib.Path(src_root) if src_root else ROOT / "mxnet_tpu"
    found = {}
    for path in sorted(src_root.rglob("*.py")):
        text = path.read_text()
        for m in _CREATE.finditer(text):
            is_f, name = m.group(1), m.group(3)
            if is_f:
                name = name.split("{", 1)[0]
            line = text.count("\n", 0, m.start()) + 1
            try:
                rel = path.relative_to(ROOT)
            except ValueError:  # scanning a tree outside the repo (tests)
                rel = path
            found.setdefault(name, []).append(f"{rel}:{line}")
    return found


def missing_names(doc_path=DESIGN, src_root=None):
    doc = pathlib.Path(doc_path).read_text()
    return {name: sites for name, sites in collect(src_root).items()
            if name not in doc}


# an MXTPU_* name counts only where it is READ from the environment: the
# stdlib accessors, the per-module _env_int/_env_str-style helpers, or a
# *_PREFIX constant feeding a dynamic os.environ scan (chaos harness) —
# a name quoted in a docstring or error message is not a definition
_ENV_READ = re.compile(
    r"(?:environ\.get\(|environ\[|getenv\(|_env_[a-z]+\(|_PREFIX\s*=\s*)"
    r"\s*([\"'])(MXTPU_[A-Z0-9_]+)\1")


def collect_env(src_root=None):
    """{env_var_or_prefix: [file:line, ...]} over mxnet_tpu/**/*.py."""
    src_root = pathlib.Path(src_root) if src_root else ROOT / "mxnet_tpu"
    found = {}
    for path in sorted(src_root.rglob("*.py")):
        text = path.read_text()
        for m in _ENV_READ.finditer(text):
            name = m.group(2)
            line = text.count("\n", 0, m.start()) + 1
            try:
                rel = path.relative_to(ROOT)
            except ValueError:
                rel = path
            found.setdefault(name, []).append(f"{rel}:{line}")
    return found


def missing_env_vars(doc_path=ENV_VARS, src_root=None):
    """Env vars read in the source but absent from docs/ENV_VARS.md.

    A trailing-underscore name is a dynamic-family prefix; it is
    documented if any documented name starts with it (e.g. the
    ``MXTPU_FAULT_<POINT>`` row covers the ``MXTPU_FAULT_`` scan).
    """
    doc = pathlib.Path(doc_path).read_text()
    return {name: sites for name, sites in collect_env(src_root).items()
            if name not in doc}


def main():
    rc = 0
    missing = missing_names()
    if not missing:
        print(f"metric docs lint: all {len(collect())} "
              "serve./telemetry./checkpoint./fault./train./mem./numerics."
              "/tune. names documented in docs/DESIGN.md")
    else:
        print("metric names missing from docs/DESIGN.md:", file=sys.stderr)
        for name, sites in sorted(missing.items()):
            print(f"  {name}  (created at {', '.join(sites)})",
                  file=sys.stderr)
        rc = 1
    missing_env = missing_env_vars()
    if not missing_env:
        print(f"env var docs lint: all {len(collect_env())} MXTPU_* "
              "variables read in mxnet_tpu/ documented in docs/ENV_VARS.md")
    else:
        print("MXTPU_* env vars missing from docs/ENV_VARS.md:",
              file=sys.stderr)
        for name, sites in sorted(missing_env.items()):
            print(f"  {name}  (read at {', '.join(sites)})", file=sys.stderr)
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
