#!/usr/bin/env python3
"""Lint: every ``serve.*`` / ``telemetry.*`` / ``checkpoint.*`` /
``fault.*`` / ``train.*`` metric name created anywhere in ``mxnet_tpu/``
must appear in docs/DESIGN.md (the Observability metric inventory), so
the exported namespace and the documentation cannot drift.

Literal names must appear verbatim; f-string names (dynamic buckets like
``serve.bucket{bucket}.call``) are checked by their literal prefix up to
the first ``{``. Exits non-zero listing the undocumented names. Run
directly or via tests/test_observability_v2.py.
"""
from __future__ import annotations

import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
DESIGN = ROOT / "docs" / "DESIGN.md"

# any Registry accessor or direct metric-class construction carrying a
# name in a linted namespace, e.g. REGISTRY.counter("serve.requests") or
# Histogram("serve.ttft_ms", ...)
_CREATE = re.compile(
    r"(?:counter|gauge|timer|histogram|Counter|Gauge|Timer|Histogram)\(\s*"
    r"(f?)([\"'])((?:serve|telemetry|checkpoint|fault|train|mem|numerics)"
    r"\.[^\"']*)\2")


def collect(src_root=None):
    """{name_or_prefix: [file:line, ...]} over mxnet_tpu/**/*.py."""
    src_root = pathlib.Path(src_root) if src_root else ROOT / "mxnet_tpu"
    found = {}
    for path in sorted(src_root.rglob("*.py")):
        text = path.read_text()
        for m in _CREATE.finditer(text):
            is_f, name = m.group(1), m.group(3)
            if is_f:
                name = name.split("{", 1)[0]
            line = text.count("\n", 0, m.start()) + 1
            try:
                rel = path.relative_to(ROOT)
            except ValueError:  # scanning a tree outside the repo (tests)
                rel = path
            found.setdefault(name, []).append(f"{rel}:{line}")
    return found


def missing_names(doc_path=DESIGN, src_root=None):
    doc = pathlib.Path(doc_path).read_text()
    return {name: sites for name, sites in collect(src_root).items()
            if name not in doc}


def main():
    missing = missing_names()
    if not missing:
        print(f"metric docs lint: all {len(collect())} "
              "serve./telemetry./checkpoint./fault./train./mem./numerics. "
              "names documented in docs/DESIGN.md")
        return 0
    print("metric names missing from docs/DESIGN.md:", file=sys.stderr)
    for name, sites in sorted(missing.items()):
        print(f"  {name}  (created at {', '.join(sites)})", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
