"""One-command TPU evidence pipeline (round-5 chip-readiness product).

Run the moment the accelerator is reachable — every stage is independent,
failures are recorded rather than fatal, and all artifacts land under
``benchmark/tpu_evidence/`` so a single ``git add`` checks them in:

  a. ``bench.py`` all five modes (resnet / resnet_train / lstm_lm /
     bert_pretrain / bert_large_pretrain), each with MFU.
  b. flash-attention block-size sweep: MXTPU_FLASH_BLOCK_Q/K grid over the
     BERT shape classes (kernels read the env at import, so one fresh
     interpreter per grid point).
  c. CPU-vs-TPU ``check_consistency`` sweep over the opperf op specs —
     the reference's CPU<->GPU oracle, finally run cross-backend.
  d. ``benchmark/opperf.py`` on device.
  e. one profiler trace of a ``Learner.step``.

Usage: ``python tools/tpu_evidence.py [stage ...]`` (default: all).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "benchmark", "tpu_evidence")
PY = sys.executable


def _run(cmd, env=None, timeout=1800):
    """Run a subprocess, return (rc, last_json_line_or_None, tail)."""
    full_env = dict(os.environ)
    # a generous one-shot init budget; the tunnel is known-up when we run
    full_env.setdefault("MXTPU_BACKEND_PROBE_TIMEOUT_S", "600")
    if env:
        full_env.update(env)
    try:
        p = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout, env=full_env, cwd=REPO)
        out = p.stdout.strip().splitlines()
        last = None
        for line in reversed(out):
            line = line.strip()
            if line.startswith("{"):
                try:
                    last = json.loads(line)
                    break
                except ValueError:
                    continue
        return p.returncode, last, "\n".join((p.stdout + p.stderr)
                                             .splitlines()[-15:])
    except subprocess.TimeoutExpired:
        return -9, None, f"timeout after {timeout}s"


def stage_bench():
    modes = ["resnet", "resnet_train", "lstm_lm", "bert_pretrain",
             "bert_large_pretrain"]
    results = {}
    for mode in modes:
        t0 = time.time()
        rc, js, tail = _run([PY, "bench.py", mode])
        results[mode] = js or {"error": f"rc={rc}: {tail[-500:]}"}
        results[mode]["wall_s"] = round(time.time() - t0, 1)
        print(f"[bench:{mode}] {json.dumps(results[mode])}", flush=True)
    with open(os.path.join(OUT, "bench_all_modes.json"), "w") as fh:
        json.dump(results, fh, indent=1)
    return results


_SWEEP_SRC = r"""
import json, os, sys, time
import numpy as onp
import jax, jax.numpy as jnp
from mxnet_tpu.ops import pallas_kernels as pk
B, H, T, D = 8, 12, int(sys.argv[1]), 64
q = jnp.asarray(onp.random.RandomState(0).randn(B, H, T, D), jnp.bfloat16)
fn = jax.jit(lambda q, k, v: pk.flash_attention(q, k, v, causal=False))
out = fn(q, q, q); out.block_until_ready()
t0 = time.perf_counter()
for _ in range(20):
    out = fn(q, q, q)
out.block_until_ready()
dt = (time.perf_counter() - t0) / 20
print(json.dumps({"t": T, "bq": pk.flash_block_q(), "bk": pk.flash_block_k(),
                  "ms": round(dt * 1e3, 4)}))
"""


def stage_flash_sweep():
    grid_q = [128, 256, 512]
    grid_k = [128, 256, 512, 1024]
    seqs = [128, 512, 2048]  # BERT-pretrain, BERT-finetune, long-context
    rows = []
    for t in seqs:
        for bq in grid_q:
            for bk in grid_k:
                if bq > t or bk > t:
                    continue
                rc, js, tail = _run(
                    [PY, "-c", _SWEEP_SRC, str(t)],
                    env={"MXTPU_FLASH_BLOCK_Q": str(bq),
                         "MXTPU_FLASH_BLOCK_K": str(bk)},
                    timeout=600)
                rows.append(js or {"t": t, "bq": bq, "bk": bk,
                                   "error": tail[-300:]})
                print(f"[flash] {json.dumps(rows[-1])}", flush=True)
    best = {}
    for r in rows:
        cur = best.get(r.get("t"))
        if "ms" in r and (cur is None or r["ms"] < cur["ms"]):
            best[r["t"]] = r
    with open(os.path.join(OUT, "flash_block_sweep.json"), "w") as fh:
        json.dump({"rows": rows, "best_per_seqlen": best}, fh, indent=1)
    return best


_CONSISTENCY_SRC = r"""
import json, sys
sys.path.insert(0, "benchmark")
from opperf import op_specs
from mxnet_tpu.ops.registry import apply_op
from mxnet_tpu.test_utils import check_consistency
from mxnet_tpu.context import num_tpus
assert num_tpus() > 0, "no accelerator present; consistency sweep degenerate"
specs = op_specs(256)
ok, bad = [], []
for name in sorted(specs):
    args, attrs = specs[name]
    try:
        check_consistency(lambda xs: apply_op(name, *xs, **dict(attrs)),
                          args, rtol=2e-2, atol=2e-2)  # bf16-tolerant
        ok.append(name)
    except AssertionError as e:
        bad.append({"op": name, "err": str(e)[:400]})
    except Exception as e:
        bad.append({"op": name, "err": f"{type(e).__name__}: {e}"[:400]})
print(json.dumps({"checked": len(ok) + len(bad), "ok": len(ok),
                  "mismatches": bad}))
"""


def stage_consistency():
    rc, js, tail = _run([PY, "-c", _CONSISTENCY_SRC], timeout=1800)
    res = js or {"error": f"rc={rc}: {tail[-800:]}"}
    with open(os.path.join(OUT, "consistency_cpu_vs_tpu.json"), "w") as fh:
        json.dump(res, fh, indent=1)
    print(f"[consistency] {json.dumps(res)[:500]}", flush=True)
    return res


def stage_opperf():
    rc, js, tail = _run(
        [PY, "benchmark/opperf.py", "--out",
         os.path.join(OUT, "opperf_ondevice.json")], timeout=1800)
    print(f"[opperf] rc={rc} {tail[-200:]}", flush=True)
    return {"rc": rc}


_PROFILE_SRC = r"""
import json, os
import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel, profiler
net = gluon.nn.HybridSequential()
for _ in range(4):
    net.add(gluon.nn.Dense(1024, activation="relu"))
net.add(gluon.nn.Dense(10))
net.initialize()
learner = parallel.Learner(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                           mx.optimizer.SGD(learning_rate=0.1))
x = mx.np.random.uniform(size=(128, 1024))
y = mx.np.random.randint(0, 10, size=(128,)).astype("float32")
learner.step(x, y)  # compile outside the trace
profiler.start()
for _ in range(5):
    loss = learner.step(x, y)
float(loss.asnumpy())
profiler.stop()
out = os.path.join("benchmark", "tpu_evidence", "learner_step_profile.txt")
with open(out, "w") as fh:
    fh.write(profiler.dumps())
print(json.dumps({"profile": out, "ok": True}))
"""


def stage_profile():
    rc, js, tail = _run([PY, "-c", _PROFILE_SRC], timeout=900)
    res = js or {"error": f"rc={rc}: {tail[-500:]}"}
    print(f"[profile] {json.dumps(res)}", flush=True)
    return res


STAGES = {"bench": stage_bench, "flash": stage_flash_sweep,
          "consistency": stage_consistency, "opperf": stage_opperf,
          "profile": stage_profile}


def main():
    os.makedirs(OUT, exist_ok=True)
    wanted = sys.argv[1:] or list(STAGES)
    summary = {"started_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                            time.gmtime())}
    for name in wanted:
        t0 = time.time()
        try:
            summary[name] = {"ok": True, "result": STAGES[name]()}
        except Exception as e:  # noqa: BLE001 — stages are independent
            summary[name] = {"ok": False,
                             "error": f"{type(e).__name__}: {e}"[:800]}
        summary[name]["wall_s"] = round(time.time() - t0, 1)
    with open(os.path.join(OUT, "summary.json"), "w") as fh:
        json.dump(summary, fh, indent=1, default=str)
    print(json.dumps({k: v.get("ok") for k, v in summary.items()
                      if isinstance(v, dict)}))


if __name__ == "__main__":
    main()
