#!/usr/bin/env python
"""Run a test repeatedly to measure flakiness (reference:
tools/flakiness_checker.py).

    python tools/flakiness_checker.py tests/test_optimizer.py::test_x -n 20
"""
from __future__ import annotations

import argparse
import subprocess
import sys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("test", help="pytest node id")
    ap.add_argument("-n", "--trials", type=int, default=20)
    ap.add_argument("--stop-on-fail", action="store_true")
    args = ap.parse_args()

    failures = 0
    for trial in range(args.trials):
        res = subprocess.run(
            [sys.executable, "-m", "pytest", args.test, "-q", "-x"],
            capture_output=True, text=True)
        ok = res.returncode == 0
        print(f"trial {trial + 1}/{args.trials}: "
              f"{'PASS' if ok else 'FAIL'}", flush=True)
        if not ok:
            failures += 1
            tail = "\n".join(res.stdout.strip().splitlines()[-12:])
            print(tail, flush=True)
            if args.stop_on_fail:
                break
    print(f"\n{failures}/{trial + 1} trials failed "
          f"({100.0 * failures / (trial + 1):.1f}%)")
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
