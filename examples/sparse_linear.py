"""Sparse linear model on LibSVM data, trained on device in CSR form.

Reference flow: example/sparse/linear_classification (LibSVMIter feeding a
sparse dot) — here the CSR triple lives in HBM and every step is
``sparse.dot`` (gather × multiply → segment_sum on device); the feature
matrix is never densified.

Run:  python examples/sparse_linear.py [path.libsvm]
(with no path, a synthetic sparse binary-classification set is generated)
"""
import os
import sys
import tempfile

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    # a site hook may re-pin the platform config; honor the env override
    import jax

    jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, io, nd  # noqa: E402
from mxnet_tpu.ndarray.ndarray import NDArray  # noqa: E402
from mxnet_tpu.ndarray import sparse  # noqa: E402


def make_synthetic(path, n=512, d=100, density=0.05, seed=7):
    rng = onp.random.RandomState(seed)
    w_true = rng.randn(d)
    with open(path, "w") as f:
        for _ in range(n):
            nnz = max(1, int(d * density))
            cols = sorted(rng.choice(d, nnz, replace=False))
            vals = rng.randn(nnz)
            y = 1 if sum(w_true[c] * v for c, v in zip(cols, vals)) > 0 \
                else 0
            f.write(str(y) + " " +
                    " ".join(f"{c}:{v:.4f}" for c, v in zip(cols, vals)) +
                    "\n")
    return d


def main():
    if len(sys.argv) > 1:
        path, d = sys.argv[1], None
    else:
        path = os.path.join(tempfile.gettempdir(), "sparse_linear.libsvm")
        d = make_synthetic(path)

    it = io.LibSVMIter(path, data_shape=(d,), batch_size=64, sparse=True,
                       last_batch_handle="discard")
    w = NDArray(onp.zeros((d,), "float32"))
    b = NDArray(onp.zeros((), "float32"))
    w.attach_grad()
    b.attach_grad()
    lr = 1.0

    for epoch in range(25):
        it.reset()
        total, count = 0.0, 0
        for batch in it:
            x, y = batch.data[0], batch.label[0]  # x: device CSRNDArray
            with autograd.record():
                logit = sparse.dot(x, w) + b
                # logistic loss, numerically stable
                loss = nd.mean(nd.relu(logit) - logit * y +
                               nd.log1p(nd.exp(-nd.abs(logit))))
            loss.backward()
            w._set_data(w._data - lr * w.grad._data)
            b._set_data(b._data - lr * b.grad._data)
            w.grad._set_data(w.grad._data * 0)
            b.grad._set_data(b.grad._data * 0)
            total += float(loss.asnumpy())
            count += 1
        print(f"epoch {epoch}: loss {total / max(count, 1):.4f}")

    # train accuracy
    it.reset()
    hit = tot = 0
    for batch in it:
        x, y = batch.data[0], batch.label[0].asnumpy()
        p = (sparse.dot(x, w) + b).asnumpy() > 0
        hit += int((p == (y > 0.5)).sum())
        tot += len(y)
    print(f"train accuracy: {hit / tot:.3f}")
    return hit / tot


if __name__ == "__main__":
    acc = main()
    assert acc > 0.9, f"sparse linear model failed to fit: acc={acc}"
