#!/usr/bin/env python
"""Distributed data-parallel ResNet training (north-star config 5 shape).

Single process: Learner compiles fwd+bwd+update over the local mesh.
Multi process (tools/launch.py): each worker trains on its data shard and
grads allreduce through the dist_sync KVStore (Gloo on CPU, ICI/DCN on TPU).

    # single host / chip
    python examples/train_resnet_dist.py --depth 18 --epochs 2
    # 3-way data parallel without a cluster
    python tools/launch.py -n 3 python examples/train_resnet_dist.py --dist
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as onp  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--depth", type=int, default=18, choices=[18, 34, 50])
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--image-size", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--dist", action="store_true",
                    help="multi-worker via kvstore dist_sync")
    ap.add_argument("--samples", type=int, default=512)
    ap.add_argument("--platform", default=None, choices=["cpu", "tpu"],
                    help="force a JAX platform (site hooks may consume "
                         "JAX_PLATFORMS before this script runs)")
    args = ap.parse_args()

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, kvstore, metric
    from mxnet_tpu.gluon.model_zoo import vision

    kv = kvstore.create("dist_sync") if args.dist else None
    rank = kv.rank if kv else 0
    nworker = kv.num_workers if kv else 1

    mx.random.seed(42)  # identical init across workers
    net = vision.get_resnet(1, args.depth, classes=args.classes)
    net.initialize(mx.initializer.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9},
                            kvstore=kv)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    # synthetic class-separable shard: each worker sees its own slice
    rng = onp.random.RandomState(1234)
    n = args.samples
    labels = rng.randint(0, args.classes, n).astype("float32")
    images = (rng.rand(n, 3, args.image_size, args.image_size)
              .astype("float32") * 0.1)
    for c in range(args.classes):
        images[labels == c, c % 3] += 0.5 + 0.05 * c
    # equal shard sizes (floor) so every worker runs the SAME number of
    # steps — uneven shards would desynchronize the allreduce collectives
    per = n // nworker
    shard = slice(rank * per, (rank + 1) * per)
    images, labels = images[shard], labels[shard]

    acc = metric.Accuracy()
    for epoch in range(args.epochs):
        tic = time.time()
        acc.reset()
        perm = onp.random.permutation(len(images))
        for i in range(0, len(images) - args.batch_size + 1,
                       args.batch_size):
            idx = perm[i:i + args.batch_size]
            x = mx.np.array(images[idx])
            y = mx.np.array(labels[idx])
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(args.batch_size * nworker)
            acc.update(y, out)
        print(f"[worker {rank}] epoch {epoch}: "
              f"acc {acc.get()[1]:.3f} ({time.time() - tic:.1f}s)",
              flush=True)
    if kv:
        kv.barrier()
    print(f"[worker {rank}] done", flush=True)


if __name__ == "__main__":
    main()
