"""Spatial Transformer Network on MNIST (reference: example/... STN usage
of SpatialTransformer, src/operator/spatial_transformer.cc).

A localization head predicts an affine transform; `npx.spatial_transformer`
warps the input before a small classifier. On randomly translated digits
the STN learns to re-center them — train accuracy beats the same
classifier without the STN.

Run:  JAX_PLATFORMS=cpu python examples/stn_mnist.py
"""
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon, npx  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402


class STNClassifier(gluon.HybridBlock):
    def __init__(self, use_stn=True, size=24):
        super().__init__()
        self._use_stn = use_stn
        self._size = size
        if use_stn:
            # predict a DAMPED delta from the identity transform: the
            # classic STN stabilization (large early warps destroy the
            # training signal)
            self.loc = nn.HybridSequential()
            self.loc.add(nn.Dense(32, activation="relu",
                                  in_units=size * size),
                         nn.Dense(6, in_units=32,
                                  weight_initializer="zeros",
                                  bias_initializer="zeros"))
        self.cls = nn.HybridSequential()
        self.cls.add(nn.Dense(64, activation="relu", in_units=12 * 12),
                     nn.Dense(10, in_units=64))

    def forward(self, x):  # x: (B, 1, S, S)
        ident = mx.np.array([1, 0, 0, 0, 1, 0], dtype="float32")
        if self._use_stn:
            delta = self.loc(x.reshape(x.shape[0], -1))
            theta = ident.reshape(1, 6) + 0.3 * delta
        else:
            # fixed identity warp: whole image downsampled to 12x12 — the
            # honest no-localization baseline through the same sampler
            theta = mx.np.broadcast_to(ident.reshape(1, 6),
                                       (x.shape[0], 6))
        x = npx.spatial_transformer(x, theta, target_shape=(12, 12))
        return self.cls(x.reshape(x.shape[0], -1))


def make_translated_digits(n, size=24, seed=0):
    """Synthetic 'digits': 10 distinct 8x8 glyph patterns pasted at random
    offsets in a size×size canvas (keeps the example network-free)."""
    rng = onp.random.RandomState(seed)
    glyphs = rng.uniform(0.5, 1.0, (10, 8, 8)).astype("float32")
    glyphs *= rng.uniform(0, 1, (10, 8, 8)) > 0.4
    xs = onp.zeros((n, 1, size, size), "float32")
    ys = rng.randint(0, 10, n)
    for i, y in enumerate(ys):
        ox, oy = rng.randint(0, size - 8, 2)
        xs[i, 0, oy:oy + 8, ox:ox + 8] = glyphs[y]
    return xs, ys.astype("float32")


def train(use_stn, xs, ys, epochs=40):
    net = STNClassifier(use_stn)
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    n, bs = len(ys), 64
    for _ in range(epochs):
        for i in range(0, n, bs):
            xb = mx.np.array(xs[i:i + bs])
            yb = mx.np.array(ys[i:i + bs])
            with autograd.record():
                loss = loss_fn(net(xb), yb)
            loss.backward()
            trainer.step(xb.shape[0])
    pred = net(mx.np.array(xs)).asnumpy().argmax(-1)
    return float((pred == ys).mean())


def main():
    xs, ys = make_translated_digits(512)
    acc_stn = train(True, xs, ys)
    acc_crop = train(False, xs, ys)
    print(f"with STN:    train acc {acc_stn:.3f}")
    print(f"fixed warp:  train acc {acc_crop:.3f}")
    return acc_stn, acc_crop


if __name__ == "__main__":
    a, b = main()
    assert a > 0.9 and a > b + 0.05, (a, b)
