#!/usr/bin/env python
"""BERT-Base masked-LM pretraining step demo (north-star config 4).

Synthetic token streams (zero-egress); shows both the script-parity path
(Trainer + autograd) and the SPMD path (parallel.Learner, one compiled
fwd+bwd+update program, grads allreduced on ICI when a mesh is present)."""
from __future__ import annotations

import argparse
import time

import numpy as onp

import os as _os

if _os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    # a site hook may re-pin the platform config; honor the env override
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import amp, autograd, gluon, np, parallel
from mxnet_tpu.gluon.model_zoo.bert import BERTForPretraining, bert_base


def synth_batch(rng, batch, seq, vocab):
    tokens = rng.randint(0, vocab, (batch, seq)).astype("int32")
    mlm_labels = rng.randint(0, vocab, (batch, seq)).astype("float32")
    nsp = rng.randint(0, 2, (batch, 1)).astype("float32")
    return (np.array(tokens),
            np.concatenate([np.array(mlm_labels), np.array(nsp)], axis=1))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=30522)
    ap.add_argument("--mode", choices=["learner", "trainer"],
                    default="learner")
    args = ap.parse_args()

    amp.init("bfloat16")
    bert = bert_base(vocab_size=args.vocab, max_length=args.seq_len)
    model = BERTForPretraining(bert, vocab_size=args.vocab)
    model.initialize(mx.initializer.Normal(0.02))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rng = onp.random.RandomState(0)

    if args.mode == "learner":
        def pretrain_loss(pair, labels):
            mlm_scores, nsp_scores = pair
            return loss_fn(mlm_scores, labels[:, :-1]).mean() + \
                loss_fn(nsp_scores, labels[:, -1]).mean()

        learner = parallel.Learner(
            model, pretrain_loss,
            mx.optimizer.AdamW(learning_rate=1e-4, wd=0.01))
        tokens, labels = synth_batch(rng, args.batch_size, args.seq_len,
                                     args.vocab)
        learner.step(tokens, labels).wait_to_read()  # compile
        tic = time.time()
        for step in range(args.steps):
            loss = learner.step(tokens, labels)
        v = float(loss)
        dt = time.time() - tic
    else:
        trainer = gluon.Trainer(model.collect_params(), "adamw",
                                {"learning_rate": 1e-4, "wd": 0.01})
        tokens, labels = synth_batch(rng, args.batch_size, args.seq_len,
                                     args.vocab)
        tic = time.time()
        for step in range(args.steps):
            with autograd.record():
                mlm, nsp = model(tokens)
                loss = loss_fn(mlm, labels[:, :-1]).mean() + \
                    loss_fn(nsp, labels[:, -1]).mean()
            loss.backward()
            trainer.step(args.batch_size)
        v = float(loss)
        dt = time.time() - tic

    tok_s = args.steps * args.batch_size * args.seq_len / dt
    print(f"{args.mode}: final loss {v:.3f}, {tok_s:.0f} tokens/s")


if __name__ == "__main__":
    main()
