#!/usr/bin/env python
"""LSTM word language model (north-star config 3; reference:
example/rnn/word_lm). Uses a local text file if given, else a synthetic
character stream, so it runs in zero-egress environments."""
from __future__ import annotations

import argparse
import time

import numpy as onp

import os as _os

if _os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    # a site hook may re-pin the platform config; honor the env override
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, np
from mxnet_tpu.gluon.model_zoo.rnn_lm import RNNModel


def load_corpus(path=None, length=100000, vocab=64):
    if path:
        with open(path, "rb") as f:
            raw = f.read()
        chars = sorted(set(raw))
        table = {c: i for i, c in enumerate(chars)}
        data = onp.array([table[c] for c in raw], dtype="int32")
        return data, len(chars)
    rng = onp.random.RandomState(0)
    # synthetic markov-ish stream: next token depends on previous
    data = onp.zeros(length, dtype="int32")
    for i in range(1, length):
        data[i] = (data[i - 1] * 31 + rng.randint(0, 7)) % vocab
    return data, vocab


def batchify(data, batch_size):
    nb = len(data) // batch_size
    return data[:nb * batch_size].reshape(batch_size, nb)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None, help="optional corpus file")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--bptt", type=int, default=35)
    ap.add_argument("--hidden", type=int, default=200)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--export-onnx", default=None, metavar="PATH",
                    help="after training, export the LM to this .onnx file "
                         "(fused LSTM -> ONNX LSTM nodes) and verify the "
                         "re-import numerically")
    args = ap.parse_args()

    corpus, vocab = load_corpus(args.data)
    stream = batchify(corpus, args.batch_size)

    model = RNNModel(vocab_size=vocab, embed_size=args.hidden,
                     hidden_size=args.hidden, num_layers=args.layers,
                     dropout=0.2, tie_weights=True)
    model.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "adam",
                            {"learning_rate": args.lr, "clip_gradient": 0.25})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        tic = time.time()
        states = model.begin_state(args.batch_size)
        total, count = 0.0, 0
        for i in range(0, stream.shape[1] - 1 - args.bptt, args.bptt):
            data = np.array(stream[:, i:i + args.bptt])
            target = np.array(stream[:, i + 1:i + 1 + args.bptt])
            states = [s.detach() for s in states]
            with autograd.record():
                logits, states = model(data, states)
                loss = loss_fn(logits, target).mean()
            loss.backward()
            trainer.step(1)
            total += float(loss)
            count += 1
        ppl = onp.exp(total / count)
        print(f"Epoch {epoch}: loss {total / count:.3f} ppl {ppl:.2f} "
              f"({time.time() - tic:.1f}s)")

    if args.export_onnx:
        from mxnet_tpu.contrib import onnx as mxonnx

        # stateless forward (states=None) is the inference entry point
        path = mxonnx.export_model(model, input_shape=(1, args.bptt),
                                   input_type="int32",
                                   onnx_file_path=args.export_onnx)
        blk = mxonnx.import_to_gluon(path)
        probe = np.array(onp.array(stream[:1, :args.bptt], "int32"))
        with autograd.predict_mode():
            want = model(probe).asnumpy()
        got = blk(probe).asnumpy()
        err = float(onp.abs(got - want).max())
        print(f"ONNX export -> {path}; re-import max |diff| = {err:.2e}")


if __name__ == "__main__":
    main()
