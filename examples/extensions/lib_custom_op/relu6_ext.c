/* Worked native extension (reference: example/extensions/lib_custom_op):
 * implements relu6 and hardswish as host float32 kernels behind the
 * versioned mxtpu extensions ABI.
 *
 * Build:  gcc -shared -fPIC -O2 -I include -o librelu6_ext.so \
 *             examples/extensions/lib_custom_op/relu6_ext.c
 * Load:   mx.library.load("librelu6_ext.so")
 */
#include "mxtpu/lib_api.h"

int mxtpu_ext_abi_version(void) { return MXTPU_EXT_ABI_VERSION; }

int mxtpu_ext_init(void) { return 0; }

int mxtpu_ext_num_ops(void) { return 2; }

const char* mxtpu_ext_op_name(int op_idx) {
  switch (op_idx) {
    case 0: return "ext_relu6";
    case 1: return "ext_hardswish";
    default: return 0;
  }
}

int mxtpu_ext_op_compute(int op_idx, const float* in, float* out,
                         int64_t n) {
  int64_t i;
  switch (op_idx) {
    case 0:
      for (i = 0; i < n; ++i) {
        float v = in[i];
        out[i] = v < 0.f ? 0.f : (v > 6.f ? 6.f : v);
      }
      return 0;
    case 1:
      for (i = 0; i < n; ++i) {
        float v = in[i];
        float r = v + 3.f;
        r = r < 0.f ? 0.f : (r > 6.f ? 6.f : r);
        out[i] = v * r / 6.f;
      }
      return 0;
    default:
      return 1;
  }
}
