#!/usr/bin/env python
"""Gluon MLP on MNIST (north-star config 1; reference:
example/gluon/mnist/mnist.py — unmodified script shape)."""
from __future__ import annotations

import argparse
import time

import os as _os

if _os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    # a site hook may re-pin the platform config; honor the env override
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, metric, np
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.data import DataLoader
from mxnet_tpu.gluon.data.vision import MNIST


def build_net():
    net = nn.HybridSequential()
    net.add(nn.Dense(128, activation="relu"),
            nn.Dense(64, activation="relu"),
            nn.Dense(10))
    return net


def transform(sample):
    img, label = sample
    return img.astype("float32") / 255.0, label


def evaluate(net, loader):
    acc = metric.Accuracy()
    for data, label in loader:
        out = net(data.reshape((data.shape[0], -1)))
        acc.update(label, out)
    return acc.get()[1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--no-hybridize", action="store_true")
    args = ap.parse_args()

    train_loader = DataLoader(MNIST(train=True).transform(transform),
                              batch_size=args.batch_size, shuffle=True,
                              num_workers=2)
    val_loader = DataLoader(MNIST(train=False).transform(transform),
                            batch_size=args.batch_size)

    net = build_net()
    net.initialize(mx.initializer.Xavier(), ctx=mx.tpu()
                   if mx.num_tpus() else mx.cpu())
    if not args.no_hybridize:
        net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        tic = time.time()
        train_loss = 0.0
        nbatch = 0
        for data, label in train_loader:
            data = data.reshape((data.shape[0], -1))
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(data.shape[0])
            train_loss += float(loss.mean())
            nbatch += 1
        acc = evaluate(net, val_loader)
        print(f"Epoch {epoch}: loss {train_loss / nbatch:.4f} "
              f"val acc {acc:.4f} ({time.time() - tic:.1f}s)")
    net.save_parameters("mnist_mlp.params.npz")


if __name__ == "__main__":
    main()
