// Native RecordIO engine: storage format + indexed reads + threaded
// prefetching batch reader.
//
// TPU-native equivalent of the reference's native IO pipeline
// (reference: src/io/iter_image_recordio_2.cc ImageRecordIOParser2,
// dmlc-core recordio streams, src/io/iter_prefetcher.h). The reference fused
// IO + JPEG decode + augmentation in C++ (OpenMP + libturbojpeg); here the
// native layer owns what the host CPU is actually bound by on a TPU VM —
// file IO, record framing, index management and double-buffered prefetch —
// while decode/augment run in Python workers (PIL/numpy release the GIL).
//
// Binary format (dmlc recordio compatible): each record is
//   u32 magic (0xced7230a) | u32 lrec | payload | pad to 4B
// where lrec = (cflag << 29) | length. cflag != 0 marks split records for
// >512MB payloads; this implementation writes cflag=0 and rejects splits on
// read (framework records are images / serialized tensors, far below 512MB).
//
// C ABI (used from Python via ctypes — no pybind dependency):
//   writer:   rio_writer_open / rio_writer_write / rio_writer_close
//   reader:   rio_reader_open / rio_reader_count / rio_reader_get /
//             rio_reader_free
//   prefetch: rio_prefetch_create / rio_prefetch_next / rio_prefetch_free

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

static const uint32_t kMagic = 0xced7230a;

extern "C" {

// ---------------------------------------------------------------------------
// writer
// ---------------------------------------------------------------------------
struct RioWriter {
  FILE* f;
};

void* rio_writer_open(const char* path, int append) {
  FILE* f = fopen(path, append ? "ab" : "wb");
  if (!f) return nullptr;
  return new RioWriter{f};
}

int rio_writer_write(void* handle, const char* data, uint64_t len) {
  auto* w = static_cast<RioWriter*>(handle);
  if (!w || !w->f) return -1;
  if (len >= (1u << 29)) return -2;  // single-part records only
  uint32_t lrec = static_cast<uint32_t>(len);
  if (fwrite(&kMagic, 4, 1, w->f) != 1) return -1;
  if (fwrite(&lrec, 4, 1, w->f) != 1) return -1;
  if (len && fwrite(data, 1, len, w->f) != len) return -1;
  static const char zeros[4] = {0, 0, 0, 0};
  size_t pad = (4 - (len & 3)) & 3;
  if (pad && fwrite(zeros, 1, pad, w->f) != pad) return -1;
  return 0;
}

void rio_writer_close(void* handle) {
  auto* w = static_cast<RioWriter*>(handle);
  if (w) {
    if (w->f) fclose(w->f);
    delete w;
  }
}

// ---------------------------------------------------------------------------
// indexed reader
// ---------------------------------------------------------------------------
struct RioReader {
  FILE* f;
  std::vector<uint64_t> offsets;  // payload offsets
  std::vector<uint32_t> sizes;
  std::vector<char> buf;          // per-handle read buffer
  std::mutex mu;
};

void* rio_reader_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  auto* r = new RioReader{f, {}, {}, {}, {}};
  // build the index in one sequential scan
  uint64_t pos = 0;
  for (;;) {
    uint32_t header[2];
    if (fread(header, 4, 2, f) != 2) break;
    if (header[0] != kMagic) {  // corrupt or trailing garbage
      break;
    }
    uint32_t cflag = header[1] >> 29;
    uint32_t len = header[1] & ((1u << 29) - 1);
    if (cflag != 0) {  // multi-part records unsupported
      fclose(f);
      delete r;
      return nullptr;
    }
    pos += 8;
    r->offsets.push_back(pos);
    r->sizes.push_back(len);
    uint64_t skip = (len + 3u) & ~3ull;
    if (fseek(f, static_cast<long>(skip), SEEK_CUR) != 0) break;
    pos += skip;
  }
  return r;
}

uint64_t rio_reader_count(void* handle) {
  auto* r = static_cast<RioReader*>(handle);
  return r ? r->offsets.size() : 0;
}

uint32_t rio_reader_size(void* handle, uint64_t idx) {
  auto* r = static_cast<RioReader*>(handle);
  if (!r || idx >= r->sizes.size()) return 0;
  return r->sizes[idx];
}

// byte offset of the record START (the magic word) — the value stock .idx
// sidecar files store, enabling interchange with externally built shards
uint64_t rio_reader_offset(void* handle, uint64_t idx) {
  auto* r = static_cast<RioReader*>(handle);
  if (!r || idx >= r->offsets.size()) return ~0ull;
  return r->offsets[idx] - 8;
}

// copies record idx into out (caller allocates rio_reader_size bytes)
int rio_reader_get(void* handle, uint64_t idx, char* out) {
  auto* r = static_cast<RioReader*>(handle);
  if (!r || idx >= r->offsets.size()) return -1;
  std::lock_guard<std::mutex> lock(r->mu);
  if (fseek(r->f, static_cast<long>(r->offsets[idx]), SEEK_SET) != 0)
    return -1;
  if (fread(out, 1, r->sizes[idx], r->f) != r->sizes[idx]) return -1;
  return 0;
}

void rio_reader_free(void* handle) {
  auto* r = static_cast<RioReader*>(handle);
  if (r) {
    if (r->f) fclose(r->f);
    delete r;
  }
}

// ---------------------------------------------------------------------------
// threaded prefetching batch reader (double buffering)
// ---------------------------------------------------------------------------
// Reads batches of records ahead of the consumer on a worker thread —
// the native analog of the reference's iter_prefetcher.h. Records of one
// batch are packed back-to-back into a single buffer with an offsets table,
// so Python receives one contiguous blob per batch (one ctypes copy).

struct Batch {
  std::vector<char> data;
  std::vector<uint64_t> offsets;  // n+1 entries
};

struct RioPrefetch {
  RioReader* reader;
  std::vector<uint64_t> order;
  uint64_t batch_size;
  uint64_t next_batch;   // producer position
  uint64_t num_batches;
  static const int kDepth = 4;
  Batch ring[kDepth];
  std::atomic<int> ready[kDepth];
  uint64_t consumer;
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv_prod, cv_cons;
  std::atomic<bool> stop;
};

static void prefetch_loop(RioPrefetch* p) {
  for (uint64_t b = 0; b < p->num_batches && !p->stop.load(); ++b) {
    int slot = static_cast<int>(b % RioPrefetch::kDepth);
    {
      std::unique_lock<std::mutex> lock(p->mu);
      p->cv_prod.wait(lock, [&] {
        return p->stop.load() || p->ready[slot].load() == 0;
      });
    }
    if (p->stop.load()) return;
    Batch& batch = p->ring[slot];
    batch.data.clear();
    batch.offsets.clear();
    batch.offsets.push_back(0);
    uint64_t start = b * p->batch_size;
    uint64_t end = start + p->batch_size;
    if (end > p->order.size()) end = p->order.size();
    for (uint64_t i = start; i < end; ++i) {
      uint64_t idx = p->order[i];
      uint32_t sz = p->reader->sizes[idx];
      size_t old = batch.data.size();
      batch.data.resize(old + sz);
      rio_reader_get(p->reader, idx, batch.data.data() + old);
      batch.offsets.push_back(batch.data.size());
    }
    {
      std::lock_guard<std::mutex> lock(p->mu);
      p->ready[slot].store(1);
    }
    p->cv_cons.notify_one();
  }
}

void* rio_prefetch_create(void* reader_handle, const uint64_t* order,
                          uint64_t n, uint64_t batch_size) {
  auto* r = static_cast<RioReader*>(reader_handle);
  if (!r || batch_size == 0) return nullptr;
  auto* p = new RioPrefetch();
  p->reader = r;
  p->order.assign(order, order + n);
  p->batch_size = batch_size;
  p->next_batch = 0;
  p->num_batches = (n + batch_size - 1) / batch_size;
  for (int i = 0; i < RioPrefetch::kDepth; ++i) p->ready[i].store(0);
  p->consumer = 0;
  p->stop.store(false);
  p->worker = std::thread(prefetch_loop, p);
  return p;
}

// Blocks until the next batch is ready. Returns number of records in the
// batch (0 = end of data). Caller then copies via rio_prefetch_data.
int64_t rio_prefetch_next(void* handle, const char** data,
                          const uint64_t** offsets, uint64_t* nbytes) {
  auto* p = static_cast<RioPrefetch*>(handle);
  if (!p || p->consumer >= p->num_batches) return 0;
  int slot = static_cast<int>(p->consumer % RioPrefetch::kDepth);
  {
    std::unique_lock<std::mutex> lock(p->mu);
    p->cv_cons.wait(lock, [&] {
      return p->stop.load() || p->ready[slot].load() == 1;
    });
  }
  if (p->stop.load()) return 0;
  Batch& batch = p->ring[slot];
  *data = batch.data.data();
  *offsets = batch.offsets.data();
  *nbytes = batch.data.size();
  return static_cast<int64_t>(batch.offsets.size() - 1);
}

// Releases the batch returned by the last rio_prefetch_next call.
void rio_prefetch_release(void* handle) {
  auto* p = static_cast<RioPrefetch*>(handle);
  if (!p || p->consumer >= p->num_batches) return;
  int slot = static_cast<int>(p->consumer % RioPrefetch::kDepth);
  {
    std::lock_guard<std::mutex> lock(p->mu);
    p->ready[slot].store(0);
    p->consumer++;
  }
  p->cv_prod.notify_one();
}

void rio_prefetch_free(void* handle) {
  auto* p = static_cast<RioPrefetch*>(handle);
  if (!p) return;
  p->stop.store(true);
  p->cv_prod.notify_all();
  p->cv_cons.notify_all();
  if (p->worker.joinable()) p->worker.join();
  delete p;
}

}  // extern "C"
