// Native threaded text parsers: CSV (dense) and LibSVM (CSR).
//
// TPU-native analog of the reference's data-parsing path
// (src/io/iter_csv.cc, src/io/iter_libsvm.cc over dmlc-core's
// threaded_parser): the file is split at line boundaries into one chunk
// per hardware thread, each chunk is tokenized with a hand-rolled float
// scanner (no locale, no strtod overhead on the fast path), and results
// are stitched in order. The Python side (mxnet_tpu/io) calls through
// ctypes and keeps batches on host until the device step needs them —
// one H2D per batch, never per sample.
//
// C ABI:
//   tp_csv_parse(path, delim, &rows, &cols) -> float*  (row-major), or
//     nullptr on error; caller frees with tp_free.
//   tp_libsvm_parse(path, &nrows, &nnz, &indptr, &indices, &values,
//     &labels) -> 0 on success; arrays freed with tp_free / tp_free_i64.
//   tp_free / tp_free_i64: release buffers returned above.

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// fast float scanner: [+-]?digits[.digits][eE[+-]digits] | nan | inf(inity)
inline const char* scan_float(const char* p, const char* end, float* out) {
  while (p < end && (*p == ' ' || *p == '\t')) ++p;
  if (p >= end) return nullptr;
  bool neg = false;
  if (*p == '-' || *p == '+') { neg = (*p == '-'); ++p; }
  if (p + 2 < end && (*p == 'n' || *p == 'N') &&
      (p[1] == 'a' || p[1] == 'A') && (p[2] == 'n' || p[2] == 'N')) {
    *out = std::nanf("");
    return p + 3;
  }
  if (p + 2 < end && (*p == 'i' || *p == 'I') &&
      (p[1] == 'n' || p[1] == 'N') && (p[2] == 'f' || p[2] == 'F')) {
    p += 3;
    // optional "inity" suffix
    const char* suffix = "inity";
    for (int k = 0; k < 5 && p < end; ++k) {
      char c = *p | 0x20;
      if (c != suffix[k]) break;
      ++p;
    }
    *out = neg ? -HUGE_VALF : HUGE_VALF;
    return p;
  }
  double v = 0.0;
  bool any = false;
  while (p < end && *p >= '0' && *p <= '9') {
    v = v * 10.0 + (*p - '0'); ++p; any = true;
  }
  if (p < end && *p == '.') {
    ++p;
    double scale = 0.1;
    while (p < end && *p >= '0' && *p <= '9') {
      v += (*p - '0') * scale; scale *= 0.1; ++p; any = true;
    }
  }
  if (!any) return nullptr;
  if (p < end && (*p == 'e' || *p == 'E')) {
    ++p;
    bool eneg = false;
    if (p < end && (*p == '-' || *p == '+')) { eneg = (*p == '-'); ++p; }
    int ex = 0;
    while (p < end && *p >= '0' && *p <= '9') { ex = ex * 10 + (*p - '0'); ++p; }
    double f = 1.0;
    while (ex--) f *= 10.0;
    v = eneg ? v / f : v * f;
  }
  *out = static_cast<float>(neg ? -v : v);
  return p;
}

struct FileBuf {
  char* data = nullptr;
  size_t size = 0;
  bool ok = false;
  explicit FileBuf(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return;
    std::fseek(f, 0, SEEK_END);
    long n = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    if (n < 0) { std::fclose(f); return; }
    data = static_cast<char*>(std::malloc(n + 1));
    size = static_cast<size_t>(n);
    ok = data && std::fread(data, 1, size, f) == size;
    std::fclose(f);
    if (data) data[size] = '\n';
  }
  ~FileBuf() { std::free(data); }
};

// split [0, size) into per-thread chunks ending on newline boundaries
std::vector<std::pair<size_t, size_t>> chunks_of(const char* data,
                                                 size_t size, int nthread) {
  std::vector<std::pair<size_t, size_t>> out;
  size_t begin = 0;
  for (int t = 0; t < nthread && begin < size; ++t) {
    size_t end = (t == nthread - 1) ? size
                                    : begin + (size - begin) / (nthread - t);
    while (end < size && data[end] != '\n') ++end;
    if (end < size) ++end;  // include the newline
    out.emplace_back(begin, end);
    begin = end;
  }
  return out;
}

}  // namespace

extern "C" {

float* tp_csv_parse(const char* path, char delim, int64_t* rows,
                    int64_t* cols) {
  FileBuf fb(path);
  if (!fb.ok) return nullptr;
  int nthread = std::max(1u, std::thread::hardware_concurrency());
  auto parts = chunks_of(fb.data, fb.size, nthread);

  // pass 1 (first line): column count
  int64_t ncol = 0;
  {
    const char* p = fb.data;
    const char* end = fb.data + fb.size;
    const char* nl = static_cast<const char*>(
        memchr(p, '\n', fb.size));
    if (!nl) nl = end;
    float v;
    while (p < nl) {
      const char* q = scan_float(p, nl, &v);
      if (!q) break;
      ++ncol;
      p = q;
      while (p < nl && *p != delim) ++p;
      if (p < nl) ++p;
    }
  }
  if (ncol == 0) return nullptr;

  // per-chunk parse into private vectors, then stitch. Malformed input
  // (unparsable token, ragged row) fails the WHOLE parse — the caller
  // falls back to the strict numpy path, matching its error behavior
  // instead of silently zero-filling.
  std::vector<std::vector<float>> results(parts.size());
  std::vector<std::thread> pool;
  std::vector<char> errs(parts.size(), 0);
  for (size_t t = 0; t < parts.size(); ++t) {
    pool.emplace_back([&, t]() {
      const char* p = fb.data + parts[t].first;
      const char* end = fb.data + parts[t].second;
      auto& out = results[t];
      out.reserve((parts[t].second - parts[t].first) / 4);
      while (p < end) {
        const char* nl = static_cast<const char*>(
            memchr(p, '\n', end - p));
        if (!nl) nl = end;
        if (nl > p && !(nl == p + 1 && *p == '\r')) {  // skip empty lines
          float v;
          const char* q = p;
          for (int64_t c = 0; c < ncol; ++c) {
            const char* r = scan_float(q, nl, &v);
            if (!r) { errs[t] = 1; return; }
            out.push_back(v);
            q = r;
            while (q < nl && *q != delim && *q != '\r') ++q;
            if (q < nl && *q == delim) ++q;
          }
          // a row with MORE fields than the header row is ragged too
          while (q < nl && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
          if (q < nl) { errs[t] = 1; return; }
        }
        p = nl + 1;
      }
    });
  }
  for (auto& th : pool) th.join();
  for (char e : errs)
    if (e) return nullptr;

  size_t total = 0;
  for (auto& r : results) total += r.size();
  float* out = static_cast<float*>(std::malloc(total * sizeof(float)));
  if (!out) return nullptr;
  size_t off = 0;
  for (auto& r : results) {
    std::memcpy(out + off, r.data(), r.size() * sizeof(float));
    off += r.size();
  }
  *rows = static_cast<int64_t>(total / ncol);
  *cols = ncol;
  return out;
}

// LibSVM: "label idx:val idx:val ...\n" -> CSR (indptr, indices, values)
int tp_libsvm_parse(const char* path, int64_t* nrows, int64_t* nnz,
                    int64_t** indptr, int64_t** indices, float** values,
                    float** labels) {
  FileBuf fb(path);
  if (!fb.ok) return -1;
  std::vector<int64_t> ip{0};
  std::vector<int64_t> ix;
  std::vector<float> vals;
  std::vector<float> labs;
  const char* p = fb.data;
  const char* end = fb.data + fb.size;
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    if (!nl) nl = end;
    if (nl > p) {
      float lab;
      const char* q = scan_float(p, nl, &lab);
      if (q) {
        labs.push_back(lab);
        while (q < nl) {
          while (q < nl && *q == ' ') ++q;
          // integer index scan — float would round indices >= 2^24
          const char* r = q;
          int64_t idx = 0;
          bool any_digit = false;
          while (r < nl && *r >= '0' && *r <= '9') {
            idx = idx * 10 + (*r - '0'); ++r; any_digit = true;
          }
          if (!any_digit || r >= nl || *r != ':') break;
          float v;
          const char* s = scan_float(r + 1, nl, &v);
          if (!s) break;
          ix.push_back(idx);
          vals.push_back(v);
          q = s;
        }
        ip.push_back(static_cast<int64_t>(ix.size()));
      }
    }
    p = nl + 1;
  }
  *nrows = static_cast<int64_t>(labs.size());
  *nnz = static_cast<int64_t>(ix.size());
  *indptr = static_cast<int64_t*>(std::malloc(ip.size() * sizeof(int64_t)));
  *indices = static_cast<int64_t*>(std::malloc(
      std::max<size_t>(1, ix.size()) * sizeof(int64_t)));
  *values = static_cast<float*>(std::malloc(
      std::max<size_t>(1, vals.size()) * sizeof(float)));
  *labels = static_cast<float*>(std::malloc(
      std::max<size_t>(1, labs.size()) * sizeof(float)));
  if (!*indptr || !*indices || !*values || !*labels) return -1;
  std::memcpy(*indptr, ip.data(), ip.size() * sizeof(int64_t));
  std::memcpy(*indices, ix.data(), ix.size() * sizeof(int64_t));
  std::memcpy(*values, vals.data(), vals.size() * sizeof(float));
  std::memcpy(*labels, labs.data(), labs.size() * sizeof(float));
  return 0;
}

void tp_free(float* p) { std::free(p); }
void tp_free_i64(int64_t* p) { std::free(p); }

}  // extern "C"
