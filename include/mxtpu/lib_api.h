/* mxnet_tpu extensions ABI — versioned C contract for out-of-tree native
 * libraries (reference: include/mxnet/lib_api.h, MX_LIBRARY_VERSION +
 * MXLoadLib c_api.cc:1522).
 *
 * An extension shared object exports, with C linkage:
 *
 *   int mxtpu_ext_abi_version(void);
 *       Must return MXTPU_EXT_ABI_VERSION this header was compiled
 *       against. The loader refuses mismatched majors (version / 100).
 *
 *   int mxtpu_ext_num_ops(void);
 *   const char* mxtpu_ext_op_name(int op_idx);
 *       Enumerate the operators this library provides.
 *
 *   int mxtpu_ext_op_compute(int op_idx,
 *                            const float* in, float* out, int64_t n);
 *       v1 compute contract: elementwise float32, `n` elements in both
 *       buffers, returns 0 on success / nonzero error code. The python
 *       loader wraps this as a host-resident op (jit=False) — the TPU
 *       compute path belongs to Pallas/XLA; native extensions cover
 *       host-side kernels (custom decoders, samplers, metrics).
 *
 *   (optional) int mxtpu_ext_init(void);
 *       Called once after load; nonzero aborts the load.
 */
#ifndef MXTPU_LIB_API_H_
#define MXTPU_LIB_API_H_

#include <stdint.h>

/* major*100 + minor: minor bumps are backward compatible */
#define MXTPU_EXT_ABI_VERSION 100

#ifdef __cplusplus
extern "C" {
#endif

int mxtpu_ext_abi_version(void);
int mxtpu_ext_num_ops(void);
const char* mxtpu_ext_op_name(int op_idx);
int mxtpu_ext_op_compute(int op_idx, const float* in, float* out,
                         int64_t n);
int mxtpu_ext_init(void);

#ifdef __cplusplus
}
#endif

#endif  /* MXTPU_LIB_API_H_ */
