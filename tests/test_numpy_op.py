"""Op-vs-NumPy oracle (reference pattern: tests/python/unittest/
test_numpy_op.py — every op checked against the NumPy reference)."""
import numpy as onp
import pytest

from mxnet_tpu import np
from mxnet_tpu.test_utils import assert_almost_equal

UNARY_CASES = [
    ("abs", onp.abs, (-2, 2)), ("exp", onp.exp, (-2, 2)),
    ("log", onp.log, (0.1, 3)), ("sqrt", onp.sqrt, (0.1, 3)),
    ("square", onp.square, (-2, 2)), ("sin", onp.sin, (-3, 3)),
    ("cos", onp.cos, (-3, 3)), ("tanh", onp.tanh, (-2, 2)),
    ("floor", onp.floor, (-3, 3)), ("ceil", onp.ceil, (-3, 3)),
    ("sign", onp.sign, (-2, 2)), ("log1p", onp.log1p, (0, 2)),
    ("expm1", onp.expm1, (-1, 1)), ("arctan", onp.arctan, (-2, 2)),
    ("sinh", onp.sinh, (-2, 2)), ("cosh", onp.cosh, (-2, 2)),
    ("arcsin", onp.arcsin, (-0.9, 0.9)), ("cbrt", onp.cbrt, (-2, 2)),
    ("reciprocal", onp.reciprocal, (0.5, 2)),
]


@pytest.mark.parametrize("name,ref,rng", UNARY_CASES,
                         ids=[c[0] for c in UNARY_CASES])
def test_unary_vs_numpy(name, ref, rng):
    x = onp.random.uniform(rng[0], rng[1], size=(3, 4)).astype("float32")
    got = getattr(np, name)(np.array(x))
    assert_almost_equal(got, ref(x).astype("float32"), rtol=1e-4, atol=1e-5)


BINARY_CASES = ["add", "subtract", "multiply", "true_divide", "power",
                "maximum", "minimum", "arctan2", "hypot", "logaddexp"]


@pytest.mark.parametrize("name", BINARY_CASES)
def test_binary_vs_numpy(name):
    a = onp.random.uniform(0.5, 2, size=(3, 4)).astype("float32")
    b = onp.random.uniform(0.5, 2, size=(4,)).astype("float32")
    got = getattr(np, name)(np.array(a), np.array(b))
    ref = getattr(onp, name)(a, b)
    assert_almost_equal(got, ref.astype("float32"), rtol=1e-4, atol=1e-5)


REDUCE_CASES = ["sum", "mean", "max", "min", "prod", "std", "var"]


@pytest.mark.parametrize("name", REDUCE_CASES)
@pytest.mark.parametrize("axis", [None, 0, 1, (0, 1)])
def test_reduce_vs_numpy(name, axis):
    x = onp.random.uniform(0.5, 1.5, size=(3, 4)).astype("float32")
    got = getattr(np, name)(np.array(x), axis=axis)
    ref = getattr(onp, name)(x, axis=axis)
    assert_almost_equal(got, onp.asarray(ref, dtype="float32"),
                        rtol=1e-4, atol=1e-5)


def test_matmul_dot_einsum():
    a = onp.random.randn(3, 4).astype("float32")
    b = onp.random.randn(4, 5).astype("float32")
    assert_almost_equal(np.matmul(np.array(a), np.array(b)), a @ b,
                        rtol=1e-4, atol=1e-4)
    assert_almost_equal(np.dot(np.array(a), np.array(b)), a.dot(b),
                        rtol=1e-4, atol=1e-4)
    got = np.einsum("ij,jk->ik", np.array(a), np.array(b))
    assert_almost_equal(got, onp.einsum("ij,jk->ik", a, b), rtol=1e-4,
                        atol=1e-4)
    c = onp.random.randn(2, 3, 4).astype("float32")
    d = onp.random.randn(2, 4, 5).astype("float32")
    assert_almost_equal(np.matmul(np.array(c), np.array(d)),
                        onp.matmul(c, d), rtol=1e-4, atol=1e-4)


def test_tensordot():
    a = onp.random.randn(3, 4, 5).astype("float32")
    b = onp.random.randn(5, 4, 2).astype("float32")
    got = np.tensordot(np.array(a), np.array(b), axes=([1, 2], [1, 0]))
    ref = onp.tensordot(a, b, axes=([1, 2], [1, 0]))
    assert_almost_equal(got, ref, rtol=1e-4, atol=1e-4)


def test_shape_manipulation():
    x = onp.arange(24).reshape(2, 3, 4).astype("float32")
    mx_x = np.array(x)
    assert_almost_equal(np.transpose(mx_x, (2, 0, 1)),
                        onp.transpose(x, (2, 0, 1)))
    assert_almost_equal(np.swapaxes(mx_x, 0, 2), onp.swapaxes(x, 0, 2))
    assert_almost_equal(np.moveaxis(mx_x, 0, -1), onp.moveaxis(x, 0, -1))
    assert_almost_equal(np.tile(mx_x, (2, 1, 1)), onp.tile(x, (2, 1, 1)))
    assert_almost_equal(np.repeat(mx_x, 2, axis=1), onp.repeat(x, 2, axis=1))
    assert_almost_equal(np.flip(mx_x, 1), onp.flip(x, 1))
    assert_almost_equal(np.roll(mx_x, 1, 0), onp.roll(x, 1, 0))
    assert_almost_equal(np.broadcast_to(np.array([1.0, 2, 3, 4]), (2, 4)),
                        onp.broadcast_to([1, 2, 3, 4], (2, 4)))


def test_concat_stack_split():
    a = onp.ones((2, 3), "float32")
    b = onp.zeros((2, 3), "float32")
    assert_almost_equal(np.concatenate([np.array(a), np.array(b)], axis=0),
                        onp.concatenate([a, b], axis=0))
    assert_almost_equal(np.stack([np.array(a), np.array(b)], axis=1),
                        onp.stack([a, b], axis=1))
    parts = np.split(np.array(onp.arange(12).reshape(4, 3)), 2, axis=0)
    assert len(parts) == 2
    assert parts[0].shape == (2, 3)
    assert_almost_equal(np.vstack([np.array(a), np.array(b)]),
                        onp.vstack([a, b]))
    assert_almost_equal(np.hstack([np.array(a), np.array(b)]),
                        onp.hstack([a, b]))


def test_where_sort_argsort():
    x = onp.random.randn(4, 5).astype("float32")
    mx_x = np.array(x)
    assert_almost_equal(np.where(mx_x > 0, mx_x, np.zeros_like(mx_x)),
                        onp.where(x > 0, x, 0))
    assert_almost_equal(np.sort(mx_x, axis=1), onp.sort(x, axis=1))
    assert_almost_equal(np.argsort(mx_x, axis=1).astype("int64"),
                        onp.argsort(x, axis=1, kind="stable"))


def test_take_pick_onehot():
    x = onp.random.randn(4, 5).astype("float32")
    idx = onp.array([0, 2, 4, 1])
    assert_almost_equal(np.take(np.array(x), np.array(idx), axis=1),
                        onp.take(x, idx, axis=1))
    got = np.pick(np.array(x), np.array(idx), axis=1)
    ref = x[onp.arange(4), idx]
    assert_almost_equal(got, ref)
    oh = np.one_hot(np.array([0, 2]), 4)
    assert oh.asnumpy().tolist() == [[1, 0, 0, 0], [0, 0, 1, 0]]


def test_cumsum_diff_clip():
    x = onp.random.randn(3, 4).astype("float32")
    assert_almost_equal(np.cumsum(np.array(x), axis=1),
                        onp.cumsum(x, axis=1), rtol=1e-4, atol=1e-5)
    assert_almost_equal(np.diff(np.array(x), axis=1), onp.diff(x, axis=1))
    assert_almost_equal(np.clip(np.array(x), -0.5, 0.5),
                        onp.clip(x, -0.5, 0.5))


def test_linalg():
    a = onp.random.randn(4, 4).astype("float32")
    spd = a @ a.T + 4 * onp.eye(4, dtype="float32")
    assert_almost_equal(np.linalg.inv(np.array(spd)) @ np.array(spd),
                        onp.eye(4), rtol=1e-3, atol=1e-3)
    assert_almost_equal(np.linalg.det(np.array(spd)),
                        onp.linalg.det(spd), rtol=1e-3, atol=1e-2)
    L = np.linalg.cholesky(np.array(spd))
    assert_almost_equal(L @ L.T, spd, rtol=1e-3, atol=1e-3)
    q, r = np.linalg.qr(np.array(a))
    assert_almost_equal(q @ r, a, rtol=1e-3, atol=1e-3)
    u, s, vt = np.linalg.svd(np.array(a), full_matrices=False)
    assert_almost_equal((u * s) @ vt, a, rtol=1e-3, atol=1e-3)
    b = onp.random.randn(4).astype("float32")
    x = np.linalg.solve(np.array(spd), np.array(b))
    assert_almost_equal(np.array(spd) @ x, b, rtol=1e-3, atol=1e-3)
    w, v = np.linalg.eigh(np.array(spd))
    assert_almost_equal(np.array(spd) @ v, v * w, rtol=1e-2, atol=1e-2)


def test_unique_nonzero_host_fallback():
    x = np.array([1, 2, 2, 3, 3, 3])
    u = np.unique(x)
    assert u.asnumpy().tolist() == [1, 2, 3]
    nz = np.nonzero(np.array([0, 1, 0, 2]))
    assert nz[0].asnumpy().tolist() == [1, 3]
    fnz = np.flatnonzero(np.array([0, 1, 0, 2]), size=2)
    assert fnz.asnumpy().tolist() == [1, 3]


def test_topk():
    x = np.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]])
    idx = np.topk(x, k=2, axis=1)
    assert idx.asnumpy().tolist() == [[0, 2], [1, 2]]
    vals = np.topk(x, k=2, axis=1, ret_typ="value")
    assert vals.asnumpy().tolist() == [[3.0, 2.0], [5.0, 4.0]]
    asc = np.topk(x, k=1, axis=1, ret_typ="value", is_ascend=True)
    assert asc.asnumpy().tolist() == [[1.0], [0.0]]


def test_pad_meshgrid():
    x = onp.ones((2, 2), "float32")
    assert_almost_equal(np.pad(np.array(x), ((1, 1), (0, 0))),
                        onp.pad(x, ((1, 1), (0, 0))))
    g1, g2 = np.meshgrid(np.arange(3), np.arange(2))
    r1, r2 = onp.meshgrid(onp.arange(3), onp.arange(2))
    assert_almost_equal(g1.astype("float32"), r1.astype("float32"))
    assert_almost_equal(g2.astype("float32"), r2.astype("float32"))


def test_fill_diagonal_in_place_and_wrap():
    """numpy contract: mutates in place, returns None; tall-matrix wrap."""
    x = np.ones((3, 3))
    ret = np.fill_diagonal(x, 0)
    assert ret is None
    assert_almost_equal(x.asnumpy(), onp.array(
        [[0, 1, 1], [1, 0, 1], [1, 1, 0]], dtype="float32"))
    # array val
    y = np.ones((3, 3))
    np.fill_diagonal(y, np.array([1.0, 2.0, 3.0]))
    assert_almost_equal(onp.diag(y.asnumpy()), [1.0, 2.0, 3.0])
    # tall without wrap: numpy stops after ncols*ncols flat elements
    t = onp.ones((5, 2), "float32")
    tw = np.array(t.copy())
    np.fill_diagonal(tw, 0)
    ref = t.copy()
    onp.fill_diagonal(ref, 0)
    assert_almost_equal(tw.asnumpy(), ref)
    # tall with wrap
    tw2 = np.array(t.copy())
    np.fill_diagonal(tw2, 0, wrap=True)
    ref2 = t.copy()
    onp.fill_diagonal(ref2, 0, wrap=True)
    assert_almost_equal(tw2.asnumpy(), ref2)
