"""Cross-path consistency oracles: eager vs hybridized (compiled) execution
must agree for every layer family — the TPU analog of the reference's
check_consistency CPU-vs-GPU oracle (test_utils.py:1490, run by
tests/python/gpu/test_operator_gpu.py for every op)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, np
from mxnet_tpu.gluon import nn, rnn
from mxnet_tpu.test_utils import assert_almost_equal

CASES = [
    ("dense", lambda: nn.Dense(8, activation="relu"), (2, 6)),
    ("dense_noflat", lambda: nn.Dense(8, flatten=False), (2, 3, 6)),
    ("conv1d", lambda: nn.Conv1D(4, 3, padding=1), (2, 3, 10)),
    ("conv2d", lambda: nn.Conv2D(4, 3, padding=1, groups=1), (2, 3, 8, 8)),
    ("conv2d_group", lambda: nn.Conv2D(4, 3, padding=1, groups=2),
     (2, 4, 8, 8)),
    ("deconv", lambda: nn.Conv2DTranspose(4, 2, strides=2), (2, 3, 5, 5)),
    ("maxpool", lambda: nn.MaxPool2D(2, 2), (2, 3, 8, 8)),
    ("avgpool_ceil", lambda: nn.AvgPool2D(3, 2, ceil_mode=True),
     (2, 3, 7, 7)),
    ("batchnorm", lambda: nn.BatchNorm(), (4, 3, 5, 5)),
    ("layernorm", lambda: nn.LayerNorm(), (2, 5, 8)),
    ("groupnorm", lambda: nn.GroupNorm(num_groups=2), (2, 4, 5, 5)),
    ("instancenorm", lambda: nn.InstanceNorm(), (2, 3, 5, 5)),
    ("rmsnorm", lambda: nn.RMSNorm(), (2, 8)),
    ("embedding", lambda: nn.Embedding(10, 4), (2, 5)),
    ("prelu", lambda: nn.PReLU(), (2, 6)),
    ("gelu", lambda: nn.GELU(), (2, 6)),
    ("swish", lambda: nn.Swish(), (2, 6)),
    ("lstm", lambda: rnn.LSTM(6, layout="NTC"), (2, 5, 4)),
    ("gru", lambda: rnn.GRU(6, layout="NTC"), (2, 5, 4)),
]


@pytest.mark.parametrize("name,make,shape", CASES,
                         ids=[c[0] for c in CASES])
def test_eager_vs_hybrid(name, make, shape):
    layer = make()
    layer.initialize()
    if name == "embedding":
        x = np.array(onp.random.randint(0, 10, shape))
    else:
        x = mx.np.random.uniform(size=shape)
    eager = layer(x)
    eager = eager[0] if isinstance(eager, tuple) else eager
    layer.hybridize()
    hybrid = layer(x)
    hybrid = hybrid[0] if isinstance(hybrid, tuple) else hybrid
    assert_almost_equal(eager.asnumpy(), hybrid.asnumpy(), rtol=1e-4,
                        atol=1e-5)


@pytest.mark.parametrize("name,make,shape",
                         [c for c in CASES
                          if c[0] not in ("embedding",)],
                         ids=[c[0] for c in CASES if c[0] != "embedding"])
def test_eager_vs_hybrid_gradients(name, make, shape):
    """Gradients through the compiled path must match eager tape grads."""
    layer_a, layer_b = make(), make()
    for layer in (layer_a, layer_b):
        layer.initialize()
    x = mx.np.random.uniform(size=shape)
    # copy weights a -> b after deferred init settles
    _ = layer_a(x), layer_b(x)
    pa = layer_a.collect_params()
    pb = layer_b.collect_params()
    for k in pa:
        pb[k].set_data(pa[k].data())
    layer_b.hybridize()

    def grads_of(layer, xin):
        params = [p for p in layer.collect_params().values()
                  if p.grad_req != "null"]
        xin.attach_grad()  # parameterless layers: compare input grads
        with autograd.record():
            out = layer(xin)
            out = out[0] if isinstance(out, tuple) else out
            loss = (out * out).sum()
        loss.backward()
        return [xin.grad.asnumpy()] + [p.grad().asnumpy() for p in params]

    xa = np.array(x.asnumpy())
    xb = np.array(x.asnumpy())
    for ga, gb in zip(grads_of(layer_a, xa), grads_of(layer_b, xb)):
        assert_almost_equal(ga, gb, rtol=1e-3, atol=1e-4)
