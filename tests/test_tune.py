"""Autotuned Pallas kernel tier (ISSUE 20): validated block-size env
accessors, padded-tail parity for all three kernel families, the
resolve tier (override > tuned winner > xla-on-miss, never silently
slower), tuning-cache persistence (round trip, corrupt/stale/foreign
files), the spec_from_key discovery loop, the watchdog-silent sweep
contract, and the serving acceptance: a warmed Predictor / DecodeEngine
resolves tuned configs for every ladder bucket with zero online tuning
and zero steady-state compiles."""
import json
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry as tm, tune
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.ops import pallas_kernels as pk


@pytest.fixture(autouse=True)
def clean_tuning(monkeypatch, tmp_path):
    # fresh tuning tier per test: in-process LRU dropped, persistent file
    # pointed at a per-test tmp path, telemetry off + zeroed, env clean.
    # PRNG snapshot mirrors test_serve: nets below reseed the global key.
    import mxnet_tpu.random as _rnd

    with _rnd._lock:
        rng_key, rng_pending = _rnd._key, _rnd._pending_seed
    host_state = _rnd.host_rng.get_state()
    tm.disable()
    tm.reset()
    for var in ("MXTPU_TUNE", "MXTPU_PALLAS_INTERPRET",
                "MXTPU_FLASH_BLOCK_Q", "MXTPU_FLASH_BLOCK_K",
                "MXTPU_TUNE_TRIALS"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("MXTPU_TUNE_CACHE", str(tmp_path / "tuning.json"))
    tune.reset()
    yield
    from mxnet_tpu.context import disable_compilation_cache

    disable_compilation_cache()
    tune.reset()
    tm.disable()
    tm.reset()
    with _rnd._lock:
        _rnd._key, _rnd._pending_seed = rng_key, rng_pending
    _rnd.host_rng.set_state(host_state)


def _interp(monkeypatch):
    monkeypatch.setenv("MXTPU_PALLAS_INTERPRET", "1")


def _attn(b=1, h=2, tq=20, tk=20, d=32, seed=0):
    import jax.numpy as jnp

    rs = onp.random.RandomState(seed)
    q = jnp.asarray(rs.randn(b, h, tq, d).astype("float32"))
    k = jnp.asarray(rs.randn(b, h, tk, d).astype("float32"))
    v = jnp.asarray(rs.randn(b, h, tk, d).astype("float32"))
    return q, k, v


def _xla_overrides():
    import contextlib

    stack = contextlib.ExitStack()
    for fam in ("flash_fwd", "flash_bwd", "layer_norm", "softmax"):
        stack.enter_context(tune.override(fam, "xla"))
    return stack


# -- satellite 1: validated block-size accessors ----------------------------
def test_block_env_defaults_and_per_call_read(monkeypatch):
    assert pk.flash_block_q() == 256
    assert pk.flash_block_k() == 512
    # read per call — no module reload needed to change them
    monkeypatch.setenv("MXTPU_FLASH_BLOCK_Q", "64")
    monkeypatch.setenv("MXTPU_FLASH_BLOCK_K", "128")
    assert pk.flash_block_q() == 64
    assert pk.flash_block_k() == 128
    # the frozen-at-import constants are gone
    assert not hasattr(pk, "DEFAULT_BLOCK_Q")
    assert not hasattr(pk, "DEFAULT_BLOCK_K")


@pytest.mark.parametrize("var,raw,fn", [
    ("MXTPU_FLASH_BLOCK_Q", "100", pk.flash_block_q),   # not a power of two
    ("MXTPU_FLASH_BLOCK_Q", "4", pk.flash_block_q),     # below min tile 8
    ("MXTPU_FLASH_BLOCK_Q", "abc", pk.flash_block_q),   # not an integer
    ("MXTPU_FLASH_BLOCK_K", "64", pk.flash_block_k),    # below min tile 128
    ("MXTPU_FLASH_BLOCK_K", "12x", pk.flash_block_k),
])
def test_block_env_validation_names_the_var(monkeypatch, var, raw, fn):
    monkeypatch.setenv(var, raw)
    with pytest.raises(MXNetError, match=var):
        fn()


# -- satellite 2: padded-tail parity, fwd and bwd, all three families -------
@pytest.mark.parametrize("causal", [False, True])
def test_attention_padded_tail_parity(monkeypatch, causal):
    import jax

    _interp(monkeypatch)
    # T=20 with block_q=8 is not block-divisible -> the padded fused path
    monkeypatch.setenv("MXTPU_FLASH_BLOCK_Q", "8")
    monkeypatch.setenv("MXTPU_FLASH_BLOCK_K", "128")
    q, k, v = _attn(tq=20, tk=20)

    def f(q_, k_, v_):
        return pk.flash_attention(q_, k_, v_, causal=causal)

    got = f(q, k, v)
    with _xla_overrides():
        want = f(q, k, v)
    onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                atol=2e-5, rtol=2e-5)

    loss = lambda *a: (f(*a) ** 2).sum()
    gg = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    with _xla_overrides():
        gw = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gg, gw):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    atol=2e-4, rtol=2e-4)


def test_attention_padded_tail_parity_segments(monkeypatch):
    import jax
    import jax.numpy as jnp

    _interp(monkeypatch)
    monkeypatch.setenv("MXTPU_FLASH_BLOCK_Q", "8")
    monkeypatch.setenv("MXTPU_FLASH_BLOCK_K", "128")
    q, k, v = _attn(tq=20, tk=20)
    # BERT-style key padding: 14 valid tokens (id 1), 6 padding (id 0)
    seg = jnp.asarray((onp.arange(20) < 14).astype("int32"))[None, :]

    def f(q_, k_, v_):
        return pk.flash_attention(q_, k_, v_, causal=False,
                                  q_segment_ids=seg, kv_segment_ids=seg)

    got = f(q, k, v)
    with _xla_overrides():
        want = f(q, k, v)
    # padding rows attend only to padding — compare the valid region
    onp.testing.assert_allclose(onp.asarray(got)[:, :, :14],
                                onp.asarray(want)[:, :, :14],
                                atol=2e-5, rtol=2e-5)

    loss = lambda *a: (f(*a)[:, :, :14] ** 2).sum()
    gg = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    with _xla_overrides():
        gw = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gg, gw):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    atol=2e-4, rtol=2e-4)


def test_layer_norm_padded_tail_parity(monkeypatch):
    import jax
    import jax.numpy as jnp

    _interp(monkeypatch)
    rs = onp.random.RandomState(3)
    # 200 rows with the default block_rows=128 pads the tail to 256;
    # 3-D input also exercises _rows_of's leading-axis flattening
    x = jnp.asarray(rs.randn(8, 25, 128).astype("float32"))
    gamma = jnp.asarray((rs.rand(128) + 0.5).astype("float32"))
    beta = jnp.asarray(rs.randn(128).astype("float32"))

    got = pk.fused_layer_norm(x, gamma, beta)
    want = pk._ln_reference(x, gamma, beta, 1e-5)
    onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                atol=1e-5, rtol=1e-5)

    loss = lambda *a: (pk.fused_layer_norm(*a) ** 2).sum()
    ref = lambda *a: (pk._ln_reference(*a, 1e-5) ** 2).sum()
    gg = jax.grad(loss, argnums=(0, 1, 2))(x, gamma, beta)
    gw = jax.grad(ref, argnums=(0, 1, 2))(x, gamma, beta)
    for a, b in zip(gg, gw):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    atol=1e-4, rtol=1e-4)


def test_softmax_padded_tail_parity(monkeypatch):
    import jax
    import jax.numpy as jnp

    _interp(monkeypatch)
    rs = onp.random.RandomState(4)
    x = jnp.asarray(rs.randn(8, 25, 128).astype("float32"))

    got = pk.fused_softmax(x)
    want = jax.nn.softmax(x, axis=-1)
    onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                atol=1e-6, rtol=1e-5)

    loss = lambda x_: (pk.fused_softmax(x_) ** 2).sum()
    ref = lambda x_: (jax.nn.softmax(x_, axis=-1) ** 2).sum()
    onp.testing.assert_allclose(onp.asarray(jax.grad(loss)(x)),
                                onp.asarray(jax.grad(ref)(x)),
                                atol=1e-5, rtol=1e-4)


# -- resolve tier -----------------------------------------------------------
def test_resolve_default_when_tuning_off():
    # tuning off: byte-identical legacy behavior, no counters, no miss log
    assert tune.resolve("flash_fwd", "flash_fwd|whatever") == "default"
    assert tune.missed() == []
    assert tm.counter("tune.cache_misses").value == 0


def test_miss_falls_back_to_xla_with_counters(monkeypatch):
    import jax.numpy as jnp

    _interp(monkeypatch)
    monkeypatch.setenv("MXTPU_TUNE", "1")
    rs = onp.random.RandomState(0)
    x = jnp.asarray(rs.randn(128, 128).astype("float32"))
    gamma = jnp.asarray(onp.ones(128, "float32"))
    beta = jnp.asarray(onp.zeros(128, "float32"))
    m0 = tm.counter("tune.cache_misses").value
    f0 = tm.counter("tune.fallback_xla").value
    got = pk.fused_layer_norm(x, gamma, beta)
    assert tm.counter("tune.cache_misses").value == m0 + 1
    assert tm.counter("tune.fallback_xla").value == f0 + 1
    key = tune.key_rows("layer_norm", 128, 128, "float32")
    assert ("layer_norm", key) in tune.missed()
    # the fallback is the XLA reference — same numbers, never slower
    onp.testing.assert_allclose(
        onp.asarray(got), onp.asarray(pk._ln_reference(x, gamma, beta, 1e-5)),
        atol=1e-6, rtol=1e-6)


def test_tuned_winner_dispatch_and_parity(monkeypatch):
    _interp(monkeypatch)
    monkeypatch.setenv("MXTPU_TUNE", "1")
    spec = tune.attention_spec("flash_fwd", 1, 2, 64, 64, 32)
    res = tune.tune_one(spec, trials=1, max_per_axis=1)
    assert res["key"] == tune.spec_key(spec)
    assert res["best_us"] <= res["default_us"]
    h0 = tm.counter("tune.cache_hits").value
    q, k, v = _attn(tq=64, tk=64)
    got = pk.flash_attention(q, k, v, causal=True)   # resolves the winner
    assert tm.counter("tune.cache_hits").value >= h0 + 1
    with _xla_overrides():
        want = pk.flash_attention(q, k, v, causal=True)
    onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                atol=2e-5, rtol=2e-5)


def test_override_scoping_and_validation():
    with pytest.raises(ValueError, match="flash_fwd"):
        with tune.override("flash_fwd", {"block_q": 0}):
            pass
    with pytest.raises(ValueError):
        with tune.override("softmax", [128]):
            pass
    # nesting restores the outer value, and overrides win with tuning off
    with tune.override("softmax", {"block_rows": 64}):
        with tune.override("softmax", "xla"):
            assert tune.resolve("softmax", "softmax|x") == "xla"
        assert tune.resolve("softmax", "softmax|x") == {"block_rows": 64}
    assert tune.resolve("softmax", "softmax|x") == "default"


# -- keys / specs -----------------------------------------------------------
def test_keys_bucket_to_the_ladder():
    assert tune.bucket(1) == 1 and tune.bucket(96) == 128
    key = tune.key_attention("flash_fwd", (2, 3, 48, 32), (2, 3, 80, 32),
                             "float32", True, False)
    assert key == "flash_fwd|bh8.tq64.tk128.d32.float32.c1.s0"
    assert (tune.key_rows("layer_norm", 200, 128, "float32")
            == "layer_norm|rows256.d128.float32")


@pytest.mark.parametrize("spec", [
    tune.attention_spec("flash_fwd", 2, 4, 128, 256, 64, causal=True,
                        seg=True),
    tune.attention_spec("flash_bwd", 1, 2, 64, 64, 32, causal=False),
    tune.rows_spec("layer_norm", 512, 256),
    tune.rows_spec("softmax", 128, 128),
])
def test_spec_from_key_closes_the_discovery_loop(spec):
    key = tune.spec_key(spec)
    rebuilt = tune.spec_from_key(key)
    assert rebuilt["kernel"] == spec["kernel"]
    assert tune.spec_key(rebuilt) == key


# -- satellite 3: persistence round trip ------------------------------------
def test_cache_roundtrip_fresh_process_no_remeasure(monkeypatch):
    _interp(monkeypatch)
    monkeypatch.setenv("MXTPU_TUNE", "1")
    specs = [tune.rows_spec("layer_norm", 128, 128),
             tune.rows_spec("softmax", 128, 128)]
    tune.autotune(specs, trials=1, max_per_axis=1)   # measures + saves
    meas = tm.counter("tune.measurements").value
    assert meas > 0
    path = tune.cache_path()
    assert os.path.exists(path)

    tune.reset()                                     # fresh-process sim
    assert tune.preload() == 2
    for s in specs:
        cfg = tune.resolve(s["kernel"], tune.spec_key(s))
        assert cfg != "default"                      # the persisted winner
    # loading winners from disk never re-measures and never misses
    assert tm.counter("tune.measurements").value == meas
    assert tune.missed() == []


def test_corrupt_cache_file_warns_and_retunes(monkeypatch, tmp_path):
    monkeypatch.setenv("MXTPU_TUNE", "1")
    path = tune.cache_path()
    with open(path, "w") as fh:
        fh.write("{this is not json")
    c0 = tm.counter("tune.cache_corrupt").value
    with pytest.warns(RuntimeWarning, match="unreadable"):
        assert tune.preload() == 0
    assert tm.counter("tune.cache_corrupt").value == c0 + 1
    # re-tuning overwrites the corrupt file and the entry round-trips
    key = tune.key_rows("layer_norm", 128, 128, "float32")
    tune.record("layer_norm", key, {"block_rows": 64})
    tune.save()
    tune.reset()
    assert tune.preload() == 1
    assert tune.resolve("layer_norm", key) == {"block_rows": 64}


def test_stale_schema_version_skipped(monkeypatch):
    monkeypatch.setenv("MXTPU_TUNE", "1")
    key = tune.key_rows("softmax", 128, 128, "float32")
    tune.record("softmax", key, {"block_rows": 32})
    path = tune.save()
    with open(path) as fh:
        doc = json.load(fh)
    doc["version"] = 99
    with open(path, "w") as fh:
        json.dump(doc, fh)
    tune.reset()
    c0 = tm.counter("tune.cache_corrupt").value
    with pytest.warns(RuntimeWarning, match="schema version"):
        assert tune.preload() == 0
    assert tm.counter("tune.cache_corrupt").value == c0 + 1


def test_foreign_env_signature_not_reused(monkeypatch):
    monkeypatch.setenv("MXTPU_TUNE", "1")
    key = tune.key_rows("softmax", 128, 128, "float32")
    tune.record("softmax", key, {"block_rows": 32})
    path = tune.save()
    with open(path) as fh:
        doc = json.load(fh)
    doc["env_signature"] = "deadbeef0123"
    with open(path, "w") as fh:
        json.dump(doc, fh)
    tune.reset()
    with pytest.warns(RuntimeWarning, match="environment signature"):
        assert tune.preload() == 0
    # a winner from another environment must not dispatch: miss -> xla
    assert tune.resolve("softmax", key) == "xla"


def test_corrupt_entry_skipped_good_entries_kept(monkeypatch):
    monkeypatch.setenv("MXTPU_TUNE", "1")
    key = tune.key_rows("layer_norm", 128, 128, "float32")
    tune.record("layer_norm", key, {"block_rows": 64})
    path = tune.save()
    with open(path) as fh:
        doc = json.load(fh)
    doc["entries"]["softmax|rows128.d128.float32"] = {
        "config": {"block_rows": -4}}             # invalid block size
    with open(path, "w") as fh:
        json.dump(doc, fh)
    tune.reset()
    c0 = tm.counter("tune.cache_corrupt").value
    with pytest.warns(RuntimeWarning, match="corrupt tuning-cache entry"):
        assert tune.preload() == 1                # the good entry survives
    assert tm.counter("tune.cache_corrupt").value == c0 + 1
    assert tune.resolve("layer_norm", key) == {"block_rows": 64}


# -- satellite 5: watchdog-silent sweep smoke -------------------------------
def test_tuner_sweep_watchdog_silent(monkeypatch):
    _interp(monkeypatch)
    monkeypatch.setenv("MXTPU_TUNE", "1")
    tm.enable()
    wd0 = dict(tm.watchdog_stats())
    c0 = int(tm.metrics().get("jit.compiles", 0))
    results = tune.autotune([tune.rows_spec("softmax", 128, 128)],
                            trials=1, max_per_axis=1, save=False)
    assert results[0]["winner"] in ("xla", "default")
    assert tm.counter("tune.measurements").value > 0
    # the tuner's jit sites are plain jax.jit, not the instrumented
    # Op/CachedOp paths: the watchdog (and the compile counters it
    # feeds on) must not see a sweep at all
    assert dict(tm.watchdog_stats()) == wd0
    assert int(tm.metrics().get("jit.compiles", 0)) == c0


def test_bench_kernels_smoke(monkeypatch, tmp_path):
    import bench

    monkeypatch.setenv("BENCH_KERNELS_SMALL", "1")
    monkeypatch.setenv("MXTPU_TUNE_CACHE", str(tmp_path / "bench.json"))
    r = bench.bench_kernels()
    assert r["metric"] == "kernel_tuned_vs_default_geomean_speedup"
    assert r["specs"] == 3 and r["watchdog_silent"]
    assert all(row["best_us"] > 0 for row in r["rows"])


# -- serving acceptance: tuned configs for every ladder bucket --------------
def _fresh_process():
    # the per-op jitted fn cache (ops/registry Op.fn) memoizes traces
    # process-wide, so an identical net built later in this test process
    # would never re-run the kernel wrappers (and so never resolve). The
    # real workflow is cross-process — warm with MXTPU_TUNE=1, tune
    # offline, restart serving — so simulate the restart: drop the op
    # trace caches along with the in-process tuning tier.
    from mxnet_tpu.ops import registry

    for op in registry._OPS.values():
        op._fn_cache.clear()
    tune.reset()


def test_predictor_warmup_resolves_tuned_configs(monkeypatch):
    _interp(monkeypatch)
    monkeypatch.setenv("MXTPU_TUNE", "1")

    def make_net():
        mx.random.seed(7)
        net = nn.HybridSequential()
        # LayerNorm over 128 lanes puts the fused kernel (and so the
        # tuning tier) on the Predictor's per-bucket trace path
        net.add(nn.Dense(128), nn.LayerNorm(), nn.Dense(3))
        net.initialize()
        net.hybridize()
        return net

    example = mx.nd.array(onp.random.RandomState(0)
                          .standard_normal((2, 6)).astype("float32"))
    # discovery pass: warm once with an empty cache, read the missed
    # buckets, tune exactly those — the documented offline workflow
    _fresh_process()
    pred = make_net().predictor(example=example, max_batch=4,
                                cache_dir=False)
    pred.warmup()
    worklist = tune.missed()
    pred.close()
    assert worklist, "warmup traced no tunable kernel bucket"
    assert all(kern == "layer_norm" for kern, _ in worklist)
    tune.autotune([tune.spec_from_key(k) for _, k in worklist],
                  trials=1, max_per_axis=1)

    # fresh-process serving pass: preloaded winners cover every bucket
    _fresh_process()
    tm.enable()
    m0 = tm.counter("tune.cache_misses").value
    t0 = tm.counter("tune.measurements").value
    h0 = tm.counter("tune.cache_hits").value
    pred2 = make_net().predictor(example=example, max_batch=4,
                                 cache_dir=False)
    try:
        pred2.warmup()
        assert tm.counter("tune.cache_hits").value >= h0 + len(worklist)
        assert tm.counter("tune.cache_misses").value == m0
        c0 = tm.metrics()["jit.compiles"]
        r0 = tm.counter("tune.cache_hits").value
        for n in (1, 2, 3, 4):
            pred2.predict(mx.nd.array(
                onp.random.RandomState(n).standard_normal(
                    (n, 6)).astype("float32")))
        # steady state: no new traces, so not even a resolve call
        assert int(tm.metrics()["jit.compiles"] - c0) == 0
        assert tm.counter("tune.cache_hits").value == r0
        assert tm.counter("tune.cache_misses").value == m0
        # a serving process never tunes online
        assert tm.counter("tune.measurements").value == t0
    finally:
        pred2.close()


def test_decode_engine_warmup_resolves_tuned_configs(monkeypatch):
    from mxnet_tpu.gluon.model_zoo import gpt_tiny
    from mxnet_tpu.serve.decode import DecodeEngine

    _interp(monkeypatch)
    monkeypatch.setenv("MXTPU_TUNE", "1")

    def make_net():
        mx.random.seed(11)
        # units=128 keeps the transformer LayerNorms lane-aligned so
        # they resolve through the tuning tier alongside flash attention
        net = gpt_tiny(vocab_size=50, dropout=0.0, num_layers=1,
                       units=128, num_heads=2, max_length=32)
        net.initialize()
        return net

    def make_engine(net):
        return DecodeEngine(net, num_slots=2, max_len=32,
                            max_prompt_len=8, prefill_batch=2,
                            cache_dir=False)

    _fresh_process()
    eng = make_engine(make_net())
    eng.warmup()
    worklist = tune.missed()
    eng.close()
    assert worklist
    assert {kern for kern, _ in worklist} >= {"layer_norm"}
    tune.autotune([tune.spec_from_key(k) for _, k in worklist],
                  trials=1, max_per_axis=1)

    _fresh_process()
    tm.enable()
    m0 = tm.counter("tune.cache_misses").value
    t0 = tm.counter("tune.measurements").value
    h0 = tm.counter("tune.cache_hits").value
    eng2 = make_engine(make_net())
    try:
        eng2.warmup()
        assert tm.counter("tune.cache_hits").value > h0
        assert tm.counter("tune.cache_misses").value == m0
        c0 = tm.metrics()["jit.compiles"]
        out = eng2.submit([1, 2, 3], max_new_tokens=4).result(timeout=120)
        assert len(out) == 4
        assert int(tm.metrics()["jit.compiles"] - c0) == 0
        assert tm.counter("tune.cache_misses").value == m0
        assert tm.counter("tune.measurements").value == t0
    finally:
        eng2.close()
